// Balanced bipartite edge coloring (Euler split) for shard assignment.
//
// Assigns each edge of a bipartite multigraph (src side, dst side) to one
// of P = 2^levels shards so that EVERY vertex's incident edges split
// floor(d/P)..ceil(d/P) across shards — on both sides simultaneously.
// Random/round-robin assignment leaves a max-of-128-lanes binomial tail
// that inflates the per-shard gather/scatter row padding of the MXU plan
// (memgraph_tpu/ops/spmv_mxu_sharded.py) by ~2x; the balanced split makes
// the per-shard Benes net ~P-fold smaller, which is what the multichip
// speedup projection rides on.
//
// Method, per halving level: pair consecutive incident edges at every
// vertex ((0,1),(2,3),... in incidence order). Each edge carries at most
// one pairing per side, so the pairing relation forms paths and cycles
// over edges; cycles alternate src-/dst-side pairings and are therefore
// even. 2-coloring each path/cycle alternately gives every vertex an
// even split of its paired edges; the odd unpaired edge tips one half by
// exactly one. Recursing log2(P) times yields the floor/ceil bound.
// O(E log P) time, O(E) memory.
//
// Reference analog: none (the reference's cuGraph/NCCL path partitions by
// contiguous vertex ranges); this exists because MXU-plan padding is
// governed by per-row MAX degree, which only balanced splits control.

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

// Split edges[0..m) into halves by pairing-graph 2-coloring.
// side_key: for each edge, its endpoint id on each side.
// Returns colors in out_color (0/1 per edge index position).
void euler_halve(const int64_t* src, const int64_t* dst,
                 const int32_t* edges, int64_t m, int64_t n_src,
                 int64_t n_dst, uint8_t* out_color,
                 std::vector<int32_t>& scratch) {
  // incidence counts per vertex (src side then dst side)
  const int64_t nv = n_src + n_dst;
  std::vector<int64_t> head(nv, -1);
  // pair links: for edge slot i (position in edges[]), partner via src
  // pairing and via dst pairing; -1 = unpaired on that side.
  std::vector<int32_t>& pair_s = scratch;  // reuse caller scratch
  pair_s.assign(2 * m, -1);
  int32_t* pair_src = pair_s.data();
  int32_t* pair_dst = pair_s.data() + m;

  // walk incidence in order, pairing consecutive edges per vertex
  for (int64_t i = 0; i < m; i++) {
    const int64_t v = src[edges[i]];
    if (head[v] < 0) {
      head[v] = i;
    } else {
      pair_src[head[v]] = static_cast<int32_t>(i);
      pair_src[i] = static_cast<int32_t>(head[v]);
      head[v] = -1;
    }
  }
  for (int64_t v = 0; v < nv; v++) head[v] = -1;
  for (int64_t i = 0; i < m; i++) {
    const int64_t v = n_src + dst[edges[i]];
    if (head[v] < 0) {
      head[v] = i;
    } else {
      pair_dst[head[v]] = static_cast<int32_t>(i);
      pair_dst[i] = static_cast<int32_t>(head[v]);
      head[v] = -1;
    }
  }

  // 2-color paths first (start at edges unpaired on either side), then
  // cycles. colored flag lives in out_color as 0xff sentinel.
  for (int64_t i = 0; i < m; i++) out_color[i] = 0xff;
  for (int pass = 0; pass < 2; pass++) {
    for (int64_t s = 0; s < m; s++) {
      if (out_color[s] != 0xff) continue;
      const bool endpoint = (pair_src[s] < 0) || (pair_dst[s] < 0);
      if (pass == 0 && !endpoint) continue;  // cycles in pass 1
      // walk: alternate colors; at each step leave via the side we did
      // NOT arrive by. Start by leaving via src pairing (or dst if the
      // path starts src-unpaired).
      int64_t cur = s;
      uint8_t color = 0;
      bool via_src = pair_src[s] >= 0;  // first hop side
      while (cur >= 0 && out_color[cur] == 0xff) {
        out_color[cur] = color;
        color ^= 1;
        const int32_t nxt = via_src ? pair_src[cur] : pair_dst[cur];
        via_src = !via_src;
        cur = nxt;
      }
    }
  }
}

}  // namespace

extern "C" {

// src/dst: edge endpoints, 0 <= src[i] < n_src, 0 <= dst[i] < n_dst.
// levels: number of halvings; shards = 2^levels (<= 8 levels supported).
// out_shard: caller-allocated E bytes.
// Returns 0 on success, 1 on invalid arguments.
int balanced_edge_color(const int64_t* src, const int64_t* dst, int64_t E,
                        int64_t n_src, int64_t n_dst, int levels,
                        uint8_t* out_shard) {
  if (E < 0 || E > INT32_MAX || levels < 0 || levels > 8) return 1;
  for (int64_t i = 0; i < E; i++) {
    if (src[i] < 0 || src[i] >= n_src || dst[i] < 0 || dst[i] >= n_dst)
      return 1;
  }
  for (int64_t i = 0; i < E; i++) out_shard[i] = 0;
  if (levels == 0 || E == 0) return 0;

  // groups of edge indices, halved level by level
  std::vector<int32_t> edges(E);
  for (int64_t i = 0; i < E; i++) edges[i] = static_cast<int32_t>(i);
  std::vector<uint8_t> color(E);
  std::vector<int32_t> scratch;

  // offsets of each group within `edges`; starts with one group [0, E)
  std::vector<int64_t> bounds = {0, E};
  for (int lev = 0; lev < levels; lev++) {
    std::vector<int64_t> new_bounds = {0};
    int64_t write = 0;
    std::vector<int32_t> out(edges.size());
    for (std::size_t g = 0; g + 1 < bounds.size(); g++) {
      const int64_t lo = bounds[g], hi = bounds[g + 1], m = hi - lo;
      euler_halve(src, dst, edges.data() + lo, m, n_src, n_dst,
                  color.data(), scratch);
      // stable partition: color 0 first, then color 1
      int64_t w0 = write;
      for (int64_t i = 0; i < m; i++)
        if (color[i] == 0) out[w0++] = edges[lo + i];
      const int64_t mid = w0;
      for (int64_t i = 0; i < m; i++)
        if (color[i] != 0) {
          out[w0++] = edges[lo + i];
          out_shard[edges[lo + i]] |= static_cast<uint8_t>(1 << lev);
        }
      write = w0;
      new_bounds.push_back(mid);
      new_bounds.push_back(write);
    }
    edges.swap(out);
    bounds.swap(new_bounds);
  }
  return 0;
}

}  // extern "C"
