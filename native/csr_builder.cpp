// Native CSR/CSC graph builder.
//
// Role parity with the reference's native graph-snapshot builder
// (/root/reference/include/mg_utils.hpp:128-170 builds adjacency lists in
// C++ for MAGE modules): this is the hot host-side step that converts a COO
// edge list into the padded CSR + CSC device layout (memgraph_tpu/ops/csr.py
// documents the layout). Two stable counting sorts by dense node id run in
// O(E + N) — significantly faster than comparison sorting — and both layouts
// are produced in one call.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libcsr_builder.so csr_builder.cpp

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Builds CSR ((src,dst)-lexsorted) and CSC ((dst,src)-lexsorted) layouts.
//
// Inputs:
//   src, dst : n_edges int64 node ids in [0, n_nodes)
//   weights  : n_edges float or nullptr (treated as 1.0f)
//   n_pad    : padded node count (>= n_nodes + 1); sink row = n_nodes
//   e_pad    : padded edge count (>= n_edges)
// Outputs (caller-allocated):
//   csr_src, csr_dst : e_pad int32   csr_w : e_pad float
//   csc_src, csc_dst : e_pad int32   csc_w : e_pad float
//   row_ptr  : n_pad + 1 int32
//   out_degree : n_pad float
// Returns 0 on success, nonzero on invalid input.
int build_csr_csc(const int64_t* src, const int64_t* dst,
                  const float* weights,
                  int64_t n_edges, int64_t n_nodes,
                  int64_t n_pad, int64_t e_pad,
                  int32_t* csr_src, int32_t* csr_dst, float* csr_w,
                  int32_t* csc_src, int32_t* csc_dst, float* csc_w,
                  int32_t* row_ptr, float* out_degree) {
  if (n_pad < n_nodes + 1 || e_pad < n_edges) return 1;
  const int32_t sink = static_cast<int32_t>(n_nodes);

  // ---- counting sort #1: stable by dst (minor key) -------------------------
  std::vector<int64_t> count(static_cast<size_t>(n_nodes) + 1, 0);
  for (int64_t e = 0; e < n_edges; ++e) {
    const int64_t d = dst[e];
    if (d < 0 || d >= n_nodes || src[e] < 0 || src[e] >= n_nodes) return 2;
    ++count[d];
  }
  std::vector<int64_t> offset(static_cast<size_t>(n_nodes) + 1, 0);
  for (int64_t v = 1; v <= n_nodes; ++v) offset[v] = offset[v - 1] + count[v - 1];
  std::vector<int32_t> tmp_src(n_edges), tmp_dst(n_edges);
  std::vector<float> tmp_w(n_edges);
  for (int64_t e = 0; e < n_edges; ++e) {
    const int64_t pos = offset[dst[e]]++;
    tmp_src[pos] = static_cast<int32_t>(src[e]);
    tmp_dst[pos] = static_cast<int32_t>(dst[e]);
    tmp_w[pos] = weights ? weights[e] : 1.0f;
  }

  // ---- counting sort #2: stable by src (major key) → (src, dst) order -----
  std::fill(count.begin(), count.end(), 0);
  for (int64_t e = 0; e < n_edges; ++e) ++count[tmp_src[e]];
  offset[0] = 0;
  for (int64_t v = 1; v <= n_nodes; ++v) offset[v] = offset[v - 1] + count[v - 1];
  // row_ptr over the padded node range
  for (int64_t v = 0; v <= n_pad; ++v) {
    row_ptr[v] = static_cast<int32_t>(v <= n_nodes ? offset[v] : n_edges);
  }
  for (int64_t v = 0; v < n_pad; ++v) {
    out_degree[v] = (v < n_nodes) ? static_cast<float>(count[v]) : 0.0f;
  }
  for (int64_t e = 0; e < n_edges; ++e) {
    const int64_t pos = offset[tmp_src[e]]++;
    csr_src[pos] = tmp_src[e];
    csr_dst[pos] = tmp_dst[e];
    csr_w[pos] = tmp_w[e];
  }
  for (int64_t e = n_edges; e < e_pad; ++e) {
    csr_src[e] = sink; csr_dst[e] = sink; csr_w[e] = 0.0f;
  }

  // ---- CSC: stable sort of the (src,dst)-ordered arrays by dst ------------
  std::fill(count.begin(), count.end(), 0);
  for (int64_t e = 0; e < n_edges; ++e) ++count[csr_dst[e]];
  offset[0] = 0;
  for (int64_t v = 1; v <= n_nodes; ++v) offset[v] = offset[v - 1] + count[v - 1];
  for (int64_t e = 0; e < n_edges; ++e) {
    const int64_t pos = offset[csr_dst[e]]++;
    csc_src[pos] = csr_src[e];
    csc_dst[pos] = csr_dst[e];
    csc_w[pos] = csr_w[e];
  }
  for (int64_t e = n_edges; e < e_pad; ++e) {
    csc_src[e] = sink; csc_dst[e] = sink; csc_w[e] = 0.0f;
  }
  return 0;
}

}  // extern "C"
