/* Example native query module against the mgtpu C ABI.
 *
 * Registers:
 *   c_degree.get()      -> (node NODE, out_degree INT, in_degree INT)
 *   c_triangles.count() -> (triangles INT)  — naive per-edge intersection
 *
 * Build: gcc -O2 -shared -fPIC -o libexample_module.so example_module.c
 */

#include "mg_procedure.h"

#include <stdlib.h>

static const mgtpu_host_api *g_api;

static int degree_cb(const mgtpu_csr_view *view, mgtpu_result *result,
                     void *host_ctx) {
  (void)host_ctx;
  int64_t n = view->n_nodes;
  int64_t *in_deg = calloc((size_t)n, sizeof(int64_t));
  if (!in_deg) return g_api->result_set_error(result, "out of memory"), 1;
  for (int64_t e = 0; e < view->n_edges; ++e) {
    int32_t d = view->col_idx[e];
    if (d < n) ++in_deg[d];
  }
  for (int64_t v = 0; v < n; ++v) {
    g_api->result_new_record(result);
    g_api->result_set_node(result, "node", v);
    g_api->result_set_int(result, "out_degree",
                          view->row_ptr[v + 1] - view->row_ptr[v]);
    g_api->result_set_int(result, "in_degree", in_deg[v]);
  }
  free(in_deg);
  return 0;
}

/* binary search for dst in v's sorted CSR row */
static int has_edge(const mgtpu_csr_view *view, int32_t v, int32_t dst) {
  int32_t lo = view->row_ptr[v], hi = view->row_ptr[v + 1];
  while (lo < hi) {
    int32_t mid = lo + (hi - lo) / 2;
    if (view->col_idx[mid] < dst)
      lo = mid + 1;
    else
      hi = mid;
  }
  return lo < view->row_ptr[v + 1] && view->col_idx[lo] == dst;
}

static int triangles_cb(const mgtpu_csr_view *view, mgtpu_result *result,
                        void *host_ctx) {
  (void)host_ctx;
  int64_t count = 0;
  for (int64_t e = 0; e < view->n_edges; ++e) {
    int32_t u = view->csr_src[e], v = view->col_idx[e];
    if (u >= view->n_nodes || v >= view->n_nodes) continue;
    /* directed triangles u->v->w->u */
    for (int32_t j = view->row_ptr[v]; j < view->row_ptr[v + 1]; ++j) {
      int32_t w = view->col_idx[j];
      if (w < view->n_nodes && has_edge(view, w, u)) ++count;
    }
  }
  g_api->result_new_record(result);
  g_api->result_set_int(result, "triangles", count / 3);
  return 0;
}

int mgtpu_init_module(const mgtpu_host_api *api, void *registry) {
  g_api = api;
  if (api->register_procedure(registry, "c_degree.get", degree_cb,
                              "node:NODE,out_degree:INT,in_degree:INT"))
    return 1;
  if (api->register_procedure(registry, "c_triangles.count", triangles_cb,
                              "triangles:INT"))
    return 1;
  return 0;
}
