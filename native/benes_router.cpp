// Benes permutation-network router.
//
// Computes the per-stage swap masks that realize a fixed permutation on a
// power-of-two array as 2*log2(N)-1 masked-swap stages (the TPU-native
// "scatter" used by memgraph_tpu/ops/spmv_mxu.py; algorithm documented in
// memgraph_tpu/ops/benes.py, which holds the pure-python reference
// implementation). The classic looping algorithm: at every level, elements
// paired at the input stage and elements paired at the output stage form
// even cycles; 2-coloring each cycle assigns elements to the top/bottom
// half-network. O(N log N) total.
//
// Masks are bit-packed MSB-first per byte to match numpy.packbits.
//
// Build: part of libcsr_builder.so (see Makefile).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline void set_bit(uint8_t* bits, int64_t i, bool v) {
  if (v) bits[i >> 3] |= static_cast<uint8_t>(0x80u >> (i & 7));
}

}  // namespace

extern "C" {

// perm: gather form — output position i receives input position perm[i].
// masks_packed: caller-allocated (2*log2(N)-1) * (N/8) bytes, zeroed here.
// Returns 0 on success, 1 on invalid arguments.
int benes_route(const int64_t* perm, int64_t N, uint8_t* masks_packed) {
  if (N < 2 || (N & (N - 1))) return 1;
  int n = 0;
  while ((int64_t{1} << n) < N) n++;
  const int n_stages = 2 * n - 1;
  const int64_t bytes_per_stage = (N + 7) >> 3;
  std::memset(masks_packed, 0,
              static_cast<size_t>(n_stages) * bytes_per_stage);

  // forward[p] = q: element at input p must reach output q. The cycle
  // walk is cache-miss-bound at large N, so the 2-coloring state rides
  // in the TOP BITS of the fwd entries (bit 31 = colored, bit 30 =
  // color) instead of a separate halves[] array — one cacheline per
  // random access where there used to be two. Requires N < 2^30.
  if (N >= (int64_t{1} << 30)) return 1;
  constexpr uint32_t kColored = 0x80000000u;
  constexpr uint32_t kColor = 0x40000000u;
  constexpr uint32_t kValue = 0x3FFFFFFFu;
  std::vector<uint32_t> fwd(N, kValue), nxt(N);
  std::vector<int32_t> inv(N);
  for (int64_t i = 0; i < N; i++) {
    if (perm[i] < 0 || perm[i] >= N) return 1;
    if (fwd[perm[i]] != kValue) return 1;  // duplicate: not a bijection
    fwd[perm[i]] = static_cast<uint32_t>(i);
  }

  for (int level = 0; level < n - 1; level++) {
    const int64_t B = N >> level;
    const int64_t h = B >> 1;
    uint8_t* in_bits = masks_packed + int64_t(level) * bytes_per_stage;
    uint8_t* out_bits =
        masks_packed + int64_t(n_stages - 1 - level) * bytes_per_stage;
    for (int64_t base = 0; base < N; base += B) {
      uint32_t* f = fwd.data() + base;
      int32_t* iv = inv.data() + base;
      for (int64_t i = 0; i < B; i++)
        iv[f[i] & kValue] = static_cast<int32_t>(i);
      for (int64_t start = 0; start < B; start++) {
        if (f[start] & kColored) continue;
        int64_t i = start;
        uint32_t color = 0;  // 0 = top half, kColor = bottom half
        while (!(f[i] & kColored)) {
          f[i] |= kColored | color;
          const int64_t ip = i ^ h;  // input partner: the other half
          const uint32_t fip = f[ip];
          if (!(fip & kColored)) f[ip] = fip | kColored | (color ^ kColor);
          // ip's output partner: the element sharing ip's output pair
          const int64_t op_out = int64_t(f[ip] & kValue) ^ h;
          i = iv[op_out];
          color = (f[ip] & kColor) ^ kColor;
        }
      }
      // IN stage: element at local input i routed to half color(i); the
      // pair (i, i+h) swaps iff the element in the top slot goes bottom.
      for (int64_t i = 0; i < B; i++) {
        const bool bottom = (f[i] & kColor) != 0;
        set_bit(in_bits, base + i, bottom == (i < h));
      }
      // OUT stage: output o receives its element from half color(iv[o]).
      for (int64_t o = 0; o < B; o++) {
        const bool bottom = (f[iv[o]] & kColor) != 0;
        set_bit(out_bits, base + o, bottom == (o < h));
      }
      // Sub-permutations (forward form, local to each half; color and
      // colored bits are consumed here, nxt starts clean).
      uint32_t* top = nxt.data() + base;
      uint32_t* bot = nxt.data() + base + h;
      for (int64_t i = 0; i < B; i++) {
        const int64_t slot = i & (h - 1);
        const uint32_t val =
            static_cast<uint32_t>(int64_t(f[i] & kValue) & (h - 1));
        if (f[i] & kColor)
          bot[slot] = val;
        else
          top[slot] = val;
      }
    }
    fwd.swap(nxt);
  }
  // middle level: blocks of 2
  uint8_t* mid = masks_packed + int64_t(n - 1) * bytes_per_stage;
  for (int64_t base = 0; base < N; base += 2) {
    const bool sw = (fwd[base] & kValue) == 1;
    set_bit(mid, base, sw);
    set_bit(mid, base + 1, sw);
  }
  return 0;
}

}  // extern "C"
