// Benes permutation-network router.
//
// Computes the per-stage swap masks that realize a fixed permutation on a
// power-of-two array as 2*log2(N)-1 masked-swap stages (the TPU-native
// "scatter" used by memgraph_tpu/ops/spmv_mxu.py; algorithm documented in
// memgraph_tpu/ops/benes.py, which holds the pure-python reference
// implementation). The classic looping algorithm: at every level, elements
// paired at the input stage and elements paired at the output stage form
// even cycles; 2-coloring each cycle assigns elements to the top/bottom
// half-network. O(N log N) total.
//
// Masks are bit-packed MSB-first per byte to match numpy.packbits.
//
// Build: part of libcsr_builder.so (see Makefile).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline void set_bit(uint8_t* bits, int64_t i, bool v) {
  if (v) bits[i >> 3] |= static_cast<uint8_t>(0x80u >> (i & 7));
}

}  // namespace

extern "C" {

// perm: gather form — output position i receives input position perm[i].
// masks_packed: caller-allocated (2*log2(N)-1) * (N/8) bytes, zeroed here.
// Returns 0 on success, 1 on invalid arguments.
int benes_route(const int64_t* perm, int64_t N, uint8_t* masks_packed) {
  if (N < 2 || (N & (N - 1))) return 1;
  int n = 0;
  while ((int64_t{1} << n) < N) n++;
  const int n_stages = 2 * n - 1;
  const int64_t bytes_per_stage = (N + 7) >> 3;
  std::memset(masks_packed, 0,
              static_cast<size_t>(n_stages) * bytes_per_stage);

  // forward[p] = q: element at input p must reach output q.
  std::vector<int32_t> fwd(N, -1), nxt(N), inv(N);
  std::vector<int8_t> halves(N);
  for (int64_t i = 0; i < N; i++) {
    if (perm[i] < 0 || perm[i] >= N) return 1;
    if (fwd[perm[i]] >= 0) return 1;  // duplicate: not a bijection
    fwd[perm[i]] = static_cast<int32_t>(i);
  }

  for (int level = 0; level < n - 1; level++) {
    const int64_t B = N >> level;
    const int64_t h = B >> 1;
    uint8_t* in_bits = masks_packed + int64_t(level) * bytes_per_stage;
    uint8_t* out_bits =
        masks_packed + int64_t(n_stages - 1 - level) * bytes_per_stage;
    for (int64_t base = 0; base < N; base += B) {
      int32_t* f = fwd.data() + base;
      int32_t* iv = inv.data() + base;
      int8_t* hv = halves.data() + base;
      for (int64_t i = 0; i < B; i++) iv[f[i]] = static_cast<int32_t>(i);
      std::memset(hv, -1, B);
      for (int64_t start = 0; start < B; start++) {
        if (hv[start] >= 0) continue;
        int64_t i = start;
        int8_t color = 0;
        while (hv[i] < 0) {
          hv[i] = color;
          const int64_t ip = i ^ h;  // input partner
          if (hv[ip] < 0) hv[ip] = color ^ 1;
          const int64_t op_out = int64_t(f[ip]) ^ h;  // ip's output partner
          i = iv[op_out];
          color = hv[ip] ^ 1;
        }
      }
      // IN stage: element at local input i routed to half hv[i]; the pair
      // (i, i+h) swaps iff the element in the top slot goes bottom.
      for (int64_t i = 0; i < B; i++) {
        const bool swap_in = (hv[i] == 1) == (i < h);
        set_bit(in_bits, base + i, swap_in);
      }
      // OUT stage: output o receives its element from half hv[iv[o]].
      for (int64_t o = 0; o < B; o++) {
        const bool swap_out = (hv[iv[o]] == 1) == (o < h);
        set_bit(out_bits, base + o, swap_out);
      }
      // Sub-permutations (forward form, local to each half).
      int32_t* top = nxt.data() + base;
      int32_t* bot = nxt.data() + base + h;
      for (int64_t i = 0; i < B; i++) {
        const int64_t slot = i & (h - 1);
        if (hv[i] == 0)
          top[slot] = static_cast<int32_t>(int64_t(f[i]) & (h - 1));
        else
          bot[slot] = static_cast<int32_t>(int64_t(f[i]) & (h - 1));
      }
    }
    fwd.swap(nxt);
  }
  // middle level: blocks of 2
  uint8_t* mid = masks_packed + int64_t(n - 1) * bytes_per_stage;
  for (int64_t base = 0; base < N; base += 2) {
    const bool sw = fwd[base] == 1;
    set_bit(mid, base, sw);
    set_bit(mid, base + 1, sw);
  }
  return 0;
}

}  // extern "C"
