/* Native query-module C ABI.
 *
 * Role parity with the reference's module ABI
 * (/root/reference/include/mg_procedure.h — mgp_graph view in,
 * mgp_result_record stream out, dlopen'd registration), re-designed for
 * this framework's TPU-first architecture: instead of a pointer-chasing
 * graph view, native modules receive the SAME padded CSR/CSC snapshot the
 * device kernels consume — zero-copy int32/float32 arrays. The host passes
 * a vtable (mgtpu_host_api) at load time; the module registers procedures
 * through it and streams result rows through mgtpu_result callbacks.
 *
 * A module implements:
 *     int mgtpu_init_module(const mgtpu_host_api *api, void *registry);
 * returning 0 on success.
 */

#ifndef MEMGRAPH_TPU_MG_PROCEDURE_H
#define MEMGRAPH_TPU_MG_PROCEDURE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct mgtpu_graph mgtpu_graph;    /* opaque: one CSR snapshot   */
typedef struct mgtpu_result mgtpu_result;  /* opaque: row stream builder */

/* Zero-copy CSR view of the current graph snapshot (see
 * memgraph_tpu/ops/csr.py for the layout contract: (src,dst)-sorted CSR,
 * (dst,src)-sorted CSC, sink-row padding). Arrays remain owned by the
 * host and are valid for the duration of the procedure call. */
typedef struct mgtpu_csr_view {
  int64_t n_nodes;        /* real vertex count                   */
  int64_t n_edges;        /* real edge count                     */
  int64_t n_pad;          /* padded vertex rows (>= n_nodes + 1) */
  int64_t e_pad;          /* padded edge slots                   */
  const int32_t *row_ptr; /* [n_pad + 1] CSR offsets             */
  const int32_t *col_idx; /* [e_pad] CSR destinations            */
  const int32_t *csr_src; /* [e_pad] CSR sources                 */
  const float *weights;   /* [e_pad] edge weights (0 = padding)  */
  const int32_t *csc_src; /* [e_pad] CSC sources                 */
  const int32_t *csc_dst; /* [e_pad] CSC destinations            */
  const int64_t *node_gids; /* [n_nodes] dense index -> storage gid */
} mgtpu_csr_view;

/* Procedure callback: compute over the view, emit rows via `result`.
 * Return 0 on success, nonzero to signal an error (use set_error). */
typedef int (*mgtpu_proc_cb)(const mgtpu_csr_view *view,
                             mgtpu_result *result, void *host_ctx);

typedef struct mgtpu_host_api {
  /* registration (call during mgtpu_init_module):
   *   name:    dotted procedure name, e.g. "c_degree.get"
   *   results: comma list of "field:TYPE" with TYPE in
   *            {INT, DOUBLE, STRING, NODE} — NODE fields are set with
   *            result_set_node from a dense vertex index */
  int (*register_procedure)(void *registry, const char *name,
                            mgtpu_proc_cb cb, const char *results);

  /* result streaming */
  int (*result_new_record)(mgtpu_result *result);
  int (*result_set_int)(mgtpu_result *result, const char *field,
                        int64_t value);
  int (*result_set_double)(mgtpu_result *result, const char *field,
                           double value);
  int (*result_set_string)(mgtpu_result *result, const char *field,
                           const char *value);
  int (*result_set_node)(mgtpu_result *result, const char *field,
                         int64_t dense_index);
  int (*result_set_error)(mgtpu_result *result, const char *message);
} mgtpu_host_api;

/* Entry point every native module must export. */
int mgtpu_init_module(const mgtpu_host_api *api, void *registry);

#ifdef __cplusplus
}
#endif

#endif /* MEMGRAPH_TPU_MG_PROCEDURE_H */
