"""mgtrace smoke: one traced query end-to-end, validated Chrome export.

The gate stage (`tools/gate.sh`) that proves the tracing plane actually
produces a CONNECTED trace and a loadable Chrome-trace-event export:

  1. arm the tracer (sample=1.0),
  2. run real Cypher through a real Interpreter (parse → plan → execute
     → MVCC commit) plus a mesh-routed analytics call (mesh-of-1
     degeneracy — the identical sharded path a TPU pod runs) under the
     same trace,
  3. assert every expected span family appears, all spans share one
     trace_id, and every parent link resolves,
  4. export Chrome-trace JSON and validate it structurally (the format
     Perfetto/chrome://tracing parses).

Exit 0 only if every check passes. Writes the export next to nothing —
pass --out to keep it for manual inspection.
"""

from __future__ import annotations

import argparse
import json
import sys


def fail(msg: str) -> None:
    print(f"trace-smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="write the Chrome-trace JSON here")
    args = ap.parse_args()

    from memgraph_tpu.observability import trace as T
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage

    T.enable(sample=1.0)
    interp = Interpreter(InterpreterContext(InMemoryStorage()))
    interp.execute(
        "UNWIND range(0, 63) AS i CREATE (:N {v: i})")
    interp.execute(
        "MATCH (a:N), (b:N) WHERE b.v = a.v + 1 OR b.v = a.v * 2 "
        "CREATE (a)-[:E]->(b)")

    # mesh-routed analytics under the same trace: the device stages
    # (transfer + chunked iterate) must join the query's trace exactly
    # as a kernel-server dispatch would
    handle = T.begin_trace("query")
    with T.activate(handle.ctx):
        import numpy as np
        from memgraph_tpu.ops import csr
        from memgraph_tpu.parallel import analytics
        from memgraph_tpu.parallel.mesh import get_mesh_context
        rng = np.random.default_rng(0)
        src = rng.integers(0, 64, 512)
        dst = rng.integers(0, 64, 512)
        graph = csr.from_coo(src, dst, n_nodes=64)
        ranks, err, iters = analytics.pagerank_mesh(
            graph, get_mesh_context(1), max_iterations=10,
            checkpoint_every=4)
    handle.finish(status="ok")
    if len(ranks) != 64 or int(iters) < 1:
        fail(f"analytics smoke returned ranks={len(ranks)} iters={iters}")

    traces = T.traces_json()
    if len(traces) < 3:
        fail(f"expected >=3 retained traces, got {len(traces)}")

    want_query = {"query", "query.parse", "query.plan", "query.execute",
                  "query.commit", "mvcc.begin", "mvcc.commit"}
    got_query = {s["name"] for s in traces[0]}
    if not want_query <= got_query:
        fail(f"query trace missing spans: {want_query - got_query}")

    device_trace = traces[-1]
    got_device = {s["name"] for s in device_trace}
    if not {"query", "device.transfer", "device.chunk"} <= got_device:
        fail(f"device trace missing spans: got {got_device}")

    for spans in traces:
        ids = {s["span_id"] for s in spans}
        tids = {s["trace_id"] for s in spans}
        if len(tids) != 1:
            fail(f"trace mixes trace_ids: {tids}")
        dangling = [s["name"] for s in spans
                    if s["parent_id"] and s["parent_id"] not in ids]
        if dangling:
            fail(f"dangling parent links: {dangling}")
        roots = [s for s in spans if not s["parent_id"]]
        if len(roots) != 1:
            fail(f"expected exactly one root span, got "
                 f"{[s['name'] for s in roots]}")

    doc = T.chrome_trace()
    encoded = json.dumps(doc)
    parsed = json.loads(encoded)
    events = parsed.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail("chrome export has no traceEvents")
    for ev in events:
        for key in ("name", "cat", "ph", "ts", "dur", "pid", "tid",
                    "args"):
            if key not in ev:
                fail(f"chrome event missing {key!r}: {ev}")
        if ev["ph"] != "X":
            fail(f"expected complete ('X') events, got {ev['ph']!r}")
        if not (isinstance(ev["ts"], (int, float)) and ev["ts"] > 0):
            fail(f"bad ts in {ev}")
        if not (isinstance(ev["dur"], (int, float)) and ev["dur"] > 0):
            fail(f"bad dur in {ev}")
        if "trace_id" not in ev["args"]:
            fail(f"chrome event args missing trace_id: {ev}")

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(encoded)
        print(f"trace-smoke: wrote {len(events)} events to {args.out}")

    print(f"trace-smoke: OK — {len(traces)} traces, {len(events)} "
          "chrome events, all parent links resolve")


if __name__ == "__main__":
    main()
