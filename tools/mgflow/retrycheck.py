"""Unsafe-retry check (the MG013 core).

A **retry region** is a ``for _ in <policy>.attempts():`` loop or a
``<policy>.call(fn, ...)`` expression, where the policy receiver is
named like a retry policy ("retry"/"policy" in its dotted text) or is a
direct ``RetryPolicy(...)`` construction. Every region must be
classified in ``utils/retry.py``'s ``IDEMPOTENCY`` registry by the
qualname of the operation it implements (the enclosing function, or a
callee resolved inside the loop):

  * unclassified region            -> finding (classify it)
  * region swallows class C where
    IDEMPOTENCY[C] == "unsafe"     -> finding (the oom/shed rule:
                                      deterministic outcomes are never
                                      retried)
  * region op is "unsafe" and it
    swallows C not registered
    "retryable"                    -> finding (blind re-send of a
                                      non-idempotent op)
  * registry entry matched by
    nothing                        -> finding (dead registration)

"Swallows" means an except handler inside an ``attempts()`` loop whose
body contains no ``raise`` (the attempt loop continues), or the
``retry_on=`` classes of a ``.call`` region (default
ConnectionError/OSError). A handler that re-raises — even
conditionally — is treated as surfacing, which under-approximates
swallowing; the justified leftovers carry baseline entries instead.
"""

from __future__ import annotations

import ast

from ..mglint.core import Finding, Project, qualname_of
from ..mglint.locking import dotted, get_model
from .spec import FlowSpec, extract_specs


def _is_policy_recv(node) -> bool:
    name = dotted(node)
    if name and ("retry" in name.lower() or "policy" in name.lower()):
        return True
    return isinstance(node, ast.Call) and \
        (dotted(node.func) or "").split(".")[-1] == "RetryPolicy"


def _qual_matches(qualname: str, key: str) -> bool:
    """Do the key's dotted segments appear contiguously in qualname's?
    ("ShardedClient.scatter_read" matches the nested
    "ShardedClient.scatter_read.one")."""
    q = qualname.split(".")
    k = key.split(".")
    n = len(k)
    return any(q[i:i + n] == k for i in range(len(q) - n + 1))


def _handler_tokens(handler: ast.ExceptHandler) -> list[str]:
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for el in elts:
        name = dotted(el)
        if name:
            out.append(name.split(".")[-1])
    return out


def _body_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


class _Region:
    def __init__(self, rel, line, qualname, kind):
        self.rel = rel
        self.line = line
        self.qualname = qualname
        self.kind = kind              # "attempts" | "call"
        self.callee_quals: list[str] = []
        self.swallowed: list[tuple[str, int]] = []   # (token, line)
        self.handled: set[str] = set()


def _collect_regions(project: Project) -> list[_Region]:
    model = get_model(project)
    regions = []
    for rel, sf in sorted(project.files.items()):
        sf.ensure_parents()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.For) and \
                    isinstance(node.iter, ast.Call) and \
                    isinstance(node.iter.func, ast.Attribute) and \
                    node.iter.func.attr == "attempts" and \
                    _is_policy_recv(node.iter.func.value):
                regions.append(_attempts_region(model, rel, sf, node))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "call" and \
                    _is_policy_recv(node.func.value):
                regions.append(_call_region(model, rel, sf, node))
    return regions


def _enclosing_info(sf, node):
    """(qualname, class name) of the function enclosing `node`."""
    qual = qualname_of(node) or "<module>"
    cls = None
    cur = getattr(node, "_mglint_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            cls = cur.name
            break
        cur = getattr(cur, "_mglint_parent", None)
    return qual, cls


def _attempts_region(model, rel, sf, node: ast.For) -> _Region:
    qual, cls = _enclosing_info(sf, node)
    region = _Region(rel, node.lineno, qual, "attempts")
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            target = model._resolve_call(sub, rel, cls)
            if target is not None:
                region.callee_quals.append(
                    model.functions[target].qualname)
        elif isinstance(sub, ast.ExceptHandler):
            tokens = _handler_tokens(sub)
            region.handled.update(tokens)
            if not _body_raises(sub):
                region.swallowed.extend(
                    (t, sub.lineno) for t in tokens)
    return region


def _call_region(model, rel, sf, node: ast.Call) -> _Region:
    qual, cls = _enclosing_info(sf, node)
    region = _Region(rel, node.lineno, qual, "call")
    if node.args:
        pseudo = ast.Call(func=node.args[0], args=[], keywords=[])
        ast.copy_location(pseudo, node)
        target = model._resolve_call(pseudo, rel, cls)
        if target is not None:
            region.callee_quals.append(model.functions[target].qualname)
    retry_on = ("ConnectionError", "OSError")
    for kw in node.keywords:
        if kw.arg == "retry_on":
            elts = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            retry_on = tuple((dotted(el) or "?").split(".")[-1]
                             for el in elts)
    region.swallowed.extend((t, node.lineno) for t in retry_on)
    region.handled.update(retry_on)
    return region


def check_retries(project: Project,
                  spec: FlowSpec | None = None) -> list[Finding]:
    if spec is None:
        spec = extract_specs(project)
    if not spec.idempotency:
        return []
    entries = spec.idem_by_name
    op_keys = {n for n in entries if n not in _class_names(entries)}
    class_keys = _class_names(entries)

    used: set[str] = set()
    findings = []
    for region in _collect_regions(project):
        # classify: enclosing qualname first, then resolved callees
        matched = [k for k in op_keys
                   if _qual_matches(region.qualname, k)]
        for cq in region.callee_quals:
            matched.extend(k for k in op_keys if _qual_matches(cq, k))
        used.update(matched)
        if not matched:
            findings.append(Finding(
                rule="MG013", path=region.rel, line=region.line, col=0,
                symbol=region.qualname,
                message=f"retry region in {region.qualname} matches no "
                        "operation entry of utils/retry.py IDEMPOTENCY "
                        "— classify it 'retryable' (idempotent, blind "
                        "re-send safe) or 'unsafe'",
                fingerprint=f"unclassified:{region.qualname}"))
            continue
        op_unsafe = any(entries[k].classification == "unsafe"
                        for k in matched)
        used.update(c for c in region.handled if c in class_keys)
        for token, line in region.swallowed:
            entry = entries.get(token)
            if entry is not None and entry.classification == "unsafe":
                findings.append(Finding(
                    rule="MG013", path=region.rel, line=line, col=0,
                    symbol=region.qualname,
                    message=f"{region.qualname} retries after "
                            f"swallowing {token}, registered 'unsafe' "
                            "in IDEMPOTENCY — this outcome is "
                            "deterministic against the current state; "
                            "retrying it is a storm, surface it",
                    fingerprint=f"retry-unsafe-class:"
                                f"{region.qualname}:{token}"))
            elif op_unsafe and (entry is None or
                                entry.classification != "retryable"):
                findings.append(Finding(
                    rule="MG013", path=region.rel, line=line, col=0,
                    symbol=region.qualname,
                    message=f"{region.qualname} is registered 'unsafe' "
                            f"(non-idempotent) but swallows {token} "
                            "and re-sends — only classes registered "
                            "'retryable' (pre-apply bounces) may be "
                            "retried here; surface the rest typed",
                    fingerprint=f"blind-retry:"
                                f"{region.qualname}:{token}"))
    for name, entry in sorted(entries.items()):
        if name not in used:
            findings.append(Finding(
                rule="MG013", path=entry.decl_rel, line=entry.decl_line,
                col=0, symbol="IDEMPOTENCY",
                message=f"IDEMPOTENCY entry {name!r} matches no retry "
                        "region or handled exception class — dead "
                        "registration, the classification guards "
                        "nothing",
                fingerprint=f"idem-unused:{name}"))
    return findings


def _class_names(entries: dict) -> set[str]:
    """Entries naming exception classes rather than operations: no dot,
    CamelCase-looking (matches the taxonomy's naming)."""
    return {n for n in entries
            if "." not in n and n[:1].isupper() and "_" not in n}
