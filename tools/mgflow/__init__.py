"""mgflow: interprocedural exception-flow & typed-outcome contract
checker for the serving planes.

Three machine checks over the shared mglint AST/call-resolution infra:

1. **Escape contracts** — per serving root declared in
   ``memgraph_tpu/flowspec.py`` (``SERVING_ROOTS``), the escape set of
   exception types reachable through the call graph must be covered by
   the root's ``raises`` contract (subclasses covered by bases).
2. **Outcome-protocol drift** — every typed outcome string a server
   emits on a declared wire (``WIRES``) must have a client-side
   decoder, and every decoder must decode something a server can emit.
3. **Registry hygiene** — dead ``SERVING_ROOTS`` entries (the function
   moved) and unused ``IDEMPOTENCY`` entries fail, so the registries
   can only shrink honestly.

Accepted violations live in ``tools/mgflow/baseline.json`` with the
same justification-required discipline as mglint: unused entries fail.

    python -m tools.mgflow check       # exit 0 clean / 1 violations /
                                       # 2 bad invocation
    python -m tools.mgflow list        # roots + contracts + wires
"""
