"""AST extraction of the flow registries from a linted project.

The analyzers never import product code: ``SERVING_ROOTS`` / ``WIRES``
(memgraph_tpu/flowspec.py) and ``IDEMPOTENCY`` (utils/retry.py) are
read back out of the scanned ASTs, the same way MG005 reads
``KNOWN_POINTS``. That keeps the tools runnable on a tree that does not
import (the whole point of a lint gate) and lets lint fixtures declare
their own miniature registries next to the code under test.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from ..mglint.core import Project


@dataclass(frozen=True)
class RootSpec:
    root_id: str
    path: str
    qualname: str
    raises: tuple
    why: str
    decl_rel: str
    decl_line: int


@dataclass(frozen=True)
class WireSideSpec:
    path: str
    scope: tuple
    extract: tuple


@dataclass(frozen=True)
class WireSpec:
    wire_id: str
    server: tuple
    client: tuple
    declared: tuple | None
    handled_inline: tuple
    decl_rel: str
    decl_line: int


@dataclass(frozen=True)
class IdemEntry:
    name: str
    classification: str          # "retryable" | "unsafe"
    decl_rel: str
    decl_line: int


@dataclass
class FlowSpec:
    roots: list = field(default_factory=list)       # [RootSpec]
    wires: list = field(default_factory=list)       # [WireSpec]
    idempotency: list = field(default_factory=list)  # [IdemEntry]

    @property
    def idem_by_name(self) -> dict:
        return {e.name: e for e in self.idempotency}


def _const(node):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _call_kwargs(call: ast.Call, fields: tuple) -> dict:
    """Positional + keyword args of a dataclass-style literal call,
    resolved against the declared field order. Non-literal values come
    back as the raw AST node."""
    out = {}
    for i, arg in enumerate(call.args):
        if i < len(fields):
            out[fields[i]] = arg
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = kw.value
    return out


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


_ROOT_FIELDS = ("root_id", "path", "qualname", "raises", "why")
_SIDE_FIELDS = ("path", "scope", "extract")
_WIRE_FIELDS = ("wire_id", "server", "client", "declared",
                "handled_inline")


def _extract_root(call: ast.Call, rel: str) -> RootSpec | None:
    kw = _call_kwargs(call, _ROOT_FIELDS)
    root_id = _const(kw.get("root_id"))
    path = _const(kw.get("path"))
    qualname = _const(kw.get("qualname"))
    if not (isinstance(root_id, str) and isinstance(path, str)
            and isinstance(qualname, str)):
        return None
    raises = _const(kw.get("raises")) if "raises" in kw else ()
    why = _const(kw.get("why")) if "why" in kw else ""
    return RootSpec(root_id=root_id, path=path, qualname=qualname,
                    raises=tuple(raises or ()),
                    why=why if isinstance(why, str) else "",
                    decl_rel=rel, decl_line=call.lineno)


def _extract_side(node) -> WireSideSpec | None:
    if not isinstance(node, ast.Call) or \
            _call_name(node) != "WireSide":
        return None
    kw = _call_kwargs(node, _SIDE_FIELDS)
    path = _const(kw.get("path"))
    if not isinstance(path, str):
        return None
    scope = _const(kw.get("scope")) if "scope" in kw else ()
    extract = _const(kw.get("extract")) if "extract" in kw else ()
    return WireSideSpec(path=path, scope=tuple(scope or ()),
                        extract=tuple(tuple(d) for d in (extract or ())))


def _extract_wire(call: ast.Call, rel: str) -> WireSpec | None:
    kw = _call_kwargs(call, _WIRE_FIELDS)
    wire_id = _const(kw.get("wire_id"))
    if not isinstance(wire_id, str):
        return None

    def sides(node):
        out = []
        if isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                side = _extract_side(el)
                if side is not None:
                    out.append(side)
        return tuple(out)

    declared = _const(kw.get("declared")) if "declared" in kw else None
    inline = _const(kw.get("handled_inline")) \
        if "handled_inline" in kw else ()
    return WireSpec(wire_id=wire_id,
                    server=sides(kw.get("server")),
                    client=sides(kw.get("client")),
                    declared=tuple(declared) if declared else None,
                    handled_inline=tuple(inline or ()),
                    decl_rel=rel, decl_line=call.lineno)


def extract_specs(project: Project) -> FlowSpec:
    """Pull every registry declaration out of the scanned tree."""
    spec = FlowSpec()
    for rel, sf in sorted(project.files.items()):
        for stmt in sf.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            name = stmt.targets[0].id
            if name == "SERVING_ROOTS" and \
                    isinstance(stmt.value, (ast.Tuple, ast.List)):
                for el in stmt.value.elts:
                    if isinstance(el, ast.Call) and \
                            _call_name(el) == "ServingRoot":
                        root = _extract_root(el, rel)
                        if root is not None:
                            spec.roots.append(root)
            elif name == "WIRES" and \
                    isinstance(stmt.value, (ast.Tuple, ast.List)):
                for el in stmt.value.elts:
                    if isinstance(el, ast.Call) and \
                            _call_name(el) == "Wire":
                        wire = _extract_wire(el, rel)
                        if wire is not None:
                            spec.wires.append(wire)
            elif name == "IDEMPOTENCY" and \
                    isinstance(stmt.value, ast.Dict):
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    key = _const(k)
                    val = _const(v)
                    if isinstance(key, str) and isinstance(val, str):
                        spec.idempotency.append(IdemEntry(
                            name=key, classification=val,
                            decl_rel=rel,
                            decl_line=getattr(k, "lineno",
                                              stmt.lineno)))
    return spec
