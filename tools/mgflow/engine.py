"""Interprocedural escape-set computation.

For every function in the project, compute the set of exception *type
tokens* that can propagate out of a call to it: explicit ``raise``
sites plus a deliberately small curated list of known-raising stdlib
calls, closed over the (conservative, mglint-shared) call graph, and
narrowed by ``except`` clauses, re-raises, exception aliases
(``except X as e: last = e`` … ``raise last``), dynamic dict-of-classes
raises (the ``_OUTCOME_ERRORS`` pattern) and ``RetryPolicy.call(fn)``
wrappers (treated as a call to ``fn`` — exhaustion re-raises, so no
narrowing).

Call resolution reuses ``tools.mglint.locking.LockModel`` — same-module
functions, ``self.method``, imported symbols and project-unique method
names; anything ambiguous contributes nothing. The result therefore
*under*-approximates reachable raises but never invents one, while the
except-narrowing *over*-approximates catches (a handler is assumed to
handle unless it re-raises into scope we track). Both biases push the
same direction: a reported escape is real enough to need a contract
entry, and silence is not proof — which is exactly the right shape for
a gate (no false alarms, honest about coverage).

Tokens are class names ("FencedException"), dotted stdlib names that
are not plain builtins ("struct.error"), or the sentinel "<unknown>"
for raises we cannot resolve (dynamic, computed) — unknown escapes must
be contracted or baselined explicitly, never ignored.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from ..mglint.core import Project
from ..mglint.locking import LockModel, dotted, get_model

#: exceptions deriving from BaseException only — NOT caught by
#: ``except Exception``
BASE_ONLY = frozenset({"KeyboardInterrupt", "SystemExit", "GeneratorExit",
                       "BaseException"})

#: builtin exception hierarchy (child -> parent), enough to narrow the
#: except clauses this codebase actually writes
BUILTIN_BASES: dict[str, str] = {
    "Exception": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "BufferError": "Exception",
    "EOFError": "Exception",
    "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "LookupError": "Exception",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "MemoryError": "Exception",
    "NameError": "Exception",
    "UnboundLocalError": "NameError",
    "OSError": "Exception",
    "IOError": "OSError",
    "BlockingIOError": "OSError",
    "ChildProcessError": "OSError",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "FileExistsError": "OSError",
    "FileNotFoundError": "OSError",
    "InterruptedError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "PermissionError": "OSError",
    "ProcessLookupError": "OSError",
    "TimeoutError": "OSError",
    "ReferenceError": "Exception",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "StopIteration": "Exception",
    "StopAsyncIteration": "Exception",
    "SyntaxError": "Exception",
    "IndentationError": "SyntaxError",
    "TypeError": "Exception",
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "Warning": "Exception",
}

#: curated known-raising calls. Deliberately SMALL: the point is the
#: handful of syscall/codec entry points serving loops actually sit on,
#: not a model of the stdlib — implicit raises (KeyError/TypeError/...)
#: are out of scope by design or every escape set would be everything.
KNOWN_RAISES_DOTTED: dict[str, tuple[str, ...]] = {
    "open": ("OSError",),
    "os.fsync": ("OSError",),
    "os.replace": ("OSError",),
    "os.rename": ("OSError",),
    "os.unlink": ("OSError",),
    "os.kill": ("OSError",),
    "os.read": ("OSError",),
    "os.write": ("OSError",),
    "os.waitpid": ("ChildProcessError",),
    "json.loads": ("ValueError",),
    "json.dumps": ("ValueError",),
    "pickle.loads": ("ValueError",),
    "pickle.dumps": ("ValueError",),
    "socket.create_connection": ("OSError",),
    "struct.unpack": ("struct.error",),
    "struct.pack": ("struct.error",),
}
KNOWN_RAISES_METHODS: dict[str, tuple[str, ...]] = {
    "sendall": ("OSError",),
    "recv": ("OSError",),
    "recv_into": ("OSError",),
    "accept": ("OSError",),
    "makefile": ("OSError",),
    "readexactly": ("asyncio.IncompleteReadError",
                    "ConnectionResetError"),
}

UNKNOWN = "<unknown>"


@dataclass(frozen=True)
class Origin:
    """Witness site for an escaping token: where it is raised (or which
    known-raising call introduces it)."""

    rel_path: str
    line: int
    desc: str


class EscapeModel:
    """Per-function escape summaries, computed to fixpoint."""

    def __init__(self, project: Project):
        self.project = project
        self.model: LockModel = get_model(project)
        # class name -> base names (project classes; builtins separate)
        self._bases: dict[str, set[str]] = {}
        # (rel, dict name) -> exception-class tokens (module-level dicts
        # whose values are names resolving to exception classes)
        self._exc_dicts: dict[tuple[str, str], frozenset[str]] = {}
        self._collect_classes()
        self._collect_exc_dicts()
        # func key -> {token: Origin}
        self.escapes: dict[str, dict[str, Origin]] = {
            key: {} for key in self.model.functions}
        self._fixpoint()

    # --- class hierarchy -------------------------------------------------

    def _collect_classes(self) -> None:
        for rel, sf in self.project.files.items():
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    bases = set()
                    for b in node.bases:
                        name = dotted(b)
                        if name:
                            bases.add(name.split(".")[-1])
                    self._bases.setdefault(node.name, set()).update(bases)

    def _ancestors(self, token: str) -> set[str]:
        """Transitive base-class names of ``token`` (token included)."""
        out: set[str] = set()
        frontier = [token]
        while frontier:
            cur = frontier.pop()
            if cur in out:
                continue
            out.add(cur)
            frontier.extend(self._bases.get(cur, ()))
            parent = BUILTIN_BASES.get(cur)
            if parent:
                frontier.append(parent)
        return out

    def is_exception_class(self, name: str) -> bool:
        short = name.split(".")[-1]
        if short in BUILTIN_BASES or short == "BaseException":
            return True
        return "BaseException" in self._ancestors(short) or \
            "Exception" in self._ancestors(short)

    def covered_by(self, token: str, catcher: str) -> bool:
        """Does exception type ``token`` match catch/contract entry
        ``catcher`` (i.e. is it ``catcher`` or a subclass)?"""
        catcher = catcher.split(".")[-1] if "." not in token else catcher
        if catcher == "BaseException":
            return True
        if catcher == "Exception":
            return token not in BASE_ONLY
        if token == UNKNOWN:
            return False      # only broad handlers swallow the unknown
        if token == catcher:
            return True
        short = token.split(".")[-1]
        return catcher.split(".")[-1] in self._ancestors(short)

    def catches(self, token: str, handler_tokens: tuple[str, ...]) -> bool:
        if not handler_tokens:            # bare except:
            return True
        return any(self.covered_by(token, h) for h in handler_tokens)

    # --- dynamic dict-of-classes raises ----------------------------------

    def _collect_exc_dicts(self) -> None:
        for rel, sf in self.project.files.items():
            for stmt in sf.tree.body:
                if not (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Dict)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    continue
                tokens = set()
                ok = bool(stmt.value.values)
                for v in stmt.value.values:
                    name = dotted(v)
                    if name and self.is_exception_class(name):
                        tokens.add(name.split(".")[-1])
                    else:
                        ok = False
                        break
                if ok:
                    self._exc_dicts[(rel, stmt.targets[0].id)] = \
                        frozenset(tokens)

    # --- token extraction -------------------------------------------------

    def _type_tokens(self, node: ast.AST | None) -> tuple[str, ...]:
        """Tokens for an except-clause type expression (None = bare)."""
        if node is None:
            return ()
        if isinstance(node, ast.Tuple):
            out: list[str] = []
            for elt in node.elts:
                out.extend(self._type_tokens(elt))
            return tuple(out)
        name = dotted(node)
        if not name:
            return (UNKNOWN,)
        short = name.split(".")[-1]
        if short in BUILTIN_BASES or short == "BaseException" \
                or short in self._bases:
            return (short,)
        return (name,)       # dotted non-builtin, e.g. struct.error

    # --- per-function evaluation -----------------------------------------

    def _eval_function(self, key: str,
                       summaries: dict[str, dict[str, Origin]]
                       ) -> dict[str, Origin]:
        fi = self.model.functions[key]
        node = fi.node
        ctx = _EvalCtx(self, fi.rel_path, fi.class_name, summaries)
        body = getattr(node, "body", [])
        out = ctx.eval_body(body, caught=(), aliases={})
        return out


class _EvalCtx:
    """One function-body evaluation: tracks exception aliases and the
    caught-token stack for bare ``raise``."""

    def __init__(self, em: EscapeModel, rel: str, cls: str | None,
                 summaries: dict[str, dict[str, Origin]]):
        self.em = em
        self.rel = rel
        self.cls = cls
        self.summaries = summaries

    # -- helpers ----------------------------------------------------------

    def _merge(self, into: dict[str, Origin], token: str,
               origin: Origin) -> None:
        into.setdefault(token, origin)

    def _call_escapes(self, call: ast.Call, out: dict[str, Origin]) -> None:
        """Escapes contributed by one call expression."""
        name = dotted(call.func)
        line = call.lineno
        if name in KNOWN_RAISES_DOTTED:
            for tok in KNOWN_RAISES_DOTTED[name]:
                self._merge(out, tok, Origin(self.rel, line,
                                             f"call to {name}()"))
            return
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in KNOWN_RAISES_METHODS:
                for tok in KNOWN_RAISES_METHODS[attr]:
                    self._merge(out, tok, Origin(self.rel, line,
                                                 f"call to .{attr}()"))
                return
            # RetryPolicy.call(fn): exhaustion re-raises, so the wrapped
            # function's escapes pass through untouched
            if attr == "call" and call.args and \
                    isinstance(call.args[0], (ast.Name, ast.Attribute)):
                pseudo = ast.Call(func=call.args[0], args=[], keywords=[])
                ast.copy_location(pseudo, call)
                target = self.em.model._resolve_call(pseudo, self.rel,
                                                     self.cls)
                if target is not None:
                    for tok, origin in self.summaries.get(
                            target, {}).items():
                        self._merge(out, tok, origin)
                return
        target = self.em.model._resolve_call(call, self.rel, self.cls)
        if target is not None:
            for tok, origin in self.summaries.get(target, {}).items():
                self._merge(out, tok, origin)

    def _scan_calls(self, expr: ast.AST, out: dict[str, Origin]) -> None:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue          # deferred execution
            if isinstance(node, ast.Call):
                self._call_escapes(node, out)
            stack.extend(ast.iter_child_nodes(node))

    def _raise_tokens(self, stmt: ast.Raise, caught: tuple,
                      aliases: dict[str, frozenset[str]]
                      ) -> tuple[str, ...]:
        exc = stmt.exc
        if exc is None:                       # bare re-raise
            return tuple(caught[-1]) if caught else (UNKNOWN,)
        if isinstance(exc, ast.Call):
            fn = exc.func
            if isinstance(fn, ast.Name) and fn.id in aliases:
                return tuple(aliases[fn.id])  # raise cls(msg)
            name = dotted(fn)
            if name:
                short = name.split(".")[-1]
                if self.em.is_exception_class(name) or \
                        short in self.em._bases or \
                        short in BUILTIN_BASES:
                    toks = self.em._type_tokens(fn)
                    return toks
            return (UNKNOWN,)
        if isinstance(exc, ast.Name) and exc.id in aliases:
            return tuple(aliases[exc.id])     # raise last
        name = dotted(exc)
        if name:
            return self.em._type_tokens(exc)
        return (UNKNOWN,)

    # -- the walk ---------------------------------------------------------

    def eval_body(self, body, caught: tuple,
                  aliases: dict[str, frozenset[str]]) -> dict[str, Origin]:
        out: dict[str, Origin] = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue          # separate scope
            if isinstance(stmt, ast.Raise):
                # `raise X from e`: X is what propagates
                for tok in self._raise_tokens(stmt, caught, aliases):
                    self._merge(out, tok, Origin(
                        self.rel, stmt.lineno, "raise"))
                if stmt.exc is not None:
                    # args of X(...) may themselves call
                    self._scan_calls(stmt.exc, out)
                continue
            if isinstance(stmt, ast.Try) or (
                    hasattr(ast, "TryStar")
                    and isinstance(stmt, getattr(ast, "TryStar"))):
                self._eval_try(stmt, caught, aliases, out)
                continue
            if isinstance(stmt, ast.Assign):
                self._track_alias(stmt, aliases)
            # every other statement: evaluate expressions for calls,
            # then recurse into compound bodies with the same context
            for _name, value in ast.iter_fields(stmt):
                if isinstance(value, ast.expr):
                    self._scan_calls(value, out)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._scan_calls(v, out)
                        elif isinstance(v, ast.stmt):
                            for tok, origin in self.eval_body(
                                    [v], caught, aliases).items():
                                self._merge(out, tok, origin)
                        elif hasattr(v, "body") and \
                                isinstance(getattr(v, "body"), list):
                            # match_case, withitem-like carriers
                            for tok, origin in self.eval_body(
                                    v.body, caught, aliases).items():
                                self._merge(out, tok, origin)
        return out

    def _track_alias(self, stmt: ast.Assign,
                     aliases: dict[str, frozenset[str]]) -> None:
        """`x = e` (e a known exception alias) and `cls = DICT.get(..)` /
        `cls = DICT[..]` over a module-level dict of exception classes."""
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0],
                                                    ast.Name):
            return
        tgt = stmt.targets[0].id
        v = stmt.value
        if isinstance(v, ast.Name) and v.id in aliases:
            aliases[tgt] = aliases[v.id]
            return
        dict_name = None
        if isinstance(v, ast.Call) and \
                isinstance(v.func, ast.Attribute) and \
                v.func.attr == "get" and \
                isinstance(v.func.value, ast.Name):
            dict_name = v.func.value.id
        elif isinstance(v, ast.Subscript) and \
                isinstance(v.value, ast.Name):
            dict_name = v.value.id
        if dict_name is not None:
            toks = self.em._exc_dicts.get((self.rel, dict_name))
            if toks:
                aliases[tgt] = toks

    def _eval_try(self, stmt, caught: tuple,
                  aliases: dict[str, frozenset[str]],
                  out: dict[str, Origin]) -> None:
        # `try: ... finally: os._exit(...)` is a process-exit barrier
        # (the fork-child idiom): nothing propagates past it into the
        # enclosing (parent-side) control flow.
        if _finally_exits(stmt.finalbody):
            for tok, origin in self.eval_body(stmt.finalbody, caught,
                                              aliases).items():
                self._merge(out, tok, origin)
            return
        body_esc = self.eval_body(stmt.body, caught, aliases)
        remaining = dict(body_esc)
        for handler in stmt.handlers:
            h_tokens = self.em._type_tokens(handler.type)
            matched = {tok: origin for tok, origin in remaining.items()
                       if self.em.catches(tok, h_tokens)}
            for tok in matched:
                remaining.pop(tok, None)
            # what a bare `raise` in this handler re-raises: the
            # matched subset when we saw it, else the static spec
            caught_now = frozenset(matched) if matched else \
                frozenset(t for t in h_tokens if t != UNKNOWN)
            h_aliases = dict(aliases)
            if handler.name:
                h_aliases[handler.name] = caught_now or \
                    frozenset((UNKNOWN,))
            h_esc = self.eval_body(handler.body,
                                   caught + (caught_now,), h_aliases)
            # alias bindings made in the handler (last = e) must
            # survive for raises AFTER the try block
            for k, v in h_aliases.items():
                if k != handler.name:
                    aliases.setdefault(k, v)
            for tok, origin in h_esc.items():
                self._merge(out, tok, origin)
        for tok, origin in remaining.items():
            self._merge(out, tok, origin)
        # orelse runs only when the body did not raise; its escapes do
        # NOT pass through the handlers. finally always runs.
        for part in (stmt.orelse, stmt.finalbody):
            for tok, origin in self.eval_body(part, caught,
                                              aliases).items():
                self._merge(out, tok, origin)


def _finally_exits(finalbody) -> bool:
    for stmt in finalbody:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    dotted(node.func) in ("os._exit", "_exit"):
                return True
    return False


def _fixpoint_escapes(em: EscapeModel) -> None:
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for key in em.model.functions:
            new = em._eval_function(key, em.escapes)
            old = em.escapes[key]
            if set(new) - set(old):
                merged = dict(old)
                for tok, origin in new.items():
                    merged.setdefault(tok, origin)
                em.escapes[key] = merged
                changed = True


# bind late so the class body stays readable
EscapeModel._fixpoint = _fixpoint_escapes


def get_escape_model(project: Project) -> EscapeModel:
    """Escape model for a project, computed once and cached — the
    mglint MG012 rule and the mgflow CLI share one fixpoint run."""
    em = getattr(project, "_mgflow_escape_model", None)
    if em is None:
        em = EscapeModel(project)
        project._mgflow_escape_model = em
    return em


def resolve_root(project: Project, model: LockModel, path_suffix: str,
                 qualname: str) -> str | None:
    """Function key for a (path suffix, qualname) registry entry, or
    None when the entry is dead (file or function moved)."""
    for rel in project.files:
        if rel.endswith(path_suffix):
            key = f"{rel}::{qualname}"
            if key in model.functions:
                return key
    return None
