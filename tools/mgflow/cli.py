"""mgflow command line.

    python -m tools.mgflow check [paths...]   # gate: 0 clean /
                                              # 1 violations / 2 bad
                                              # invocation
    python -m tools.mgflow list  [paths...]   # roots + contracts +
                                              # wires + idempotency

`check` runs the escape-contract, protocol-drift and registry-hygiene
checks with the justification-required baseline discipline
(tools/mgflow/baseline.json); `list` prints the declared surface so a
reviewer can audit the contracts without reading the registry source.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..mglint.core import Project, load_baseline
from .contracts import check_contracts
from .engine import get_escape_model
from .protocol import check_wires
from .retrycheck import check_retries
from .spec import extract_specs

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.mgflow",
        description="exception-flow & typed-outcome contract checker")
    p.add_argument("command", choices=("check", "list"))
    p.add_argument("paths", nargs="*", default=["memgraph_tpu"],
                   help="directories to analyze (default: memgraph_tpu)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: tools/mgflow/"
                        "baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: show every finding")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON output")
    return p


def run_checks(project: Project):
    """All mgflow findings for a project (MG012 + MG013 + MGF-PROTO),
    suppression-comment filtered like run_rules."""
    spec = extract_specs(project)
    em = get_escape_model(project) if spec.roots else None
    findings = []
    findings.extend(check_contracts(project, spec, em))
    findings.extend(check_retries(project, spec))
    findings.extend(check_wires(project, spec))
    kept, suppressed = [], 0
    for f in findings:
        sf = project.files.get(f.path)
        if sf is not None and sf.is_suppressed(f.rule, f.line):
            suppressed += 1
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return spec, kept, suppressed


def _cmd_list(project: Project, as_json: bool) -> int:
    spec = extract_specs(project)
    if as_json:
        doc = {
            "roots": [{"root_id": r.root_id, "path": r.path,
                       "qualname": r.qualname,
                       "raises": list(r.raises), "why": r.why}
                      for r in spec.roots],
            "wires": [{"wire_id": w.wire_id,
                       "declared": list(w.declared or ()),
                       "handled_inline": list(w.handled_inline)}
                      for w in spec.wires],
            "idempotency": {e.name: e.classification
                            for e in spec.idempotency},
        }
        print(json.dumps(doc, indent=2))
        return 0
    print(f"serving roots ({len(spec.roots)}):")
    for r in spec.roots:
        contract = ", ".join(r.raises) if r.raises else "(total)"
        print(f"  {r.root_id:20s} {r.path}::{r.qualname}")
        print(f"  {'':20s} raises: {contract}")
        if r.why:
            print(f"  {'':20s} why: {r.why}")
    print(f"wires ({len(spec.wires)}):")
    for w in spec.wires:
        decl = "::".join(w.declared) if w.declared else "(emitted set)"
        inline = ", ".join(w.handled_inline) or "-"
        print(f"  {w.wire_id:20s} declared: {decl}  "
              f"inline: {inline}")
    print(f"idempotency ({len(spec.idempotency)}):")
    for e in spec.idempotency:
        print(f"  {e.classification:10s} {e.name}")
    return 0


def main(argv: list[str] | None = None) -> int:
    # intermixed: paths may follow options (`check --no-baseline dir`)
    args = build_parser().parse_intermixed_args(argv)
    project = Project(args.paths or ["memgraph_tpu"])
    if not project.files:
        print(f"mgflow: no Python files under {args.paths}",
              file=sys.stderr)
        return 2

    if args.command == "list":
        return _cmd_list(project, args.json)

    try:
        baseline = {} if args.no_baseline else \
            load_baseline(args.baseline)
    except (ValueError, OSError) as e:
        print(f"mgflow: broken baseline: {e}", file=sys.stderr)
        return 2

    spec, findings, suppressed = run_checks(project)
    unbaselined = [f for f in findings if f.key not in baseline]
    baselined = [f for f in findings if f.key in baseline]
    seen = {f.key for f in findings}
    unused = sorted(k for k in baseline if k not in seen)

    if args.json:
        doc = {
            "findings": [f.as_dict() for f in unbaselined],
            "baselined": [f.as_dict() for f in baselined],
            "suppressed": suppressed,
            "unused_baseline": unused,
            "parse_errors": project.errors,
            "roots": len(spec.roots),
            "wires": len(spec.wires),
        }
        print(json.dumps(doc, indent=2))
        return 1 if (unbaselined or unused or project.errors) else 0

    for err in project.errors:
        print(f"PARSE ERROR: {err}")
    for f in unbaselined:
        print(f.render())
    for key in unused:
        print(f"unused baseline entry (remove it): {key}")
    print(f"mgflow: {len(project.files)} files, {len(spec.roots)} "
          f"roots, {len(spec.wires)} wires — {len(unbaselined)} "
          f"finding(s), {len(baselined)} baselined, "
          f"{suppressed} suppressed, {len(unused)} unused baseline "
          "entr(ies)")
    return 1 if (unbaselined or unused or project.errors) else 0
