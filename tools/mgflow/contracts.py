"""Escape-contract check (the MG012 core).

For every ``SERVING_ROOTS`` entry declared in the scanned tree: resolve
the root function, compute its interprocedural escape set, and report
every token the ``raises=`` contract does not cover (subclass-aware) at
the witness raise site. A registry entry whose function no longer
exists is itself a finding — the registry can only shrink honestly.
"""

from __future__ import annotations

from ..mglint.core import Finding, Project
from .engine import EscapeModel, get_escape_model, resolve_root
from .spec import FlowSpec, extract_specs


def check_contracts(project: Project,
                    spec: FlowSpec | None = None,
                    em: EscapeModel | None = None) -> list[Finding]:
    if spec is None:
        spec = extract_specs(project)
    if not spec.roots:
        return []
    if em is None:
        em = get_escape_model(project)

    findings = []
    for root in spec.roots:
        key = resolve_root(project, em.model, root.path, root.qualname)
        if key is None:
            findings.append(Finding(
                rule="MG012", path=root.decl_rel, line=root.decl_line,
                col=0, symbol=root.root_id,
                message=f"serving root {root.root_id!r} "
                        f"({root.path}::{root.qualname}) resolves to no "
                        "function in the scanned tree — dead registry "
                        "entry, its contract guards nothing",
                fingerprint=f"dead-root:{root.root_id}"))
            continue
        rel = key.split("::", 1)[0]
        for token, origin in sorted(em.escapes[key].items()):
            if any(em.covered_by(token, c) for c in root.raises):
                continue
            contract = ", ".join(root.raises) if root.raises \
                else "(empty: the root must be total)"
            findings.append(Finding(
                rule="MG012", path=origin.rel_path, line=origin.line,
                col=0, symbol=root.root_id,
                message=f"{token} can escape serving root "
                        f"{root.root_id!r} ({rel}::{root.qualname}) "
                        f"via {origin.desc} but the declared contract "
                        f"is {contract} — handle it in the loop, add a "
                        "typed reply, or extend the contract",
                fingerprint=f"escape:{root.root_id}:{token}"))
    return findings
