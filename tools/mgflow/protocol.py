"""Typed-outcome protocol drift check (both directions, MG005-style).

For every ``WIRES`` entry: read the server-emitted vocabulary and the
client-decoded vocabulary straight out of the source (the ``extract``
directives documented on ``flowspec.WireSide``), then enforce

  server -> client:
    * every emitted outcome is in the declared vocabulary
    * every declared (or emitted) outcome has a client decoder — a
      literal comparison site — or is listed ``handled_inline``
  client -> server:
    * every decoded outcome is declared (no dead decoders: a decoder
      for an outcome no server can emit is drift that already happened)
    * every ``handled_inline`` value is declared

Extraction collects CONSTANTS only; an outcome shipped through a
variable is simply not collected (it cannot create a false positive,
and the declared-vocabulary direction still covers it).
"""

from __future__ import annotations

import ast

from ..mglint.core import Finding, Project, qualname_of
from ..mglint.locking import dotted
from .spec import FlowSpec, WireSideSpec, WireSpec, extract_specs


def _in_scope(node, scope: tuple) -> bool:
    if not scope:
        return True
    qual = qualname_of(node)
    return any(qual == s or qual.startswith(s + ".") for s in scope)


def _module_assign(sf, name: str):
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name:
            return stmt
    return None


def _extract_side(project: Project, side: WireSideSpec) -> dict:
    """{outcome: (rel, line)} — first site wins as the witness."""
    sf = project.by_suffix(side.path)
    if sf is None:
        return {}
    sf.ensure_parents()
    out: dict[str, tuple] = {}

    def add(value, line):
        if isinstance(value, str):
            out.setdefault(value, (sf.rel_path, line))

    for directive, arg in side.extract:
        if directive == "dict_keys":
            stmt = _module_assign(sf, arg)
            if stmt is not None and isinstance(stmt.value, ast.Dict):
                for k in stmt.value.keys:
                    if isinstance(k, ast.Constant):
                        add(k.value, k.lineno)
            continue
        if directive == "tuple_const":
            stmt = _module_assign(sf, arg)
            if stmt is not None and \
                    isinstance(stmt.value, (ast.Tuple, ast.List)):
                for el in stmt.value.elts:
                    if isinstance(el, ast.Constant):
                        add(el.value, el.lineno)
            continue
        for node in ast.walk(sf.tree):
            if not _in_scope(node, side.scope):
                continue
            if directive == "dict_value" and \
                    isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and \
                            k.value == arg and \
                            isinstance(v, ast.Constant):
                        add(v.value, v.lineno)
            elif directive == "send_tuple0" and \
                    isinstance(node, ast.Call) and \
                    (dotted(node.func) or "").split(".")[-1] == arg:
                for a in node.args:
                    if isinstance(a, ast.Tuple) and a.elts and \
                            isinstance(a.elts[0], ast.Constant):
                        add(a.elts[0].value, a.lineno)
            elif directive == "return_tuple0" and \
                    isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Tuple) and \
                    node.value.elts and \
                    isinstance(node.value.elts[0], ast.Constant):
                add(node.value.elts[0].value, node.lineno)
            elif directive == "compare" and \
                    isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if not any(_matches_var(op, arg) for op in operands):
                    continue
                for op in operands:
                    if isinstance(op, ast.Constant):
                        add(op.value, op.lineno)
                    elif isinstance(op, (ast.Tuple, ast.List,
                                         ast.Set)):
                        for el in op.elts:
                            if isinstance(el, ast.Constant):
                                add(el.value, el.lineno)
    return out


def _matches_var(node, var: str) -> bool:
    if var == "[0]":
        return isinstance(node, ast.Subscript) and \
            isinstance(node.slice, ast.Constant) and \
            node.slice.value == 0
    name = dotted(node)
    return bool(name) and name.split(".")[-1] == var


def _declared(project: Project, wire: WireSpec, emitted: dict) -> dict:
    if wire.declared is None:
        return dict(emitted)
    path, symbol = wire.declared
    sf = project.by_suffix(path)
    if sf is None:
        return dict(emitted)
    stmt = _module_assign(sf, symbol)
    out: dict[str, tuple] = {}
    if stmt is not None and isinstance(stmt.value,
                                       (ast.Tuple, ast.List)):
        for el in stmt.value.elts:
            if isinstance(el, ast.Constant) and \
                    isinstance(el.value, str):
                out.setdefault(el.value, (sf.rel_path, el.lineno))
    return out or dict(emitted)


def check_wires(project: Project,
                spec: FlowSpec | None = None) -> list[Finding]:
    if spec is None:
        spec = extract_specs(project)
    findings = []
    for wire in spec.wires:
        emitted: dict[str, tuple] = {}
        for side in wire.server:
            for v, site in _extract_side(project, side).items():
                emitted.setdefault(v, site)
        decoded: dict[str, tuple] = {}
        for side in wire.client:
            for v, site in _extract_side(project, side).items():
                decoded.setdefault(v, site)
        declared = _declared(project, wire, emitted)
        inline = set(wire.handled_inline)
        wid = wire.wire_id

        for v, (rel, line) in sorted(emitted.items()):
            if v not in declared:
                findings.append(Finding(
                    rule="MGF-PROTO", path=rel, line=line, col=0,
                    symbol=wid,
                    message=f"wire {wid!r}: server emits outcome {v!r} "
                            "missing from the declared vocabulary "
                            f"({'::'.join(wire.declared)})"
                            if wire.declared else
                            f"wire {wid!r}: server emits undeclared "
                            f"outcome {v!r}",
                    fingerprint=f"undeclared-emit:{wid}:{v}"))
        for v in sorted(set(declared) | set(emitted)):
            if v in decoded or v in inline:
                continue
            rel, line = declared.get(v) or emitted[v]
            findings.append(Finding(
                rule="MGF-PROTO", path=rel, line=line, col=0,
                symbol=wid,
                message=f"wire {wid!r}: outcome {v!r} has no client "
                        "decoder — the client would see it as a "
                        "generic failure, losing the typed taxonomy",
                fingerprint=f"undecoded:{wid}:{v}"))
        for v, (rel, line) in sorted(decoded.items()):
            if v not in declared:
                findings.append(Finding(
                    rule="MGF-PROTO", path=rel, line=line, col=0,
                    symbol=wid,
                    message=f"wire {wid!r}: client decodes outcome "
                            f"{v!r} that no server declares or emits — "
                            "dead decoder, the drift already happened",
                    fingerprint=f"dead-decoder:{wid}:{v}"))
        for v in sorted(inline):
            if v not in declared:
                findings.append(Finding(
                    rule="MGF-PROTO", path=wire.decl_rel,
                    line=wire.decl_line, col=0, symbol=wid,
                    message=f"wire {wid!r}: handled_inline value {v!r} "
                            "is not in the declared vocabulary",
                    fingerprint=f"inline-undeclared:{wid}:{v}"))
    return findings
