"""Gate smoke for the sharded OLTP execution plane (r18, mgshard):
spawn 4 shard workers, drive routed point reads/writes, one
scatter-gather read, one cross-shard 2PC transaction, one LIVE
shard-move under the same data, a worker kill + typed-error respawn,
and a clean shutdown.

Functional counterpart of the mgbench --shards group sized for the dev
gate (~seconds, fork-safe on any host): this proves the plane WORKS
everywhere; the bench proves it SCALES on multi-core hosts.

Usage: python -m tools.shard_smoke
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_SHARDS = 4
N_USERS = 100


def log(msg: str) -> None:
    print(f"shard-smoke: {msg}", flush=True)


def fail(msg: str) -> int:
    log(f"FAIL: {msg}")
    return 1


def main() -> int:
    from memgraph_tpu.exceptions import WorkerCrashedError
    from memgraph_tpu.sharding import ShardPlane, ShardedClient

    plane = ShardPlane(n_shards=N_SHARDS).start()
    try:
        client = ShardedClient(plane)
        log(f"{N_SHARDS} shard workers up, map epoch "
            f"{plane.map.epoch}: {plane.map.owners}")
        client.ddl("CREATE INDEX ON :User(id)")

        # routed writes + point reads
        for i in range(N_USERS):
            client.write("CREATE (:User {id: $id, age: $age})",
                         {"id": i, "age": i % 40}, key=i)
        for i in (0, 17, 63, 99):
            _c, rows = client.read(
                "MATCH (n:User {id: $id}) RETURN n.age", {"id": i},
                key=i)
            if rows != [[i % 40]]:
                return fail(f"point read {i} returned {rows}")
        log(f"routed {N_USERS} writes + point reads OK")

        # scatter-gather with merge
        _c, rows = client.read(
            "MATCH (n:User) RETURN count(n), sum(n.age)")
        expected_sum = sum(i % 40 for i in range(N_USERS))
        if rows != [[N_USERS, expected_sum]]:
            return fail(f"scatter-gather merged {rows}, expected "
                        f"[[{N_USERS}, {expected_sum}]]")
        log(f"scatter-gather count/sum OK: {rows[0]}")

        # cross-shard 2PC
        k1 = 0
        k2 = next(k for k in range(1, 64)
                  if client.shard_for(k) != client.shard_for(k1))
        out = client.write_multi([
            (k1, "MATCH (n:User {id: $id}) SET n.flag = true",
             {"id": k1}),
            (k2, "MATCH (n:User {id: $id}) SET n.flag = true",
             {"id": k2}),
        ])
        if len(out["shards"]) != 2:
            return fail(f"2PC touched {out['shards']}, expected 2 "
                        "shards")
        _c, rows = client.read(
            "MATCH (n:User) WHERE n.flag RETURN count(n)")
        if rows != [[2]]:
            return fail(f"cross-shard txn visible rows: {rows}")
        log(f"cross-shard 2PC across shards {out['shards']} OK "
            f"(txn {out['txn_id']})")

        # live shard-move: epoch bumps, data survives, stale client
        # bounces then lands
        epoch0 = plane.map.epoch
        moved = client.shard_for(k1)
        new_owner = plane.shard_move(moved)
        if plane.map.epoch <= epoch0:
            return fail("shard-move did not mint a new epoch")
        _c, rows, ack = client.write(
            "MATCH (n:User {id: $id}) SET n.moved = true", {"id": k1},
            key=k1)
        if ack["epoch"] != plane.map.epoch:
            return fail(f"post-move ack epoch {ack['epoch']} != map "
                        f"epoch {plane.map.epoch}")
        _c, rows = client.read("MATCH (n:User) RETURN count(n)")
        if rows != [[N_USERS]]:
            return fail(f"data lost in move: {rows}")
        log(f"shard {moved} moved to {new_owner} (epoch {epoch0} -> "
            f"{plane.map.epoch}), data intact, stale write re-routed")

        # worker kill: typed retryable error + per-shard WAL recovery
        victim = client.shard_for(17)
        plane.kill_worker(victim)
        try:
            plane.request(victim, "read",
                          {"query": "MATCH (n) RETURN count(n)",
                           "params": {}, "epoch": plane.map.epoch})
            return fail("dead worker did not raise the typed error")
        except WorkerCrashedError:
            pass
        _c, rows = client.read(
            "MATCH (n:User {id: 17}) RETURN n.age", key=17)
        if rows != [[17 % 40]]:
            return fail(f"post-respawn recovery lost data: {rows}")
        log(f"shard {victim} kill -> typed error -> respawn + WAL "
            "recovery OK")
    finally:
        plane.close()
    log("clean shutdown — PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
