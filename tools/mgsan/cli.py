"""mgsan command line: `python -m tools.mgsan <cmd>`.

    explore   run the built-in scenario bank over N seeded schedules,
              printing a per-seed trace digest (same seed => same digest)
    workload  run the randomized MVCC workload and check its history
    check     offline-check a previously dumped history JSONL file

Exit codes: 0 clean, 1 violations/races found, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.mgsan",
        description="memgraph_tpu dynamic concurrency sanitizer")
    sub = p.add_subparsers(dest="cmd", required=True)

    ex = sub.add_parser("explore", help="seeded schedule exploration")
    ex.add_argument("--seeds", type=int, default=10,
                    help="number of seeds per scenario (default 10)")
    ex.add_argument("--seed-base", type=int, default=0)
    ex.add_argument("--scenario", action="append", default=None,
                    help="scenario name (repeatable; default: all)")
    ex.add_argument("--trace", action="store_true",
                    help="print full schedule traces, not just digests")

    wl = sub.add_parser("workload", help="randomized MVCC workload + check")
    wl.add_argument("--seed", type=int, default=0)
    wl.add_argument("--threads", type=int, default=4)
    wl.add_argument("--txns", type=int, default=8)
    wl.add_argument("--keys", type=int, default=3)
    wl.add_argument("--break-isolation", action="store_true",
                    help="disable write-write conflict detection (the "
                         "checker MUST then flag lost updates)")
    wl.add_argument("--dump", metavar="PATH",
                    help="write the history JSONL to PATH")

    ck = sub.add_parser("check", help="offline-check a history JSONL")
    ck.add_argument("history", help="path to a history .jsonl")
    return p


def _cmd_explore(args) -> int:
    from .racedetect import detecting
    from .scenarios import SCENARIOS
    from .scheduler import DeadlockError, Scheduler

    names = args.scenario or sorted(SCENARIOS)
    bad = 0
    for name in names:
        build = SCENARIOS.get(name)
        if build is None:
            print(f"unknown scenario {name!r} "
                  f"(known: {', '.join(sorted(SCENARIOS))})",
                  file=sys.stderr)
            return 2
        for seed in range(args.seed_base, args.seed_base + args.seeds):
            sched = Scheduler(seed=seed)
            with detecting() as det:
                check = build(sched)
                try:
                    sched.run()
                    violations = check()
                except DeadlockError as e:
                    violations = [f"DEADLOCK: {e}"]
            text = sched.trace_text()
            digest = hashlib.sha256(text.encode()).hexdigest()[:16]
            status = "ok"
            if violations:
                status = "; ".join(violations)
                bad += 1
            if det.races:
                status += f" [{len(det.races)} race(s)]"
                bad += 1
            print(f"{name:20s} seed={seed:<4d} steps={len(sched.trace):<4d} "
                  f"trace={digest} {status}")
            if args.trace:
                print(text)
    return 1 if bad else 0


def _cmd_workload(args) -> int:
    from .isocheck import check_history, run_workload

    history, stats = run_workload(
        seed=args.seed, threads=args.threads,
        txns_per_thread=args.txns, keys=args.keys,
        break_isolation=args.break_isolation)
    if args.dump:
        history.dump(args.dump)
    violations = check_history(history)
    print(f"workload: {stats['committed']} committed, "
          f"{stats['aborted']} aborted, {len(history.events)} events, "
          f"{len(violations)} violation(s)")
    for v in violations:
        print(f"  {v}")
    if args.break_isolation:
        # inverted contract: the checker proving it CAN see the damage
        if not violations:
            print("FAIL: isolation was disabled but the checker saw "
                  "nothing", file=sys.stderr)
            return 1
        print("(expected: isolation was deliberately broken)")
        return 0
    return 1 if violations else 0


def _cmd_check(args) -> int:
    from .isocheck import HistoryLog, check_history

    try:
        history = HistoryLog.load(args.history)
    except (OSError, ValueError) as e:
        print(f"mgsan: cannot load {args.history}: {e}", file=sys.stderr)
        return 2
    violations = check_history(history)
    print(f"{len(history.events)} events, {len(violations)} violation(s)")
    for v in violations:
        print(f"  {v}")
    return 1 if violations else 0


def main(argv=None) -> int:
    # Arm lock tracking BEFORE any memgraph_tpu module creates a lock
    # (all product imports are lazy, inside the _cmd_* handlers): the
    # schedule explorer can only preempt at TrackedLock acquisitions —
    # a task parked at a yield point while holding a *plain* lock would
    # wedge every other task that touches it.
    os.environ.setdefault("MG_TRACK_LOCKS", "1")
    args = build_parser().parse_args(argv)
    if args.cmd == "explore":
        return _cmd_explore(args)
    if args.cmd == "workload":
        return _cmd_workload(args)
    return _cmd_check(args)
