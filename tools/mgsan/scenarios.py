"""Built-in schedule-exploration scenarios (shared by tests and CLI).

Each ``scenario_*`` function takes a Scheduler, spawns its tasks, and
returns a ``check()`` thunk that validates the invariant after the
schedule completes — returning a list of violation strings (empty ==
invariant held under that interleaving).

The first three are the tier-1 smoke (true negatives: correct code must
hold its invariant under EVERY explored schedule); ``scenario_racy_counter``
is the true-positive fixture — a deliberately unsynchronized
read-modify-write that the explorer must catch losing updates on at
least one seed.
"""

from __future__ import annotations

from memgraph_tpu.utils import sanitize as _san


def scenario_metrics_counter(sched):
    """Two tasks increment one lock-guarded Metrics counter 3x each."""
    from memgraph_tpu.observability.metrics import Metrics
    m = Metrics()

    def bump():
        for _ in range(3):
            m.increment("sanity.hits")

    sched.spawn(bump, name="inc-a")
    sched.spawn(bump, name="inc-b")

    def check():
        got = dict((n, v) for n, _k, v in m.snapshot())
        if got.get("sanity.hits") != 6.0:
            return [f"metrics lost updates: {got.get('sanity.hits')} != 6"]
        return []

    return check


def scenario_storage_commits(sched):
    """Two tasks each create+commit a vertex on one shared storage."""
    from memgraph_tpu.storage import InMemoryStorage
    st = InMemoryStorage()
    label = st.label_mapper.name_to_id("N")

    def txn(n):
        for _ in range(n):
            acc = st.access()
            v = acc.create_vertex()
            v.add_label(label)
            acc.commit()

    sched.spawn(txn, 2, name="writer-a")
    sched.spawn(txn, 2, name="writer-b")

    def check():
        out = []
        if len(st._vertices) != 4:
            out.append(f"expected 4 vertices, got {len(st._vertices)}")
        gids = sorted(st._vertices)
        if gids != [0, 1, 2, 3]:
            out.append(f"gid allocation not dense/unique: {gids}")
        if st.latest_commit_ts() != 1 + 4:
            out.append(f"commit ts drifted: {st.latest_commit_ts()}")
        return out

    return check


def scenario_replica_health(sched):
    """Concurrent RPC-failure bookkeeping on one ReplicaClient: the
    failure streak is a read-modify-write shared between the shipping
    path and the heartbeat thread — no increment may be lost."""
    from memgraph_tpu.replication.main_role import (ReplicaClient,
                                                    ReplicationMode)

    class _St:
        def latest_commit_ts(self):
            return 10

    c = ReplicaClient("r1", "127.0.0.1:7687", ReplicationMode.ASYNC,
                      _St())

    def fail(n):
        for _ in range(n):
            c._mark_failed("ship", OSError("injected"))

    sched.spawn(fail, 2, name="shipper")
    sched.spawn(fail, 2, name="heartbeat")

    def check():
        if c.failures != 4:
            return [f"lost failure increments: {c.failures} != 4"]
        return []

    return check


def scenario_racy_counter(sched):
    """TRUE POSITIVE: unsynchronized read-modify-write with an explicit
    yield between the read and the write. Some seeds MUST lose updates."""

    class Racy:
        def __init__(self):
            self.count = 0

        def bump(self):
            snap = self.count
            _san.yield_point("racy:between-read-and-write")
            self.count = snap + 1

    r = Racy()

    def loop():
        for _ in range(2):
            r.bump()

    sched.spawn(loop, name="racy-a")
    sched.spawn(loop, name="racy-b")

    def check():
        if r.count != 4:
            return [f"lost update: count {r.count} != 4"]
        return []

    return check


#: name -> builder; the smoke runs the first three, the sweep all of them
SCENARIOS = {
    "metrics_counter": scenario_metrics_counter,
    "storage_commits": scenario_storage_commits,
    "replica_health": scenario_replica_health,
    "racy_counter": scenario_racy_counter,
}

#: invariant-holding scenarios (every seed must pass)
CLEAN_SCENARIOS = ("metrics_counter", "storage_commits", "replica_health")
