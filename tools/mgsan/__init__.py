"""mgsan — dynamic concurrency sanitizer suite for memgraph_tpu.

Three cooperating parts, all armed through the lightweight annotation
shim ``memgraph_tpu/utils/sanitize.py`` (no-ops unless armed):

* ``scheduler``  — loom/CHESS-style deterministic schedule explorer:
  multi-threaded scenarios run one thread at a time under a
  seed-replayable schedule (same seed => byte-identical trace).
* ``racedetect`` — FastTrack-style vector-clock data-race detector over
  TrackedLock acquire/release and ``shared_read``/``shared_write``
  annotations; reports racy access pairs with both sites.
* ``isocheck``   — MVCC isolation checker: records per-transaction
  read/write/commit events into a history log and verifies
  snapshot-isolation invariants offline (G1a, G1b, future reads,
  lost updates / overlapping committed writers).

Complements mglint: MG001-MG007 prove static properties (lock order,
declared fields guarded on every path); mgsan witnesses the *dynamic*
ones (executed interleavings are race-free, histories serializable).
"""

from .scheduler import DeadlockError, Scheduler, SchedulerError, explore  # noqa: F401
from .racedetect import Detector, detecting, arm, disarm, current_detector  # noqa: F401
from .isocheck import HistoryLog, check_history, recording, run_workload  # noqa: F401
