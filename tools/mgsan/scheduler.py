"""Deterministic cooperative schedule explorer (loom / CHESS style).

A ``Scheduler`` owns N *tasks* (real threads) but lets exactly ONE run
at any moment. Tasks pause at schedule points — every
``TrackedLock`` acquisition (utils/locks.py calls back in here), every
``shared_read``/``shared_write`` annotation, and every explicit
``sanitize.yield_point()`` — and a seeded RNG picks which runnable task
proceeds. Because only one task executes between points, the entire
interleaving is a pure function of the seed: the same seed replays a
byte-identical schedule trace (``trace_text()``), so a failing
interleaving found by a randomized campaign is replayed exactly by
re-running with its seed — the same arming pattern as
``utils/faultinject.seeded_schedule``.

Lock handling: a task acquiring a TrackedLock first yields (scheduling
decision *before* the acquire), then try-acquires in a blocked/retry
loop. A task that cannot take the lock parks in BLOCKED state and is
not scheduled again until the holder releases — so a paused holder can
never deadlock the harness. If every live task is BLOCKED the program
itself has deadlocked and ``DeadlockError`` reports who holds what:
the explorer doubles as a deadlock finder.

Plain (untracked) locks are invisible to the scheduler: scenarios must
synchronize through tracked locks or annotated state. A task wedged on
something invisible trips the watchdog timeout instead of hanging CI.
"""

from __future__ import annotations

import random
import threading

# tools/ must be importable standalone: resolve the repo root the same
# way tools/mglint does (tests insert the repo root on sys.path)
from memgraph_tpu.utils import sanitize as _san


class SchedulerError(RuntimeError):
    """Harness-level failure (watchdog, step explosion, misuse)."""


class DeadlockError(SchedulerError):
    """Every live task is blocked on a tracked lock: real deadlock."""


_TLS = threading.local()


def _resolver():
    """Installed as sanitize._SCHED_RESOLVER: scheduler for the current
    thread, or None for threads the explorer does not own."""
    return getattr(_TLS, "sched", None)


class _Task:
    __slots__ = ("idx", "name", "fn", "args", "state", "label",
                 "blocked_on", "error", "thread")

    def __init__(self, idx: int, name: str, fn, args):
        self.idx = idx
        self.name = name
        self.fn = fn
        self.args = args
        self.state = "new"        # new|waiting|running|blocked|done
        self.label = "start"      # where the task is parked
        self.blocked_on = None    # id(TrackedLock) while state == blocked
        self.error: BaseException | None = None
        self.thread: threading.Thread | None = None


class Scheduler:
    """One exploration run: spawn tasks, then ``run()`` one seeded
    schedule to completion."""

    def __init__(self, seed: int = 0, max_steps: int = 50_000,
                 watchdog_s: float = 30.0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.max_steps = max_steps
        self.watchdog_s = watchdog_s
        self.trace: list[tuple[int, int, str]] = []  # (step, task, label)
        self._tasks: list[_Task] = []
        self._cv = threading.Condition()
        self._owner: dict[int, int] = {}   # id(lock) -> task idx
        self._started = False

    # --- scenario construction ------------------------------------------

    def spawn(self, fn, *args, name: str | None = None) -> None:
        if self._started:
            raise SchedulerError("spawn() after run()")
        idx = len(self._tasks)
        self._tasks.append(_Task(idx, name or f"t{idx}", fn, args))

    # --- the scheduling loop --------------------------------------------

    def run(self) -> "list[tuple[int, int, str]]":
        if self._started:
            raise SchedulerError("a Scheduler runs exactly once")
        self._started = True
        if not self._tasks:
            return self.trace
        # idempotent global install: the resolver is TLS-scoped, so
        # non-explorer threads always resolve to None
        _san._SCHED_RESOLVER = _resolver
        for task in self._tasks:
            task.thread = threading.Thread(
                target=self._bootstrap, args=(task,),
                name=f"mgsan-{task.name}", daemon=True)
            task.thread.start()
        step = 0
        with self._cv:
            while True:
                if all(t.state == "done" for t in self._tasks):
                    break
                runnable = [t for t in self._tasks
                            if t.state in ("new", "waiting")]
                if not runnable:
                    blocked = [t for t in self._tasks
                               if t.state == "blocked"]
                    held = {lock_id: idx
                            for lock_id, idx in self._owner.items()}
                    detail = "; ".join(
                        f"{t.name} blocked at {t.label}"
                        for t in blocked)
                    raise DeadlockError(
                        f"seed {self.seed}: all live tasks blocked "
                        f"({detail}); lock owners: {held}")
                step += 1
                if step > self.max_steps:
                    raise SchedulerError(
                        f"seed {self.seed}: exceeded {self.max_steps} "
                        "schedule steps (livelock or missing yield?)")
                task = runnable[self.rng.randrange(len(runnable))]
                self.trace.append((step, task.idx, task.label))
                task.state = "running"
                self._cv.notify_all()
                deadline_hit = not self._cv.wait_for(
                    lambda: task.state != "running",
                    timeout=self.watchdog_s)
                if deadline_hit:
                    raise SchedulerError(
                        f"seed {self.seed}: task {task.name} did not "
                        f"reach a schedule point within "
                        f"{self.watchdog_s}s (blocked on an untracked "
                        "primitive?)")
        errors = [t for t in self._tasks if t.error is not None]
        if errors:
            raise errors[0].error
        return self.trace

    def _bootstrap(self, task: _Task) -> None:
        _TLS.sched = self
        _TLS.task = task
        with self._cv:
            while task.state != "running":
                self._cv.wait()
        try:
            task.fn(*task.args)
        except BaseException as e:   # surfaced by run()
            task.error = e
        finally:
            with self._cv:
                task.state = "done"
                task.label = "done"
                self._cv.notify_all()

    # --- schedule points (called from sanitize/locks) --------------------

    def yield_point(self, label: str = "") -> None:
        task = getattr(_TLS, "task", None)
        if task is None or task.state != "running":
            return
        with self._cv:
            task.state = "waiting"
            task.label = label or "yield"
            self._cv.notify_all()
            while task.state != "running":
                self._cv.wait()

    def lock_acquire(self, tracked) -> None:
        """Called from TrackedLock.acquire for scheduler-owned threads."""
        task = getattr(_TLS, "task", None)
        if task is None:
            tracked._lock.acquire()
            return
        self.yield_point(f"acquire:{tracked.name}")
        while not tracked._lock.acquire(False):
            with self._cv:
                task.state = "blocked"
                task.blocked_on = id(tracked)
                task.label = f"blocked:{tracked.name}"
                self._cv.notify_all()
                while task.state != "running":
                    self._cv.wait()
        with self._cv:
            self._owner[id(tracked)] = task.idx

    def lock_released(self, tracked) -> None:
        with self._cv:
            self._owner.pop(id(tracked), None)
            for t in self._tasks:
                if t.state == "blocked" and t.blocked_on == id(tracked):
                    t.state = "waiting"
                    t.blocked_on = None
                    t.label = f"retry:{tracked.name}"

    # --- replayable trace -------------------------------------------------

    def trace_text(self) -> str:
        """Canonical one-line-per-step rendering; byte-identical across
        runs with the same seed and scenario."""
        names = {t.idx: t.name for t in self._tasks}
        return "\n".join(f"{step:04d} {names[idx]} {label}"
                         for step, idx, label in self.trace)


def explore(build, seeds, check=None) -> dict:
    """Run ``build(scheduler) -> ctx`` under one seeded schedule per seed.

    ``build`` spawns tasks on the scheduler it receives and returns an
    arbitrary context object; ``check(ctx)``, if given, runs after the
    schedule completes and its return value is collected. Returns
    {seed: {"trace": trace_text, "check": check result}}.
    """
    out = {}
    for seed in seeds:
        sched = Scheduler(seed=seed)
        ctx = build(sched)
        sched.run()
        out[seed] = {"trace": sched.trace_text(),
                     "check": check(ctx) if check is not None else None}
    return out
