"""MVCC isolation checker: history recording + offline SI verification.

The storage layer emits transaction life-cycle events through
``sanitize.mvcc_event`` (no-op unless a recorder is installed):

    {"e": "begin",  "txn": id, "start_ts": ts}
    {"e": "read",   "txn": id, "gid": g, "prop": p, "value": v}
    {"e": "write",  "txn": id, "gid": g, "prop": p, "value": v}
    {"e": "commit", "txn": id, "commit_ts": ts}     (ro=True if no-delta)
    {"e": "abort",  "txn": id}

``check_history`` verifies snapshot-isolation invariants *offline*,
Elle-style: the workload writes globally-unique values, so every read
maps back to exactly one writing transaction and version order needs no
storage cooperation. Checked invariants:

* **G1a (aborted read)** — no committed txn reads a value written by an
  aborted txn.
* **G1b (intermediate read)** — no txn reads a non-final write another
  txn made to the same key.
* **SI snapshot rule / dirty read** — a read's writer must have
  committed at or before the reader's start_ts (own writes exempt).
* **Lost update / first-committer-wins** — two committed txns that both
  wrote the same object must not have overlapping [start_ts, commit_ts]
  windows; additionally a committed read-modify-write must have read
  the immediately-preceding committed version.
* **Own-write visibility** — a txn that reads after its own write sees
  its own latest value.

``run_workload`` drives a randomized concurrent read-modify-write
workload against a real InMemoryStorage and returns the recorded
history; ``break_isolation=True`` disables ``prepare_for_write``
(write-write conflict detection) first, which MUST make the checker
flag lost updates — the tier-1 fixture for the checker itself.
"""

from __future__ import annotations

import json
import random
import threading

from memgraph_tpu.utils import sanitize as _san


class HistoryLog:
    """Append-only, thread-safe event log with JSONL round-trip."""

    def __init__(self):
        self._mu = threading.Lock()
        self.events: list[dict] = []

    def record(self, ev: dict) -> None:
        with self._mu:
            self.events.append(ev)

    def snapshot(self) -> list[dict]:
        with self._mu:
            return list(self.events)

    def dump(self, path: str) -> None:
        with self._mu, open(path, "w", encoding="utf-8") as f:
            for ev in self.events:
                f.write(json.dumps(ev) + "\n")

    @staticmethod
    def load(path: str) -> "HistoryLog":
        log = HistoryLog()
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    log.events.append(json.loads(line))
        return log


class recording:
    """Context manager installing a HistoryLog as the mvcc_event sink
    (preserving every other armed hook)."""

    def __init__(self):
        self.log = HistoryLog()

    def __enter__(self) -> HistoryLog:
        self._prev = _san._MVCC_HOOK
        _san._MVCC_HOOK = self.log.record
        return self.log

    def __exit__(self, *exc) -> None:
        _san._MVCC_HOOK = self._prev


# --- offline checker ---------------------------------------------------------


class _Txn:
    __slots__ = ("tid", "start_ts", "commit_ts", "status", "reads",
                 "writes")

    def __init__(self, tid):
        self.tid = tid
        self.start_ts = None
        self.commit_ts = None
        self.status = "active"     # active | committed | aborted
        self.reads: list[tuple] = []    # (key, value, seq)
        self.writes: list[tuple] = []   # (key, value, seq)


def check_history(events: "list[dict] | HistoryLog") -> list[str]:
    """Verify SI invariants over a recorded history; returns violation
    strings (empty == history is snapshot-consistent)."""
    if isinstance(events, HistoryLog):
        events = events.snapshot()
    txns: dict[int, _Txn] = {}
    violations: list[str] = []

    def txn(tid) -> _Txn:
        t = txns.get(tid)
        if t is None:
            t = txns[tid] = _Txn(tid)
        return t

    for seq, ev in enumerate(events):
        kind = ev.get("e")
        t = txn(ev["txn"])
        if kind == "begin":
            t.start_ts = ev.get("start_ts")
        elif kind == "read":
            t.reads.append(((ev["gid"], ev.get("prop")), ev.get("value"),
                            seq))
        elif kind == "write":
            t.writes.append(((ev["gid"], ev.get("prop")), ev.get("value"),
                             seq))
        elif kind == "commit":
            t.status = "committed"
            t.commit_ts = ev.get("commit_ts")
        elif kind == "abort":
            t.status = "aborted"

    # value -> writer map; duplicate written values make reads ambiguous
    writer_of: dict = {}    # (key, value) -> (txn, index within key-writes)
    final_write: dict = {}  # (tid, key) -> value of the txn's LAST write
    for t in txns.values():
        per_key_counts: dict = {}
        for key, value, _seq in t.writes:
            if value is None:
                continue
            idx = per_key_counts.get(key, 0)
            per_key_counts[key] = idx + 1
            wk = (key, value)
            if wk in writer_of and writer_of[wk][0] is not t:
                violations.append(
                    f"ambiguous history: value {value!r} for {key} "
                    f"written by txns {writer_of[wk][0].tid} and {t.tid} "
                    "(workload must write unique values)")
            writer_of[wk] = (t, idx)
            final_write[(t.tid, key)] = value

    for t in txns.values():
        own_last: dict = {}
        write_seqs = {s: (k, v) for k, v, s in t.writes}
        for key, value, seq in t.reads:
            # replay own writes up to this read for own-visibility check
            for ws in sorted(write_seqs):
                if ws < seq:
                    k, v = write_seqs[ws]
                    own_last[k] = v
            if key in own_last:
                if own_last[key] != value:
                    violations.append(
                        f"own-write visibility: txn {t.tid} wrote "
                        f"{own_last[key]!r} to {key} but then read "
                        f"{value!r}")
                continue
            if value is None:
                continue    # initial / absent version
            got = writer_of.get((key, value))
            if got is None:
                continue    # pre-history value (setup transaction)
            w, widx = got
            if w is t:
                continue
            if w.status == "aborted":
                violations.append(
                    f"G1a dirty/aborted read: txn {t.tid} read {value!r} "
                    f"({key}) written by aborted txn {w.tid}")
                continue
            n_writes = sum(1 for k, _v, _s in w.writes if k == key
                           and _v is not None)
            if widx != n_writes - 1:
                violations.append(
                    f"G1b intermediate read: txn {t.tid} read {value!r} "
                    f"({key}), a non-final write of txn {w.tid}")
            if w.status == "committed" and t.start_ts is not None \
                    and w.commit_ts is not None \
                    and w.commit_ts > t.start_ts:
                violations.append(
                    f"SI snapshot violation: txn {t.tid} "
                    f"(start_ts {t.start_ts}) read {value!r} ({key}) "
                    f"committed at {w.commit_ts} > its snapshot")
            if w.status == "active":
                violations.append(
                    f"dirty read: txn {t.tid} read {value!r} ({key}) "
                    f"from txn {w.tid} which never committed")

    # first-committer-wins: committed writers of the same OBJECT must not
    # overlap, and an RMW must have read the immediately-preceding version
    by_object: dict = {}
    for t in txns.values():
        if t.status != "committed" or t.commit_ts is None:
            continue
        for key, _value, _seq in t.writes:
            gid = key[0]
            by_object.setdefault(gid, set()).add(t.tid)
    for gid, tids in sorted(by_object.items(), key=lambda kv: str(kv[0])):
        writers = sorted((txns[tid] for tid in tids),
                         key=lambda t: t.commit_ts)
        for earlier, later in zip(writers, writers[1:]):
            if later.start_ts is not None \
                    and later.start_ts < earlier.commit_ts:
                violations.append(
                    f"lost update / ww-conflict on gid {gid}: txns "
                    f"{earlier.tid} (commit {earlier.commit_ts}) and "
                    f"{later.tid} (start {later.start_ts}, commit "
                    f"{later.commit_ts}) overlap — both committed")
    return violations


# --- randomized workload ------------------------------------------------------


def run_workload(seed: int = 0, threads: int = 4, txns_per_thread: int = 8,
                 keys: int = 3, storage=None, break_isolation: bool = False):
    """Concurrent read-modify-write workload over a real storage.

    Returns (history HistoryLog, stats dict). With
    ``break_isolation=True``, write-write conflict detection
    (``prepare_for_write``) is disabled for the duration — the checker
    must then report lost updates.
    """
    from memgraph_tpu.exceptions import SerializationError
    from memgraph_tpu.storage import InMemoryStorage
    from memgraph_tpu.storage import storage as storage_mod

    st = storage or InMemoryStorage()
    prop = st.property_mapper.name_to_id("val")
    setup = st.access()
    gids = []
    for _ in range(keys):
        v = setup.create_vertex()
        v.set_property(prop, "init")
        gids.append(v.vertex.gid)
    setup.commit()

    stats = {"committed": 0, "aborted": 0}
    stats_mu = threading.Lock()
    start = threading.Barrier(threads)

    def worker(widx: int):
        rng = random.Random(f"{seed}:{widx}")
        start.wait()
        for i in range(txns_per_thread):
            acc = st.access()
            try:
                gid = gids[rng.randrange(len(gids))]
                from memgraph_tpu.storage.storage import VertexAccessor
                va = VertexAccessor(st._vertices[gid], acc)
                va.get_property(prop)
                # hold the snapshot open between read and write: these
                # transactions are so small the GIL would otherwise run
                # them back-to-back and no seed ever truly conflicts
                import time
                time.sleep(rng.random() * 0.002)
                va.set_property(prop, f"{widx}.{i}")
                acc.commit()
                with stats_mu:
                    stats["committed"] += 1
            except SerializationError:
                acc.abort()
                with stats_mu:
                    stats["aborted"] += 1
        return None

    orig_pfw = storage_mod.prepare_for_write
    if break_isolation:
        storage_mod.prepare_for_write = lambda *a, **k: None
    try:
        with recording() as history:
            ts = [threading.Thread(target=worker, args=(w,))
                  for w in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    finally:
        storage_mod.prepare_for_write = orig_pfw
    return history, stats


def run_injected_lost_update(storage=None):
    """Deterministic lost-update fixture: two transactions interleaved
    in ONE thread, both read-modify-writing the same property with
    conflict detection disabled. Both commit — a textbook lost update
    the checker MUST flag. (With detection enabled the same interleaving
    raises SerializationError instead; see tests.)"""
    from memgraph_tpu.storage import InMemoryStorage
    from memgraph_tpu.storage import storage as storage_mod
    from memgraph_tpu.storage.storage import VertexAccessor

    st = storage or InMemoryStorage()
    prop = st.property_mapper.name_to_id("val")
    setup = st.access()
    v = setup.create_vertex()
    v.set_property(prop, "init")
    gid = v.vertex.gid
    setup.commit()

    orig_pfw = storage_mod.prepare_for_write
    storage_mod.prepare_for_write = lambda *a, **k: None
    try:
        with recording() as history:
            a1 = st.access()
            a2 = st.access()
            v1 = VertexAccessor(st._vertices[gid], a1)
            v2 = VertexAccessor(st._vertices[gid], a2)
            v1.get_property(prop)
            v2.get_property(prop)          # same snapshot: lost update
            v1.set_property(prop, "t1.0")
            v2.set_property(prop, "t2.0")
            a1.commit()
            a2.commit()                    # silently clobbers t1's write
    finally:
        storage_mod.prepare_for_write = orig_pfw
    return history
