"""FastTrack-style vector-clock data-race detector (MG_SAN=1).

Happens-before tracking:

* each thread carries a vector clock; thread *creation* copies the
  parent's clock into the child (``threading.Thread.start`` is patched
  while armed) and ``join`` merges the child's final clock back;
* every ``TrackedLock`` release publishes the releasing thread's clock
  on the lock and bumps the thread's own epoch; every acquire joins the
  lock's clock into the acquiring thread (utils/locks.py calls the
  hooks installed here);
* every ``shared_read``/``shared_write`` annotation on a declared
  ``shared_field`` checks the access against the field's last-writer
  epoch (FastTrack write epochs) and per-thread read clocks.

An access pair unordered by happens-before is a data race; the report
carries **both** access sites (file:line of the annotation's caller),
the two thread names, and the access kinds. Races dedupe on
(field label, kind, site pair) so a racy hot loop produces one finding,
not thousands.

Scope is deliberate: only *annotated* fields are checked, so
synchronization the detector cannot see (queue.Queue hand-off, plain
locks, Condition wake-ups) never yields false positives — unannotated
state is simply out of scope, exactly like TSan's
ANNOTATE_BENIGN_RACE-free manual instrumentation mode.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field

from memgraph_tpu.utils import sanitize as _san

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
_SHIM_FILES = ("sanitize.py", "locks.py")


def _site(depth: int = 2) -> str:
    """First frame outside the sanitizer plumbing: the annotated access."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return "<unknown>"
    while frame is not None:
        fn = frame.f_code.co_filename
        base = os.path.basename(fn)
        if not (fn.startswith(_THIS_DIR) or base in _SHIM_FILES):
            return f"{fn}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>"


@dataclass
class Race:
    label: str                    # "Metrics._counters"
    kind: str                     # write-write | read-write | write-read
    prior_site: str
    prior_thread: str
    site: str
    thread: str

    def render(self) -> str:
        return (f"DATA RACE on {self.label} [{self.kind}]: "
                f"{self.prior_thread} @ {self.prior_site}  vs  "
                f"{self.thread} @ {self.site}")


class _VarState:
    __slots__ = ("write", "write_site", "write_thread", "reads",
                 "read_sites")

    def __init__(self):
        self.write = None          # (tid, epoch) of last write
        self.write_site = ""
        self.write_thread = ""
        self.reads: dict[int, int] = {}       # tid -> epoch of last read
        self.read_sites: dict[int, tuple] = {}  # tid -> (site, name)


@dataclass
class Detector:
    """One detection session. ``arm()`` installs a process-global one."""

    allowlist: frozenset = frozenset()
    races: list = field(default_factory=list)

    def __post_init__(self):
        # the detector's own mutex is a strict leaf and deliberately a
        # *plain* lock: a TrackedLock here would recurse into the hooks
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._next_tid = [0]
        self._clocks: dict[int, dict[int, int]] = {}
        self._lock_clocks: dict[int, dict[int, int]] = {}
        self._pending_forks: dict[int, dict[int, int]] = {}
        self._final_clocks: dict[int, dict[int, int]] = {}
        self._seen_pairs: set = set()

    # --- thread registry --------------------------------------------------

    def _current(self) -> tuple[int, dict]:
        tid = getattr(self._tls, "tid", None)
        if tid is None:
            with self._mu:
                tid = self._next_tid[0]
                self._next_tid[0] += 1
                clock = {tid: 1}
                parent = self._pending_forks.pop(
                    threading.get_ident(), None)
                if parent is not None:
                    clock.update({t: c for t, c in parent.items()
                                  if t != tid})
                    clock[tid] = 1
                self._clocks[tid] = clock
            self._tls.tid = tid
        return tid, self._clocks[tid]

    def fork_snapshot(self) -> dict:
        """Parent-side half of a thread fork: snapshot + epoch bump."""
        tid, clock = self._current()
        with self._mu:
            snap = dict(clock)
            clock[tid] += 1
        return snap

    def adopt_fork(self, parent_snapshot: dict) -> None:
        """Child-side half, keyed by the child's OS ident (runs before
        any access the child makes)."""
        with self._mu:
            self._pending_forks[threading.get_ident()] = parent_snapshot

    def finish_thread(self) -> None:
        tid, clock = self._current()
        with self._mu:
            self._final_clocks[threading.get_ident()] = dict(clock)

    def join_thread(self, ident: int) -> None:
        tid, clock = self._current()
        with self._mu:
            final = self._final_clocks.get(ident)
            if final:
                for t, c in final.items():
                    if clock.get(t, 0) < c:
                        clock[t] = c

    # --- lock hooks -------------------------------------------------------

    def on_acquire(self, lock) -> None:
        tid, clock = self._current()
        with self._mu:
            lc = self._lock_clocks.get(id(lock))
            if lc:
                for t, c in lc.items():
                    if clock.get(t, 0) < c:
                        clock[t] = c

    def on_release(self, lock) -> None:
        tid, clock = self._current()
        with self._mu:
            self._lock_clocks[id(lock)] = dict(clock)
            clock[tid] += 1

    # --- declared fields / accesses --------------------------------------

    def on_declare(self, owner, fields) -> None:
        # identity comes from (id(owner), field) at access time; the
        # declaration itself needs no bookkeeping beyond existing — it
        # is primarily the static marker for MG006/MG007
        pass

    def on_access(self, kind: str, owner, fname: str) -> None:
        label = f"{type(owner).__name__}.{fname}"
        if label in self.allowlist:
            return
        tid, clock = self._current()
        me = threading.current_thread().name
        site = _site()
        key = (id(owner), fname)
        with self._mu:
            st = self._vars_get(key)
            if kind == "w":
                if st.write is not None:
                    wtid, wepoch = st.write
                    if wtid != tid and clock.get(wtid, 0) < wepoch:
                        self._record(label, "write-write", st.write_site,
                                     st.write_thread, site, me)
                for rtid, repoch in st.reads.items():
                    if rtid != tid and clock.get(rtid, 0) < repoch:
                        rsite, rname = st.read_sites[rtid]
                        self._record(label, "read-write", rsite, rname,
                                     site, me)
                st.write = (tid, clock[tid])
                st.write_site = site
                st.write_thread = me
                st.reads = {}
                st.read_sites = {}
            else:
                if st.write is not None:
                    wtid, wepoch = st.write
                    if wtid != tid and clock.get(wtid, 0) < wepoch:
                        self._record(label, "write-read", st.write_site,
                                     st.write_thread, site, me)
                st.reads[tid] = clock[tid]
                st.read_sites[tid] = (site, me)

    def _vars_get(self, key) -> _VarState:
        vars_ = getattr(self, "_vars", None)
        if vars_ is None:
            vars_ = self._vars = {}
        st = vars_.get(key)
        if st is None:
            st = vars_[key] = _VarState()
        return st

    def _record(self, label, kind, psite, pthread, site, me) -> None:
        pair = (label, kind, psite, site)
        if pair in self._seen_pairs:
            return
        self._seen_pairs.add(pair)
        self.races.append(Race(label, kind, psite, pthread, site, me))

    def report(self) -> str:
        lines = [f"mgsan race detector: {len(self.races)} race(s)"]
        lines += [f"  {r.render()}" for r in self.races]
        return "\n".join(lines)


# --- process-global arming ----------------------------------------------------

_DETECTOR: Detector | None = None
_ORIG_START = threading.Thread.start
_ORIG_JOIN = threading.Thread.join


def current_detector() -> Detector | None:
    return _DETECTOR


def _patched_start(self):
    det = _DETECTOR
    if det is None:
        return _ORIG_START(self)
    snap = det.fork_snapshot()
    orig_run = self.run

    def run():
        d = _DETECTOR
        if d is not None:
            d.adopt_fork(snap)
        try:
            orig_run()
        finally:
            if d is not None:
                d.finish_thread()

    self.run = run
    return _ORIG_START(self)


def _patched_join(self, timeout=None):
    _ORIG_JOIN(self, timeout)
    det = _DETECTOR
    if det is not None and not self.is_alive():
        det.join_thread(self.ident)


def arm(allowlist=()) -> Detector:
    """Install a process-global detector: lock + access hooks, patched
    Thread.start/join for fork/join happens-before edges."""
    global _DETECTOR
    det = Detector(allowlist=frozenset(allowlist))
    _DETECTOR = det
    _san.install_hooks(
        access=det.on_access,
        declare=det.on_declare,
        mvcc=_san._MVCC_HOOK,
        lock_acq=det.on_acquire,
        lock_rel=det.on_release,
    )
    threading.Thread.start = _patched_start
    threading.Thread.join = _patched_join
    return det


def disarm() -> None:
    global _DETECTOR
    _DETECTOR = None
    threading.Thread.start = _ORIG_START
    threading.Thread.join = _ORIG_JOIN
    _san.install_hooks(mvcc=_san._MVCC_HOOK)


class detecting:
    """Context manager for tests: arm a fresh detector, restore on exit.

    with detecting() as det:
        ... run threads ...
    assert det.races == []
    """

    def __init__(self, allowlist=()):
        self.allowlist = allowlist
        self.detector: Detector | None = None

    def __enter__(self) -> Detector:
        self._prev = _DETECTOR
        self.detector = arm(self.allowlist)
        return self.detector

    def __exit__(self, *exc) -> None:
        global _DETECTOR
        if self._prev is None:
            disarm()
        else:
            _DETECTOR = self._prev
            _san.install_hooks(
                access=self._prev.on_access,
                declare=self._prev.on_declare,
                mvcc=_san._MVCC_HOOK,
                lock_acq=self._prev.on_acquire,
                lock_rel=self._prev.on_release,
            )
