"""Gate smoke for the PPR serving plane: spawn the kernel server, fire
64 concurrent requests from threads, assert the coalescing ratio beats
1 (requests actually shared batches), assert a repeat request hits the
result cache, and shut down cleanly.

Functional counterpart of benchmarks/ppr_serving_bench.py sized for the
dev gate (~seconds, CPU-safe): this proves the serving plane WORKS on
every host; the bench proves it is FAST on accelerator hosts.

Usage: python -m tools.ppr_smoke
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

CLIENTS = 64
N, E = 2000, 12000


def log(msg: str) -> None:
    print(f"ppr-smoke: {msg}", flush=True)


def fail(msg: str) -> "int":
    log(f"FAIL: {msg}")
    return 1


def _metric(name):
    from memgraph_tpu.observability.metrics import global_metrics
    return dict((n, v) for n, _k, v in global_metrics.snapshot()).get(
        name, 0.0)


def main() -> int:
    from memgraph_tpu.server.kernel_server import KernelClient, KernelServer

    sock = os.path.join(tempfile.mkdtemp(prefix="pprsmoke"), "ks.sock")
    srv = KernelServer(sock, wedge_after_s=60)
    srv._ppr.window_s = 0.02        # wide window: 64 threads must ride
    server_thread = threading.Thread(target=srv.serve_forever,
                                     daemon=True)
    server_thread.start()
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=120)
            break
        except OSError:
            time.sleep(0.05)
    if client is None:
        return fail("kernel server never bound its socket")

    rng = np.random.default_rng(0)
    src = rng.integers(0, N, E)
    dst = rng.integers(0, N, E)
    client.ppr([0], src=src, dst=dst, n_nodes=N, graph_key="smoke",
               graph_version=1, tol=1e-6)
    log(f"graph staged ({N} nodes, {E} edges)")

    req_before = _metric("ppr.requests_total")
    batch_before = _metric("ppr.batches_total")
    results: dict = {}
    errors: list = []
    barrier = threading.Barrier(CLIENTS)

    def worker(i):
        try:
            for attempt in range(50):
                try:
                    c = KernelClient(sock, timeout=120)
                    break
                except OSError:
                    time.sleep(0.05)
            barrier.wait(timeout=60)
            h, out = c.ppr([i % N], graph_key="smoke", graph_version=1,
                           n_nodes=N, tol=1e-6, top_k=5)
            results[i] = h
            c.close()
        except Exception as e:  # noqa: BLE001 — smoke reports, not raises
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    if errors:
        return fail(f"{len(errors)} of {CLIENTS} concurrent requests "
                    f"errored; first: {errors[0]}")
    if len(results) != CLIENTS:
        return fail(f"only {len(results)} of {CLIENTS} requests "
                    "completed")
    req_delta = _metric("ppr.requests_total") - req_before
    batch_delta = max(_metric("ppr.batches_total") - batch_before, 1.0)
    ratio = req_delta / batch_delta
    max_batch = max(h["batch_size"] for h in results.values())
    log(f"{CLIENTS} concurrent requests -> {int(batch_delta)} batches "
        f"(coalescing ratio {ratio:.1f}, widest batch {max_batch})")
    if ratio <= 1.0:
        return fail(f"coalescing ratio {ratio:.2f} <= 1 — requests "
                    "never shared a batch")

    # repeat request must ride the result cache, not the device
    h, _ = client.ppr([1], graph_key="smoke", graph_version=1, n_nodes=N,
                      tol=1e-6, top_k=5)
    if h.get("cache") != "hit":
        return fail(f"repeat request missed the cache "
                    f"(cache={h.get('cache')!r})")
    log("repeat request: cache hit")

    client.shutdown()
    client.close()
    server_thread.join(timeout=30)
    if server_thread.is_alive():
        return fail("server did not shut down cleanly")
    log("clean shutdown")
    log("PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
