"""Gate smoke for the mgdelta incremental-analytics plane (r19): spawn
the kernel server, import a graph at v1, ship a delta-only request at
v2 (changed indices + incident edges, NO full edge arrays), assert the
resident generation refreshed O(delta) and the reply matches a cold
reference; then assert the warm-start contracts — pagerank warm on
repeat, WCC warm on an adds-only delta, the LOUD typed cold after a
removal — and the change-log-wrap typed fallback.

Functional counterpart of bench.py --stage delta sized for the dev gate
(~seconds, CPU-safe): this proves the delta plane WORKS on every host;
the bench proves it is FAST on accelerator hosts.

Usage: python -m tools.delta_smoke
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N, E = 600, 4000


def log(msg: str) -> None:
    print(f"delta-smoke: {msg}", flush=True)


def fail(msg: str) -> int:
    log(f"FAIL: {msg}")
    return 1


def _metric(name):
    from memgraph_tpu.observability.metrics import global_metrics
    return dict((n, v) for n, _k, v in global_metrics.snapshot()).get(
        name, 0.0)


def _incident(src, dst, changed, n):
    bitmap = np.zeros(n, dtype=bool)
    bitmap[np.asarray(changed, dtype=np.int64)] = True
    sel = bitmap[src] | bitmap[dst]
    return (src[sel].astype(np.int64), dst[sel].astype(np.int64),
            np.ones(int(sel.sum()), dtype=np.float32))


def main() -> int:
    from memgraph_tpu.ops.components import weakly_connected_components
    from memgraph_tpu.ops.csr import from_coo
    from memgraph_tpu.parallel.analytics import pagerank_mesh
    from memgraph_tpu.parallel.mesh import get_mesh_context
    from memgraph_tpu.server.kernel_server import (KernelClient,
                                                   KernelServer)
    from memgraph_tpu.storage.storage import (ChangeLogUnknowable,
                                              InMemoryStorage)

    sock = os.path.join(tempfile.mkdtemp(prefix="deltasmoke"), "ks.sock")
    srv = KernelServer(sock, wedge_after_s=60)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=120)
            break
        except OSError:
            time.sleep(0.05)
    if client is None:
        return fail("kernel server never came up")

    rng = np.random.default_rng(0)
    src = rng.integers(0, N, E)
    dst = rng.integers(0, N, E)
    tol = 1e-6
    client.pagerank(src=src, dst=dst, n_nodes=N, graph_key="smoke",
                    graph_version=1, tol=tol)
    log("v1 imported + cold pagerank served")

    # commit: ship ONLY the delta payload at v2
    add_src = rng.integers(0, N, 12)
    add_dst = rng.integers(0, N, 12)
    src2 = np.concatenate([src, add_src])
    dst2 = np.concatenate([dst, add_dst])
    changed = np.unique(np.concatenate([add_src,
                                        add_dst])).astype(np.int32)
    inc_src, inc_dst, inc_w = _incident(src2, dst2, changed, N)
    applied0 = _metric("delta.applied_total")
    ranks, err, iters = client.pagerank(
        n_nodes=N, graph_key="smoke", graph_version=2, base_version=1,
        changed=changed, inc_src=inc_src, inc_dst=inc_dst, inc_w=inc_w,
        tol=tol)
    if _metric("delta.applied_total") <= applied0:
        return fail("delta request did not ride the O(delta) apply")
    if err > tol:
        return fail(f"warm reply err {err} above tol {tol}")
    ref, _, it_ref = pagerank_mesh(from_coo(src2, dst2, n_nodes=N),
                                   get_mesh_context(1), tol=tol)
    gap = float(np.abs(np.asarray(ref) - np.asarray(ranks)[:N]).max())
    if gap > 10 * tol:
        return fail(f"delta-refreshed result diverges from cold "
                    f"reference (Linf {gap})")
    if iters > it_ref:
        return fail(f"warm start took MORE iterations than cold "
                    f"({iters} > {it_ref})")
    log(f"delta-only request served fresh result (Linf {gap:.2e}, "
        f"warm {iters} vs cold {it_ref} iters)")

    # WCC monotone gate: warm on adds-only, LOUD cold after a removal
    h1, out1 = client.semiring(algorithm="wcc", graph_key="smoke",
                               n_nodes=N, graph_version=2)
    h2, out2 = client.semiring(algorithm="wcc", graph_key="smoke",
                               n_nodes=N, graph_version=2)
    if not h2.get("warm_started"):
        return fail("repeat WCC did not warm-start")
    src3, dst3 = np.delete(src2, [0]), np.delete(dst2, [0])
    ch3 = np.unique(np.concatenate([src2[:1], dst2[:1]])).astype(
        np.int32)
    i3 = _incident(src3, dst3, ch3, N)
    cold0 = _metric("delta.cold_start_total")
    h3, out3 = client.semiring(
        algorithm="wcc", graph_key="smoke", n_nodes=N, graph_version=3,
        base_version=2, changed=ch3, inc_src=i3[0], inc_dst=i3[1],
        inc_w=i3[2])
    if h3.get("warm_started"):
        return fail("removal delta warm-started WCC (monotone-unsafe)")
    if _metric("delta.cold_start_total") <= cold0:
        return fail("monotone-unsafe cold start was not counted")
    ref_c, _ = weakly_connected_components(from_coo(src3, dst3,
                                                    n_nodes=N))
    if not np.array_equal(np.asarray(ref_c), out3["components"][:N]):
        return fail("post-removal WCC does not match cold reference")
    log("WCC monotone gate held (warm on repeat, LOUD cold on removal)")

    # change-log wrap: the typed verdict forces the full-export path
    st = InMemoryStorage()
    for i in range(1100):
        st._bump_topology({i})
    verdict = st.changes_between(0, st.topology_version)
    if not isinstance(verdict, ChangeLogUnknowable) or verdict:
        return fail("wrapped change log did not return the typed falsy "
                    "ChangeLogUnknowable")
    log(f"change-log wrap verdict: {verdict!r}")

    try:
        client.shutdown()
        client.close()
    except OSError:
        pass
    log("OK: delta plane end-to-end (O(delta) refresh, warm contracts, "
        "typed wrap fallback)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
