"""Gate smoke for the mgtier out-of-core streamed tier (r21): spawn
the kernel server under an HBM budget the graph's RESIDENT estimate
exceeds (but the streamed working set fits), assert the admission
guard flips the request onto the streamed path automatically, and that
the streamed result is bit-identical to the resident comparator (same
kernels, same fold order) and matches the in-process reference. Then:
WCC rides the streamed path too (partition-equivalent labels), a
non-streamable algorithm against the same oversized graph sheds with
the typed non-retryable verdict instead of lying, and the compressed
wire formats actually compress (bf16/int8 >= 1.8x vs raw COO bytes).

Functional counterpart of bench.py --stage tier sized for the dev gate
(~seconds, CPU-safe): this proves out-of-core execution WORKS on every
host; overlap/throughput numbers are the bench's job on accelerator
hosts.

Usage: python -m tools.tier_smoke
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# small per-buffer budget so the smoke graph splits into real blocks
os.environ.setdefault("MEMGRAPH_TPU_TIER_BLOCK_BYTES", str(1 << 15))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N, E = 2000, 16000
#: fits the streamed working set (~130 KiB) but NOT the resident
#: estimate (~830 KiB): admission must pick "streamed", not "shed"
BUDGET = 300_000


def log(msg: str) -> None:
    print(f"tier-smoke: {msg}", flush=True)


def fail(msg: str) -> int:
    log(f"FAIL: {msg}")
    return 1


def _metric(name):
    from memgraph_tpu.observability.metrics import global_metrics
    return dict((n, v) for n, _k, v in global_metrics.snapshot()).get(
        name, 0.0)


def _same_partition(a, b) -> bool:
    pairs = set(zip(a.tolist(), b.tolist()))
    return len(pairs) == len(set(a.tolist())) == len(set(b.tolist()))


def main() -> int:
    from memgraph_tpu.ops import tier as mgtier
    from memgraph_tpu.ops.components import weakly_connected_components
    from memgraph_tpu.ops.csr import from_coo
    from memgraph_tpu.ops.pagerank import pagerank
    from memgraph_tpu.parallel.distributed import pagerank_streamed
    from memgraph_tpu.server.kernel_server import (AdmissionRejected,
                                                   KernelClient,
                                                   KernelServer)

    sock = os.path.join(tempfile.mkdtemp(prefix="tiersmoke"), "ks.sock")
    srv = KernelServer(sock, wedge_after_s=60,
                       hbm_budget_bytes=BUDGET)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=120)
            break
        except OSError:
            time.sleep(0.05)
    if client is None:
        return fail("kernel server never came up")

    rng = np.random.default_rng(21)
    src = rng.integers(0, N, E)
    dst = rng.integers(0, N, E)
    w = (rng.random(E) + 0.1).astype(np.float32)
    tol = 1e-8

    # 1. oversized pagerank: admission flips it onto the streamed path
    streamed0 = _metric("tier.admission_streamed_total")
    h, out = client.semiring(algorithm="pagerank", src=src, dst=dst,
                             weights=w, n_nodes=N, graph_key="smoke",
                             graph_version=1, tol=tol)
    if h.get("tier") != "streamed":
        return fail(f"oversized pagerank was not streamed "
                    f"(tier={h.get('tier')!r}, budget {BUDGET})")
    if _metric("tier.admission_streamed_total") <= streamed0:
        return fail("streamed verdict was not counted")
    if _metric("tier.blocks_streamed_total") <= 0:
        return fail("no edge blocks actually streamed")
    ranks = np.asarray(out["ranks"])[:N]

    # 2. bit-identical to the resident comparator (same kernels, same
    #    fold order, whole graph pre-placed) and close to the classic
    #    segment-backend reference
    t = mgtier.tier_from_scsr(
        __import__("memgraph_tpu.ops.csr", fromlist=["shard_edges"])
        .shard_edges(src.astype(np.int64), dst.astype(np.int64), w,
                     N, mgtier.plan_blocks(N, E, "f32",
                                           mgtier.block_bytes_budget()),
                     by="src"))
    res, _err, _it = pagerank_streamed(t, tol=tol, resident=True)
    if not np.array_equal(ranks, res):
        gap = float(np.abs(ranks - res).max())
        return fail(f"streamed != resident comparator (Linf {gap:.2e})")
    ref, _, _ = pagerank(from_coo(src, dst, weights=w, n_nodes=N),
                         tol=tol)
    gap = float(np.abs(np.asarray(ref)[:N] - ranks).max())
    if gap > 1e-5:
        return fail(f"streamed result diverges from reference "
                    f"(Linf {gap})")
    log(f"pagerank streamed: bit-identical to resident comparator, "
        f"Linf {gap:.2e} vs segment reference")

    # 3. WCC rides the streamed path too
    h2, out2 = client.semiring(algorithm="wcc", graph_key="smoke",
                               n_nodes=N, graph_version=1)
    if h2.get("tier") != "streamed":
        return fail(f"oversized WCC was not streamed "
                    f"(tier={h2.get('tier')!r})")
    ref_c, _ = weakly_connected_components(from_coo(src, dst, n_nodes=N))
    if not _same_partition(np.asarray(ref_c)[:N],
                           np.asarray(out2["components"])[:N]):
        return fail("streamed WCC labels are not partition-equivalent "
                    "to the reference")
    log("WCC streamed: partition-equivalent to reference")

    # 4. a non-streamable algorithm against the same oversized graph
    #    must SHED (typed, non-retryable) — never silently go resident
    shed0 = _metric("tier.admission_shed_total")
    try:
        client.semiring(algorithm="labelprop", graph_key="smoke",
                        n_nodes=N, graph_version=1)
        return fail("non-streamable oversized labelprop was admitted")
    except AdmissionRejected as e:
        if e.retryable:
            return fail("shed verdict claims to be retryable")
    if _metric("tier.admission_shed_total") <= shed0:
        return fail("shed verdict was not counted")
    log("non-streamable labelprop shed with the typed verdict")

    # 5. the wire actually compresses: bf16/int8 blocks vs raw COO
    for precision, floor in (("bf16", 1.8), ("int8", 1.8)):
        tp = mgtier.plan_tier(src.astype(np.int64), dst.astype(np.int64),
                              w, N, precision=precision)
        ratio = (sum(b.raw_nbytes for b in tp.blocks)
                 / sum(b.nbytes for b in tp.blocks))
        if ratio < floor:
            return fail(f"{precision} wire ratio {ratio:.2f} "
                        f"< {floor}")
        log(f"{precision} wire compression {ratio:.2f}x vs raw COO")

    try:
        client.shutdown()
        client.close()
    except OSError:
        pass
    log("OK: out-of-core tier end-to-end (auto-streamed admission, "
        "bit-exact vs resident, typed shed, compressed wire)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
