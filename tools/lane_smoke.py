"""mglane smoke: compiled hit + loud typed fallback + schema-change
invalidation round trip, end to end through the interpreter.

    python -m tools.lane_smoke

Functional on every host (CPU jax included) — the perf claim is the
bench's job (mgbench lane groups + perf_gate.check_lane); this gate
proves the MACHINERY: a lane-eligible query compiles once and serves
from the compiled program, refusal shapes fall back loudly with their
typed reason while answering identically, and index DDL drops every
compiled lane (stale lanes never serve) with results bit-identical to
the serial interpreter before and after.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(f"lane-smoke: {msg}", flush=True)


def fail(msg: str) -> "None":
    log(f"FAIL: {msg}")
    sys.exit(1)


def metric(name: str) -> float:
    from memgraph_tpu.observability.metrics import global_metrics
    return {n: v for n, _k, v in global_metrics.snapshot()}.get(name, 0.0)


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from memgraph_tpu.ops import pipeline as pl
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import (InMemoryStorage, StorageConfig,
                                      StorageMode)

    storage = InMemoryStorage(StorageConfig(
        storage_mode=StorageMode.IN_MEMORY_TRANSACTIONAL))
    ctx = InterpreterContext(storage)
    acc = storage.access()
    lid = storage.label_mapper.name_to_id("U")
    page = storage.property_mapper.name_to_id("age")
    rng = np.random.default_rng(5)
    n_nodes = 6000
    vs = []
    for i in range(n_nodes):
        v = acc.create_vertex()
        v.add_label(lid)
        v.set_property(page, int(i % 80))
        vs.append(v)
    te = storage.edge_type_mapper.name_to_id("F")
    for _ in range(24000):
        a, b = rng.integers(0, n_nodes, 2)
        acc.create_edge(vs[a], vs[b], te)
    acc.commit()
    interp = Interpreter(ctx)

    def run(q):
        _, rows, _ = interp.execute(q)
        return rows

    def serial(q):
        os.environ["MEMGRAPH_TPU_DISABLE_PARALLEL"] = "1"
        ctx.invalidate_plans()
        try:
            return run(q)
        finally:
            os.environ.pop("MEMGRAPH_TPU_DISABLE_PARALLEL", None)
            ctx.invalidate_plans()

    agg_q = ("MATCH (n:U) WHERE n.age > 40 RETURN count(*) AS c, "
             "sum(n.age) AS s, min(n.age) AS mn, max(n.age) AS mx")
    hop_q = ("MATCH (a:U)-[:F]->(b)-[:F]->(m) WHERE a.age < 2 "
             "RETURN count(m) AS c")

    # 1. compiled hit: first run compiles, second serves from the cache
    c0, h0 = metric("lane.compiled_total"), metric("lane.hit_total")
    first = run(agg_q)
    if metric("lane.compiled_total") <= c0:
        fail("no lane program compiled for the aggregate tail")
    if metric("lane.hit_total") <= h0:
        fail("aggregate tail did not serve from the lane")
    c1 = metric("lane.compiled_total")
    second = run(agg_q)
    if metric("lane.compiled_total") != c1:
        fail("repeat query recompiled — fingerprint cache broken")
    if first != second:
        fail(f"repeat query changed answers: {first} vs {second}")
    log(f"compiled hit OK: {first[0]} (1 compile, repeat = cache hit)")

    # 2. hop lane parity vs the serial interpreter
    lane_rows = run(hop_q)
    ser_rows = serial(hop_q)
    if lane_rows != ser_rows:
        fail(f"two-hop lane diverges: {lane_rows} vs {ser_rows}")
    log(f"two-hop lane OK: count={lane_rows[0][0]} == serial")

    # 3. loud typed fallback: avg is a refusal shape — identical
    #    answers, reason counted
    avg_q = "MATCH (n:U) RETURN count(*) AS c, avg(n.age) AS a"
    f0 = metric("lane.fallback_total.agg_avg")
    lane_rows = run(avg_q)
    if metric("lane.fallback_total.agg_avg") <= f0:
        fail("avg refusal not counted under lane.fallback_total.agg_avg")
    ser_rows = serial(avg_q)
    if lane_rows != ser_rows:
        fail(f"avg fallback diverges: {lane_rows} vs {ser_rows}")
    log("loud fallback OK: agg_avg counted, results identical")

    # 4. schema-change invalidation round trip
    run(agg_q)
    if pl.resident_programs() == 0:
        fail("expected resident lane programs before DDL")
    run("CREATE INDEX ON :U(age)")
    if pl.resident_programs() != 0:
        fail("CREATE INDEX left compiled lanes resident (stale-lane "
             "hazard)")
    after = run(agg_q)
    oracle = serial(agg_q)
    if after != oracle:
        fail(f"post-DDL lane diverges from interpreter: {after} vs "
             f"{oracle}")
    log("schema invalidation OK: DDL dropped lanes, results identical")

    log("ALL CHECKS PASSED")


if __name__ == "__main__":
    main()
