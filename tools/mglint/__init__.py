"""mglint — project-native static analysis for memgraph_tpu.

The hot write path, WAL, fault injection, and replication are
invariant-heavy: every mutation needs an undo delta, every WAL opcode a
replay handler, every fault point a registration, and locks must nest in
one global order. The reference C++ Memgraph leans on sanitizers and
Jepsen-style checking for this class of bug; this Python reproduction
gets neither for free. mglint is the replacement: AST-based rules that
encode the invariants the code review keeps re-checking by hand, run in
tier-1 forever (tests/test_mglint.py).

Rules:
    MG001  lock-order        static lock-nesting graph; order inversions
    MG002  blocking-under-lock  fsync/socket/sleep/subprocess in a
                                critical section
    MG003  swallowed-exception  broad except that neither logs,
                                re-raises, nor routes the error
    MG004  jax-purity        host side effects inside jitted ops
    MG005  registry-coverage WAL opcodes and fault points fully wired

Usage:
    python -m tools.mglint memgraph_tpu/            # human output
    python -m tools.mglint --json memgraph_tpu/     # machine output

Inline suppression:  # mglint: disable=MG003 — <why>
Accepted findings live in tools/mglint/baseline.json, one justification
per entry. Exit is non-zero on any unbaselined finding.

The runtime counterpart is memgraph_tpu/utils/locks.py (TrackedLock):
MG001 proves the *static* acquisition graph acyclic; TrackedLock, armed
with MG_TRACK_LOCKS=1, witnesses the *dynamic* graph during the test
suite and fails on cycles.
"""

from .core import Finding, Project, load_baseline, run_rules  # noqa: F401
from .registry import RULES, register  # noqa: F401
