"""mglint core: project model, findings, suppressions, baseline.

A `Project` parses every .py file under the scan roots exactly once and
hands rules a uniform view (path -> AST + source lines + suppression
map). Findings carry a *stable* key — rule : relative path : enclosing
symbol : rule-chosen fingerprint — deliberately excluding line numbers,
so a baseline entry survives unrelated edits to the same file.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO

_SUPPRESS_RE = re.compile(
    r"#\s*mglint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s*[—#-].*)?$")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass(frozen=True)
class Finding:
    rule: str            # "MG001"
    path: str            # posix-style path relative to the scan cwd
    line: int
    col: int
    message: str
    symbol: str = ""     # enclosing qualname ("Class.method") or ""
    fingerprint: str = ""  # rule-chosen stable detail (never a line no.)

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.fingerprint}"

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}{sym}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "symbol": self.symbol, "key": self.key}


class SourceFile:
    """One parsed file: AST, raw lines, and the suppression line-map."""

    def __init__(self, path: str, rel_path: str, text: str):
        self.path = path
        self.rel_path = rel_path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._suppressed: dict[int, set[str]] | None = None

    @property
    def suppressed(self) -> dict[int, set[str]]:
        """line number -> set of rule ids disabled on that line.

        A trailing comment covers its own line; a standalone comment
        line covers itself and the next line.
        """
        if self._suppressed is None:
            out: dict[int, set[str]] = {}
            try:
                tokens = list(tokenize.generate_tokens(
                    StringIO(self.text).readline))
            except (tokenize.TokenError, IndentationError):
                tokens = []
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip().upper() for r in m.group(1).split(",")
                         if r.strip()}
                line = tok.start[0]
                out.setdefault(line, set()).update(rules)
                # standalone comment: also covers the next non-comment
                # line (multi-line justification comments are one unit)
                if self.lines[line - 1].lstrip().startswith("#"):
                    nxt = line + 1
                    while nxt <= len(self.lines) and \
                            self.lines[nxt - 1].lstrip().startswith("#"):
                        nxt += 1
                    out.setdefault(nxt, set()).update(rules)
            self._suppressed = out
        return self._suppressed

    def is_suppressed(self, rule: str, line: int) -> bool:
        got = self.suppressed.get(line, ())
        return rule in got or "ALL" in got

    def ensure_parents(self) -> None:
        """Attach parent links exactly once per file per run; every rule
        that needs qualnames shares the same annotated tree."""
        if not getattr(self, "_parents_attached", False):
            attach_parents(self.tree)
            self._parents_attached = True


class Project:
    """All parsed sources under the scan roots."""

    def __init__(self, roots: list[str], cwd: str | None = None):
        self.cwd = os.path.abspath(cwd or os.getcwd())
        self.files: dict[str, SourceFile] = {}   # rel_path -> SourceFile
        self.errors: list[str] = []
        for root in roots:
            root = os.path.abspath(root)
            if os.path.isfile(root):
                self._load(root)
                continue
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__",))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        self._load(os.path.join(dirpath, name))

    def _load(self, path: str) -> None:
        rel = os.path.relpath(path, self.cwd).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            self.files[rel] = SourceFile(path, rel, text)
        except (OSError, SyntaxError, ValueError) as e:
            self.errors.append(f"{rel}: cannot parse: {e}")

    def by_suffix(self, suffix: str) -> "SourceFile | None":
        """The unique file whose relative path ends with `suffix`
        (posix-style), or None."""
        hits = [sf for rel, sf in self.files.items()
                if rel.endswith(suffix)]
        return hits[0] if len(hits) == 1 else None


# --- qualname helper used by several rules ---------------------------------


def attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._mglint_parent = node  # type: ignore[attr-defined]


def qualname_of(node: ast.AST) -> str:
    """Dotted Class.method / function name enclosing `node` (best effort;
    requires attach_parents() on the tree)."""
    parts: list[str] = []
    cur = getattr(node, "_mglint_parent", None)
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        parts.append(node.name)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "_mglint_parent", None)
    return ".".join(reversed(parts))


# --- baseline ---------------------------------------------------------------


def load_baseline(path: str | None = None) -> dict[str, str]:
    """baseline.json -> {finding key: justification}. Every entry MUST
    carry a non-empty justification — an unexplained baseline entry is
    itself an error (raised here so the tier-1 gate catches it)."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    out: dict[str, str] = {}
    for entry in doc.get("entries", ()):
        key = entry.get("key", "")
        just = (entry.get("justification") or "").strip()
        if not key:
            raise ValueError(f"{path}: baseline entry without a key")
        if not just:
            raise ValueError(
                f"{path}: baseline entry {key!r} has no justification — "
                "every accepted finding must say why it is accepted")
        out[key] = just
    return out


# --- driver -----------------------------------------------------------------


@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)      # unbaselined
    baselined: list[Finding] = field(default_factory=list)
    suppressed_count: int = 0
    unused_baseline: list[str] = field(default_factory=list)
    parse_errors: list[str] = field(default_factory=list)


def run_rules(project: Project, baseline: dict[str, str] | None = None,
              only: set[str] | None = None) -> RunResult:
    # importing .rules registers every rule exactly once
    from . import rules as _rules  # noqa: F401
    from .registry import RULES

    baseline = baseline or {}
    result = RunResult(parse_errors=list(project.errors))
    seen_keys: set[str] = set()
    for rule_id in sorted(RULES):
        if only and rule_id not in only:
            continue
        for finding in RULES[rule_id](project):
            sf = project.files.get(finding.path)
            if sf is not None and sf.is_suppressed(finding.rule,
                                                   finding.line):
                result.suppressed_count += 1
                continue
            seen_keys.add(finding.key)
            if finding.key in baseline:
                result.baselined.append(finding)
            else:
                result.findings.append(finding)
    if not only:
        result.unused_baseline = sorted(k for k in baseline
                                        if k not in seen_keys)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.baselined.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
