"""mglint command line: `python -m tools.mglint [paths...]`.

Exit codes: 0 clean (or everything baselined/suppressed), 1 unbaselined
findings, 2 bad invocation / broken baseline.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import (DEFAULT_BASELINE, Project, load_baseline,
                   run_rules)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.mglint",
        description="memgraph_tpu project-native static analysis")
    p.add_argument("paths", nargs="*", default=["memgraph_tpu"],
                   help="files or directories to analyze "
                        "(default: memgraph_tpu)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON output")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: tools/mglint/"
                        "baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: show every finding")
    p.add_argument("--rule", action="append", default=None,
                   metavar="MG00X",
                   help="run only this rule (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="list registered rules and exit")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings covered by the baseline")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from . import rules as _rules  # noqa: F401
        from .registry import RULES
        for rule_id in sorted(RULES):
            entry = RULES[rule_id]
            first_line = (entry.doc or "").splitlines()[0] if entry.doc \
                else ""
            print(f"{rule_id}  {entry.name:24s} {first_line}")
        return 0

    try:
        baseline = {} if args.no_baseline else \
            load_baseline(args.baseline)
    except (ValueError, OSError) as e:
        print(f"mglint: broken baseline: {e}", file=sys.stderr)
        return 2

    paths = args.paths or ["memgraph_tpu"]
    project = Project(paths)
    if not project.files:
        print(f"mglint: no Python files under {paths}",
              file=sys.stderr)
        return 2
    only = {r.upper() for r in args.rule} if args.rule else None
    result = run_rules(project, baseline, only=only)

    if args.json:
        doc = {
            "findings": [f.as_dict() for f in result.findings],
            "baselined": [f.as_dict() for f in result.baselined],
            "suppressed": result.suppressed_count,
            "unused_baseline": result.unused_baseline,
            "parse_errors": result.parse_errors,
            "files_scanned": len(project.files),
        }
        print(json.dumps(doc, indent=2))
        return 1 if (result.findings or result.parse_errors) else 0

    for err in result.parse_errors:
        print(f"PARSE ERROR: {err}")
    for f in result.findings:
        print(f.render())
    if args.show_baselined:
        for f in result.baselined:
            print(f"(baselined) {f.render()}")
    for key in result.unused_baseline:
        print(f"note: unused baseline entry: {key}")
    n, b, s = (len(result.findings), len(result.baselined),
               result.suppressed_count)
    print(f"mglint: {len(project.files)} files, {n} finding(s), "
          f"{b} baselined, {s} suppressed")
    return 1 if (result.findings or result.parse_errors) else 0
