"""Rule registry: one decorator, one dict, deterministic order."""

from __future__ import annotations

#: rule id -> rule callable(project) -> iterable[Finding]
RULES: dict[str, "RuleEntry"] = {}


class RuleEntry:
    __slots__ = ("rule_id", "name", "doc", "fn")

    def __init__(self, rule_id: str, name: str, doc: str, fn):
        self.rule_id = rule_id
        self.name = name
        self.doc = doc
        self.fn = fn

    def __call__(self, project):
        return self.fn(project)


def register(rule_id: str, name: str):
    """Register a rule function under `rule_id` (e.g. "MG001")."""

    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id}")
        RULES[rule_id] = RuleEntry(rule_id, name,
                                   (fn.__doc__ or "").strip(), fn)
        return fn

    return deco
