"""Shared static lock model for MG001 (lock order) and MG002 (blocking
under lock).

Pass 1 finds every lock *creation* site — ``self.X = threading.Lock()``
(also RLock/Condition and the project's ``tracked_lock(...)`` wrappers)
inside a class body, or a module-level assignment — and gives each lock
a stable identity: ``Class.attr`` or ``module.py:NAME``.

Pass 2 walks every function with an explicit held-lock stack: a
``with <lock>:`` pushes, leaving the block pops. Everything observed
while the stack is non-empty (nested acquisitions, calls) is recorded.
Call targets are resolved conservatively — same-module functions,
``self.method`` in the same class, and methods whose name is unique
across the whole project; anything ambiguous is dropped rather than
guessed, so the graph under-approximates but never invents an edge.

A fixpoint then computes each function's *may-acquire* set (locks it or
any resolved callee can take) and *blocking-ops* set (fsync, socket
I/O, sleep, subprocess). MG001 turns held->acquired pairs into a
digraph and reports strongly-connected components; MG002 reports
blocking operations reachable while a storage/replication/server lock
is held.

Attribute receivers other than ``self`` resolve only when the attribute
name has exactly one creating class project-wide; otherwise the lock is
*anonymous* — it still counts as "a lock is held" for MG002 but never
contributes identity edges to MG001.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .core import Project, SourceFile

LOCKISH_ATTR = re.compile(r"(?:^|_)(lock|cond|mutex|sem)", re.I)

_LOCK_CTOR_ATTRS = {"Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore"}
_TRACKED_CTORS = {"tracked_lock", "tracked_rlock", "tracked_condition",
                  "TrackedLock"}

# call patterns that block the calling thread (syscalls / sleeps)
_BLOCKING_DOTTED = {
    "os.fsync": "fsync", "os.replace": "rename", "os.rename": "rename",
    "time.sleep": "sleep",
    "subprocess.run": "subprocess", "subprocess.call": "subprocess",
    "subprocess.Popen": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "socket.create_connection": "socket connect",
    # device-plane dispatches (r12): a device call made while holding a
    # storage/server lock is EXACTLY the wedge class the kernel-server
    # supervision exists to contain — a hung tunnel or lost chip stalls
    # every thread queued behind that lock
    "jax.device_put": "device dispatch (device_put)",
    "jax.block_until_ready": "device sync (block_until_ready)",
}
_BLOCKING_METHODS = {
    "sendall": "socket send", "sendto": "socket send",
    "recv": "socket recv", "recv_into": "socket recv",
    "accept": "socket accept", "makefile": "socket I/O",
    "fsync": "fsync",
    # project replication protocol helpers (replication/protocol.py)
    "send_json": "socket send", "send_frame": "socket send",
    "recv_frame": "socket recv",
    # device dispatch / sync entry points reachable as methods
    "block_until_ready": "device sync (block_until_ready)",
    "to_device": "device dispatch (to_device)",
    "put_edge_blocks": "device dispatch (device_put)",
    "put_replicated": "device dispatch (device_put)",
    "device_fault_point": "device dispatch (fault boundary)",
}
_BLOCKING_NAMES = {"open": "file open", "sleep": "sleep",
                   # kernel-server protocol helpers
                   # (server/kernel_server.py framing)
                   "_send_msg": "kernel-server send",
                   "_recv_msg": "kernel-server recv",
                   "device_fault_point": "device dispatch "
                                         "(fault boundary)"}

#: subsystems whose locks sit on commit / session critical paths
CRITICAL_DIRS = ("storage", "replication", "server", "coordination")

#: container methods that MUTATE their receiver — `self.shared.append(x)`
#: counts as a write to the shared field for MG006/MG007
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "remove", "discard", "pop", "popleft",
    "popitem", "clear", "update", "setdefault", "extend", "insert",
    "sort",
})

#: method names that shadow stdlib container/file/thread APIs — never
#: resolved by project-wide uniqueness (a `cache.values()` must not
#: resolve to some class's `values`); `self.x()` still resolves exactly.
_COMMON_METHODS = frozenset({
    "flush", "clear", "values", "keys", "items", "get", "put", "pop",
    "append", "appendleft", "add", "remove", "close", "write", "read",
    "start", "stop", "join", "send", "update", "copy", "count",
    "index", "sort", "extend", "insert", "discard", "popleft", "popitem",
    "release", "set", "wait", "notify", "notify_all", "open", "next",
    "submit", "map", "result", "acquire", "run", "readline", "seek",
    "tell", "name", "encode", "decode", "strip", "split", "format",
    "setdefault", "union", "difference", "intersection", "shutdown",
    "cancel", "done", "exception", "warning", "error", "info", "debug",
})


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_ctor(call: ast.Call) -> str | None:
    """'plain'/'rlock'/'tracked' when `call` creates a lock, else None."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTOR_ATTRS:
        base = dotted(fn.value)
        if base and base.split(".")[-1] == "threading":
            return "rlock" if fn.attr == "RLock" else "plain"
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name in _TRACKED_CTORS:
        return "rlock" if name == "tracked_rlock" else "tracked"
    return None


@dataclass
class LockDef:
    lock_id: str
    kind: str              # plain | rlock | tracked
    rel_path: str
    line: int


@dataclass
class Acquisition:
    lock_id: str | None    # None = anonymous (lock-ish but unresolved)
    attr: str              # source-level name, for messages
    line: int
    col: int


@dataclass
class CallSite:
    target: str | None     # resolved function key, or None
    text: str              # rendered call, for messages
    line: int
    col: int


@dataclass
class HeldEvent:
    """Something that happened while >= 1 lock was held."""
    held: tuple[Acquisition, ...]
    acquisition: Acquisition | None = None
    call: CallSite | None = None
    blocking: tuple[str, CallSite] | None = None   # (op label, site)


@dataclass
class FieldAccess:
    """One syntactic access to a declared shared_field, with the lock
    regions held at that point. `held` snapshots the live Acquisition
    objects — two accesses are atomic w.r.t. each other iff they share
    one (identity-compared) acquisition, i.e. sit in the SAME `with`
    region, not merely under the same lock name."""
    cls: str               # declaring class ("Metrics")
    fname: str             # field name ("_counters")
    kind: str              # "r" | "w"
    line: int
    col: int
    held: tuple[Acquisition, ...]
    in_return: bool = False   # load consumed by a `return` statement


@dataclass
class FuncInfo:
    key: str               # "<rel_path>::<qualname>"
    rel_path: str
    qualname: str
    class_name: str | None
    node: ast.AST
    acquisitions: list[Acquisition] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    events: list[HeldEvent] = field(default_factory=list)
    direct_blocking: list[tuple[str, CallSite]] = field(
        default_factory=list)
    shared_accesses: list[FieldAccess] = field(default_factory=list)
    # fixpoint results
    may_acquire: set[str] = field(default_factory=set)
    may_block: dict[str, str] = field(default_factory=dict)  # op -> via


def get_model(project: Project) -> "LockModel":
    """The project's LockModel, built exactly ONCE and shared by every
    rule that needs lock regions / call resolution (MG001, MG002, MG006,
    MG007). The model walk dominates mglint runtime, so the single-pass
    driver keeps the tier-1 gate flat as rules accumulate."""
    model = getattr(project, "_mglint_lock_model", None)
    if model is None:
        model = LockModel(project)
        project._mglint_lock_model = model
    return model


class LockModel:
    def __init__(self, project: Project):
        self.project = project
        self.defs: dict[str, LockDef] = {}
        # attr name -> set of owning class names (for unique resolution)
        self._attr_owners: dict[str, set[str]] = {}
        self._module_locks: dict[tuple[str, str], str] = {}
        self.functions: dict[str, FuncInfo] = {}
        self._module_funcs: dict[tuple[str, str], str] = {}
        self._methods: dict[str, list[str]] = {}   # name -> func keys
        # (rel, local name) -> module rel path  /  (module rel, symbol)
        self._mod_alias: dict[tuple[str, str], str] = {}
        self._sym_import: dict[tuple[str, str], tuple[str, str]] = {}
        # shared_field(self, "a", "b") declarations (MG006/MG007):
        # class -> declared fields / field -> declaring classes
        self.shared_decls: dict[str, set[str]] = {}
        self.shared_owners: dict[str, set[str]] = {}
        self._class_bases: dict[str, set[str]] = {}
        self._collect_definitions()
        self._collect_imports()
        self._collect_functions()
        self._fixpoint()

    # --- import resolution ------------------------------------------------

    def _module_file(self, parts: list[str]) -> str | None:
        if not parts or not all(parts):
            return None
        base = "/".join(parts)
        for cand in (f"{base}.py", f"{base}/__init__.py"):
            if cand in self.project.files:
                return cand
        return None

    def _collect_imports(self) -> None:
        for rel, sf in self.project.files.items():
            pkg = rel.split("/")[:-1]
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ImportFrom):
                    if node.level:
                        if node.level - 1 > len(pkg):
                            continue
                        base = pkg[:len(pkg) - (node.level - 1)]
                        base += node.module.split(".") if node.module \
                            else []
                    else:
                        base = node.module.split(".") if node.module \
                            else []
                    mod_file = self._module_file(base)
                    for a in node.names:
                        if a.name == "*":
                            continue
                        local = a.asname or a.name
                        sub = self._module_file(base + [a.name])
                        if sub is not None:
                            self._mod_alias[(rel, local)] = sub
                        elif mod_file is not None:
                            self._sym_import[(rel, local)] = (mod_file,
                                                              a.name)
                elif isinstance(node, ast.Import):
                    for a in node.names:
                        mod_file = self._module_file(a.name.split("."))
                        if mod_file is not None:
                            local = a.asname or a.name.split(".")[0]
                            self._mod_alias[(rel, local)] = mod_file

    # --- pass 1: lock creation sites ------------------------------------

    def _collect_definitions(self) -> None:
        for rel, sf in self.project.files.items():
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                self._class_bases.setdefault(node.name, set()).update(
                    b.id if isinstance(b, ast.Name) else b.attr
                    for b in node.bases
                    if isinstance(b, (ast.Name, ast.Attribute)))
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call)
                            and self._is_shared_decl(sub)):
                        fields = {a.value for a in sub.args[1:]
                                  if isinstance(a, ast.Constant)
                                  and isinstance(a.value, str)}
                        if fields:
                            self.shared_decls.setdefault(
                                node.name, set()).update(fields)
                            for f in fields:
                                self.shared_owners.setdefault(
                                    f, set()).add(node.name)
                    if not (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    kind = _is_lock_ctor(sub.value)
                    if kind is None:
                        continue
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            lock_id = f"{node.name}.{tgt.attr}"
                            self.defs.setdefault(lock_id, LockDef(
                                lock_id, kind, rel, sub.lineno))
                            self._attr_owners.setdefault(
                                tgt.attr, set()).add(node.name)
            # module-level locks
            for stmt in sf.tree.body:
                if (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Call)):
                    kind = _is_lock_ctor(stmt.value)
                    if kind is None:
                        continue
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            mod = rel.rsplit("/", 1)[-1]
                            lock_id = f"{mod}:{tgt.id}"
                            self.defs.setdefault(lock_id, LockDef(
                                lock_id, kind, rel, stmt.lineno))
                            self._module_locks[(rel, tgt.id)] = lock_id

    @staticmethod
    def _is_shared_decl(call: ast.Call) -> bool:
        """True for `shared_field(<owner>, "f", ...)` calls (any import
        spelling: bare name or `sanitize.shared_field`)."""
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        return name == "shared_field" and len(call.args) >= 2

    # --- shared-field access resolution (MG006/MG007) --------------------

    def _inherits(self, cls: str, owner: str) -> bool:
        seen, frontier = set(), {cls}
        while frontier:
            cur = frontier.pop()
            if cur == owner:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            frontier |= self._class_bases.get(cur, set())
        return False

    def resolve_shared_owner(self, node: ast.Attribute,
                             fi: FuncInfo) -> str | None:
        """Declaring class for an `X.field` access, or None.

        `self.field` resolves through the enclosing class (including
        inherited declarations); any other receiver resolves only when
        exactly ONE class project-wide declares that field name —
        ambiguity is dropped, never guessed, mirroring resolve_lock."""
        owners = self.shared_owners.get(node.attr)
        if not owners:
            return None
        recv = node.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            if not fi.class_name:
                return None
            if fi.class_name in owners:
                return fi.class_name
            for owner in owners:
                if self._inherits(fi.class_name, owner):
                    return owner
            return None
        if len(owners) == 1:
            return next(iter(owners))
        return None

    def is_constructor_of(self, fi: FuncInfo, owner: str) -> bool:
        """True when `fi` is __init__/__post_init__ of the declaring
        class (or a subclass): the object is thread-local during
        construction, so unguarded field setup there is not a race."""
        short = fi.qualname.rsplit(".", 1)[-1]
        if short not in ("__init__", "__post_init__"):
            return False
        cls = fi.class_name
        return cls is not None and (cls == owner
                                    or self._inherits(cls, owner))

    @staticmethod
    def _access_kind(node: ast.Attribute) -> str:
        """'w' for stores, subscript-stores (`x.f[k] = v`) and mutating
        method calls (`x.f.append(v)`); 'r' otherwise."""
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return "w"
        parent = getattr(node, "_mglint_parent", None)
        if (isinstance(parent, ast.Subscript) and parent.value is node
                and isinstance(parent.ctx, (ast.Store, ast.Del))):
            return "w"
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _MUTATOR_METHODS):
            grand = getattr(parent, "_mglint_parent", None)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return "w"
        return "r"

    @staticmethod
    def _in_return(node: ast.AST) -> bool:
        """True when the access sits inside a `return` expression: the
        function exits with it, so it cannot be the "check" half of a
        check-then-act within this function (MG007)."""
        cur = getattr(node, "_mglint_parent", None)
        while cur is not None and isinstance(cur, ast.expr):
            cur = getattr(cur, "_mglint_parent", None)
        return isinstance(cur, ast.Return)

    # --- lock expression resolution -------------------------------------

    def resolve_lock(self, expr: ast.AST, rel: str,
                     cls: str | None) -> tuple[str | None, str] | None:
        """(lock_id | None, display name) when `expr` looks like a lock;
        None when it clearly is not one."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            owners = self._attr_owners.get(attr, set())
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self" and cls):
                if cls in owners:
                    return f"{cls}.{attr}", f"self.{attr}"
            if len(owners) == 1:
                owner = next(iter(owners))
                return f"{owner}.{attr}", dotted(expr) or attr
            if owners or LOCKISH_ATTR.search(attr):
                return None, dotted(expr) or attr   # anonymous lock
            return None
        if isinstance(expr, ast.Name):
            lock_id = self._module_locks.get((rel, expr.id))
            if lock_id:
                return lock_id, expr.id
            if LOCKISH_ATTR.search(expr.id):
                return None, expr.id
        return None

    # --- pass 2: function walks -----------------------------------------

    def _collect_functions(self) -> None:
        # phase A: register every function so calls resolve project-wide
        for rel, sf in self.project.files.items():
            self._register_scope(sf, sf.tree.body, qual="", cls=None)
        for key, fi in self.functions.items():
            short = fi.qualname.rsplit(".", 1)[-1]
            if fi.class_name:
                self._methods.setdefault(short, []).append(key)
            else:
                self._module_funcs[(fi.rel_path, short)] = key
        # phase B: walk bodies (resolution indexes are now complete);
        # parent links are needed for shared-field access kinds and are
        # attached exactly once per file (shared with MG003 et al.)
        if self.shared_owners:
            for sf in self.project.files.values():
                sf.ensure_parents()
        for fi in self.functions.values():
            sf = self.project.files[fi.rel_path]
            self._walk_function(sf, fi, fi.node.body, held=[])

    def _register_scope(self, sf: SourceFile, body, qual: str,
                        cls: str | None) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{stmt.name}" if qual else stmt.name
                fi = FuncInfo(key=f"{sf.rel_path}::{q}",
                              rel_path=sf.rel_path, qualname=q,
                              class_name=cls, node=stmt)
                self.functions[fi.key] = fi
                # nested defs become their own FuncInfo
                self._register_scope(sf, stmt.body, qual=q, cls=cls)
            elif isinstance(stmt, ast.ClassDef):
                q = f"{qual}.{stmt.name}" if qual else stmt.name
                self._register_scope(sf, stmt.body, qual=q,
                                     cls=stmt.name)

    def _walk_function(self, sf: SourceFile, fi: FuncInfo, body,
                       held: list[Acquisition]) -> None:
        """Statement-level walk with an explicit held-lock stack. Nested
        compound statements (if/for/while/try/match) recurse with the
        same stack; `with <lock>:` pushes for the extent of its body."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # deferred execution: separate scope
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in stmt.items:
                    got = self.resolve_lock(item.context_expr,
                                            sf.rel_path, fi.class_name)
                    if got is None:
                        self._scan_expr(sf, fi, item.context_expr, held)
                        continue
                    lock_id, name = got
                    acq = Acquisition(lock_id, name,
                                      item.context_expr.lineno,
                                      item.context_expr.col_offset)
                    fi.acquisitions.append(acq)
                    if held:
                        fi.events.append(HeldEvent(tuple(held),
                                                   acquisition=acq))
                    held.append(acq)
                    pushed += 1
                self._walk_function(sf, fi, stmt.body, held)
                if pushed:
                    del held[-pushed:]
                continue
            for _name, value in ast.iter_fields(stmt):
                if isinstance(value, ast.expr):
                    self._scan_expr(sf, fi, value, held)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._scan_expr(sf, fi, v, held)
                        elif isinstance(v, ast.ExceptHandler):
                            if v.type is not None:
                                self._scan_expr(sf, fi, v.type, held)
                            self._walk_function(sf, fi, v.body, held)
                        elif isinstance(v, ast.stmt):
                            self._walk_function(sf, fi, [v], held)
                        elif hasattr(v, "body") and \
                                isinstance(getattr(v, "body"), list):
                            # match_case and friends
                            self._walk_function(sf, fi, v.body, held)

    def _scan_expr(self, sf: SourceFile, fi: FuncInfo, expr: ast.AST,
                   held: list[Acquisition]) -> None:
        """Visit every Call inside an expression (lambda bodies are
        deferred execution and skipped)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                self._visit_call(sf, fi, node, held)
            elif (isinstance(node, ast.Attribute)
                    and node.attr in self.shared_owners):
                owner = self.resolve_shared_owner(node, fi)
                if owner is not None:
                    fi.shared_accesses.append(FieldAccess(
                        owner, node.attr, self._access_kind(node),
                        node.lineno, node.col_offset, tuple(held),
                        in_return=self._in_return(node)))
            stack.extend(ast.iter_child_nodes(node))

    def _visit_call(self, sf: SourceFile, fi: FuncInfo, call: ast.Call,
                    held: list[Acquisition]) -> None:
        name = dotted(call.func)
        site = CallSite(None, name or "<call>", call.lineno,
                        call.col_offset)
        # .acquire() is an acquisition event
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"):
            got = self.resolve_lock(call.func.value, sf.rel_path,
                                    fi.class_name)
            if got is not None:
                acq = Acquisition(got[0], got[1], call.lineno,
                                  call.col_offset)
                fi.acquisitions.append(acq)
                if held:
                    fi.events.append(HeldEvent(tuple(held),
                                               acquisition=acq))
            return
        # blocking classification
        op = None
        if name in _BLOCKING_DOTTED:
            op = _BLOCKING_DOTTED[name]
        elif (isinstance(call.func, ast.Attribute)
                and call.func.attr in _BLOCKING_METHODS):
            op = _BLOCKING_METHODS[call.func.attr]
        elif (isinstance(call.func, ast.Name)
                and call.func.id in _BLOCKING_NAMES):
            op = _BLOCKING_NAMES[call.func.id]
        if op is not None:
            entry = (op, site)
            fi.direct_blocking.append(entry)
            if held:
                fi.events.append(HeldEvent(tuple(held), blocking=entry))
            return
        # plain call: resolve for the graph
        site.target = self._resolve_call(call, sf.rel_path, fi.class_name)
        fi.calls.append(site)
        if held:
            fi.events.append(HeldEvent(tuple(held), call=site))

    def _resolve_call(self, call: ast.Call, rel: str,
                      cls: str | None) -> str | None:
        fn = call.func
        if isinstance(fn, ast.Name):
            local = self._module_funcs.get((rel, fn.id))
            if local is not None:
                return local
            # imported symbol: from mod import f
            target = self._sym_import.get((rel, fn.id))
            if target is not None:
                return self._module_funcs.get(target)
            return None
        if isinstance(fn, ast.Attribute):
            short = fn.attr
            if isinstance(fn.value, ast.Name):
                base = fn.value.id
                if base == "self" and cls:
                    for key in self._methods.get(short, ()):
                        fi = self.functions[key]
                        if fi.class_name == cls and fi.rel_path == rel:
                            return key
                # module alias: pr.pagerank() -> ops/pagerank.py::pagerank
                mod = self._mod_alias.get((rel, base))
                if mod is not None:
                    return self._module_funcs.get((mod, short))
                # imported class: Cls.method() (also covers Cls()
                # instances only when unique-name resolution hits below)
                sym = self._sym_import.get((rel, base))
                if sym is not None:
                    key = f"{sym[0]}::{sym[1]}.{short}"
                    if key in self.functions:
                        return key
            if short in _COMMON_METHODS:
                return None
            candidates = self._methods.get(short, ())
            if len(candidates) == 1:
                return candidates[0]
        return None

    # --- fixpoint summaries ----------------------------------------------

    def _fixpoint(self) -> None:
        for fi in self.functions.values():
            fi.may_acquire = {a.lock_id for a in fi.acquisitions
                              if a.lock_id}
            fi.may_block = {op: op for op, _ in fi.direct_blocking}
        changed = True
        while changed:
            changed = False
            for fi in self.functions.values():
                for site in fi.calls:
                    if site.target is None:
                        continue
                    callee = self.functions.get(site.target)
                    if callee is None:
                        continue
                    new_locks = callee.may_acquire - fi.may_acquire
                    if new_locks:
                        fi.may_acquire |= new_locks
                        changed = True
                    for op in callee.may_block:
                        if op not in fi.may_block:
                            fi.may_block[op] = \
                                f"via {callee.qualname}: " \
                                f"{callee.may_block[op]}" \
                                if not callee.may_block[op].startswith(
                                    "via ") else callee.may_block[op]
                            changed = True

    # --- helpers for the rules -------------------------------------------

    def callee(self, site: CallSite) -> FuncInfo | None:
        return self.functions.get(site.target) if site.target else None

    def is_rlock(self, lock_id: str) -> bool:
        d = self.defs.get(lock_id)
        return d is not None and d.kind == "rlock"
