"""MG007 — check-then-act: a declared shared field is READ in one lock
region and then WRITTEN in a different (or no) region inside the same
function.

The classic TOCTOU shape: take the lock, read the value, drop the lock,
decide, re-take a lock, write — another thread interleaves between the
two regions and the write acts on a stale read. Atomicity is judged by
*region identity*, not lock name: re-acquiring the same lock in a second
`with` block is still two regions (the interleaving window is the gap
between them). A read and write covered by one common live acquisition
are atomic and never flagged.

The canonical fix is recognized: a write whose OWN region re-reads the
field before acting (`if key in self.cache: ...` under the write lock)
has re-validated the stale decision and is clean — only writes that act
on the earlier region's read with no re-check are flagged.

Deliberate splits that dodge even the re-check carry an inline
`# mglint: disable=MG007` with the reason at the write site.
"""

from __future__ import annotations

from ..core import Finding, Project
from ..locking import get_model
from ..registry import register


@register("MG007", "check-then-act")
def check(project: Project):
    """Shared-field read then write must share one lock region."""
    model = get_model(project)
    findings = []
    for key in sorted(model.functions):
        fi = model.functions[key]
        if not fi.shared_accesses:
            continue
        reported: set[tuple] = set()
        loads: dict[tuple, list] = {}    # (cls, field) -> earlier loads
        for fa in fi.shared_accesses:
            fk = (fa.cls, fa.fname)
            if fa.kind == "r":
                # a returned read exits the function: it can never be
                # the "check" half (e.g. an early-return branch)
                if not fa.in_return:
                    loads.setdefault(fk, []).append(fa)
                continue
            if fk in reported or model.is_constructor_of(fi, fa.cls):
                continue
            held_ids = {id(a) for a in fa.held}
            # a load sharing a live acquisition with this write is a
            # re-check under the write's own region: the stale earlier
            # read was re-validated, the canonical check-then-act fix
            if any(held_ids & {id(a) for a in ld.held}
                   for ld in loads.get(fk, ())):
                continue
            for ld in loads.get(fk, ()):
                if held_ids & {id(a) for a in ld.held}:
                    continue
                reported.add(fk)
                findings.append(Finding(
                    "MG007", fi.rel_path, fa.line, fa.col,
                    f"check-then-act on {fa.cls}.{fa.fname}: read at "
                    f"line {ld.line} and this write share no lock "
                    f"region (stale-read window between them)",
                    symbol=fi.qualname,
                    fingerprint=f"{fa.cls}.{fa.fname}"))
                break
    return findings
