"""MG006 — unguarded-shared-field: a field declared via
`sanitize.shared_field(self, ...)` is accessed with NO lock held.

The declaration is a contract: this attribute is read/written by more
than one thread, so every access must sit inside some lock region (the
dynamic race detector checks the *executed* schedules; this rule checks
every *syntactic* path). Deliberate lock-free reads — monotonic
timestamp gauges where a stale value is merely conservative — carry an
inline `# mglint: disable=MG006` with the reason, or a baseline entry.

Construction is exempt: `__init__`/`__post_init__` of the declaring
class (or a subclass) runs before the object is published to other
threads. Receivers other than `self` resolve only when exactly one
class project-wide declares the field name — ambiguous names are
dropped, never guessed.
"""

from __future__ import annotations

from ..core import Finding, Project
from ..locking import get_model
from ..registry import register


@register("MG006", "unguarded-shared-field")
def check(project: Project):
    """Every access to a declared shared field must hold some lock."""
    model = get_model(project)
    findings = []
    seen: set[tuple] = set()
    for key in sorted(model.functions):
        fi = model.functions[key]
        for fa in fi.shared_accesses:
            if fa.held:
                continue
            if model.is_constructor_of(fi, fa.cls):
                continue
            # one finding per (function, field, kind): a hot loop that
            # touches the field five times is one defect, not five
            dedupe = (fi.key, fa.cls, fa.fname, fa.kind)
            if dedupe in seen:
                continue
            seen.add(dedupe)
            verb = "written" if fa.kind == "w" else "read"
            findings.append(Finding(
                "MG006", fi.rel_path, fa.line, fa.col,
                f"shared field {fa.cls}.{fa.fname} {verb} with no lock "
                f"held (declared shared_field)",
                symbol=fi.qualname,
                fingerprint=f"{fa.cls}.{fa.fname}:{fa.kind}"))
    return findings
