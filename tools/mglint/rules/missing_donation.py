"""MG010 — missing-donation: fixpoint-shaped jitted entry points whose
carry buffers are not donated.

A ``lax.while_loop`` fixpoint holds its iterate in HBM. Without
``donate_argnums`` the caller's input buffer AND the loop's output
buffer are live simultaneously — double the HBM residency of every
O(n)/O(n·B) state vector, which is exactly the headroom the
admission-controlled serving plane budgets against. Donating the carry
(the previous chunk's output, a freshly built seed) lets XLA alias
input to output: before r17 there was not a single ``donate_argnums``
in the tree.

The rule flags ``jax.jit`` applications — call form, decorator form,
and ``jax.jit(builder(...))`` where the builder is a same-module
function — whose jitted computation contains a ``while_loop`` and whose
jit call carries no ``donate_argnums``/``donate_argnames``. Kernels
that genuinely cannot donate (every input reused across calls, the host
loop re-reads the previous iterate, a caller retains the seed) carry a
justified baseline entry — the decision is recorded either way.

Scope: ``ops/`` and ``parallel/`` (the jitted device plane).
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, qualname_of
from ..locking import dotted
from ..registry import register
from .jax_purity import _jit_static_args

_JIT_NAMES = {"jit", "pjit"}
_DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


def _in_scope(rel: str) -> bool:
    return "/ops/" in f"/{rel}" or "/parallel/" in f"/{rel}"


def _has_while_loop(fn: ast.AST, funcs: dict | None = None,
                    _depth: int = 0, _seen: set | None = None) -> bool:
    """while_loop in this function or (transitively, same module) in
    anything it calls — the jitted entry often delegates to a `_loop`
    helper."""
    if _depth > 4:
        return False
    _seen = _seen if _seen is not None else set()
    if id(fn) in _seen:
        return False
    _seen.add(id(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = (dotted(node.func) or "").split(".")[-1]
            if name == "while_loop":
                return True
            callee = (funcs or {}).get(name)
            if callee is not None and _has_while_loop(
                    callee, funcs, _depth + 1, _seen):
                return True
    return False


def _module_funcs(tree: ast.AST) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _jit_target_has_while(arg: ast.AST, funcs: dict) -> bool:
    """Resolve the jitted computation: a local function name, a
    builder call returning one, or a lambda/partial — then look for a
    while_loop in its body."""
    if isinstance(arg, ast.Name):
        fn = funcs.get(arg.id)
        return fn is not None and _has_while_loop(fn, funcs)
    if isinstance(arg, ast.Call):
        callee = (dotted(arg.func) or "").split(".")[-1]
        if callee == "partial" and arg.args:
            return _jit_target_has_while(arg.args[0], funcs)
        fn = funcs.get(callee)
        if fn is not None and _has_while_loop(fn, funcs):
            return True
        # wrapper call (shard_map(step, ...), identity wrappers,
        # functools pipelines): resolve local-function arguments too
        return any(_jit_target_has_while(a, funcs)
                   for a in arg.args if isinstance(a, (ast.Name,
                                                       ast.Lambda)))
    if isinstance(arg, ast.Lambda):
        return _has_while_loop(arg)
    return False


@register("MG010", "missing-donation")
def check(project: Project):
    """jit-of-while_loop without donate_argnums in ops//parallel/."""
    findings: list[Finding] = []
    for rel, sf in sorted(project.files.items()):
        if not _in_scope(rel):
            continue
        sf.ensure_parents()
        funcs = _module_funcs(sf.tree)

        for node in ast.walk(sf.tree):
            hit = None        # (line, col, symbol)
            # decorator form: @jax.jit / @partial(jax.jit, ...)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    is_jit, _static = _jit_static_args(deco)
                    if not is_jit:
                        continue
                    if isinstance(deco, ast.Call) and any(
                            kw.arg in _DONATE_KWARGS
                            for kw in deco.keywords):
                        continue
                    if _has_while_loop(node, funcs):
                        hit = (deco.lineno,
                               getattr(deco, "col_offset", 0),
                               node.name)
            # call form: jax.jit(f, ...) / jax.jit(builder(...), ...)
            elif isinstance(node, ast.Call):
                name = (dotted(node.func) or "").split(".")[-1]
                if name not in _JIT_NAMES or not node.args:
                    continue
                if any(kw.arg in _DONATE_KWARGS
                       for kw in node.keywords):
                    continue
                if _jit_target_has_while(node.args[0], funcs):
                    sym = qualname_of(node) or "<module>"
                    hit = (node.lineno,
                           getattr(node, "col_offset", 0), sym)
            if hit is None:
                continue
            line, col, sym = hit
            findings.append(Finding(
                rule="MG010", path=rel, line=line, col=col, symbol=sym,
                message=f"jitted fixpoint {sym} iterates a while_loop "
                        "but donates no inputs — the carry's HBM "
                        "residency doubles; add donate_argnums for the "
                        "loop state (or baseline with why donation is "
                        "illegal here)",
                fingerprint=f"missing-donation@{sym}"))
    return findings
