"""MG004 — jax-purity: no host side effects inside jitted ops.

Functions that reach ``jax.jit`` / ``pjit`` / ``pallas_call`` in
``ops/`` trace ONCE and replay as compiled XLA programs; a ``print``,
``time.time()``, Python ``random``, host mutation, or a ``np.``
call on a traced argument either silently freezes a trace-time value
into the compiled program (wrong results on the second call) or breaks
fusion with a host round-trip. GraphBLAST-style kernel-purity
discipline is what keeps fused TPU paths correct as they grow.

Jit regions are: functions decorated with ``@jax.jit`` / ``@jit`` /
``@partial(jax.jit, ...)``, functions wrapped inline via
``jax.jit(f)``, nested functions defined inside a jit region, and
same-module functions called from one (transitively).

Inside a region this rule flags:
  * ``print(...)``               (use jax.debug.print)
  * ``time.time/perf_counter/monotonic/sleep``
  * Python stdlib ``random.*``   (use jax.random with explicit keys)
  * ``np.<fn>(...)`` applied directly to a traced parameter of the
    jitted entry function (static_argnames are exempt)
  * ``os.environ`` mutation, ``open(...)``, ``.block_until_ready()``
  * ``global`` / ``nonlocal`` declarations (trace-time host mutation)
"""

from __future__ import annotations

import ast

from ..core import Finding, Project
from ..locking import dotted
from ..registry import register

_JIT_NAMES = {"jit", "pjit"}
_TIME_BAD = {"time.time", "time.perf_counter", "time.monotonic",
             "time.sleep", "time.process_time"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}


def _jit_static_args(deco: ast.AST) -> tuple[bool, set[str]]:
    """(is_jit_decorator, static arg names)."""
    if isinstance(deco, (ast.Name, ast.Attribute)):
        name = dotted(deco) or ""
        short = name.split(".")[-1]
        return short in _JIT_NAMES, set()
    if isinstance(deco, ast.Call):
        fn_name = dotted(deco.func) or ""
        short = fn_name.split(".")[-1]
        if short in _JIT_NAMES:
            return True, _static_names(deco)
        if short == "partial" and deco.args:
            inner = dotted(deco.args[0]) or ""
            if inner.split(".")[-1] in _JIT_NAMES:
                return True, _static_names(deco)
    return False, set()


def _static_names(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        out.add(el.value)
            elif isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                out.add(kw.value.value)
    return out


class _ModuleScan:
    """Per-module jit-region discovery."""

    def __init__(self, sf):
        self.sf = sf
        self.funcs: dict[str, ast.AST] = {}      # local name -> def node
        self.jit_roots: dict[str, set[str]] = {}  # name -> static args
        self.calls: dict[str, set[str]] = {}      # caller -> callee names
        self._index(sf.tree, prefix="")
        self._find_inline_jit(sf.tree)

    def _index(self, tree: ast.AST, prefix: str) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            self.funcs.setdefault(node.name, node)
            callees = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name):
                    callees.add(sub.func.id)
            self.calls[node.name] = callees
            for deco in node.decorator_list:
                is_jit, static = _jit_static_args(deco)
                if is_jit:
                    self.jit_roots[node.name] = static

    def _find_inline_jit(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name.split(".")[-1] in _JIT_NAMES and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in self.funcs:
                    self.jit_roots.setdefault(arg.id,
                                              _static_names(node))

    def jit_region(self) -> dict[str, tuple[str, set[str]]]:
        """function name -> (root name, root's static args) for every
        function transitively reachable from a jit root via same-module
        calls."""
        region: dict[str, tuple[str, set[str]]] = {}
        work = [(root, root) for root in self.jit_roots]
        while work:
            name, root = work.pop()
            if name in region or name not in self.funcs:
                continue
            region[name] = (root, self.jit_roots.get(root, set()))
            for callee in self.calls.get(name, ()):
                if callee in self.funcs and callee not in region:
                    work.append((callee, root))
        return region


def _traced_params(fn: ast.AST, static: set[str]) -> set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args
             + args.kwonlyargs]
    return {n for n in names if n not in static and n != "self"}


@register("MG004", "jax-purity")
def check(project: Project):
    """No host side effects inside jit-reachable ops/ functions."""
    findings = []
    for rel, sf in project.files.items():
        if "/ops/" not in f"/{rel}":
            continue
        scan = _ModuleScan(sf)
        region = scan.jit_region()
        if not region:
            continue
        seen: set[tuple[int, int, str]] = set()
        for name, (root, static) in sorted(region.items()):
            fn = scan.funcs[name]
            is_root = name == root
            traced = _traced_params(fn, static) if is_root else set()
            for node in ast.walk(fn):
                bad = _classify(node, traced, is_root)
                if bad is None:
                    continue
                mark = (node.lineno, getattr(node, "col_offset", 0),
                        bad)
                if mark in seen:   # nested defs walk twice
                    continue
                seen.add(mark)
                where = name if is_root else f"{name} (reached from " \
                    f"jitted {root})"
                findings.append(Finding(
                    rule="MG004", path=rel, line=node.lineno,
                    col=getattr(node, "col_offset", 0), symbol=name,
                    message=f"{bad} inside jit region of {where} — "
                            "host side effect in a traced function",
                    fingerprint=f"impure:{bad.split('(')[0].strip()}"
                                f"@{name}"))
    return findings


def _classify(node: ast.AST, traced: set[str],
              is_root: bool) -> str | None:
    if isinstance(node, ast.Global):
        return "global statement"
    if isinstance(node, ast.Call):
        name = dotted(node.func) or ""
        short = name.split(".")[-1]
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            return "print() call"
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            return "open() call"
        if name in _TIME_BAD:
            return f"{name}() call"
        root_mod = name.split(".")[0]
        if root_mod == "random":
            return f"stdlib {name}() call"
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "block_until_ready":
            return ".block_until_ready() call"
        if root_mod in _NUMPY_ALIASES and is_root and traced:
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in traced:
                    return f"{name}() on traced argument '{arg.id}'"
    if isinstance(node, ast.Subscript):
        tgt = dotted(node.value) or ""
        if tgt == "os.environ" and isinstance(getattr(node, "ctx", None),
                                              (ast.Store, ast.Del)):
            return "os.environ mutation"
    return None
