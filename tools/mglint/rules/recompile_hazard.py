"""MG008 — recompile-hazard: silent per-call retrace/recompile in the
device plane.

``jax.jit`` caches compiled programs on FUNCTION IDENTITY plus abstract
argument signatures. Three codebase patterns defeat that cache without
any error — the program just quietly recompiles on every call, which on
the tunneled accelerator costs seconds per invocation and melts the
serving plane's latency budget (the static half of the
``jit.compile_total`` runtime witness):

  * ``jit-per-call`` — ``jax.jit(...)`` applied inside a function (or a
    ``@jax.jit`` decorator on a nested def) whose result is NOT stored
    through a recognized memo: each call builds a fresh closure, so
    jit's identity-keyed cache never hits. Recognized memos: the jit
    value (or a tuple holding it) assigned into a subscript
    (``CACHE[key] = ...``); an enclosing function using the
    get-then-build-then-store idiom (``.get(`` + a subscript store, or
    ``getattr`` + ``object.__setattr__``); or the enclosing function
    being a builder that such a memo function calls / receives as an
    argument (``_pc_cached``, ``_FIXPOINT_CACHE``, plan caches).
  * ``traced-branch`` — Python ``if``/``while``/ternary on a traced
    parameter of a jit root: either a trace-time concretization error,
    or (once someone "fixes" it by making the arg static) one compiled
    program PER VALUE.
  * ``unhashable-static`` — ``static_argnames``/``static_argnums``
    naming a parameter whose default is a list/dict/set literal:
    unhashable statics fail at call time, and mutable defaults that
    vary per call mean one compile per distinct value anyway.

Scope: ``ops/`` and ``parallel/`` (the jitted device plane).
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, qualname_of
from ..locking import dotted
from ..registry import register
from .jax_purity import _ModuleScan, _jit_static_args, _traced_params

_JIT_NAMES = {"jit", "pjit"}


def _in_scope(rel: str) -> bool:
    return "/ops/" in f"/{rel}" or "/parallel/" in f"/{rel}"


def _is_jit_call(node: ast.Call) -> bool:
    name = dotted(node.func) or ""
    return name.split(".")[-1] in _JIT_NAMES


def _enclosing_funcs(node: ast.AST):
    cur = getattr(node, "_mglint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield cur
        cur = getattr(cur, "_mglint_parent", None)


def _has_memo_idiom(fn: ast.AST) -> bool:
    """The get-then-build-then-store caching idiom."""
    has_get = has_store = has_getattr = has_setattr = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = dotted(node.func) or ""
            short = callee.split(".")[-1]
            if short == "get" and isinstance(node.func, ast.Attribute):
                has_get = True
            if short == "setdefault":
                has_get = has_store = True
            if callee == "getattr":
                has_getattr = True
            if callee == "object.__setattr__":
                has_setattr = True
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Subscript) for t in node.targets):
                has_store = True
    return (has_get and has_store) or (has_getattr and has_setattr)


def _stored_in_subscript(call: ast.Call) -> bool:
    """The jit value (possibly inside a tuple/chained assign) lands in a
    subscript store: ``CACHE[k] = jax.jit(...)`` / ``c[k] = (p, jit)``."""
    cur = call
    parent = getattr(cur, "_mglint_parent", None)
    while parent is not None and isinstance(parent, (ast.Tuple, ast.List)):
        cur = parent
        parent = getattr(cur, "_mglint_parent", None)
    if isinstance(parent, ast.Assign):
        return any(isinstance(t, ast.Subscript) for t in parent.targets)
    if isinstance(parent, ast.Return):
        # returned to the caller: the builder itself decides nothing —
        # resolved through the cached-builder name set instead
        return False
    return False


def _collect_cached_builders(project: Project) -> set[str]:
    """Names exempt from jit-per-call because a memo-idiom function
    calls them or receives them as call arguments (the builder half of
    the get-then-build-then-store pattern), computed project-wide."""
    memo_funcs: set[str] = set()
    infos = []          # (fn node, sf)
    for rel, sf in project.files.items():
        if not rel.endswith(".py"):
            continue
        sf.ensure_parents()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                infos.append(node)
                if _has_memo_idiom(node):
                    memo_funcs.add(node.name)
    exempt: set[str] = set()
    for fn in infos:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = (dotted(node.func) or "").split(".")[-1]
            if fn.name in memo_funcs:
                # builders CALLED from a memo function
                exempt.add(callee)
                # builders PASSED INTO another call from a memo function
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        exempt.add(arg.id)
            elif callee in memo_funcs:
                # builders passed as arguments TO a memo function
                # (the `_pc_cached("kind", _builder, ...)` shape)
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        exempt.add(arg.id)
    return exempt


_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set)


@register("MG008", "recompile-hazard")
def check(project: Project):
    """Per-call jit, traced-value branching, unhashable static args."""
    findings: list[Finding] = []
    cached_builders: set[str] | None = None
    for rel, sf in sorted(project.files.items()):
        if not _in_scope(rel):
            continue
        sf.ensure_parents()

        # --- jit-per-call --------------------------------------------
        for node in ast.walk(sf.tree):
            hit_line = None
            builder_chain = None
            if isinstance(node, ast.Call) and _is_jit_call(node):
                encl = list(_enclosing_funcs(node))
                if not encl:
                    continue          # module-level jit: compiled once
                if _stored_in_subscript(node):
                    continue
                builder_chain = encl
                hit_line = node.lineno
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jit_deco = next(
                    (d for d in node.decorator_list
                     if _jit_static_args(d)[0]), None)
                if jit_deco is None:
                    continue
                encl = list(_enclosing_funcs(node))
                if not encl:
                    continue          # module-level decorated def
                builder_chain = encl
                hit_line = node.lineno
            if hit_line is None:
                continue
            if any(_has_memo_idiom(fn) for fn in builder_chain):
                continue
            if cached_builders is None:
                cached_builders = _collect_cached_builders(project)
            if any(fn.name in cached_builders for fn in builder_chain):
                continue
            sym = qualname_of(node if isinstance(node, ast.FunctionDef)
                              else builder_chain[0])
            findings.append(Finding(
                rule="MG008", path=rel, line=hit_line,
                col=getattr(node, "col_offset", 0), symbol=sym,
                message="jax.jit applied per call (fresh closure each "
                        "invocation defeats jit's identity-keyed cache: "
                        "silent retrace + recompile every call) — store "
                        "the jitted fn in a keyed cache",
                fingerprint=f"jit-per-call@{sym}"))

        # --- traced-branch + unhashable-static over jit roots ---------
        scan = _ModuleScan(sf)
        for name, static in sorted(scan.jit_roots.items()):
            fn = scan.funcs.get(name)
            if fn is None:
                continue
            traced = _traced_params(fn, static)
            findings.extend(_traced_branches(rel, fn, name, traced))
            findings.extend(_unhashable_statics(rel, fn, name, static))
    return findings


def _branch_names(test: ast.AST, traced: set[str]) -> set[str]:
    """Traced params referenced as bare Names in a branch test —
    excluding structural uses (None checks, .shape/.dtype attributes,
    isinstance/len) that are static at trace time."""
    bad: set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if any(isinstance(o, ast.Constant) and o.value is None
                   for o in operands):
                continue  # `x is None`: pytree structure, not a value
        if not isinstance(node, ast.Name) or node.id not in traced:
            continue
        parent = getattr(node, "_mglint_parent", None)
        if isinstance(parent, ast.Attribute):
            continue      # x.shape / x.ndim / x.dtype — static
        if isinstance(parent, ast.Call) and parent.func is not node:
            callee = (dotted(parent.func) or "").split(".")[-1]
            if callee in ("isinstance", "len", "getattr", "hasattr"):
                continue
        if isinstance(parent, ast.Compare):
            operands = [parent.left] + list(parent.comparators)
            if any(isinstance(o, ast.Constant) and o.value is None
                   for o in operands):
                continue
        bad.add(node.id)
    return bad


def _traced_branches(rel, fn, name, traced):
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
        else:
            continue
        bad = _branch_names(test, traced)
        if not bad:
            continue
        which = ", ".join(sorted(bad))
        yield Finding(
            rule="MG008", path=rel, line=node.lineno,
            col=getattr(node, "col_offset", 0), symbol=name,
            message=f"Python branch on traced argument(s) {which} of "
                    f"jitted {name} — concretization error at trace "
                    "time, or one compiled program per value if made "
                    "static; use lax.cond/jnp.where",
            fingerprint=f"traced-branch:{which}@{name}")


def _unhashable_statics(rel, fn, name, static):
    args = fn.args
    defaults = dict(zip([a.arg for a in args.args[::-1]],
                        list(args.defaults)[::-1]))
    kw_defaults = {a.arg: d for a, d in zip(args.kwonlyargs,
                                            args.kw_defaults) if d}
    defaults.update(kw_defaults)
    for pname in sorted(static):
        default = defaults.get(pname)
        if default is not None and isinstance(default, _MUTABLE_DEFAULTS):
            yield Finding(
                rule="MG008", path=rel, line=default.lineno,
                col=getattr(default, "col_offset", 0), symbol=name,
                message=f"static argument {pname!r} of jitted {name} "
                        "defaults to an unhashable mutable literal — "
                        "static args must be hashable (and stable, or "
                        "every distinct value compiles its own program)",
                fingerprint=f"unhashable-static:{pname}@{name}")
