"""MG002 — blocking-under-lock: no fsync / socket I/O / sleep /
subprocess / DEVICE DISPATCH while a storage, replication, server, or
coordination lock is held.

A commit-critical lock held across a syscall turns one slow disk or one
wedged peer into a stall for every thread behind the lock (the
reference's "never fsync under the engine lock" discipline). Findings
are deduplicated per (function, lock): one finding lists every blocking
operation reachable inside that function's critical section, directly
or through a resolved call chain.

Device dispatches (r12) — `jax.device_put`, `.to_device()` /
`put_edge_blocks` placements, compiled-call invocations entering
through the `device_fault_point()` boundary, and kernel-server
`_send_msg`/`_recv_msg` frames — are classified as blocking too: a
hung device tunnel or a lost chip under a storage/server lock is
EXACTLY the wedge class the kernel-server supervision (deadline +
health-check restart) exists to contain, and it must never hide behind
a lock the rest of the system waits on.

Deliberate cases — e.g. the WAL writer's own append lock, whose entire
purpose is serializing write+fsync, or the kernel server's dispatch
lock, which is supervised by construction — belong in the baseline
with a justification, not silently ignored.
"""

from __future__ import annotations

from ..core import Finding, Project
from ..locking import CRITICAL_DIRS, get_model
from ..registry import register


def _critical(rel_path: str) -> bool:
    parts = rel_path.split("/")
    return any(p in CRITICAL_DIRS for p in parts[:-1])


@register("MG002", "blocking-under-lock")
def check(project: Project):
    """No fsync/socket/sleep/subprocess inside a critical section."""
    model = get_model(project)
    # (func key, lock display) -> {"ops": [...], "line": first line, ...}
    grouped: dict[tuple[str, str], dict] = {}

    for fi in model.functions.values():
        if not _critical(fi.rel_path):
            continue
        for ev in fi.events:
            ops: list[tuple[str, int]] = []
            if ev.blocking is not None:
                op, site = ev.blocking
                ops.append((f"{op} [{site.text}]", site.line))
            elif ev.call is not None:
                callee = model.callee(ev.call)
                if callee is not None and callee.may_block:
                    for op, via in sorted(callee.may_block.items()):
                        label = via if via.startswith("via ") else \
                            f"via {callee.qualname}(): {op}"
                        ops.append((label, ev.call.line))
            if not ops:
                continue
            innermost = ev.held[-1]
            lock_name = innermost.lock_id or innermost.attr
            key = (fi.key, lock_name)
            entry = grouped.setdefault(key, {
                "fi": fi, "lock": lock_name, "ops": [],
                "line": ops[0][1]})
            entry["ops"].extend(ops)

    findings = []
    for (_fk, _lock), entry in sorted(grouped.items()):
        fi = entry["fi"]
        op_list = sorted({op for op, _ln in entry["ops"]})
        shown = "; ".join(op_list[:4])
        if len(op_list) > 4:
            shown += f"; +{len(op_list) - 4} more"
        findings.append(Finding(
            rule="MG002", path=fi.rel_path, line=entry["line"], col=0,
            symbol=fi.qualname,
            message=f"blocking operation(s) while holding "
                    f"{entry['lock']}: {shown}",
            fingerprint=f"block-under:{entry['lock']}"))
    return findings
