"""MG009 — host-sync-in-hot-path: device→host round trips inside the
serving/fixpoint hot paths.

A ``.item()``, ``np.asarray(...)``, ``jax.device_get(...)`` or
``block_until_ready()`` on a DEVICE value blocks the calling thread
until the device drains — inside the semiring fixpoint, the
kernel-server dispatch loop, or the PPR batch drain loop that turns an
async pipelined plane into a lock-step one (the r16 batch-extract at
``server/kernel_server.py`` was the motivating case: four separate
syncs per chunk where one fused ``device_get`` suffices).

Hot roots (path-component + qualname suffix) and everything reachable
from them through same-module calls plus project-unique cross-module
names:

  * ``ops/semiring.py``: ``fixpoint``, ``mxu_fixpoint``
  * ``server/kernel_server.py``: the PPR serving plane's ``_run`` /
    ``_execute_group`` / ``_compute`` drain path and the supervised
    ``_supervised`` dispatch
  * anything they call (``ppr_topk``, ``personalized_pagerank_batch``)

Within a hot function the rule is TAINT-based so host-side numpy work
stays silent: a name bound from a DEVICE PRODUCER call (a project
function that returns device values — the configured set below — or a
jitted local) is device-tainted, taint propagates through subscripts /
attributes / tuple unpacking, and a sync op applied to a tainted
expression fires. Syncs on untainted values (wire bytes, cache entries)
are free. ``.item()`` / ``.block_until_ready()`` / ``.tolist()`` are
device-sync by construction and fire untainted too.

The ONE deliberate fused result transfer a reply needs carries an
inline ``# mglint: disable=MG009`` with its justification.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, qualname_of
from ..locking import dotted
from ..registry import register

#: (directory component, qualname suffix) hot roots — directory
#: matching (not exact file) so the rule's TP/TN fixtures under
#: tests/lint_fixtures/{ops,server}/ exercise the same code path
HOT_ROOTS = (
    ("ops/", "fixpoint"),
    ("ops/", "mxu_fixpoint"),
    ("server/", "PprServingPlane._run"),
    ("server/", "PprServingPlane._execute_group"),
    ("server/", "PprServingPlane._compute"),
    ("server/", "KernelServer._supervised"),
)

#: calls whose results are device values (taint sources); jitted
#: functions discovered per-module are added dynamically
DEVICE_PRODUCERS = {
    "personalized_pagerank_batch", "ppr_topk", "spmv", "fixpoint",
    "edge_reduce", "edge_combine", "device_put",
}

#: attribute calls that synchronize regardless of taint
_ALWAYS_SYNC_ATTRS = {"item", "block_until_ready", "tolist"}

#: call targets that synchronize when applied to a tainted value
_SYNC_CALLS = {"np.asarray", "np.array", "np.ascontiguousarray",
               "numpy.asarray", "numpy.array",
               "numpy.ascontiguousarray", "jax.device_get",
               "device_get", "float", "int"}


def _fn_index(project: Project):
    """qualname -> (rel, fn node) for every function, with parents."""
    out: dict[str, list] = {}
    for rel, sf in project.files.items():
        sf.ensure_parents()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(node.name, []).append((rel, node))
    return out


def _hot_functions(project: Project):
    """Resolve hot roots, then close over callees: same-file calls plus
    cross-module calls whose bare name is unique project-wide."""
    index = _fn_index(project)
    hot: dict[tuple, ast.AST] = {}   # (rel, qualname) -> fn
    work: list[tuple] = []
    for rel, sf in project.files.items():
        for dir_part, qn_suffix in HOT_ROOTS:
            if f"/{dir_part}" not in f"/{rel}":
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qn = qualname_of(node)
                    if qn == qn_suffix or qn.endswith("." + qn_suffix):
                        hot[(rel, qn)] = node
                        work.append((rel, node))
    seen = {id(fn) for _rel, fn in work}
    while work:
        rel, fn = work.pop()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = (dotted(node.func) or "").split(".")[-1]
            cands = index.get(callee, ())
            target = None
            if len(cands) == 1:
                target = cands[0]
            else:
                same = [c for c in cands if c[0] == rel]
                if len(same) == 1:
                    target = same[0]
            if target is not None and id(target[1]) not in seen:
                seen.add(id(target[1]))
                hot[(target[0], qualname_of(target[1]))] = target[1]
                work.append(target)
    return hot


def _jit_locals(fn: ast.AST) -> set[str]:
    """Local names bound to jax.jit(...) results inside this function."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            callee = (dotted(node.value.func) or "").split(".")[-1]
            if callee in ("jit", "pjit"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _base_names(expr: ast.AST) -> set[str]:
    """Root Name ids an expression reads through subscripts/attrs."""
    out: set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


def _assign_targets(node: ast.Assign) -> list[str]:
    out = []
    for t in node.targets:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                if isinstance(el, ast.Name):
                    out.append(el.id)
    return out


@register("MG009", "host-sync-in-hot-path")
def check(project: Project):
    """Host syncs on device values reachable from the hot paths."""
    findings: list[Finding] = []
    hot = _hot_functions(project)
    for (rel, qn), fn in sorted(hot.items(),
                                key=lambda kv: (kv[0][0], kv[0][1])):
        producers = DEVICE_PRODUCERS | _jit_locals(fn)
        tainted: set[str] = set()
        assigns = [n for n in ast.walk(fn)
                   if isinstance(n, ast.Assign)]

        def _is_sync_call(v) -> bool:
            if not isinstance(v, ast.Call):
                return False
            full = dotted(v.func) or ""
            return full in _SYNC_CALLS \
                or full.split(".")[-1] == "device_get"

        # seed: names bound from device-producer calls
        for node in assigns:
            v = node.value
            if isinstance(v, ast.Call) and not _is_sync_call(v):
                callee = (dotted(v.func) or "").split(".")[-1]
                if callee in producers:
                    tainted.update(_assign_targets(node))
        # propagate through expressions (subscripts, attrs, tuples,
        # list wrapping) to a fixpoint; sync-call RESULTS are host
        # values and never taint
        changed = True
        while changed:
            changed = False
            for node in assigns:
                if _is_sync_call(node.value):
                    continue
                if _base_names(node.value) & tainted:
                    for t in _assign_targets(node):
                        if t not in tainted:
                            tainted.add(t)
                            changed = True
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            full = dotted(node.func) or ""
            short = full.split(".")[-1]
            sync_kind = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _ALWAYS_SYNC_ATTRS:
                sync_kind = f".{node.func.attr}()"
            elif full in _SYNC_CALLS or short == "device_get":
                args_names = set()
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    args_names |= _base_names(a)
                if args_names & tainted:
                    sync_kind = f"{full or short}()"
            if sync_kind is None:
                continue
            findings.append(Finding(
                rule="MG009", path=rel, line=node.lineno,
                col=getattr(node, "col_offset", 0), symbol=qn,
                message=f"{sync_kind} host sync on a device value "
                        f"inside hot path {qn} — fuse into one "
                        "device_get per batch/chunk or move it off the "
                        "dispatch thread",
                fingerprint=f"host-sync:{sync_kind.strip('().')}@{qn}"))
    return findings
