"""Importing this package registers every rule in the registry."""

from . import lock_order  # noqa: F401
from . import blocking_under_lock  # noqa: F401
from . import swallowed_exception  # noqa: F401
from . import jax_purity  # noqa: F401
from . import registry_coverage  # noqa: F401
from . import shared_field  # noqa: F401
from . import check_then_act  # noqa: F401
from . import recompile_hazard  # noqa: F401
from . import host_sync  # noqa: F401
from . import missing_donation  # noqa: F401
from . import device_alloc  # noqa: F401
from . import escape_contract  # noqa: F401
from . import unsafe_retry  # noqa: F401
