"""MG003 — swallowed-exception: a broad ``except`` must log, re-raise,
route through RetryPolicy, or otherwise *use* the error.

Flags ``except:``, ``except Exception:``, ``except BaseException:``
handlers whose body does none of:

  * re-raise (any ``raise``),
  * call a logging-ish method (exception/warning/error/info/debug/
    critical, or anything on a logger object),
  * reference ``RetryPolicy`` / a ``retry_policy`` attribute,
  * use the bound exception name (``except Exception as e`` followed by
    shipping ``e`` somewhere is routing, not swallowing).

The undo-delta/replication stack is exactly where a silently-dropped
error turns into a wedged replica or a half-applied commit; when a
swallow IS the contract (e.g. Cypher's ``toInteger`` returning null),
say so with an inline ``# mglint: disable=MG003 — why`` suppression.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, attach_parents, qualname_of
from ..registry import register

_BROAD = {"Exception", "BaseException"}
_LOGGING_METHODS = {"exception", "warning", "error", "info", "debug",
                    "critical", "log", "warn"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = node.id if isinstance(node, ast.Name) else (
            node.attr if isinstance(node, ast.Attribute) else None)
        if name in _BROAD:
            return True
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    """True when the handler body raises, logs, retries, or uses the
    bound exception."""
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name):
            if node.id == "RetryPolicy":
                return True
            if bound and node.id == bound:
                return True
        if isinstance(node, ast.Attribute):
            if node.attr in ("retry_policy", "RetryPolicy"):
                return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _LOGGING_METHODS:
                return True
    return False


@register("MG003", "swallowed-exception")
def check(project: Project):
    """Broad except must log, re-raise, retry, or use the error."""
    findings = []
    for rel, sf in project.files.items():
        sf.ensure_parents()
        per_scope: dict[str, int] = {}
        hits = [n for n in ast.walk(sf.tree)
                if isinstance(n, ast.ExceptHandler)]
        for node in sorted(hits, key=lambda n: (n.lineno,
                                                n.col_offset)):
            if not _is_broad(node) or _handles(node):
                continue
            qual = qualname_of(node)
            nth = per_scope.get(qual, 0)
            per_scope[qual] = nth + 1
            shape = "bare except" if node.type is None else \
                "except Exception" if not node.name else \
                f"except Exception as {node.name} (unused)"
            findings.append(Finding(
                rule="MG003", path=rel, line=node.lineno,
                col=node.col_offset, symbol=qual,
                message=f"{shape} swallows the error: neither logs, "
                        "re-raises, routes through RetryPolicy, nor "
                        "uses the exception",
                fingerprint=f"swallow#{nth}@{qual or 'module'}"))
    return findings
