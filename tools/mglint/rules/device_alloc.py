"""MG011 — unaccounted-device-allocation: device materialization on a
serving path that never consulted an admission estimator.

The kernel server admits work by ESTIMATE (`_estimate_request_bytes`,
`_lane_state_bytes`, `ops.tier.streamed_request_bytes`, ...) and
tools/mgmem machine-checks those estimators against XLA's buffer
assignment. That contract only holds if every device allocation on a
serving path actually sits inside an estimated scope: a stray
``jax.device_put`` or eager ``jnp.zeros(...)`` in the dispatch layer is
HBM the admission verdict never priced — exactly the drift mgmem's
static model cannot see.

Scope is the DISPATCH layer, not the compiled kernels: serving roots
(below) plus their SAME-FILE call closure. Cross-module callees are the
kernel layer whose footprint the mgmem per-kernel models already price;
pulling them in would double-police accounted allocations.

Within that hot set, a function is ACCOUNTED when an admission
estimator call is reachable to or from it in the same-file call graph:

  * it (or something it calls, transitively) consults an estimator —
    the driver that prices its own run, e.g. ``_tier_fixpoint``; or
  * it is reachable FROM an estimator-consulting function — the helpers
    a priced dispatch invokes, e.g. ``_op_pagerank`` under
    ``_supervised``'s verdict, ``_put_block`` under the tier driver.

Allocations elsewhere fire. Deliberate exceptions go in the EXEMPTIONS
table with a justification; an exemption whose file is in the scanned
project but which matches no allocation is reported as UNUSED so the
table can only shrink honestly (same discipline as the baselines).
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, qualname_of
from ..locking import dotted
from ..registry import register

#: (directory component, qualname suffix) serving roots — directory
#: matching (not exact file) so the TP/TN fixtures under
#: tests/lint_fixtures/{server,ops}/ exercise the same code path
SERVING_ROOTS = (
    ("server/", "KernelServer._supervised"),
    ("server/", "KernelServer._dispatch_op"),
    ("server/", "PprServingPlane.submit"),
    ("server/", "PprServingPlane._run"),
    ("server/", "PprServingPlane._execute_group"),
    ("server/", "PprServingPlane._compute"),
    ("parallel/", "_tier_fixpoint"),
    ("parallel/", "pagerank_streamed"),
    ("parallel/", "katz_streamed"),
    ("parallel/", "wcc_streamed"),
    ("ops/", "stage_edges"),
)

#: calls that ROUTE a scope through the admission accounting — the
#: kernel server's estimators, the PPR lane pricer, and the tier plane's
#: streamed estimate (tools/mgmem verifies each against the model)
ESTIMATORS = {
    "_estimate_request_bytes", "_graph_footprint_bytes",
    "_lane_state_bytes", "_ppr_chunk_lanes",
    "streamed_request_bytes", "admission_verdict",
}

#: eager device materializations: an explicit placement, or a jnp
#: constructor outside a traced context (inside jit these fold into the
#: compiled footprint the mgmem model already prices)
_JNP_CTORS = {
    "zeros", "ones", "full", "empty", "arange", "eye", "asarray",
    "array", "zeros_like", "ones_like", "full_like", "linspace",
}
_JNP_MODULES = ("jnp", "jax.numpy")

#: "<path suffix>::<qualname>" -> justification. Matched entries
#: silence the allocation; entries whose file IS in the scanned project
#: but match nothing produce an unused-exemption finding.
EXEMPTIONS = {
    "server/kernel_server.py::probe_device":
        "the device probe is one fixed 128x128 warmup matmul (64 KiB + "
        "compile scratch) that establishes platform identity BEFORE the "
        "admission plane serves anything — a constant, not "
        "request-scoped HBM, and freed when the probe returns",
    "ops/pipeline.py::stage_edges":
        "compiled-lane edge staging places the LOCAL in-process graph's "
        "padded edge columns, bounded by the storage's own edge count — "
        "the lane plane serves the embedded engine, not the daemon's "
        "admission-guarded socket; residency is capped and observable "
        "via resident_programs()/drop_programs()",
    # fixture entries: only ever in scope when tests/lint_fixtures is
    # the scanned project (tests/test_mglint.py), never in the gate run
    "lint_fixtures/server/mg011_device_alloc.py::exempt_staging":
        "fixture: exercises the exemption table match path",
    "lint_fixtures/server/mg011_device_alloc.py::gone_function":
        "fixture: deliberately dead entry — the unused-exemption "
        "detector must flag it",
}


def _is_alloc(node: ast.Call) -> str | None:
    """'device_put' / 'jnp.zeros' when the call materializes on device."""
    full = dotted(node.func) or ""
    parts = full.split(".")
    if parts[-1] == "device_put":
        return full or "device_put"
    if len(parts) >= 2 and parts[-1] in _JNP_CTORS \
            and ".".join(parts[:-1]) in _JNP_MODULES:
        return full
    return None


def _file_functions(sf):
    """Top-level-name -> fn node for one file (methods by bare name)."""
    sf.ensure_parents()
    out: dict[str, list[ast.AST]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _callees(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = (dotted(node.func) or "").split(".")[-1]
            if name:
                out.add(name)
    return out


def _calls_estimator(fn: ast.AST) -> bool:
    return bool(_callees(fn) & ESTIMATORS)


def _accounted_names(index: dict[str, list[ast.AST]]) -> set[str]:
    """Function names in this file that are routed through accounting:
    estimator callers, everything that can REACH one through same-file
    calls, and everything REACHABLE FROM one."""
    edges = {name: set() for name in index}
    for name, fns in index.items():
        for fn in fns:
            edges[name] |= {c for c in _callees(fn) if c in index}
    seeded = {name for name, fns in index.items()
              if any(_calls_estimator(fn) for fn in fns)}
    # backward: callers of accounted functions price their dispatch
    reach = set(seeded)
    changed = True
    while changed:
        changed = False
        for name, cs in edges.items():
            if name not in reach and cs & reach:
                reach.add(name)
                changed = True
    # forward: helpers a priced dispatch invokes run under its verdict
    out = set(reach)
    work = list(seeded)
    while work:
        for c in edges.get(work.pop(), ()):
            if c not in out:
                out.add(c)
                work.append(c)
    return out


def _hot_set(project: Project):
    """(rel, qualname) -> fn for roots + same-file call closure."""
    hot: dict[tuple, ast.AST] = {}
    for rel, sf in project.files.items():
        index = _file_functions(sf)
        work: list[ast.AST] = []
        for dir_part, qn_suffix in SERVING_ROOTS:
            if f"/{dir_part}" not in f"/{rel}":
                continue
            for fns in index.values():
                for fn in fns:
                    qn = qualname_of(fn)
                    if qn == qn_suffix or qn.endswith("." + qn_suffix):
                        if (rel, qn) not in hot:
                            hot[(rel, qn)] = fn
                            work.append(fn)
        seen = {id(fn) for fn in work}
        while work:
            fn = work.pop()
            for callee in _callees(fn):
                for target in index.get(callee, ()):
                    if id(target) not in seen:
                        seen.add(id(target))
                        hot[(rel, qualname_of(target))] = target
                        work.append(target)
    return hot


def _exemption_for(rel: str, qn: str) -> str | None:
    bare = qn.split(".")[-1]
    for key in EXEMPTIONS:
        path_part, _, fn_part = key.partition("::")
        if rel.endswith(path_part) and fn_part in (qn, bare):
            return key
    return None


@register("MG011", "unaccounted-device-allocation")
def check(project: Project):
    """Device allocations on serving paths outside estimated scopes."""
    findings: list[Finding] = []
    hot = _hot_set(project)
    accounted_by_file: dict[str, set[str]] = {}
    used_exemptions: set[str] = set()
    for (rel, qn), fn in sorted(hot.items(),
                                key=lambda kv: (kv[0][0], kv[0][1])):
        acc = accounted_by_file.get(rel)
        if acc is None:
            acc = accounted_by_file[rel] = \
                _accounted_names(_file_functions(project.files[rel]))
        # nested defs are scanned inside their outer hot function and
        # inherit ITS accounting status (env_of/iterate closures run
        # under the driver's priced scope)
        if qn.split(".")[-1] in acc:
            continue
        exempt = _exemption_for(rel, qn)
        seen_lines: set[tuple] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            alloc = _is_alloc(node)
            if alloc is None or (node.lineno, node.col_offset) \
                    in seen_lines:
                continue
            seen_lines.add((node.lineno, node.col_offset))
            if exempt is not None:
                used_exemptions.add(exempt)
                continue
            findings.append(Finding(
                rule="MG011", path=rel, line=node.lineno,
                col=getattr(node, "col_offset", 0), symbol=qn,
                message=f"{alloc}() materializes device memory inside "
                        f"serving path {qn} without an admission "
                        "estimate — route the scope through an "
                        "estimator (price it, export the gauge) or "
                        "register a justified EXEMPTIONS entry",
                fingerprint=f"unaccounted-alloc:{alloc}@{qn}"))
    # dead-entry detection: an exemption whose file is part of THIS
    # scan but which silenced nothing is stale — delete it
    for key in sorted(EXEMPTIONS):
        if key in used_exemptions:
            continue
        path_part = key.partition("::")[0]
        rel = next((r for r in project.files if r.endswith(path_part)),
                   None)
        if rel is None:
            continue
        findings.append(Finding(
            rule="MG011", path=rel, line=1, col=0,
            symbol=key.partition("::")[2],
            message=f"unused MG011 exemption '{key}' — the allocation "
                    "it justified is gone; delete the entry",
            fingerprint=f"unused-exemption:{key}"))
    return findings
