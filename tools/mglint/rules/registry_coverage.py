"""MG005 — registry-coverage: every WAL opcode and fault point is fully
wired.

WAL opcodes (``OP_* = 0x..`` in storage/durability/wal.py) need four
handlers to round-trip a commit through crash recovery AND replication:

  * encode   — referenced in wal.py outside its own assignment
               (framed by encode_txn_ops / the txn grouping protocol)
  * replay   — referenced in storage/durability/recovery.py
               (``_apply_wal_txn``), or handled by wal.py's own
               ``_group_txns`` protocol layer (TXN_BEGIN / TXN_END)
  * replication-apply — replication/replica.py must import the shared
               applier ``_apply_wal_txn`` (one applier for recovery and
               replicas is the invariant; a replica-side fork would
               have to re-handle every opcode)

A new opcode with a missing replay arm recovers to silent data loss;
the reference enforces this with exhaustive switch statements the
compiler checks — this rule is the Python stand-in.

Fault points: every ``fire("x")`` / ``faulty_write("x", ...)`` site
must name a point registered in utils/faultinject.py KNOWN_POINTS (a
typo'd point silently never fires), and every registered point must
have at least one live fire site (a dead registration means a fault
campaign "covers" a path that no longer exists).

Nemesis ops: the ``NEMESIS_OPS`` registry (the contract the mgchaos
schedule generator draws from) must stay wired both ways — every
network-level op needs a live ``net_<op>`` installer in faultinject.py,
and every installer (a ``net_*`` function that adds link rules) must be
reachable from a registered op, or chaos campaigns "cover" ops that can
no longer fire (the same dead-registration hazard as fault points; the
per-op *test* coverage half of this contract lives in
tests/test_chaos.py, which asserts the seeded sweep exercises every
registered op).
"""

from __future__ import annotations

import ast

from ..core import Finding, Project
from ..locking import dotted
from ..registry import register


def _op_constants(sf) -> dict[str, int]:
    out = {}
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id.startswith("OP_") \
                and isinstance(stmt.value, ast.Constant):
            out[stmt.targets[0].id] = (stmt.value.value,
                                       stmt.lineno)
    return out


def _names_used(tree: ast.AST) -> set[str]:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            used.add(node.attr)
    return used


def _names_in_function(tree: ast.AST, fn_name: str) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fn_name:
            return _names_used(node)
    return set()


@register("MG005", "registry-coverage")
def check(project: Project):
    """WAL opcodes and fault points must be fully wired end to end."""
    findings = []
    findings.extend(_check_wal_opcodes(project))
    findings.extend(_check_fault_points(project))
    findings.extend(_check_nemesis_ops(project))
    return findings


def _check_wal_opcodes(project: Project):
    wal = project.by_suffix("durability/wal.py")
    if wal is None:
        return []
    recovery = project.by_suffix("durability/recovery.py")
    replica = project.by_suffix("replication/replica.py")
    ops = _op_constants(wal)
    if not ops:
        return []

    # encode side: any use in wal.py beyond the defining assignment
    wal_uses: dict[str, int] = {}
    for node in ast.walk(wal.tree):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id.startswith("OP_"):
            wal_uses[node.id] = wal_uses.get(node.id, 0) + 1
    group_txn_names = _names_in_function(wal.tree, "_group_txns")
    recovery_names = _names_used(recovery.tree) \
        if recovery is not None else set()

    replica_shares_applier = False
    if replica is not None:
        for node in ast.walk(replica.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    "recovery" in node.module:
                if any(a.name == "_apply_wal_txn" for a in node.names):
                    replica_shares_applier = True
    replica_names = _names_used(replica.tree) \
        if replica is not None else set()

    findings = []
    for op_name, (_value, line) in sorted(ops.items()):
        missing = []
        if not wal_uses.get(op_name):
            missing.append("encode (never framed in wal.py)")
        replayed = op_name in recovery_names or \
            op_name in group_txn_names
        if not replayed:
            missing.append("recovery replay (no handler in "
                           "recovery.py/_group_txns)")
        repl_ok = replica_shares_applier or op_name in replica_names \
            or op_name in group_txn_names
        if not repl_ok:
            missing.append("replication apply (replica.py neither "
                           "imports _apply_wal_txn nor handles it)")
        if missing:
            findings.append(Finding(
                rule="MG005", path=wal.rel_path, line=line, col=0,
                symbol=op_name,
                message=f"WAL opcode {op_name} is missing handlers: "
                        + "; ".join(missing),
                fingerprint=f"wal-op:{op_name}"))
    return findings


#: ops the cluster harness (not the network model) implements; they have
#: no net_* installer by design
_CLUSTER_LEVEL_OPS = {"kill_restart"}


def _nemesis_op_installer(op: str) -> str:
    """Registered op name -> the net_* installer expected to back it
    ("partition_oneway" rides net_partition's bidirectional flag)."""
    if op == "partition_oneway":
        return "net_partition"
    return f"net_{op}"


def _check_nemesis_ops(project: Project):
    fi_mod = project.by_suffix("utils/faultinject.py")
    if fi_mod is None:
        return []
    ops: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "NEMESIS_OPS" \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            for el in stmt.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    ops[el.value] = stmt.lineno
    if not ops:
        return []

    # net_* installers = module-level functions whose body calls _net_add
    installers: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if not isinstance(stmt, ast.FunctionDef) or \
                not stmt.name.startswith("net_"):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "_net_add":
                installers[stmt.name] = stmt.lineno
                break

    findings = []
    for op, line in sorted(ops.items()):
        if op in _CLUSTER_LEVEL_OPS:
            continue
        wanted = _nemesis_op_installer(op)
        if wanted not in installers:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="NEMESIS_OPS",
                message=f"nemesis op {op!r} has no {wanted}() installer "
                        "— scheduling it would be a silent no-op",
                fingerprint=f"nemesis-dead:{op}"))
    expected = {_nemesis_op_installer(op) for op in ops
                if op not in _CLUSTER_LEVEL_OPS}
    for name, line in sorted(installers.items()):
        if name not in expected:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol=name,
                message=f"link-rule installer {name}() backs no entry "
                        "of NEMESIS_OPS — chaos campaigns can never "
                        "schedule it",
                fingerprint=f"nemesis-unregistered:{name}"))
    return findings


def _check_fault_points(project: Project):
    fi_mod = project.by_suffix("utils/faultinject.py")
    if fi_mod is None:
        return []
    known: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "KNOWN_POINTS" \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            for el in stmt.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    known[el.value] = stmt.lineno

    findings = []
    fired: set[str] = set()
    for rel, sf in project.files.items():
        if sf is fi_mod:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            short = name.split(".")[-1]
            if short not in ("fire", "faulty_write"):
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            point = node.args[0].value
            fired.add(point)
            if known and point not in known:
                findings.append(Finding(
                    rule="MG005", path=rel, line=node.lineno,
                    col=node.col_offset, symbol=short,
                    message=f"fault point {point!r} is not registered "
                            "in faultinject.KNOWN_POINTS — arming it "
                            "is impossible and the site never fires",
                    fingerprint=f"fault-unregistered:{point}"))
    for point, line in sorted(known.items()):
        if point not in fired:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="KNOWN_POINTS",
                message=f"registered fault point {point!r} has no "
                        "fire()/faulty_write() site — dead "
                        "registration, campaigns covering it test "
                        "nothing",
                fingerprint=f"fault-dead:{point}"))
    return findings
