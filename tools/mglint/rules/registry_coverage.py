"""MG005 — registry-coverage: every WAL opcode and fault point is fully
wired.

WAL opcodes (``OP_* = 0x..`` in storage/durability/wal.py) need four
handlers to round-trip a commit through crash recovery AND replication:

  * encode   — referenced in wal.py outside its own assignment
               (framed by encode_txn_ops / the txn grouping protocol)
  * replay   — referenced in storage/durability/recovery.py
               (``_apply_wal_txn``), or handled by wal.py's own
               ``_group_txns`` protocol layer (TXN_BEGIN / TXN_END)
  * replication-apply — replication/replica.py must import the shared
               applier ``_apply_wal_txn`` (one applier for recovery and
               replicas is the invariant; a replica-side fork would
               have to re-handle every opcode)

A new opcode with a missing replay arm recovers to silent data loss;
the reference enforces this with exhaustive switch statements the
compiler checks — this rule is the Python stand-in.

Fault points: every ``fire("x")`` / ``faulty_write("x", ...)`` site
must name a point registered in utils/faultinject.py KNOWN_POINTS (a
typo'd point silently never fires), and every registered point must
have at least one live fire site (a dead registration means a fault
campaign "covers" a path that no longer exists).

Nemesis ops: the ``NEMESIS_OPS`` registry (the contract the mgchaos
schedule generator draws from) must stay wired both ways — every
network-level op needs a live ``net_<op>`` installer in faultinject.py,
and every installer (a ``net_*`` function that adds link rules) must be
reachable from a registered op, or chaos campaigns "cover" ops that can
no longer fire (the same dead-registration hazard as fault points; the
per-op *test* coverage half of this contract lives in
tests/test_chaos.py, which asserts the seeded sweep exercises every
registered op).

Span names (r13, mgtrace): every literal span name opened in product
code — ``span("x")`` / ``record_span("x", ...)`` / ``begin_trace("x")``
— must be declared in observability/trace.py ``SPAN_NAMES`` (a typo'd
name silently fragments a trace), and every declared name must have at
least one live open site. Spans may ONLY be opened through that
context-manager API: any call to the private ``_begin_span``/
``_end_span`` primitives outside trace.py is a manual begin/end
imbalance waiting to happen and is flagged outright.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project
from ..locking import dotted
from ..registry import register


def _op_constants(sf) -> dict[str, int]:
    out = {}
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id.startswith("OP_") \
                and isinstance(stmt.value, ast.Constant):
            out[stmt.targets[0].id] = (stmt.value.value,
                                       stmt.lineno)
    return out


def _names_used(tree: ast.AST) -> set[str]:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            used.add(node.attr)
    return used


def _names_in_function(tree: ast.AST, fn_name: str) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fn_name:
            return _names_used(node)
    return set()


@register("MG005", "registry-coverage")
def check(project: Project):
    """WAL opcodes and fault points must be fully wired end to end."""
    findings = []
    findings.extend(_check_wal_opcodes(project))
    findings.extend(_check_fault_points(project))
    findings.extend(_check_nemesis_ops(project))
    findings.extend(_check_device_nemesis_ops(project))
    findings.extend(_check_spmv_registry(project))
    findings.extend(_check_span_registry(project))
    findings.extend(_check_stat_registry(project))
    return findings


def _check_wal_opcodes(project: Project):
    wal = project.by_suffix("durability/wal.py")
    if wal is None:
        return []
    recovery = project.by_suffix("durability/recovery.py")
    replica = project.by_suffix("replication/replica.py")
    ops = _op_constants(wal)
    if not ops:
        return []

    # encode side: any use in wal.py beyond the defining assignment
    wal_uses: dict[str, int] = {}
    for node in ast.walk(wal.tree):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id.startswith("OP_"):
            wal_uses[node.id] = wal_uses.get(node.id, 0) + 1
    group_txn_names = _names_in_function(wal.tree, "_group_txns")
    recovery_names = _names_used(recovery.tree) \
        if recovery is not None else set()

    replica_shares_applier = False
    if replica is not None:
        for node in ast.walk(replica.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    "recovery" in node.module:
                if any(a.name == "_apply_wal_txn" for a in node.names):
                    replica_shares_applier = True
    replica_names = _names_used(replica.tree) \
        if replica is not None else set()

    findings = []
    for op_name, (_value, line) in sorted(ops.items()):
        missing = []
        if not wal_uses.get(op_name):
            missing.append("encode (never framed in wal.py)")
        replayed = op_name in recovery_names or \
            op_name in group_txn_names
        if not replayed:
            missing.append("recovery replay (no handler in "
                           "recovery.py/_group_txns)")
        repl_ok = replica_shares_applier or op_name in replica_names \
            or op_name in group_txn_names
        if not repl_ok:
            missing.append("replication apply (replica.py neither "
                           "imports _apply_wal_txn nor handles it)")
        if missing:
            findings.append(Finding(
                rule="MG005", path=wal.rel_path, line=line, col=0,
                symbol=op_name,
                message=f"WAL opcode {op_name} is missing handlers: "
                        + "; ".join(missing),
                fingerprint=f"wal-op:{op_name}"))
    return findings


#: ops the cluster harness (not the network model) implements; they have
#: no net_* installer by design (node churn, the r18 shard-plane ops,
#: and the r17 stream-consumer op drive ChaosCluster / ShardPlane /
#: StreamChaosHarness hooks directly)
_CLUSTER_LEVEL_OPS = {"kill_restart", "shard_move", "shard_worker_kill",
                      "stream_consumer_kill"}


def _nemesis_op_installer(op: str) -> str:
    """Registered op name -> the net_* installer expected to back it
    ("partition_oneway" rides net_partition's bidirectional flag)."""
    if op == "partition_oneway":
        return "net_partition"
    return f"net_{op}"


def _check_nemesis_ops(project: Project):
    fi_mod = project.by_suffix("utils/faultinject.py")
    if fi_mod is None:
        return []
    ops: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "NEMESIS_OPS" \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            for el in stmt.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    ops[el.value] = stmt.lineno
    if not ops:
        return []

    # net_* installers = module-level functions whose body calls _net_add
    installers: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if not isinstance(stmt, ast.FunctionDef) or \
                not stmt.name.startswith("net_"):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "_net_add":
                installers[stmt.name] = stmt.lineno
                break

    findings = []
    for op, line in sorted(ops.items()):
        if op in _CLUSTER_LEVEL_OPS:
            continue
        wanted = _nemesis_op_installer(op)
        if wanted not in installers:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="NEMESIS_OPS",
                message=f"nemesis op {op!r} has no {wanted}() installer "
                        "— scheduling it would be a silent no-op",
                fingerprint=f"nemesis-dead:{op}"))
    expected = {_nemesis_op_installer(op) for op in ops
                if op not in _CLUSTER_LEVEL_OPS}
    for name, line in sorted(installers.items()):
        if name not in expected:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol=name,
                message=f"link-rule installer {name}() backs no entry "
                        "of NEMESIS_OPS — chaos campaigns can never "
                        "schedule it",
                fingerprint=f"nemesis-unregistered:{name}"))
    return findings


def _collect_tuple_registry(fi_mod, name: str) -> dict[str, int]:
    """{literal: lineno} for a module-level tuple/list-of-str registry."""
    out: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            for el in stmt.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    out[el.value] = stmt.lineno
    return out


def _check_device_nemesis_ops(project: Project):
    """DEVICE_NEMESIS_OPS ↔ device.* fault-point wiring, both ways.

    Device nemesis ops arm SCALAR ``device.*`` points (there is no
    net_* installer — the fault is in the accelerator, not a link), so
    the contract is: every ``device_<x>`` op needs a registered
    ``device.<x>`` point in KNOWN_POINTS, and every ``device.*`` point
    must be reachable from a registered op — else chaos campaigns
    "cover" device faults that can never fire, or a device point exists
    the device sweep can never schedule. The fire-site half (every
    registered point needs a live fire() site) already rides
    ``_check_fault_points``; the dynamic half (the seeded device sweep
    exercises every op) lives in tests/test_device_resilience.py.
    """
    fi_mod = project.by_suffix("utils/faultinject.py")
    if fi_mod is None:
        return []
    ops = _collect_tuple_registry(fi_mod, "DEVICE_NEMESIS_OPS")
    known = _collect_tuple_registry(fi_mod, "KNOWN_POINTS")
    device_points = {p: ln for p, ln in known.items()
                     if p.startswith("device.")}
    if not ops and not device_points:
        return []

    def point_for(op: str) -> str:
        return "device." + op[len("device_"):]

    findings = []
    for op, line in sorted(ops.items()):
        if not op.startswith("device_"):
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="DEVICE_NEMESIS_OPS",
                message=f"device nemesis op {op!r} must be named "
                        "device_<point>",
                fingerprint=f"device-nemesis-misnamed:{op}"))
            continue
        if point_for(op) not in device_points:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="DEVICE_NEMESIS_OPS",
                message=f"device nemesis op {op!r} has no registered "
                        f"fault point {point_for(op)!r} — scheduling it "
                        "would be a silent no-op",
                fingerprint=f"device-nemesis-dead:{op}"))
    backed = {point_for(op) for op in ops if op.startswith("device_")}
    for point, line in sorted(device_points.items()):
        if point not in backed:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="KNOWN_POINTS",
                message=f"device fault point {point!r} backs no entry "
                        "of DEVICE_NEMESIS_OPS — device chaos "
                        "campaigns can never schedule it",
                fingerprint=f"device-point-unscheduled:{point}"))
    return findings


def _check_fault_points(project: Project):
    fi_mod = project.by_suffix("utils/faultinject.py")
    if fi_mod is None:
        return []
    known: dict[str, int] = {}
    for stmt in fi_mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "KNOWN_POINTS" \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            for el in stmt.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    known[el.value] = stmt.lineno

    findings = []
    fired: set[str] = set()
    for rel, sf in project.files.items():
        if sf is fi_mod:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            short = name.split(".")[-1]
            if short not in ("fire", "faulty_write"):
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            point = node.args[0].value
            fired.add(point)
            if known and point not in known:
                findings.append(Finding(
                    rule="MG005", path=rel, line=node.lineno,
                    col=node.col_offset, symbol=short,
                    message=f"fault point {point!r} is not registered "
                            "in faultinject.KNOWN_POINTS — arming it "
                            "is impossible and the site never fires",
                    fingerprint=f"fault-unregistered:{point}"))
    for point, line in sorted(known.items()):
        if point not in fired:
            findings.append(Finding(
                rule="MG005", path=fi_mod.rel_path, line=line, col=0,
                symbol="KNOWN_POINTS",
                message=f"registered fault point {point!r} has no "
                        "fire()/faulty_write() site — dead "
                        "registration, campaigns covering it test "
                        "nothing",
                fingerprint=f"fault-dead:{point}"))
    return findings


# --------------------------------------------------------------------------
# SpMV-algorithm semiring-core + mesh coverage (ops/__init__.py
# SPMV_ALGORITHMS)
# --------------------------------------------------------------------------
#
# The semiring kernel core (ops/semiring.py, r10) is only a win if every
# SpMV-shaped algorithm actually rides it. The contract:
#   * ops/__init__.py keeps a SPMV_ALGORITHMS registry; each entry names
#     its single-chip "entry" target, EXACTLY ONE of a "sharded" target
#     or a justified "exempt" string, and (when ops/semiring.py is in
#     the scanned tree) a "core" declaration — a SEMIRINGS key naming
#     the (⊕, ⊗) pair its inner loop iterates, or "blocks" for custom
#     rounds composed from the core's building blocks;
#   * every "module:function" target must statically resolve to a
#     function defined in a scanned file (a typo'd target would only
#     surface when a user requests a mesh);
#   * every ops/ module whose AST shows the SpMV shape (a segment_*
#     reduction AND a while_loop) OR that imports the semiring core
#     must be covered by some entry, so a new algorithm cannot silently
#     miss the mesh path; and
#   * NO ops/ module outside the core engine (semiring / spmv_* /
#     benes*) may contain a function that hand-rolls a direct
#     ``jax.ops.segment_*`` reduction inside a ``while_loop`` pipeline
#     ("spmv-handrolled") — residual hand-rolled kernels bypass the
#     core's backends, precision variants and stage attribution.

_SPMV_MIN_JUSTIFICATION = 40   # chars; "TODO" is not a justification

#: modules that ARE the shared engine (the registry's targets ride
#: them); they may use segment primitives directly
_SPMV_CORE_PREFIXES = ("semiring", "spmv_", "benes")


def _registry_dict(sf, name: str):
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, ast.Dict):
            return stmt.value, stmt.lineno
    return None, 0


def _literal_or_none(node):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _target_resolves(project: Project, target: str) -> bool:
    """Does 'pkg.mod:fn' point at a def in a scanned file?"""
    if ":" not in target:
        return False
    mod, fn = target.split(":", 1)
    sf = project.by_suffix(mod.replace(".", "/") + ".py")
    if sf is None:
        return False
    return any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               and n.name == fn for n in sf.tree.body)


def _has_spmv_shape(sf) -> bool:
    has_segment = has_loop = False
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            name = (dotted(node.func) or "").split(".")[-1]
            if name.startswith("segment_"):
                has_segment = True
            elif name == "while_loop":
                has_loop = True
        if has_segment and has_loop:
            return True
    return False


def _imports_semiring_core(sf) -> bool:
    """Does this module import ops/semiring.py (ride the core)?"""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[-1] == "semiring":
                return True
            if any(a.name == "semiring" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.split(".")[-1] == "semiring"
                   for a in node.names):
                return True
    return False


def _handrolled_functions(sf):
    """Top-level functions containing BOTH a direct segment_* call and a
    while_loop call — a residual hand-rolled SpMV pipeline."""
    out = []
    for fn in sf.tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_segment = has_loop = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = (dotted(node.func) or "").split(".")[-1]
                if name.startswith("segment_"):
                    has_segment = True
                elif name == "while_loop":
                    has_loop = True
        if has_segment and has_loop:
            out.append((fn.name, fn.lineno))
    return out


def _semiring_names(project: Project):
    """Literal keys of ops/semiring.py's SEMIRINGS table (None when the
    core module is not in the scanned tree — fixture projects)."""
    sr_mod = project.by_suffix("ops/semiring.py")
    if sr_mod is None:
        return None
    table, _line = _registry_dict(sr_mod, "SEMIRINGS")
    if table is None:
        return None
    names = set()
    for key_node in table.keys:
        key = _literal_or_none(key_node)
        if isinstance(key, str):
            names.add(key)
    return names


def _check_spmv_registry(project: Project):
    ops_init = project.by_suffix("ops/__init__.py")
    if ops_init is None:
        return []
    reg, reg_line = _registry_dict(ops_init, "SPMV_ALGORITHMS")
    findings = []
    if reg is None:
        findings.append(Finding(
            rule="MG005", path=ops_init.rel_path, line=1, col=0,
            symbol="SPMV_ALGORITHMS",
            message="ops/__init__.py has no SPMV_ALGORITHMS registry — "
                    "the mesh-coverage contract has nothing to check",
            fingerprint="spmv-registry-missing"))
        return findings

    semiring_names = _semiring_names(project)
    covered_modules: set[str] = set()
    for key_node, val_node in zip(reg.keys, reg.values):
        algo = _literal_or_none(key_node)
        entry = _literal_or_none(val_node)
        if not isinstance(algo, str) or not isinstance(entry, dict):
            findings.append(Finding(
                rule="MG005", path=ops_init.rel_path,
                line=getattr(key_node, "lineno", reg_line), col=0,
                symbol="SPMV_ALGORITHMS",
                message="SPMV_ALGORITHMS entries must be literal "
                        "str -> dict",
                fingerprint=f"spmv-nonliteral:{algo!r}"))
            continue
        line = getattr(key_node, "lineno", reg_line)
        sharded = entry.get("sharded")
        exempt = entry.get("exempt")
        if semiring_names is not None:
            core = entry.get("core")
            if not isinstance(core, str) or not core:
                findings.append(Finding(
                    rule="MG005", path=ops_init.rel_path, line=line,
                    col=0, symbol=algo,
                    message=f"SPMV_ALGORITHMS[{algo!r}] must declare "
                            "'core': the SEMIRINGS key its inner loop "
                            "iterates, or 'blocks' for custom rounds "
                            "over the core's building blocks",
                    fingerprint=f"spmv-no-core:{algo}"))
            elif core != "blocks" and core not in semiring_names:
                findings.append(Finding(
                    rule="MG005", path=ops_init.rel_path, line=line,
                    col=0, symbol=algo,
                    message=f"SPMV_ALGORITHMS[{algo!r}].core = "
                            f"{core!r} names no ops/semiring.py "
                            "SEMIRINGS entry (and is not 'blocks')",
                    fingerprint=f"spmv-unknown-core:{algo}:{core}"))
        if (sharded is None) == (exempt is None):
            findings.append(Finding(
                rule="MG005", path=ops_init.rel_path, line=line, col=0,
                symbol=algo,
                message=f"SPMV_ALGORITHMS[{algo!r}] must declare "
                        "exactly one of 'sharded' (mesh entry point) "
                        "or 'exempt' (justification)",
                fingerprint=f"spmv-undeclared:{algo}"))
        if exempt is not None and (not isinstance(exempt, str)
                                   or len(exempt.strip())
                                   < _SPMV_MIN_JUSTIFICATION):
            findings.append(Finding(
                rule="MG005", path=ops_init.rel_path, line=line, col=0,
                symbol=algo,
                message=f"SPMV_ALGORITHMS[{algo!r}] exemption needs a "
                        "real justification (>= "
                        f"{_SPMV_MIN_JUSTIFICATION} chars)",
                fingerprint=f"spmv-stub-exemption:{algo}"))
        for field_name in ("entry", "sharded"):
            target = entry.get(field_name)
            if target is None:
                continue
            if not isinstance(target, str) \
                    or not _target_resolves(project, target):
                findings.append(Finding(
                    rule="MG005", path=ops_init.rel_path, line=line,
                    col=0, symbol=algo,
                    message=f"SPMV_ALGORITHMS[{algo!r}].{field_name} "
                            f"target {target!r} does not resolve to a "
                            "function in the scanned tree",
                    fingerprint=f"spmv-dangling:{algo}:{field_name}"))
            if isinstance(target, str) and ":" in target:
                covered_modules.add(target.split(":", 1)[0]
                                    .rsplit(".", 1)[-1])

    # sweep: every SpMV-shaped or core-riding ops/ module must be
    # covered by an entry, and no non-core module may hand-roll a
    # segment_* + while_loop pipeline
    for rel, sf in sorted(project.files.items()):
        if "/ops/" not in rel or rel.endswith("__init__.py"):
            continue
        mod = rel.rsplit("/", 1)[-1][:-3]
        # the kernel cores themselves (semiring, spmv_mxu*, benes*) are
        # the shared engine the registry's targets ride, not algorithms
        # to register
        if mod.startswith(_SPMV_CORE_PREFIXES):
            continue
        spmv_shaped = _has_spmv_shape(sf)
        rides_core = _imports_semiring_core(sf)
        if (spmv_shaped or rides_core) and mod not in covered_modules:
            findings.append(Finding(
                rule="MG005", path=rel, line=1, col=0, symbol=mod,
                message=f"ops/{mod}.py has an SpMV-shaped kernel "
                        "(segment reduction inside while_loop, or a "
                        "semiring-core import) but no SPMV_ALGORITHMS "
                        "entry references it — it silently misses the "
                        "mesh path",
                fingerprint=f"spmv-uncovered:{mod}"))
        for fn_name, fn_line in _handrolled_functions(sf):
            findings.append(Finding(
                rule="MG005", path=rel, line=fn_line, col=0,
                symbol=fn_name,
                message=f"ops/{mod}.py:{fn_name} hand-rolls a "
                        "segment_* reduction inside a while_loop — "
                        "route it through ops/semiring.py (spmv / "
                        "edge_reduce / fixpoint) so it inherits the "
                        "MXU + mesh backends, precision variants and "
                        "stage attribution",
                fingerprint=f"spmv-handrolled:{mod}:{fn_name}"))
    return findings


# --------------------------------------------------------------------------
# mgtrace span-name coverage (observability/trace.py SPAN_NAMES)
# --------------------------------------------------------------------------

#: the sanctioned span-opening API (all context-manager / atomic-record
#: shaped; no caller can leave a span open by mistake)
_SPAN_OPEN_FUNCS = ("span", "record_span", "begin_trace")


def _check_span_registry(project: Project):
    tr = project.by_suffix("observability/trace.py")
    if tr is None:
        return []
    names = _collect_tuple_registry(tr, "SPAN_NAMES")
    if not names:
        return []

    findings = []
    opened: set[str] = set()
    for rel, sf in project.files.items():
        if sf is tr:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (dotted(node.func) or "").split(".")[-1]
            if fname in ("_begin_span", "_end_span"):
                findings.append(Finding(
                    rule="MG005", path=rel, line=node.lineno,
                    col=node.col_offset, symbol=fname,
                    message=f"{fname}() is private to trace.py — spans "
                            "open only via the context-manager API "
                            "(span / record_span / begin_trace); manual "
                            "begin/end pairs are imbalance hazards",
                    fingerprint=f"span-manual:{fname}"))
                continue
            if fname not in _SPAN_OPEN_FUNCS:
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            span_name = node.args[0].value
            opened.add(span_name)
            if span_name not in names:
                findings.append(Finding(
                    rule="MG005", path=rel, line=node.lineno,
                    col=node.col_offset, symbol=fname,
                    message=f"span name {span_name!r} is not declared "
                            "in observability/trace.py SPAN_NAMES — an "
                            "undeclared name fragments traces and "
                            "dashboards can never know it exists",
                    fingerprint=f"span-unregistered:{span_name}"))
    for span_name, line in sorted(names.items()):
        if span_name not in opened:
            findings.append(Finding(
                rule="MG005", path=tr.rel_path, line=line, col=0,
                symbol="SPAN_NAMES",
                message=f"declared span name {span_name!r} has no open "
                        "site — dead registration, dashboards covering "
                        "it watch a span that can never fire",
                fingerprint=f"span-dead:{span_name}"))
    return findings


# --------------------------------------------------------------------------
# metric-name coverage (observability/metrics.py STAT_NAMES) — r14, mgstat
# --------------------------------------------------------------------------
#
# Every name emitted through global_metrics.increment()/set_gauge()/
# observe() must be declared exactly once in STAT_NAMES; entries ending
# in "*" declare a dynamic FAMILY (f-string sites whose literal prefix
# matches). Four failure modes fire:
#   * stat-unregistered  — a literal name no registry entry covers
#                          (typo: the series silently splits)
#   * stat-dynamic-unregistered — an f-string name whose literal prefix
#                          matches no declared family
#   * stat-dead          — a declared exact name with no emit site
#   * stat-dead-family   — a declared family with no dynamic emit site
#   * stat-duplicate     — a name declared more than once

_METRIC_EMIT_FUNCS = ("increment", "set_gauge", "observe")


def _collect_registry_with_dupes(sf, name: str):
    """[(literal, lineno)] preserving duplicates (the 'declared once'
    half of the contract needs them)."""
    out = []
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            for el in stmt.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    out.append((el.value, getattr(el, "lineno",
                                                  stmt.lineno)))
    return out


def _check_stat_registry(project: Project):
    mx = project.by_suffix("observability/metrics.py")
    if mx is None:
        return []
    declared = _collect_registry_with_dupes(mx, "STAT_NAMES")
    if not declared:
        return []

    findings = []
    seen: set[str] = set()
    for name, line in declared:
        if name in seen:
            findings.append(Finding(
                rule="MG005", path=mx.rel_path, line=line, col=0,
                symbol="STAT_NAMES",
                message=f"metric name {name!r} is declared more than "
                        "once in STAT_NAMES — every name is declared "
                        "exactly once",
                fingerprint=f"stat-duplicate:{name}"))
        seen.add(name)
    exact = {n for n, _l in declared if not n.endswith("*")}
    families = {n[:-1] for n, _l in declared if n.endswith("*")}

    def family_of(prefix: str):
        for fam in families:
            if prefix.startswith(fam):
                return fam
        return None

    used_exact: set[str] = set()
    used_family: set[str] = set()
    for rel, sf in project.files.items():
        if sf is mx:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            d = (dotted(node.func) or "").split(".")
            if len(d) < 2 or d[-1] not in _METRIC_EMIT_FUNCS \
                    or d[-2] != "global_metrics":
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                stat = arg.value
                fam = family_of(stat)
                if stat in exact:
                    used_exact.add(stat)
                elif fam is not None:
                    used_family.add(fam)
                else:
                    findings.append(Finding(
                        rule="MG005", path=rel, line=node.lineno,
                        col=node.col_offset, symbol=d[-1],
                        message=f"metric name {stat!r} is not declared "
                                "in observability/metrics.py STAT_NAMES "
                                "— a typo'd name silently splits the "
                                "series and dashboards never learn it "
                                "exists",
                        fingerprint=f"stat-unregistered:{stat}"))
            elif isinstance(arg, ast.JoinedStr):
                first = arg.values[0] if arg.values else None
                prefix = first.value \
                    if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str) else ""
                fam = family_of(prefix)
                if fam is not None:
                    used_family.add(fam)
                else:
                    findings.append(Finding(
                        rule="MG005", path=rel, line=node.lineno,
                        col=node.col_offset, symbol=d[-1],
                        message=f"dynamic metric name (prefix "
                                f"{prefix!r}) matches no STAT_NAMES "
                                "family — declare '<prefix>*' so the "
                                "family is discoverable",
                        fingerprint=f"stat-dynamic-unregistered:"
                                    f"{prefix}"))
    for name, line in declared:
        if name.endswith("*"):
            if name[:-1] not in used_family:
                findings.append(Finding(
                    rule="MG005", path=mx.rel_path, line=line, col=0,
                    symbol="STAT_NAMES",
                    message=f"declared metric family {name!r} has no "
                            "dynamic emit site — dead registration",
                    fingerprint=f"stat-dead-family:{name}"))
        elif name not in used_exact and family_of(name) is None:
            findings.append(Finding(
                rule="MG005", path=mx.rel_path, line=line, col=0,
                symbol="STAT_NAMES",
                message=f"declared metric name {name!r} has no emit "
                        "site — dead registration, dashboards covering "
                        "it watch a metric that can never move",
                fingerprint=f"stat-dead:{name}"))
    return findings
