"""MG001 — lock-order: the static lock-acquisition nesting graph must be
acyclic.

Every observed "lock B acquired while lock A is held" (directly, or via
a conservatively-resolved call chain) adds edge A -> B. A cycle in that
graph means two code paths can interleave into a deadlock; a self-edge
on a non-reentrant lock means one thread can deadlock against itself
(or two threads against two instances of the same class).

The runtime counterpart is utils/locks.TrackedLock (MG_TRACK_LOCKS=1),
which witnesses the *dynamic* graph during the test suite. This rule's
view is an under-approximation (unresolvable receivers contribute no
edges) while the witness only sees executed interleavings — each covers
the other's blind side, and both must stay acyclic.
"""

from __future__ import annotations

from ..core import Finding, Project
from ..locking import LockModel, get_model
from ..registry import register


def _build_edges(model: LockModel):
    """(from_id, to_id) -> example site dict."""
    edges: dict[tuple[str, str], dict] = {}

    def add(frm, to, rel, line, qual, via=None):
        key = (frm, to)
        if key not in edges:
            edges[key] = {"path": rel, "line": line, "qual": qual,
                          "via": via}

    for fi in model.functions.values():
        for ev in fi.events:
            held_ids = [a.lock_id for a in ev.held if a.lock_id]
            if ev.acquisition is not None and ev.acquisition.lock_id:
                for h in held_ids:
                    add(h, ev.acquisition.lock_id, fi.rel_path,
                        ev.acquisition.line, fi.qualname)
            elif ev.call is not None:
                callee = model.callee(ev.call)
                if callee is None:
                    continue
                for target in callee.may_acquire:
                    for h in held_ids:
                        add(h, target, fi.rel_path, ev.call.line,
                            fi.qualname, via=callee.qualname)
    return edges


def _sccs(nodes, succ):
    """Tarjan SCCs, iterative (analysis code must not recursion-limit)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(succ.get(nxt, ()))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


@register("MG001", "lock-order")
def check(project: Project):
    """Static lock-nesting graph must be acyclic (deadlock risk)."""
    model = get_model(project)
    edges = _build_edges(model)
    succ: dict[str, set[str]] = {}
    nodes: set[str] = set()
    for (frm, to) in edges:
        nodes.add(frm)
        nodes.add(to)
        succ.setdefault(frm, set()).add(to)

    findings = []
    # self-edges: re-acquiring a non-reentrant lock id
    for (frm, to), site in sorted(edges.items()):
        if frm == to and not model.is_rlock(frm):
            via = f" (via {site['via']})" if site.get("via") else ""
            findings.append(Finding(
                rule="MG001", path=site["path"], line=site["line"],
                col=0, symbol=site["qual"],
                message=f"lock {frm} acquired while already held{via} — "
                        "self-deadlock on a non-reentrant lock (or "
                        "unordered same-class instances)",
                fingerprint=f"self-edge:{frm}"))

    for comp in _sccs(sorted(nodes), succ):
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        cyc = " -> ".join(sorted(comp))
        # report each edge inside the SCC once, at its example site
        for (frm, to), site in sorted(edges.items()):
            if frm in comp_set and to in comp_set and frm != to:
                via = f" via {site['via']}()" if site.get("via") else ""
                findings.append(Finding(
                    rule="MG001", path=site["path"], line=site["line"],
                    col=0, symbol=site["qual"],
                    message=f"lock-order cycle [{cyc}]: {frm} -> "
                            f"{to}{via} participates in an inversion "
                            "(deadlock risk)",
                    fingerprint=f"cycle-edge:{frm}->{to}"))
    return findings
