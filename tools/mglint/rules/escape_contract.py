"""MG012 — undeclared-escape: serving roots keep their exception-flow
contracts.

Every ``ServingRoot`` declared in a ``SERVING_ROOTS`` registry inside
the scanned tree names a long-lived dispatch loop / RPC handler and the
exception types it is allowed to let escape (``raises=``; subclasses
covered by their bases, an empty contract means the root must be
total). This rule computes each root's interprocedural escape set —
explicit raise sites plus known-raising stdlib calls, closed over the
call graph and narrowed by except clauses, re-raises, exception
aliases and RetryPolicy wrappers (tools/mgflow/engine.py) — and
reports every escaping type the contract does not cover, at its
witness raise site. Dead registry entries (the named function no
longer exists) are findings too: the registry can only shrink
honestly.

Trees with no ``SERVING_ROOTS`` registry (fixtures, tools) are out of
scope and produce nothing.
"""

from __future__ import annotations

from ...mgflow.contracts import check_contracts
from ...mgflow.spec import extract_specs
from ..registry import register


@register("MG012", "undeclared-escape")
def check(project):
    """Exceptions escaping a serving root outside its raises= contract."""
    spec = extract_specs(project)
    if not spec.roots:
        return []
    return check_contracts(project, spec)
