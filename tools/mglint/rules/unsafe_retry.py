"""MG013 — unsafe-retry: retry regions honor the IDEMPOTENCY registry.

A retry region is a ``for _ in <policy>.attempts():`` loop or a
``<policy>.call(fn, ...)`` wrapper. Each region must be classified in
``utils/retry.py``'s ``IDEMPOTENCY`` registry (by the qualname of the
operation it implements), and the classification is enforced:

  * an unclassified region is a finding — every retry loop states
    whether blind re-send is safe;
  * swallowing an exception class registered ``unsafe`` and retrying
    is a finding wherever it happens (the oom/shed rule: outcomes that
    are deterministic against current state are never retried);
  * an operation registered ``unsafe`` may retry only classes
    registered ``retryable`` (pre-apply bounces) — anything else it
    swallows is a blind re-send of a non-idempotent op;
  * a registry entry matched by no region/handled class is a dead
    registration and a finding.

Trees with no ``IDEMPOTENCY`` registry (fixtures, tools) are out of
scope and produce nothing.
"""

from __future__ import annotations

from ...mgflow.retrycheck import check_retries
from ...mgflow.spec import extract_specs
from ..registry import register


@register("MG013", "unsafe-retry")
def check(project):
    """Retry regions violating the IDEMPOTENCY registry's classification."""
    spec = extract_specs(project)
    if not spec.idempotency:
        return []
    return check_retries(project, spec)
