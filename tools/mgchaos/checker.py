"""Cluster safety checker: offline verification of a chaos history.

The chaos harness records a Jepsen-style history (same JSONL round-trip
as ``tools/mgsan/isocheck.HistoryLog``): every client-visible write
attempt with its outcome and fencing epoch, every nemesis step, the
post-heal convergence event, and a final read of the cluster state.

Workload model: each client owns ONE register (key) and writes strictly
increasing integer values to it. That makes recovery checking exact
without any storage cooperation — for every key, the ok-acked values
form a monotone sequence, so "no acked write lost" reduces to
``final[key] >= max(acked values for key)``.

Events::

    {"e":"invoke", "op":n, "client":c, "key":k, "value":v}
    {"e":"ok",     "op":n, "node":main, "epoch":e}
    {"e":"fail",   "op":n, "err":...}    definitely did not happen
    {"e":"info",   "op":n, "err":...}    indeterminate (may surface later)
    {"e":"nemesis","round":r, "op":kind, "phase":"start"|"heal", ...}
    {"e":"converged", "seconds":s, "node":main, "epoch":e}
    {"e":"final",  "node":main, "epoch":e, "state":{key: value}}

Checked invariants (the acceptance contract):

* **No acked write lost** — after the final heal, every key's final
  value is >= every value whose write was acked.
* **Final value provenance** — the final value of a key was actually
  written by an acked or indeterminate op (a ``fail``-ed write that
  surfaces anyway means an abort was acked as an abort and happened
  regardless).
* **At most one acking owner per (epoch, shard)** — two nodes acking
  writes in the same fencing epoch is split-brain, full stop. Sharded
  histories (r18) tag acks with ``"shard"``: each shard may have its
  own owner per epoch, but never two; unsharded histories degenerate
  to the classic one-main-per-epoch check.
* **Election liveness** — the history contains a ``converged`` event
  within ``heal_window`` seconds of the final heal (a new acking MAIN
  emerged), and at least one post-heal acked write exists.
"""

from __future__ import annotations

from memgraph_tpu.utils import faultinject as FI  # noqa: F401  (re-export hub)
from tools.mgsan.isocheck import HistoryLog

__all__ = ["HistoryLog", "check_cluster_history"]


def check_cluster_history(events, heal_window: float = 30.0) -> list[str]:
    """Verify cluster-safety invariants over a chaos history; returns
    violation strings (empty == the run was safe)."""
    if isinstance(events, HistoryLog):
        events = events.snapshot()

    invokes: dict[int, dict] = {}
    outcomes: dict[int, dict] = {}
    # keyed (epoch, shard): in a sharded run each shard legitimately
    # has its own acking owner per epoch; shard None (unsharded
    # histories) degenerates to the classic per-epoch check
    epoch_ackers: dict[tuple, set] = {}
    converged = None
    final = None
    saw_nemesis = False
    for ev in events:
        kind = ev.get("e")
        if kind == "invoke":
            invokes[ev["op"]] = ev
        elif kind in ("ok", "fail", "info"):
            outcomes[ev["op"]] = ev
            if kind == "ok":
                epoch_ackers.setdefault(
                    (int(ev.get("epoch") or 0), ev.get("shard")),
                    set()).add(ev.get("node"))
        elif kind == "nemesis":
            saw_nemesis = True
        elif kind == "converged":
            converged = ev
        elif kind == "final":
            final = ev

    violations: list[str] = []

    # ---- split-brain: one acking owner per (epoch, shard) ---------------
    for (epoch, shard), nodes in sorted(
            epoch_ackers.items(),
            key=lambda kv: (kv[0][0], str(kv[0][1]))):
        if len(nodes) > 1:
            where = f"epoch {epoch}" if shard is None \
                else f"epoch {epoch} shard {shard}"
            violations.append(
                f"split-brain: {where} has {len(nodes)} acking "
                f"owners ({', '.join(sorted(map(str, nodes)))})")

    # ---- acked-write durability ----------------------------------------
    if final is None:
        violations.append("history has no final read: cannot verify "
                          "acked-write durability")
        return violations
    state = final.get("state", {})
    acked_max: dict[str, int] = {}
    written: dict[str, set] = {}
    for op, inv in invokes.items():
        key, value = inv["key"], inv["value"]
        out = outcomes.get(op)
        outcome = out["e"] if out else "info"   # no outcome = in flight
        if outcome != "fail":
            written.setdefault(key, set()).add(value)
        if outcome == "ok":
            acked_max[key] = max(acked_max.get(key, -1), value)
    for key, highest in sorted(acked_max.items()):
        fin = state.get(key)
        if fin is None or int(fin) < highest:
            violations.append(
                f"lost acked write: key {key} acked value {highest} but "
                f"final state has {fin!r}")
    for key, fin in sorted(state.items()):
        if fin is None:
            continue
        ok_vals = written.get(key, set())
        if int(fin) != 0 and int(fin) not in ok_vals:
            violations.append(
                f"phantom final value: key {key} ended at {fin!r}, which "
                f"no acked/indeterminate write produced")

    # ---- election liveness ---------------------------------------------
    if saw_nemesis:
        if converged is None:
            violations.append(
                "liveness: no convergence event — the cluster never "
                "produced a new acking MAIN after the final heal")
        elif float(converged.get("seconds", 0.0)) > heal_window:
            violations.append(
                f"liveness: convergence took "
                f"{converged['seconds']:.1f}s > heal window "
                f"{heal_window:.1f}s")
    return violations
