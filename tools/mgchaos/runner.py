"""One chaos campaign: bring-up → workload → nemesis → heal → verify.

``run_chaos(seed)`` is the unit both the CLI and the test sweep drive:
it builds a fresh in-process cluster, starts the register workload,
replays the seed's nemesis schedule, heals, waits for the cluster to
produce a new acking MAIN (the liveness bound), snapshots the final
state, and runs the offline safety checker. The nemesis schedule —
and therefore the shape of the whole campaign — is a pure function of
the seed.
"""

from __future__ import annotations

import itertools
import logging
import time

from memgraph_tpu.utils import faultinject as FI
from .checker import check_cluster_history
from .cluster import ChaosClient, ChaosCluster, wait_for
from .nemesis import Nemesis, schedule

log = logging.getLogger(__name__)

HEAL_WINDOW_S = 30.0     # bounded liveness: new acking MAIN within this

#: the replication-cluster subset of the nemesis registry: the r18
#: shard-plane ops (shard_move / shard_worker_kill) drive a ShardPlane
#: harness instead (tools/mgchaos/shard.py run_shard_chaos), and the
#: r17 stream-consumer op drives the StreamChaosHarness
#: (tools/mgchaos/stream.py run_stream_chaos)
CLUSTER_OPS = tuple(op for op in FI.NEMESIS_OPS
                    if not op.startswith(("shard_", "stream_")))


def run_chaos(seed: int, rounds: int = 4, n_clients: int = 3,
              n_coords: int = 3, n_data: int = 3, fencing: bool = True,
              dwell: tuple[float, float] = (1.2, 2.2),
              recover: tuple[float, float] = (1.2, 2.0),
              ops: tuple[str, ...] = CLUSTER_OPS,
              heal_window: float = HEAL_WINDOW_S):
    """Run one seeded campaign. Returns (history, violations, stats)."""
    FI.reset()
    cluster = ChaosCluster(seed=seed, n_coords=n_coords, n_data=n_data,
                           fencing=fencing)
    try:
        cluster.start()
        gids = cluster.setup_registers(n_clients)
        ops_counter = itertools.count()
        clients = [ChaosClient(cluster, i, f"k{i}", gids[f"k{i}"],
                               ops_counter)
                   for i in range(n_clients)]
        for c in clients:
            c.start()
        nodes = sorted(cluster.coord_ids) + sorted(cluster.data_ids)
        sched = schedule(seed, nodes, sorted(cluster.data_ids),
                         rounds=rounds, dwell=dwell, recover=recover,
                         ops=ops)
        Nemesis(cluster, cluster.history).run(sched)

        # final heal, then the bounded-liveness probe: some client must
        # get a validly-acked write through the (possibly new) MAIN
        cluster.heal_all()
        heal_t0 = time.monotonic()
        probe = clients[0]
        converged = wait_for(lambda: probe.one_op(),
                             timeout=heal_window, interval=0.2)
        if converged:
            elapsed = time.monotonic() - heal_t0
            main, epoch = cluster.cluster_view()
            cluster.history.record({"e": "converged",
                                    "seconds": round(elapsed, 2),
                                    "node": main, "epoch": epoch})
        for c in clients:
            c.stop()
        for c in clients:
            c.join(timeout=10)
        # quiesce: let in-flight finalizes/reconciliation drain before
        # the final read (acked writes are already ON the main — this
        # only avoids racing a reconcile-triggered catch-up)
        time.sleep(0.5)
        main, epoch = cluster.cluster_view()
        final_state = cluster.read_final_state(main, gids) \
            if main is not None else {}
        cluster.history.record({"e": "final", "node": main,
                                "epoch": epoch, "state": final_state})
        violations = check_cluster_history(cluster.history,
                                           heal_window=heal_window)
        stats = {
            "seed": seed,
            "rounds": rounds,
            "acked": sum(c.acked for c in clients),
            "ops": next(ops_counter),
            "converged": converged,
            "main": main,
            "epoch": epoch,
            "violations": len(violations),
        }
        return cluster.history, violations, stats
    finally:
        cluster.stop()
        FI.reset()


def run_split_brain_scenario(fencing: bool = True, n_coords: int = 3,
                             heal_window: float = HEAL_WINDOW_S):
    """The canonical split-brain script, deterministic by construction:

    1. isolate the MAIN from everybody (coordinator AND replicas);
    2. a client with a stale route table keeps writing at the old MAIN;
    3. the coordinator promotes a replica (new fencing epoch);
    4. heal — the deposed MAIN is demoted and resynced from its
       successor, wiping whatever it acked while isolated.

    With ``fencing=False`` (SYNC replication, epochs ignored) step 2
    ACKS writes that step 4 destroys — the checker MUST flag the run
    (the checker-honesty contract). With ``fencing=True`` the same
    script is safe: STRICT_SYNC refuses the isolated writes outright
    and the epoch-aware client rejects any ack that slips through.

    Returns (history, violations, stats).
    """
    FI.reset()
    cluster = ChaosCluster(seed=0, n_coords=n_coords, n_data=3,
                           fencing=fencing)
    hist = cluster.history
    try:
        cluster.start()
        gids = cluster.setup_registers(1)
        gid = gids["k0"]
        old_main, epoch0 = cluster.cluster_view()
        hist.record({"e": "nemesis", "round": 0, "op": "partition_node",
                     "phase": "start", "targets": [old_main]})
        FI.net_partition_node(old_main)

        # stale-route-table client: keeps writing AT the old main
        value, acked_on_old = 0, 0
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            _, epoch_now = cluster.cluster_view()
            if epoch_now > epoch0 and (acked_on_old or cluster.fencing):
                # failover happened; unsafe mode also waits until at
                # least one stale write was acked (the loss to detect)
                break
            value += 1
            op = value
            hist.record({"e": "invoke", "op": op, "client": 0,
                         "key": "k0", "value": value})
            try:
                cluster.write(old_main, gid, value)
            except Exception as e:  # noqa: BLE001 — outcome classified below
                from memgraph_tpu.exceptions import (
                    FencedException, ReplicaUnavailableException)
                kind = "fail" if isinstance(
                    e, (FencedException, ReplicaUnavailableException)) \
                    else "info"
                hist.record({"e": kind, "op": op,
                             "err": type(e).__name__})
                time.sleep(0.2)
                continue
            repl = cluster.data[old_main].replication
            ack_epoch, fenced = repl.fencing_info() if repl \
                else (0, True)
            if cluster.fencing and (fenced or ack_epoch < epoch0):
                hist.record({"e": "info", "op": op,
                             "err": "stale-epoch-ack"})
            else:
                hist.record({"e": "ok", "op": op, "node": old_main,
                             "epoch": ack_epoch})
                acked_on_old += 1
            time.sleep(0.2)

        # heal; the coordinator demotes + resyncs the deposed main
        cluster.heal_all()
        hist.record({"e": "nemesis", "round": 0, "op": "partition_node",
                     "phase": "heal", "targets": [old_main]})
        heal_t0 = time.monotonic()
        new_main_holder = {}

        def _converged():
            main, epoch = cluster.cluster_view()
            if main is None:
                return False
            repl = cluster.data[old_main].replication
            if repl is None or repl.role != "replica":
                return False   # the deposed main must be demoted
            new_main_holder["main"], new_main_holder["epoch"] = \
                main, epoch
            return True

        converged = wait_for(_converged, timeout=heal_window,
                             interval=0.2)
        if converged:
            hist.record({"e": "converged",
                         "seconds":
                             round(time.monotonic() - heal_t0, 2),
                         "node": new_main_holder["main"],
                         "epoch": new_main_holder["epoch"]})
        time.sleep(0.5)
        main, epoch = cluster.cluster_view()
        final_state = cluster.read_final_state(main, gids) \
            if main is not None else {}
        hist.record({"e": "final", "node": main, "epoch": epoch,
                     "state": final_state})
        violations = check_cluster_history(hist, heal_window=heal_window)
        stats = {"seed": "scripted-split-brain", "rounds": 1,
                 "acked": acked_on_old, "ops": value,
                 "converged": converged, "main": main, "epoch": epoch,
                 "violations": len(violations)}
        return hist, violations, stats
    finally:
        cluster.stop()
        FI.reset()
