"""Seeded device-plane nemesis: byte-replayable accelerator fault
schedules driven through the supervised kernel plane.

The cluster nemesis (nemesis.py) faults LINKS; this module faults the
ACCELERATOR. Each round arms one scalar ``device.*`` fault point
(``faultinject.DEVICE_NEMESIS_OPS`` — the MG005-checked registry) at a
seeded dispatch hit, in one of three injection contexts:

    pagerank        mid-flight in a checkpoint-resumable mesh pagerank
                    (parallel/checkpoint.py) — must resume from the last
                    checkpoint and produce a BIT-EXACT result
    kernel_request  mid-flight in a supervised kernel-server request —
                    the client must get either a correct result (after
                    typed retries) and never wedge
    probe           during the device probe (bench.py's path) — the
                    failure must classify to its typed outcome

A schedule is a pure function of the seed (``device_schedule_text``
renders it canonically, so determinism is testable as byte identity),
and the default schedule enumerates every (op, context) pair — coverage
of the whole matrix by construction, which is what the gate's
``device-smoke`` stage and the 10-seed sweep in
tests/test_device_resilience.py replay.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass

import numpy as np

from memgraph_tpu.utils import faultinject as FI

log = logging.getLogger(__name__)

DEVICE_CONTEXTS = ("pagerank", "kernel_request", "probe")

#: resumable-loop checkpoint interval the smoke rounds run with
SMOKE_K = 4
#: fixed iteration budget (tol=-1 pins the run to exactly this many)
SMOKE_ITERS = 16


@dataclass(frozen=True)
class DeviceOp:
    round: int
    kind: str        # one of faultinject.DEVICE_NEMESIS_OPS
    context: str     # one of DEVICE_CONTEXTS
    hit: int         # 1-based dispatch hit at which the fault fires
    arg: float       # hang delay seconds (0 when unused)

    def render(self) -> str:
        return (f"r{self.round:02d} {self.kind}@{self.context}"
                f" hit={self.hit} arg={self.arg:.3f}")


def device_schedule(seed: int, rounds: int | None = None,
                    ops: tuple[str, ...] = FI.DEVICE_NEMESIS_OPS,
                    contexts: tuple[str, ...] = DEVICE_CONTEXTS,
                    max_hit: int = 3) -> list[DeviceOp]:
    """Derive a deterministic device fault schedule from ``seed``.

    The default (rounds=None) enumerates every (op, context) pair once,
    in seeded order — full matrix coverage per seed. An explicit
    ``rounds`` truncates (smoke) or extends by seeded resampling."""
    for op in ops:
        if op not in FI.DEVICE_NEMESIS_OPS:
            raise ValueError(f"unknown device nemesis op {op!r}")
    for ctx in contexts:
        if ctx not in DEVICE_CONTEXTS:
            raise ValueError(f"unknown device context {ctx!r}")
    rng = random.Random(seed)
    pairs = [(op, ctx) for op in ops for ctx in contexts]
    rng.shuffle(pairs)
    if rounds is not None:
        while len(pairs) < rounds:
            pairs.append(pairs[rng.randrange(len(pairs))])
        pairs = pairs[:rounds]
    out = []
    for i, (op, ctx) in enumerate(pairs):
        arg = round(0.25 + rng.random() * 0.25, 3) \
            if op == "device_hang" else 0.0
        out.append(DeviceOp(round=i, kind=op, context=ctx,
                            hit=rng.randint(1, max_hit), arg=arg))
    return out


def device_schedule_text(seed: int, rounds: int | None = None,
                         **kw) -> str:
    """Canonical one-op-per-line rendering; same seed ⇒ identical bytes."""
    ops = device_schedule(seed, rounds, **kw)
    lines = [f"device-nemesis seed={seed} rounds={len(ops)}"]
    lines += [op.render() for op in ops]
    return "\n".join(lines) + "\n"


def _arm(op: DeviceOp) -> None:
    point = FI.device_point_for_op(op.kind)
    if op.kind == "device_hang":
        FI.arm(point, "delay", arg=op.arg, at=op.hit)
    else:
        # in-process rounds arm "raise" even for device_lost — the
        # process-kill variant needs a real daemon subprocess and lives
        # in the device_chaos-marked test tier
        FI.arm(point, "raise", at=op.hit)


def _counters() -> dict[str, float]:
    from memgraph_tpu.observability.metrics import global_metrics
    return {name: value for name, _k, value in global_metrics.snapshot()
            if name.startswith(("kernel_server.", "analytics."))}


class DeviceSmokeEnv:
    """Shared state for a device nemesis campaign: a tiny graph, the
    mesh context, an in-thread supervised kernel server, and unfaulted
    reference results every round is compared against bit-exactly."""

    N, E = 200, 1200

    def __init__(self, tmpdir: str):
        import os
        import threading
        from memgraph_tpu.ops import csr
        from memgraph_tpu.parallel.mesh import get_mesh_context
        from memgraph_tpu.server.kernel_server import (
            KernelClient, KernelServer, SupervisedKernelClient)
        from memgraph_tpu.utils.retry import RetryPolicy

        rng = np.random.default_rng(7)
        self.src = rng.integers(0, self.N, self.E)
        self.dst = rng.integers(0, self.N, self.E)
        self.graph = csr.from_coo(self.src, self.dst, n_nodes=self.N)
        self.ctx = get_mesh_context(min(2, _device_count()))
        self.ref_ranks = self._pagerank()           # unfaulted reference

        self.sock = os.path.join(tmpdir, "device_smoke.sock")
        self.server = KernelServer(self.sock, wedge_after_s=30.0,
                                   checkpoint_every=SMOKE_K)
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()
        deadline = time.monotonic() + 60
        probe = None
        while time.monotonic() < deadline:
            try:
                probe = KernelClient(self.sock, timeout=10)
                break
            except OSError:
                time.sleep(0.05)
        if probe is None:
            raise RuntimeError("in-thread kernel server never came up")
        probe.close()
        self.client = SupervisedKernelClient(
            self.sock, spawn=False, deadline_s=5.0,
            retry=RetryPolicy(base_delay=0.1, max_delay=0.5,
                              max_retries=4, attempt_timeout=30.0))
        self.ref_server = self._kernel_request()    # unfaulted reference

    def _pagerank(self, report=None):
        from memgraph_tpu.parallel import analytics
        ranks, _err, _it = analytics.pagerank_mesh(
            self.graph, self.ctx, max_iterations=SMOKE_ITERS, tol=-1.0,
            checkpoint_every=SMOKE_K, report=report)
        return np.asarray(ranks)

    def _pagerank_deadline(self, report):
        """The chunk-deadline variant used for hang rounds."""
        from memgraph_tpu.ops.csr import shard_csr
        from memgraph_tpu.parallel.distributed import \
            pagerank_partition_centric
        scsr = shard_csr(self.graph, self.ctx, by="src")
        ranks, _e, _i = pagerank_partition_centric(
            scsr, self.ctx, max_iterations=SMOKE_ITERS, tol=-1.0,
            checkpoint_every=SMOKE_K, chunk_deadline_s=0.05,
            report=report)
        return np.asarray(ranks)

    def _kernel_request(self):
        ranks, _err, _it = self.client.pagerank(
            src=self.src, dst=self.dst, n_nodes=self.N,
            graph_key="smoke", max_iterations=SMOKE_ITERS, tol=1e-12)
        return np.asarray(ranks)

    def close(self):
        try:
            self.client.close()
        except OSError as e:
            log.debug("closing smoke client: %s", e)
        try:
            from memgraph_tpu.server.kernel_server import KernelClient
            c = KernelClient(self.sock, timeout=5)
            c.shutdown()
            c.close()
        except OSError as e:
            log.debug("shutting down smoke server: %s", e)


def _device_count() -> int:
    import jax
    return len(jax.devices())


def run_device_round(env: DeviceSmokeEnv, op: DeviceOp) -> tuple[list, set]:
    """Execute one schedule round. Returns (failures, observed outcomes)."""
    from memgraph_tpu.parallel.checkpoint import RunReport
    from memgraph_tpu.server.kernel_server import probe_device
    from memgraph_tpu.utils.devicefault import classify_device_error

    failures: list[str] = []
    observed: set[str] = set()
    FI.reset()
    _arm(op)
    before = _counters()
    t0 = time.monotonic()
    try:
        if op.context == "pagerank":
            report = RunReport()
            ranks = env._pagerank_deadline(report) \
                if op.kind == "device_hang" else env._pagerank(report)
            if not np.array_equal(ranks, env.ref_ranks):
                failures.append(f"{op.render()}: pagerank result is not "
                                "bit-exact vs the unfaulted run")
            observed.update(report.faults)
            if report.slow_chunks:
                observed.add("deadline_exceeded")
            if report.lost_spans and max(report.lost_spans) > SMOKE_K:
                failures.append(f"{op.render()}: resume redid "
                                f"{max(report.lost_spans)} iterations "
                                f"(> k={SMOKE_K})")
            if op.kind != "device_hang" and not report.resumes:
                failures.append(f"{op.render()}: armed fault never "
                                "produced a resume")
        elif op.context == "kernel_request":
            from memgraph_tpu.server.kernel_server import KernelOom
            # hang rounds get a deadline BELOW the hang delay so the
            # dispatch must come back as a typed deadline_exceeded
            # (everything is warm by now; a healthy dispatch is ms)
            deadline = 0.12 if op.kind == "device_hang" else None
            try:
                ranks, _e, _i = env.client.pagerank(
                    graph_key="smoke", max_iterations=SMOKE_ITERS,
                    tol=1e-12, deadline_s=deadline)
            except KernelOom:
                if op.kind != "device_oom":
                    raise
                # oom at the dispatch boundary is typed and deliberately
                # NOT retried (deterministic against this budget) —
                # the typed propagation IS the contract
                observed.add("oom")
            else:
                if not np.array_equal(np.asarray(ranks), env.ref_server):
                    failures.append(f"{op.render()}: kernel request "
                                    "result is not bit-exact vs the "
                                    "unfaulted run")
        elif op.context == "probe":
            # the armed hit counts probe DISPATCHES: probe until it fires
            fired = None
            for _ in range(op.hit):
                t_p = time.monotonic()
                try:
                    probe_device()
                except Exception as e:  # noqa: BLE001 — classified below
                    kind = classify_device_error(e)
                    if kind is None:
                        raise
                    fired = kind
                    observed.add(kind)
                    break
                if op.kind == "device_hang" and \
                        time.monotonic() - t_p >= op.arg:
                    fired = "deadline_exceeded"
                    observed.add("deadline_exceeded")
                    break
            if fired is None:
                failures.append(f"{op.render()}: probe fault never "
                                "fired")
    except Exception as e:  # noqa: BLE001 — a round must not kill the run
        failures.append(f"{op.render()}: unexpected escape "
                        f"{type(e).__name__}: {e}")
    finally:
        FI.reset()
    elapsed = time.monotonic() - t0
    if elapsed > 30.0:
        failures.append(f"{op.render()}: round took {elapsed:.1f}s — "
                        "a client wedged")
    after = _counters()
    for name, value in after.items():
        if value > before.get(name, 0.0):
            for outcome in ("deadline_exceeded", "device_error", "oom",
                            "shed", "device_lost"):
                if outcome in name:
                    observed.add(outcome)
            if "device_fault" in name:
                observed.add(name.split(".")[-1].replace("_total", ""))
    return failures, observed


#: what each op must have visibly produced somewhere across its rounds
_EXPECT = {
    "device_call": {"device_error"},
    "device_oom": {"oom"},
    "device_hang": {"deadline_exceeded"},
    "device_lost": {"device_lost", "device_error"},
}


def run_device_matrix(seed: int, rounds: int | None = None,
                      tmpdir: str | None = None, echo=print):
    """One seeded campaign over the (op × context) matrix. Returns
    (failures, observed_by_op)."""
    import tempfile
    sched = device_schedule(seed, rounds)
    failures: list[str] = []
    observed_by_op: dict[str, set] = {}
    with tempfile.TemporaryDirectory() as td:
        env = DeviceSmokeEnv(tmpdir or td)
        try:
            for op in sched:
                f, obs = run_device_round(env, op)
                failures.extend(f)
                observed_by_op.setdefault(op.kind, set()).update(obs)
                echo(f"  {op.render()}: "
                     f"{'FAIL' if f else 'ok'} observed={sorted(obs)}")
        finally:
            env.close()
    for op_kind, wanted in _EXPECT.items():
        if op_kind not in observed_by_op:
            continue   # not scheduled (truncated smoke)
        if not (observed_by_op[op_kind] & wanted):
            failures.append(
                f"op {op_kind}: none of the expected typed outcomes "
                f"{sorted(wanted)} was ever observed "
                f"(got {sorted(observed_by_op[op_kind])})")
    return failures, observed_by_op
