"""mgchaos — Jepsen-style cluster chaos harness for memgraph_tpu.

Three cooperating parts, capping PR 2's crash harness and PR 4's
sanitizers at the CLUSTER level:

* ``nemesis``  — seeded, byte-replayable fault schedules over the
  peer-aware network model in ``memgraph_tpu/utils/faultinject.py``
  (symmetric/asymmetric partitions, delay, duplicate, reorder, node
  kill/restart churn).
* ``cluster``  — an in-process HA topology (Raft coordinators + MAIN +
  replicas on real sockets) plus the register workload whose every
  client-visible ack carries its fencing epoch.
* ``checker``  — offline cluster-safety verification over the recorded
  history: zero acked-write loss, at most one acking MAIN per fencing
  epoch, bounded post-heal election liveness.

The hardening it gates: Raft pre-vote + leader lease, monotonic fencing
epochs minted through Raft on every promotion, replica-side stale-main
rejection, self-fencing deposed MAINs, and idempotent retry-backed
coordinator failover with topology reconciliation.
"""

from .checker import check_cluster_history  # noqa: F401
from .nemesis import Nemesis, NemesisOp, schedule, schedule_text  # noqa: F401
from .runner import run_chaos  # noqa: F401
