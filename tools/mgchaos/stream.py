"""Stream-plane chaos: seeded stream_consumer_kill campaigns.

The same Jepsen shape as ``runner.run_chaos`` / ``shard.run_shard_chaos``,
pointed at the r17 streaming-ingestion plane: producers append JSONL
records to FILE stream sources while ingestion runs through the
supervised consumer loop into a WAL-enabled storage, and the nemesis
SIGKILL-kills consumers mid-batch (``Stream.kill()`` — no graceful ack,
no offset persistence) and restarts them cold. A concurrent reader
polls analytics counts the whole time. The offline checker then proves:

* EXACTLY-ONCE ingestion across kills — every produced record lands in
  the graph exactly once (the transactional WAL offset record dedups
  redelivery; zero duplicates, zero acked-batch loss);
* ALWAYS-FRESH reads — the analytics count is monotone non-decreasing
  and every read during the campaign succeeds (consumer churn never
  makes committed ingest un-readable or rolls visible state back);
* bounded post-heal liveness — the consumers drain the full backlog
  inside the heal window.

``run_stream_chaos(seed)`` is a pure function of the seed via the
shared ``nemesis.schedule`` — a failing campaign replays exactly.
"""

from __future__ import annotations

import json
import shutil
import tempfile
import threading
import time

from memgraph_tpu.query import streams as S
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage, StorageConfig
from memgraph_tpu.storage.durability.recovery import recover, wire_durability
from memgraph_tpu.storage.kvstore import KVStore

from .checker import HistoryLog
from .cluster import wait_for
from .nemesis import Nemesis, schedule

STREAM_OPS = ("stream_consumer_kill",)

_TRANSFORM = "mgchaos_stream_ingest"


def _transform(batch):
    return [{"query": "CREATE (:C {stream: $s, id: $id})",
             "parameters": dict(json.loads(m.payload_str()))}
            for m in batch]


class StreamChaosHarness:
    """Adapts a set of live Streams to the Nemesis cluster-hook
    interface (targets are stream names from the seeded schedule).

    A kill is ``Stream.kill()`` — the consumer dies like a SIGKILLed
    process, mid-batch, with no graceful source ack. The restart builds
    a FRESH ``Stream`` from the spec (crash-restart semantics: a new
    source seeded only from the durably-recovered offsets), so every
    kill round exercises the WAL-offset redelivery dedup for real."""

    def __init__(self, ictx, specs: dict[str, S.StreamSpec],
                 history: HistoryLog) -> None:
        self.ictx = ictx
        self.history = history
        self.specs = specs
        self.streams: dict[str, S.Stream] = {}
        self.kills = 0

    def start_all(self) -> None:
        for name, spec in self.specs.items():
            self.streams[name] = S.Stream(spec, self.ictx)
            self.streams[name].start()

    def stop_all(self) -> None:
        for stream in self.streams.values():
            stream.stop()

    def stream_consumer_kill(self, target: str) -> None:
        self.kills += 1
        self.streams[target].kill()

    def stream_consumer_restart(self, target: str) -> None:
        fresh = S.Stream(self.specs[target], self.ictx)
        fresh.start()
        self.streams[target] = fresh


class _Producer(threading.Thread):
    """Appends JSONL records to one stream's source file, recording
    every produced id into the history (the ground truth the checker
    holds ingestion to)."""

    def __init__(self, name: str, path: str, history: HistoryLog,
                 interval: float = 0.03) -> None:
        super().__init__(daemon=True)
        self.name_ = name
        self.path = path
        self.history = history
        self.interval = interval
        self.produced = 0
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            rec = {"s": self.name_, "id": self.produced}
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
            self.history.record({"e": "produce", "stream": self.name_,
                                 "id": self.produced})
            self.produced += 1
            self._halt.wait(self.interval)

    def stop(self) -> None:
        self._halt.set()


class _Reader(threading.Thread):
    """Always-fresh probe: polls the ingested count throughout the
    campaign. Reads must always succeed and never regress."""

    def __init__(self, ictx, history: HistoryLog,
                 interval: float = 0.1) -> None:
        super().__init__(daemon=True)
        self.ictx = ictx
        self.history = history
        self.interval = interval
        self.reads = 0
        self._halt = threading.Event()

    def run(self) -> None:
        interp = Interpreter(self.ictx, system=True)
        while not self._halt.is_set():
            try:
                _c, rows, _s = interp.execute(
                    "MATCH (c:C) RETURN count(c)")
                self.history.record({"e": "read", "count": rows[0][0]})
            except Exception as e:  # noqa: BLE001 — a failed read IS a finding
                self.history.record({"e": "read_error",
                                     "err": type(e).__name__})
            self.reads += 1
            self._halt.wait(self.interval)

    def stop(self) -> None:
        self._halt.set()


def check_stream_history(history: HistoryLog, final_counts: dict,
                         drained: bool) -> list[str]:
    """Offline exactly-once + freshness checker over a campaign history."""
    violations: list[str] = []
    produced: dict[str, int] = {}
    last_read = -1
    for ev in history.snapshot():
        kind = ev.get("e")
        if kind == "produce":
            produced[ev["stream"]] = produced.get(ev["stream"], 0) + 1
        elif kind == "read":
            if ev["count"] < last_read:
                violations.append(
                    f"stale read: count regressed {last_read} -> "
                    f"{ev['count']} (committed ingest became invisible)")
            last_read = ev["count"]
        elif kind == "read_error":
            violations.append(
                f"read failed during consumer churn: {ev['err']}")
    if not drained:
        violations.append("consumers never drained the backlog "
                          "inside the heal window")
        return violations
    for name, n in sorted(produced.items()):
        got = final_counts.get(name, {})
        dups = {i: c for i, c in got.items() if c > 1}
        if dups:
            violations.append(
                f"stream {name}: DUPLICATE ingestion (exactly-once "
                f"broken): {sorted(dups.items())[:5]}")
        missing = [i for i in range(n) if i not in got]
        if missing:
            violations.append(
                f"stream {name}: lost records after heal: "
                f"{missing[:10]} ({len(missing)} of {n})")
    return violations


def run_stream_chaos(seed: int, rounds: int = 4, n_streams: int = 2,
                     dwell: tuple[float, float] = (0.4, 0.9),
                     recover_w: tuple[float, float] = (0.3, 0.6),
                     heal_window: float = 30.0):
    """One seeded stream-plane campaign; returns (history, violations,
    stats) — the same contract as runner.run_chaos."""
    history = HistoryLog()
    workdir = tempfile.mkdtemp(prefix="mgchaos-stream-")
    storage = InMemoryStorage(StorageConfig(
        durability_dir=f"{workdir}/data", wal_enabled=True))
    recover(storage)
    wal = wire_durability(storage)
    ictx = InterpreterContext(storage)
    ictx.kvstore = KVStore(f"{workdir}/kv.db")
    S.TRANSFORMATIONS[_TRANSFORM] = _transform
    names = [f"s{i}" for i in range(n_streams)]
    specs = {name: S.StreamSpec(
        name=name, kind="file", topics=[f"{workdir}/{name}.jsonl"],
        transform=_TRANSFORM, batch_size=4, batch_interval_sec=0.05)
        for name in names}
    harness = StreamChaosHarness(ictx, specs, history)
    producers = [_Producer(name, specs[name].topics[0], history)
                 for name in names]
    reader = _Reader(ictx, history)
    try:
        harness.start_all()
        for p in producers:
            p.start()
        reader.start()
        sched = schedule(seed, names, names, rounds=rounds, dwell=dwell,
                         recover=recover_w, ops=STREAM_OPS, streams=names)
        Nemesis(harness, history).run(sched)

        for p in producers:
            p.stop()
        for p in producers:
            p.join(timeout=10)

        # bounded liveness: the consumers must drain the whole backlog
        interp = Interpreter(ictx, system=True)
        total = sum(p.produced for p in producers)

        def _ingested() -> int:
            _c, rows, _s = interp.execute("MATCH (c:C) RETURN count(c)")
            return rows[0][0]

        heal_t0 = time.monotonic()
        drained = wait_for(lambda: _ingested() >= total,
                           timeout=heal_window, interval=0.2)
        if drained:
            history.record({"e": "converged",
                            "seconds":
                                round(time.monotonic() - heal_t0, 2)})
        reader.stop()
        reader.join(timeout=10)
        harness.stop_all()

        # final scatter: per-stream multiset of ingested ids
        final_counts: dict[str, dict[int, int]] = {}
        _c, rows, _s = interp.execute(
            "MATCH (c:C) RETURN c.stream, c.id, count(*)")
        for stream_name, rec_id, cnt in rows:
            final_counts.setdefault(stream_name, {})[rec_id] = cnt
        history.record({"e": "final",
                        "counts": {k: len(v)
                                   for k, v in final_counts.items()}})
        violations = check_stream_history(history, final_counts, drained)
        stats = {"seed": seed, "rounds": rounds, "produced": total,
                 "ingested": _ingested(), "kills": harness.kills,
                 "reads": reader.reads, "converged": drained,
                 "violations": len(violations)}
        return history, violations, stats
    finally:
        for p in producers:
            p.stop()
        reader.stop()
        harness.stop_all()
        S.TRANSFORMATIONS.pop(_TRANSFORM, None)
        wal.close()
        shutil.rmtree(workdir, ignore_errors=True)
