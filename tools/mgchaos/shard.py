"""Shard-plane chaos: seeded shard_move / shard_worker_kill campaigns.

The same Jepsen shape as ``runner.run_chaos``, pointed at the r18
sharded OLTP execution plane instead of the replication cluster: N
register-writing clients route through a ``ShardedClient`` (each client
owns one key, keys spread across shards), while the nemesis live-moves
shards to fresh workers and SIGKILLs shard owners mid-traffic. The
offline checker then proves:

* zero acked-write loss across moves and owner kills (per-shard WAL
  recovery + snapshot-ship/delta-catch-up must not drop an ack);
* at most ONE acking owner per (epoch, shard) — the fencing chain
  (map epoch minted by the placement authority, grant-epoch-checked
  write acks) holds under churn;
* bounded post-heal liveness (a probe write lands after the last op).

``run_shard_chaos(seed)`` is a pure function of the seed via the shared
``nemesis.schedule`` — a failing campaign replays exactly.
"""

from __future__ import annotations

import itertools
import threading
import time

from memgraph_tpu.exceptions import MemgraphTpuError
from memgraph_tpu.sharding import ShardPlane, ShardedClient
from memgraph_tpu.sharding.partition import shard_for_key

from .checker import HistoryLog, check_cluster_history
from .cluster import wait_for
from .nemesis import Nemesis, schedule

SHARD_OPS = ("shard_move", "shard_worker_kill")


class ShardChaosHarness:
    """Adapts a ShardPlane to the Nemesis cluster-hook interface
    (shard targets are shard-id strings from the seeded schedule)."""

    def __init__(self, plane: ShardPlane, history: HistoryLog) -> None:
        self.plane = plane
        self.history = history

    def shard_move(self, target: str) -> None:
        self.plane.shard_move(int(target))

    def shard_kill(self, target: str) -> None:
        self.plane.kill_worker(int(target))

    def shard_restart(self, target: str) -> None:
        self.plane.restart_worker(int(target))


class _RegisterClient(threading.Thread):
    """One register key, strictly increasing values, routed writes.
    Ack events carry (node=owner name, epoch, shard) so the checker can
    prove per-shard ownership uniqueness."""

    def __init__(self, client: ShardedClient, idx: int, key: str,
                 history: HistoryLog, ops_counter) -> None:
        super().__init__(daemon=True)
        self.client = client
        self.idx = idx
        self.key = key
        self.history = history
        self.ops = ops_counter
        self.value = 0
        self.acked = 0
        self._halt = threading.Event()

    def one_op(self) -> bool:
        self.value += 1
        op = next(self.ops)
        shard = self.client.shard_for(self.key)
        self.history.record({"e": "invoke", "op": op,
                             "client": self.idx, "key": self.key,
                             "value": self.value})
        try:
            _cols, _rows, ack = self.client.write(
                "MERGE (r:Reg {k: $k}) SET r.v = $v",
                {"k": self.key, "v": self.value}, key=self.key)
        except MemgraphTpuError as e:
            # retries exhausted mid-churn: indeterminate (a prepare may
            # have landed); the checker treats info as maybe-committed
            self.history.record({"e": "info", "op": op,
                                 "err": type(e).__name__})
            return False
        self.history.record({"e": "ok", "op": op,
                             "node": ack.get("owner"),
                             "epoch": ack["epoch"],
                             "shard": ack["shard"]})
        self.acked += 1
        return True

    def run(self) -> None:
        while not self._halt.is_set():
            self.one_op()
            time.sleep(0.05)

    def stop(self) -> None:
        self._halt.set()


def run_shard_chaos(seed: int, rounds: int = 4, n_shards: int = 4,
                    n_clients: int = 4,
                    dwell: tuple[float, float] = (0.4, 0.9),
                    recover: tuple[float, float] = (0.3, 0.6),
                    heal_window: float = 30.0):
    """One seeded shard-plane campaign; returns (history, violations,
    stats) — the same contract as runner.run_chaos."""
    history = HistoryLog()
    plane = ShardPlane(n_shards=n_shards).start()
    harness = ShardChaosHarness(plane, history)
    try:
        client = ShardedClient(plane)
        ops_counter = itertools.count(1)
        # spread client keys over distinct shards where possible
        keys, used = [], set()
        for i in itertools.count():
            key = f"k{i}"
            sid = shard_for_key(key, n_shards)
            if sid not in used or len(keys) >= n_shards:
                keys.append(key)
                used.add(sid)
            if len(keys) == n_clients:
                break
        clients = [_RegisterClient(ShardedClient(plane), i, keys[i],
                                   history, ops_counter)
                   for i in range(n_clients)]
        for c in clients:
            c.start()
        shard_ids = [str(s) for s in range(n_shards)]
        sched = schedule(seed, shard_ids, shard_ids, rounds=rounds,
                         dwell=dwell, recover=recover, ops=SHARD_OPS,
                         shards=shard_ids)
        Nemesis(harness, history).run(sched)

        # bounded liveness: a probe write must land post-heal
        heal_t0 = time.monotonic()
        probe = clients[0]
        converged = wait_for(lambda: probe.one_op(),
                             timeout=heal_window, interval=0.2)
        if converged:
            history.record({"e": "converged",
                            "seconds":
                                round(time.monotonic() - heal_t0, 2),
                            "node": "shard-plane",
                            "epoch": client.plane.map.epoch})
        for c in clients:
            c.stop()
        for c in clients:
            c.join(timeout=10)
        # final read: scatter the registers off the (possibly moved)
        # owners — acked values must all have survived
        client.refresh_map()
        final_state = {}
        for key in keys:
            _cols, rows = client.read(
                "MATCH (r:Reg {k: $k}) RETURN r.v", {"k": key},
                key=key)
            final_state[key] = rows[0][0] if rows else 0
        history.record({"e": "final", "node": "shard-plane",
                        "epoch": plane.map.epoch,
                        "state": final_state})
        violations = check_cluster_history(history,
                                           heal_window=heal_window)
        stats = {"seed": seed, "rounds": rounds,
                 "acked": sum(c.acked for c in clients),
                 "ops": next(ops_counter) - 1,
                 "converged": converged,
                 "epoch": plane.map.epoch,
                 "violations": len(violations)}
        return history, violations, stats
    finally:
        plane.close()
