"""mgchaos command line: `python -m tools.mgchaos <cmd>`.

    run          one seeded chaos campaign (cluster + nemesis + checker)
    sweep        N seeded campaigns; any violation fails the sweep
    schedule     print a seed's nemesis schedule (byte-replayable)
    check        offline-check a previously dumped history JSONL
    device-smoke one seeded DEVICE nemesis round (accelerator faults
                 through the supervised kernel plane; gate stage)
    device-schedule  print a seed's device nemesis schedule
    shard        one seeded SHARD-plane campaign (shard_move +
                 shard_worker_kill against a live ShardPlane; r18)

Exit codes: 0 safe, 1 violations found, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.mgchaos",
        description="memgraph_tpu Jepsen-style cluster chaos harness")
    sub = p.add_subparsers(dest="cmd", required=True)

    rn = sub.add_parser("run", help="one seeded chaos campaign")
    rn.add_argument("--seed", type=int, default=0)
    rn.add_argument("--rounds", type=int, default=4)
    rn.add_argument("--clients", type=int, default=3)
    rn.add_argument("--no-fencing", action="store_true",
                    help="deliberately unsafe SYNC cluster without "
                         "fencing (the checker MUST flag it)")
    rn.add_argument("--expect-unsafe", action="store_true",
                    help="invert the exit code: succeed only when the "
                         "checker FOUND violations (honesty check)")
    rn.add_argument("--dump", metavar="PATH",
                    help="write the history JSONL to PATH")

    sw = sub.add_parser("sweep", help="N seeded campaigns")
    sw.add_argument("--seeds", type=int, default=10)
    sw.add_argument("--seed-base", type=int, default=0)
    sw.add_argument("--rounds", type=int, default=4)

    sub.add_parser(
        "honesty",
        help="checker-honesty gate: the scripted split-brain scenario "
             "must be FLAGGED without fencing and CLEAN with it")

    sc = sub.add_parser("schedule", help="print a seed's nemesis schedule")
    sc.add_argument("--seed", type=int, default=0)
    sc.add_argument("--rounds", type=int, default=4)
    sc.add_argument("--coords", type=int, default=3)
    sc.add_argument("--data", type=int, default=3)

    ck = sub.add_parser("check", help="offline-check a history JSONL")
    ck.add_argument("history", help="path to a chaos history .jsonl")

    ds = sub.add_parser(
        "device-smoke",
        help="seeded device nemesis round: accelerator faults "
             "(call/oom/hang/lost) injected mid-pagerank, mid-kernel-"
             "request and during probe, through the supervised plane")
    ds.add_argument("--seed", type=int, default=0)
    ds.add_argument("--rounds", type=int, default=None,
                    help="truncate the (op x context) matrix "
                         "(default: full matrix)")

    dsch = sub.add_parser("device-schedule",
                          help="print a seed's device nemesis schedule")
    dsch.add_argument("--seed", type=int, default=0)
    dsch.add_argument("--rounds", type=int, default=None)

    sh = sub.add_parser(
        "shard",
        help="one seeded shard-plane campaign: live shard moves + "
             "owner kills under register traffic, offline-checked")
    sh.add_argument("--seed", type=int, default=0)
    sh.add_argument("--rounds", type=int, default=4)
    sh.add_argument("--shards", type=int, default=4)
    sh.add_argument("--clients", type=int, default=4)
    sh.add_argument("--dump", metavar="PATH",
                    help="write the history JSONL to PATH")
    return p


def _force_cpu_backend() -> None:
    """Device-smoke runs on the CPU backend unless the operator opts a
    real accelerator in: the stage validates the resilience machinery
    deterministically, and the dev-gate must not touch (or hang on) a
    tunneled device. Must run before jax is first imported."""
    platform = os.environ.get("MGCHAOS_DEVICE_PLATFORM", "cpu")
    os.environ["JAX_PLATFORMS"] = platform
    flags = os.environ.get("XLA_FLAGS", "")
    if platform == "cpu" and \
            "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=2").strip()


def _report(seed: int, violations: list[str], stats: dict) -> None:
    verdict = "SAFE" if not violations else "UNSAFE"
    print(f"seed {seed}: {verdict} — {stats['acked']} acked / "
          f"{stats['ops']} ops, main={stats['main']} "
          f"epoch={stats['epoch']} converged={stats['converged']}")
    for v in violations:
        print(f"  VIOLATION: {v}")


def _cmd_run(args) -> int:
    from .runner import run_chaos
    history, violations, stats = run_chaos(
        args.seed, rounds=args.rounds, n_clients=args.clients,
        fencing=not args.no_fencing)
    _report(args.seed, violations, stats)
    if args.dump:
        history.dump(args.dump)
        print(f"history written to {args.dump}")
    if args.expect_unsafe:
        if violations:
            print("checker-honesty: violations found, as expected")
            return 0
        print("checker-honesty FAILED: the unsafe run was NOT flagged",
              file=sys.stderr)
        return 1
    return 1 if violations else 0


def _cmd_sweep(args) -> int:
    from .runner import run_chaos
    bad = 0
    for i in range(args.seeds):
        seed = args.seed_base + i
        _, violations, stats = run_chaos(seed, rounds=args.rounds)
        _report(seed, violations, stats)
        bad += bool(violations)
    print(f"sweep: {args.seeds - bad}/{args.seeds} seeds safe")
    return 1 if bad else 0


def _cmd_honesty(_args) -> int:
    from .runner import run_split_brain_scenario
    _, unsafe_violations, _ = run_split_brain_scenario(fencing=False)
    _, safe_violations, _ = run_split_brain_scenario(fencing=True)
    ok = bool(unsafe_violations) and not safe_violations
    print(f"checker-honesty: fencing-off flagged={bool(unsafe_violations)}"
          f" ({len(unsafe_violations)} violation(s)), "
          f"fencing-on clean={not safe_violations}")
    for v in unsafe_violations:
        print(f"  [expected] {v}")
    for v in safe_violations:
        print(f"  [UNEXPECTED] {v}", file=sys.stderr)
    return 0 if ok else 1


def _cmd_schedule(args) -> int:
    from .nemesis import schedule_text
    coords = [f"c{i + 1}" for i in range(args.coords)]
    data = [f"i{i + 1}" for i in range(args.data)]
    sys.stdout.write(schedule_text(args.seed, sorted(coords) + sorted(data),
                                   sorted(data), rounds=args.rounds))
    return 0


def _cmd_device_smoke(args) -> int:
    _force_cpu_backend()
    from .device import run_device_matrix
    print(f"device nemesis smoke: seed={args.seed}")
    failures, observed = run_device_matrix(args.seed, rounds=args.rounds)
    for f in failures:
        print(f"  FAILURE: {f}", file=sys.stderr)
    ops = ", ".join(f"{k}→{sorted(v)}" for k, v in sorted(observed.items()))
    print(f"device-smoke: {'UNSAFE' if failures else 'SAFE'} — "
          f"{len(failures)} failure(s); outcomes: {ops}")
    return 1 if failures else 0


def _cmd_device_schedule(args) -> int:
    from .device import device_schedule_text
    sys.stdout.write(device_schedule_text(args.seed, args.rounds))
    return 0


def _cmd_shard(args) -> int:
    from .shard import run_shard_chaos
    history, violations, stats = run_shard_chaos(
        args.seed, rounds=args.rounds, n_shards=args.shards,
        n_clients=args.clients)
    verdict = "SAFE" if not violations else "UNSAFE"
    print(f"shard seed {args.seed}: {verdict} — {stats['acked']} acked "
          f"/ {stats['ops']} ops, epoch={stats['epoch']} "
          f"converged={stats['converged']}")
    for v in violations:
        print(f"  VIOLATION: {v}")
    if args.dump:
        history.dump(args.dump)
        print(f"history written to {args.dump}")
    return 1 if violations else 0


def _cmd_check(args) -> int:
    from .checker import HistoryLog, check_cluster_history
    violations = check_cluster_history(HistoryLog.load(args.history))
    for v in violations:
        print(f"VIOLATION: {v}")
    print(f"{len(violations)} violation(s)")
    return 1 if violations else 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return {"run": _cmd_run, "sweep": _cmd_sweep, "honesty": _cmd_honesty,
            "schedule": _cmd_schedule, "check": _cmd_check,
            "device-smoke": _cmd_device_smoke,
            "device-schedule": _cmd_device_schedule,
            "shard": _cmd_shard}[args.cmd](args)
