"""Seeded nemesis: a byte-replayable schedule of cluster faults.

A nemesis schedule is a PURE function of ``(seed, node lists, rounds)``
— the same arming contract as ``faultinject.seeded_schedule`` and the
mgsan scheduler: a failure found by a randomized campaign replays
exactly by re-running with its seed. ``schedule_text`` renders the
whole schedule as one canonical string, so determinism is testable as
byte identity.

Each round picks one op from ``faultinject.NEMESIS_OPS``:

    partition          symmetric partition of a chosen peer pair
    partition_oneway   asymmetric link: src→dst lost, dst→src intact
    partition_node     isolate one node from everybody (a "pause")
    delay              fixed latency on a link
    duplicate          every message on the link delivered twice
    reorder            seeded jitter on the link (messages overtake)
    kill_restart       hard-kill a DATA node, restart it after the dwell
    shard_move         live-rebalance a shard to a fresh worker (r18)
    shard_worker_kill  SIGKILL a shard owner; the heal respawns it
    stream_consumer_kill  kill a stream consumer mid-batch; heal
                       restarts it from the durably-committed offset (r17)

then dwells, heals (or restarts), and lets the cluster recover before
the next round. The ``Nemesis`` executor applies ops against a live
``ChaosCluster`` through the faultinject network model.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from memgraph_tpu.utils import faultinject as FI


@dataclass(frozen=True)
class NemesisOp:
    round: int
    kind: str                  # one of faultinject.NEMESIS_OPS
    targets: tuple[str, ...]   # the node(s)/link the op hits
    arg: float                 # delay/jitter seconds (0 when unused)
    dwell: float               # seconds the fault stays active
    recover: float             # seconds of calm after heal/restart

    def render(self) -> str:
        return (f"r{self.round:02d} {self.kind}"
                f"({','.join(self.targets)})"
                f" arg={self.arg:.3f} dwell={self.dwell:.2f}"
                f" recover={self.recover:.2f}")


def schedule(seed: int, nodes: list[str], data_nodes: list[str],
             rounds: int = 6, dwell: tuple[float, float] = (1.5, 3.0),
             recover: tuple[float, float] = (1.5, 2.5),
             ops: tuple[str, ...] = FI.NEMESIS_OPS,
             shards: list[str] | None = None,
             streams: list[str] | None = None) -> list[NemesisOp]:
    """Derive a deterministic fault schedule from ``seed``.

    ``nodes`` is every partitionable node (coordinators + data);
    ``data_nodes`` the subset eligible for kill/restart churn;
    ``shards`` the shard-id targets for the r18 shard-plane ops
    (defaults to ``data_nodes`` so a schedule stays derivable from any
    node census); ``streams`` the stream names the r17 consumer-kill op
    targets (defaults to ``data_nodes`` likewise). Lists are consumed
    in the given order, so pass them in a canonical (sorted) order for
    cross-process replay."""
    for op in ops:
        if op not in FI.NEMESIS_OPS:
            raise ValueError(f"unknown nemesis op {op!r}")
    shard_targets = shards if shards else data_nodes
    stream_targets = streams if streams else data_nodes
    rng = random.Random(seed)
    out: list[NemesisOp] = []
    for rnd in range(rounds):
        kind = ops[rng.randrange(len(ops))]
        arg = 0.0
        if kind in ("shard_move", "shard_worker_kill"):
            targets = (shard_targets[rng.randrange(len(shard_targets))],)
        elif kind == "stream_consumer_kill":
            targets = (
                stream_targets[rng.randrange(len(stream_targets))],)
        elif kind == "kill_restart":
            targets = (data_nodes[rng.randrange(len(data_nodes))],)
        elif kind == "partition_node":
            targets = (nodes[rng.randrange(len(nodes))],)
        else:
            i = rng.randrange(len(nodes))
            j = rng.randrange(len(nodes) - 1)
            if j >= i:
                j += 1
            targets = (nodes[i], nodes[j])
            if kind == "delay":
                arg = round(0.05 + rng.random() * 0.2, 3)
            elif kind == "reorder":
                arg = round(0.02 + rng.random() * 0.1, 3)
        out.append(NemesisOp(
            round=rnd, kind=kind, targets=targets, arg=arg,
            dwell=round(rng.uniform(*dwell), 2),
            recover=round(rng.uniform(*recover), 2)))
    return out


def schedule_text(seed: int, nodes: list[str], data_nodes: list[str],
                  rounds: int = 6, **kw) -> str:
    """Canonical one-op-per-line rendering; same seed ⇒ identical bytes."""
    lines = [f"nemesis seed={seed} nodes={','.join(nodes)} "
             f"data={','.join(data_nodes)} rounds={rounds}"]
    lines += [op.render()
              for op in schedule(seed, nodes, data_nodes, rounds, **kw)]
    return "\n".join(lines) + "\n"


class Nemesis:
    """Applies a schedule against a live ChaosCluster, recording every
    step into the cluster history so the checker can correlate faults
    with anomalies."""

    def __init__(self, cluster, history=None):
        self.cluster = cluster
        self.history = history

    def _record(self, op: NemesisOp, phase: str) -> None:
        if self.history is not None:
            self.history.record({"e": "nemesis", "round": op.round,
                                 "op": op.kind, "phase": phase,
                                 "targets": list(op.targets)})

    def apply(self, op: NemesisOp) -> None:
        self._record(op, "start")
        if op.kind == "partition":
            FI.net_partition(op.targets[0], op.targets[1])
        elif op.kind == "partition_oneway":
            FI.net_partition(op.targets[0], op.targets[1],
                             bidirectional=False)
        elif op.kind == "partition_node":
            FI.net_partition_node(op.targets[0])
        elif op.kind == "delay":
            FI.net_delay(op.targets[0], op.targets[1], op.arg)
        elif op.kind == "duplicate":
            FI.net_duplicate(op.targets[0], op.targets[1])
        elif op.kind == "reorder":
            FI.net_reorder(op.targets[0], op.targets[1], op.arg)
        elif op.kind == "kill_restart":
            self.cluster.kill(op.targets[0])
        elif op.kind == "shard_move":
            # the move IS the fault AND the recovery (epoch bump +
            # cutover); the dwell just lets traffic ride the new owner
            self.cluster.shard_move(op.targets[0])
        elif op.kind == "shard_worker_kill":
            self.cluster.shard_kill(op.targets[0])
        elif op.kind == "stream_consumer_kill":
            self.cluster.stream_consumer_kill(op.targets[0])
        else:  # pragma: no cover - schedule() validates op kinds
            raise ValueError(f"unknown nemesis op {op.kind!r}")

    def heal(self, op: NemesisOp) -> None:
        if op.kind == "kill_restart":
            self.cluster.restart(op.targets[0])
        elif op.kind == "shard_worker_kill":
            self.cluster.shard_restart(op.targets[0])
        elif op.kind == "stream_consumer_kill":
            self.cluster.stream_consumer_restart(op.targets[0])
        elif op.kind == "shard_move":
            pass   # cutover already healed it; record the phase below
        elif op.kind == "partition_node":
            FI.net_heal(op.targets[0])
        else:
            FI.net_heal(op.targets[0], op.targets[1])
        self._record(op, "heal")

    def run(self, sched: list[NemesisOp], sleep=None) -> None:
        """Execute a whole schedule: apply → dwell → heal → recover."""
        import time
        sleep = sleep or time.sleep
        for op in sched:
            self.apply(op)
            sleep(op.dwell)
            self.heal(op)
            sleep(op.recover)
