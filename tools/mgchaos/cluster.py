"""In-process chaos cluster: coordinators + data instances + workload.

One ``ChaosCluster`` wires up a real HA topology — N Raft coordinators,
one MAIN and M replicas with real mgmt/replication sockets on
localhost — inside the current process, so the nemesis can partition
links through the faultinject network model AND hard-kill nodes by
tearing their servers down (the in-process analog of the PR-2
subprocess kill: sockets die mid-conversation, state the node did not
replicate is lost to its peers until heal).

Storage is treated as each node's durable disk (it survives a
kill/restart); WAL-level crash consistency has its own subprocess
matrix in tests/test_durability.py — this harness is about CLUSTER
safety: fencing, failover, replication holes.

``ChaosClient`` implements the Jepsen workload: each client owns one
register key and writes strictly increasing values through the current
MAIN (per the leader coordinator's replicated state), recording every
invoke/ok/fail/info with the fencing epoch into the shared history.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

from memgraph_tpu.coordination.coordinator import CoordinatorInstance
from memgraph_tpu.coordination.data_instance import (
    DataInstanceManagementServer)
from memgraph_tpu.exceptions import (FencedException, MemgraphTpuError,
                                     ReplicaUnavailableException)
from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.replication.main_role import ReplicationState
from memgraph_tpu.storage import InMemoryStorage
from memgraph_tpu.storage.storage import VertexAccessor
from memgraph_tpu.utils import faultinject as FI
from tools.mgsan.isocheck import HistoryLog

log = logging.getLogger(__name__)


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def wait_for(pred, timeout: float = 15.0, interval: float = 0.05) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class ChaosDataNode:
    """One data instance: storage (the node's 'disk'), an interpreter
    context, a mgmt server, and a replication state named for the
    nemesis link model."""

    def __init__(self, name: str, mgmt_port: int, repl_port: int):
        self.name = name
        self.mgmt_port = mgmt_port
        self.repl_port = repl_port
        self.storage = InMemoryStorage()
        self.alive = False
        self.ictx: InterpreterContext | None = None
        self.mgmt: DataInstanceManagementServer | None = None
        # simulated durable replication state for restart (the real
        # server persists role/epoch in its kvstore)
        self._saved_role = "main"
        self._saved_epoch = 0
        self.start()

    @property
    def mgmt_address(self) -> str:
        return f"127.0.0.1:{self.mgmt_port}"

    @property
    def repl_address(self) -> str:
        return f"127.0.0.1:{self.repl_port}"

    @property
    def replication(self) -> ReplicationState | None:
        return getattr(self.ictx, "replication", None) if self.ictx \
            else None

    def start(self) -> None:
        self.ictx = InterpreterContext(self.storage)
        self.ictx.replication = ReplicationState(
            self.storage, ictx=self.ictx, node_name=self.name)
        self.mgmt = DataInstanceManagementServer(
            self.ictx, "127.0.0.1", self.mgmt_port, node_name=self.name)
        self.mgmt.start()
        if self._saved_role == "replica":
            self.ictx.replication.set_role_replica(
                "0.0.0.0", self.repl_port, epoch=self._saved_epoch)
        else:
            self.ictx.replication.fencing_epoch = self._saved_epoch
        self.alive = True

    def kill(self) -> None:
        """Hard kill: every socket dies mid-conversation; unreplicated
        in-memory session state (pending 2PC, connections) is lost."""
        if not self.alive:
            return
        self.alive = False
        repl = self.replication
        if repl is not None:
            self._saved_role = repl.role
            self._saved_epoch = repl.current_epoch()
            repl.shutdown()
        if self.mgmt is not None:
            self.mgmt.stop()
        if self.ictx is not None:
            self.ictx.replication = None

    def restart(self) -> None:
        if self.alive:
            return
        self.start()


class ChaosCluster:
    """The full topology plus the shared history log."""

    HEALTH_INTERVAL = 0.2

    def __init__(self, seed: int = 0, n_coords: int = 3, n_data: int = 3,
                 fencing: bool = True):
        FI.net_seed(seed)
        self.seed = seed
        self.fencing = fencing
        self.history = HistoryLog()
        coord_ids = [f"c{i + 1}" for i in range(n_coords)]
        data_ids = [f"i{i + 1}" for i in range(n_data)]
        self.coord_ids, self.data_ids = coord_ids, data_ids
        raft_ports = free_ports(n_coords)
        data_ports = free_ports(2 * n_data)
        self.coordinators: dict[str, CoordinatorInstance] = {}
        for i, cid in enumerate(coord_ids):
            peers = {coord_ids[j]: ("127.0.0.1", raft_ports[j])
                     for j in range(n_coords) if j != i}
            coord = CoordinatorInstance(
                cid, "127.0.0.1", raft_ports[i], peers,
                # STRICT_SYNC + no degradation is the split-brain-proof
                # profile; fencing=False is the checker-honesty mode (a
                # deliberately unsafe SYNC cluster the checker must flag)
                repl_mode="STRICT_SYNC" if fencing else "SYNC",
                election_seed=seed * 1000 + i)
            coord.HEALTH_CHECK_INTERVAL = self.HEALTH_INTERVAL
            self.coordinators[cid] = coord
        self.data: dict[str, ChaosDataNode] = {}
        for i, did in enumerate(data_ids):
            self.data[did] = ChaosDataNode(
                did, data_ports[2 * i], data_ports[2 * i + 1])

    # --- topology bring-up --------------------------------------------------

    def start(self, main: str | None = None) -> None:
        for coord in self.coordinators.values():
            coord.start()
        if not wait_for(lambda: self.leader() is not None, timeout=20):
            raise RuntimeError("no raft leader elected at bring-up")
        leader = self.leader()
        for did, node in self.data.items():
            if not leader.register_instance(did, node.mgmt_address,
                                            node.repl_address):
                raise RuntimeError(f"register_instance({did}) failed")
        main = main or self.data_ids[0]
        if not leader.set_instance_to_main(main):
            raise RuntimeError(f"set_instance_to_main({main}) failed")
        ok = wait_for(lambda: self._main_ready(main), timeout=20)
        if not ok:
            raise RuntimeError("initial topology never became ready")

    def _main_ready(self, main: str) -> bool:
        repl = self.data[main].replication
        if repl is None or repl.role != "main":
            return False
        others = [d for d in self.data_ids if d != main]
        from memgraph_tpu.replication.main_role import ReplicaStatus
        with repl._lock:
            clients = dict(repl.replicas)
        return sorted(clients) == sorted(others) and all(
            c.status is ReplicaStatus.READY for c in clients.values())

    # --- cluster views ------------------------------------------------------

    def leader(self) -> CoordinatorInstance | None:
        for coord in self.coordinators.values():
            if coord.raft.is_leader():
                return coord
        return None

    def cluster_view(self) -> tuple[str | None, int]:
        """(main name, fencing epoch) per the current raft leader, or
        the freshest epoch any coordinator knows when leaderless."""
        leader = self.leader()
        if leader is not None:
            with leader._lock:
                return leader.main_name, leader.epoch
        best = (None, 0)
        for coord in self.coordinators.values():
            with coord._lock:
                if coord.epoch >= best[1]:
                    best = (coord.main_name, coord.epoch)
        return best

    # --- nemesis node ops ---------------------------------------------------

    def kill(self, name: str) -> None:
        node = self.data.get(name)
        if node is not None:
            log.warning("chaos: killing %s", name)
            node.kill()

    def restart(self, name: str) -> None:
        node = self.data.get(name)
        if node is not None:
            log.warning("chaos: restarting %s", name)
            node.restart()

    def heal_all(self) -> None:
        FI.net_heal()
        for node in self.data.values():
            if not node.alive:
                node.restart()

    def stop(self) -> None:
        FI.net_heal()
        for coord in self.coordinators.values():
            coord.stop()
        for node in self.data.values():
            node.kill()

    # --- workload -----------------------------------------------------------

    def setup_registers(self, n_clients: int) -> dict[str, int]:
        """Create one register vertex per client ON THE MAIN (value 0);
        replication ships them everywhere. Returns {key: gid}."""
        main, _ = self.cluster_view()
        node = self.data[main]
        st = node.storage
        prop = st.property_mapper.name_to_id("v")
        gids = {}
        for c in range(n_clients):
            acc = st.access()
            v = acc.create_vertex()
            v.set_property(prop, 0)
            acc.commit()
            gids[f"k{c}"] = v.vertex.gid
        return gids

    def write(self, node_name: str, gid: int, value: int) -> None:
        """One register write through the full commit path (2PC votes,
        fencing, replication) of the named node."""
        node = self.data[node_name]
        if not node.alive or node.replication is None:
            raise MemgraphTpuError(f"node {node_name} is down")
        if node.replication.role != "main":
            # a real server refuses writes on replicas at the
            # interpreter layer; the harness mirrors that check
            raise ReplicaUnavailableException(
                f"{node_name} is not MAIN")
        st = node.storage
        prop = st.property_mapper.name_to_id("v")
        acc = st.access()
        va = VertexAccessor(st._vertices[gid], acc)
        va.set_property(prop, value)
        acc.commit()

    def read_final_state(self, node_name: str,
                         gids: dict[str, int]) -> dict[str, int]:
        node = self.data[node_name]
        st = node.storage
        prop = st.property_mapper.name_to_id("v")
        out = {}
        for key, gid in gids.items():
            acc = st.access()
            try:
                va = VertexAccessor(st._vertices[gid], acc)
                out[key] = va.get_property(prop)
            finally:
                acc.abort()
        return out


class ChaosClient(threading.Thread):
    """One Jepsen client: writes increasing values to its own register
    via whatever node the coordinators currently call MAIN."""

    def __init__(self, cluster: ChaosCluster, idx: int, key: str,
                 gid: int, op_counter, interval: float = 0.05):
        super().__init__(daemon=True, name=f"chaos-client-{idx}")
        self.cluster = cluster
        self.idx = idx
        self.key = key
        self.gid = gid
        self.interval = interval
        self.next_value = 1
        self.known_epoch = 0
        self._ops = op_counter       # shared itertools.count
        # NB: not "_stop" — threading.Thread owns that attribute
        self._halt = threading.Event()
        self.acked = 0

    def stop(self) -> None:
        self._halt.set()

    def one_op(self) -> bool:
        """Attempt one write; returns True when it was validly acked."""
        hist = self.cluster.history
        main, epoch = self.cluster.cluster_view()
        self.known_epoch = max(self.known_epoch, epoch)
        if main is None:
            return False
        op = next(self._ops)
        value = self.next_value
        hist.record({"e": "invoke", "op": op, "client": self.idx,
                     "key": self.key, "value": value})
        try:
            self.cluster.write(main, self.gid, value)
        except (FencedException, ReplicaUnavailableException) as e:
            # refused BEFORE any replica prepared: definitely did not
            # happen anywhere — a clean, safe failure
            hist.record({"e": "fail", "op": op, "err": type(e).__name__})
            self.next_value += 1
            return False
        except Exception as e:  # noqa: BLE001 — anything else is ambiguous
            hist.record({"e": "info", "op": op, "err": type(e).__name__})
            self.next_value += 1
            return False
        repl = self.cluster.data[main].replication
        ack_epoch, fenced = repl.fencing_info() if repl is not None \
            else (0, True)
        if self.cluster.fencing and \
                (fenced or ack_epoch < self.known_epoch):
            # the commit reported success but the acking node's epoch is
            # already stale — a fencing-aware client refuses the ack
            hist.record({"e": "info", "op": op, "err": "stale-epoch-ack"})
            self.next_value += 1
            return False
        self.known_epoch = max(self.known_epoch, ack_epoch)
        hist.record({"e": "ok", "op": op, "node": main,
                     "epoch": ack_epoch})
        self.next_value += 1
        self.acked += 1
        return True

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self.one_op()
            except Exception:  # noqa: BLE001 — a client crash must not
                # kill the workload thread silently mid-campaign
                log.exception("chaos client %d op crashed", self.idx)
            self._halt.wait(self.interval)
