"""Per-kernel compiled memory facts from XLA's buffer assignment.

Every fact comes from the SAME artifact mgxla contract-checks: the
abstract lowering of the real product builder, compiled on the forced
8-virtual-device CPU mesh. ``compiled.memory_analysis()`` reports the
buffer assignment XLA actually committed to — argument/output/temp
bytes and the alias bytes donation actually saved — so the numbers are
the compiler's, not a hand count.

Donation effectiveness is machine-checkable here too: a donated param
whose buffer XLA reuses shows up in ``alias_size_in_bytes`` (and as an
``input_output_alias`` entry in the HLO); a donation XLA cannot honor
(shape/dtype mismatch, no matching output slot) is SILENTLY dropped at
compile time with only a UserWarning — the exact failure mode that
turns "donated fixpoint carry" into a full extra copy of the iterate
on a production device. We trap that warning per compile.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass

from tools.mgxla import hlo
from tools.mgxla.checker import Dims, build_compiled
from tools.mgxla.manifest import MANIFEST

#: the forced mesh width (mesh:* stats are per-device of this many)
N_SHARDS = 8

#: canonical shape points every scalable kernel is lowered at: vary n
#: at fixed e, then e at fixed n, so the (1, n, e) fit is exact.
#: mxu:* kernels carry a fixed internal Benes plan — one point, and
#: the model degrades to a constant at that shape.
SHAPE_POINTS = (Dims(n_pad=64, n_edges=256),
                Dims(n_pad=128, n_edges=256),
                Dims(n_pad=128, n_edges=512))

_DONATION_WARNING = "donated buffers were not usable"


@dataclass(frozen=True)
class MemFacts:
    """One kernel's compiled memory facts at one shape point."""

    kernel: str
    n_pad: int
    n_edges: int
    lanes: int                # PPR bucket width (1 for everything else)
    replicas: int             # mesh shards the stats are per-device of
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int          # donated bytes XLA actually aliased
    generated_code_bytes: int  # 0 on the CPU backend; real on TPU
    donated_aliased: int      # input_output_alias params in the HLO
    donation_dropped: int     # declared donations XLA silently copied
    dropped_bytes: int        # bytes of those silently-copied buffers

    @property
    def peak_bytes(self) -> int:
        """Whole-request device high-water mark: arguments + outputs +
        temps minus the output bytes aliased onto donated inputs,
        times the mesh width for sharded kernels (each device holds
        1/replicas; admission budgets the whole request)."""
        per_device = (self.argument_bytes + self.output_bytes
                      + self.temp_bytes - self.alias_bytes)
        return int(per_device) * self.replicas

    def as_dict(self) -> dict:
        return {"kernel": self.kernel, "n_pad": self.n_pad,
                "n_edges": self.n_edges, "lanes": self.lanes,
                "replicas": self.replicas,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "alias_bytes": self.alias_bytes,
                "generated_code_bytes": self.generated_code_bytes,
                "donated_aliased": self.donated_aliased,
                "donation_dropped": self.donation_dropped,
                "dropped_bytes": self.dropped_bytes,
                "peak_bytes": self.peak_bytes}


def kernel_lanes(kernel: str) -> int:
    """PPR bucket width baked into a ppr_batch kernel id (else 1)."""
    if ":ppr_batch:" in kernel:
        tag = kernel.rsplit(":", 1)[1]
        return int(tag.lstrip("bwarm") or 8)
    return 1


def shape_points(kernel: str) -> tuple:
    if kernel.startswith("mxu:"):
        return (SHAPE_POINTS[0],)     # fixed plan; dims are ignored
    return SHAPE_POINTS


def _parse_dropped(message: str) -> tuple[int, int]:
    """(count, bytes) of donated buffers XLA refused, from the jax
    UserWarning text (``ShapedArray(float32[64])`` entries)."""
    import re
    count = 0
    total = 0
    sizes = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
             "uint32": 4, "bfloat16": 2, "float16": 2, "uint16": 2,
             "int16": 2, "int8": 1, "uint8": 1, "bool": 1}
    for dtype, shape in re.findall(r"ShapedArray\((\w+)\[([\d,\s]*)\]",
                                   message):
        count += 1
        elems = 1
        for d in shape.replace(" ", "").split(","):
            if d:
                elems *= int(d)
        total += elems * sizes.get(dtype, 4)
    return count, total


def extract(kernel: str, dims: Dims) -> MemFacts:
    """Lower + compile one manifest kernel at `dims`; read the buffer
    assignment. Raises whatever the builder raises (the caller reports
    build failures as typed violations)."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = build_compiled(kernel, dims)
    dropped = 0
    dropped_bytes = 0
    for w in caught:
        if _DONATION_WARNING in str(w.message):
            c, b = _parse_dropped(str(w.message))
            dropped += c
            dropped_bytes += b
    ma = compiled.memory_analysis()
    donated = len(hlo.donated_params(compiled.as_text()))
    return MemFacts(
        kernel=kernel, n_pad=dims.n_pad, n_edges=dims.n_edges,
        lanes=kernel_lanes(kernel),
        replicas=N_SHARDS if kernel.startswith("mesh:") else 1,
        argument_bytes=int(ma.argument_size_in_bytes),
        output_bytes=int(ma.output_size_in_bytes),
        temp_bytes=int(ma.temp_size_in_bytes),
        alias_bytes=int(ma.alias_size_in_bytes),
        generated_code_bytes=int(ma.generated_code_size_in_bytes),
        donated_aliased=donated, donation_dropped=dropped,
        dropped_bytes=dropped_bytes)


def extract_all(kernel: str) -> list:
    """All shape points for one kernel, in SHAPE_POINTS order."""
    return [extract(kernel, d) for d in shape_points(kernel)]


def manifest_kernels() -> list:
    return sorted(MANIFEST)
