"""mgmem command line: ``python -m tools.mgmem check``.

Exit codes: 0 clean (or everything baselined), 1 violations / unused
baseline entries, 2 bad invocation, broken baseline, or an environment
that cannot lower the manifest (a host without the jax toolchain must
skip LOUDLY in the gate, never silently pass).
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.mgmem",
        description="compiled-artifact HBM accounting: machine-check "
                    "the admission guard against XLA's buffer "
                    "assignment")
    sub = p.add_subparsers(dest="cmd")
    chk = sub.add_parser("check", help="extract, fit, and gate")
    chk.add_argument("--only", action="append", default=None,
                     metavar="KERNEL",
                     help="check only this manifest kernel "
                          "(repeatable; skips envelope + admission "
                          "cross-checks)")
    chk.add_argument("--json", action="store_true",
                     help="machine-readable JSON output")
    chk.add_argument("--baseline", default=None,
                     help="baseline file (default: tools/mgmem/"
                          "baseline.json)")
    chk.add_argument("--no-baseline", action="store_true",
                     help="ignore the baseline: show every violation")
    chk.add_argument("--record", default=None, metavar="MEM_rN.json",
                     help="also write the canonical MEM record "
                          "perf_gate.check_memory enforces")
    env = sub.add_parser(
        "envelopes",
        help="print (or --write into BASELINE.json) the per-kernel "
             "canonical-point peak envelopes")
    env.add_argument("--write", action="store_true")
    lst = sub.add_parser("list", help="list manifest kernels and their "
                                      "fitted models")
    lst.add_argument("--json", action="store_true")
    return p


def _load_baseline(path: str | None):
    """Same loader discipline as mglint/mgxla: every entry needs a key
    and a non-empty justification."""
    import os

    from tools.mglint.core import load_baseline

    from .check import BASELINE_PATH
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    return load_baseline(path)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd is None:
        build_parser().print_help()
        return 2

    try:
        from tools.mgxla.manifest import MANIFEST
    except Exception as e:  # noqa: BLE001 — toolchainless host
        print(f"mgmem: cannot import the mgxla manifest ({e})",
              file=sys.stderr)
        return 2

    if args.cmd == "list":
        from .facts import kernel_lanes, shape_points
        if args.json:
            print(json.dumps(
                {k: {"lanes": kernel_lanes(k),
                     "shape_points": [[d.n_pad, d.n_edges]
                                      for d in shape_points(k)]}
                 for k in sorted(MANIFEST)}, indent=2))
        else:
            for k in sorted(MANIFEST):
                print(k)
        return 0

    from .check import (REPO_BASELINE_PATH, canonical_record,
                        memory_envelope_from, run_check)

    if args.cmd == "envelopes":
        report = run_check(envelope=None, admission=False)
        if report.violations:
            print(report.render())
            print("mgmem: refusing to write envelopes over a failing "
                  "sweep", file=sys.stderr)
            return 1
        envelope = memory_envelope_from(report)
        if args.write:
            with open(REPO_BASELINE_PATH, encoding="utf-8") as f:
                doc = json.load(f)
            doc.setdefault("envelopes", {})["memory"] = envelope
            with open(REPO_BASELINE_PATH, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
            print(f"mgmem: wrote {len(envelope['kernels'])} kernel "
                  f"envelopes into BASELINE.json")
        else:
            print(json.dumps(envelope, indent=2))
        return 0

    try:
        baseline = {} if args.no_baseline else _load_baseline(
            args.baseline)
    except (ValueError, OSError) as e:
        print(f"mgmem: broken baseline: {e}", file=sys.stderr)
        return 2

    only = set(args.only) if args.only else None
    if only:
        unknown = only - set(MANIFEST)
        if unknown:
            print(f"mgmem: unknown kernels {sorted(unknown)}; see "
                  "`python -m tools.mgmem list`", file=sys.stderr)
            return 2
    try:
        report = run_check(only=only, baseline=baseline)
    except ImportError as e:
        print(f"mgmem: lowering unavailable on this host ({e}) — "
              "NOTHING was checked", file=sys.stderr)
        return 2

    if args.record and only is None:
        record = canonical_record(report)
        with open(args.record, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"mgmem: wrote {args.record}")

    if args.json:
        print(json.dumps({
            "kernels_checked": report.kernels_checked,
            "violations": [{"kernel": v.kernel, "check": v.check,
                            "detail": v.detail, "key": v.key,
                            "snippet": v.snippet}
                           for v in report.violations],
            "baselined": [v.key for v in report.baselined],
            "unused_baseline": report.unused_baseline,
            "ok": report.ok}, indent=2))
    else:
        print(report.render())
    return 0 if report.ok else 1
