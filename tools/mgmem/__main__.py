"""``python -m tools.mgmem`` entry point.

Memory facts come from the SAME forced 8-virtual-device CPU mesh the
mgxla contract checker lowers on, so the env plumbing must happen
BEFORE any import that could pull jax in.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# the axon site hook can pre-initialize jax onto the tunneled TPU
# regardless of env; re-apply the cpu pin the same way the kernel-server
# daemon does
from memgraph_tpu.utils.jax_cache import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

from .cli import main  # noqa: E402

sys.exit(main())
