"""mgmem check driver: facts -> models -> admission + envelope gates.

Violation keys are stable strings (``kernel:check:detail``) consumed by
``tools/mgmem/baseline.json`` under the exact loader / justification
discipline mglint and mgxla use: every accepted violation needs a
written justification, and an entry no longer matched by any violation
is reported as UNUSED so the baseline can only shrink honestly.

Checks per manifest kernel:

* ``build``              — the product builder failed to lower/compile;
* ``donation-dropped``   — a declared donation XLA silently copied
                           (the UserWarning trap), with the bytes;
* ``donation-copied``    — the contract declares donations but the
                           compiled artifact aliased ZERO bytes;
* ``model-fit``          — the peak is not linear in (n, e) within
                           :data:`~.model.FIT_TOLERANCE` (a hidden
                           super-linear intermediate);
* ``envelope``           — canonical-point peak grew past the
                           BASELINE.json memory envelope (the
                           memory-regression gate, enforced again by
                           ``perf_gate.check_memory`` over the
                           committed MEM record);
* ``admission-*``        — the serving estimators vs the models
                           (:mod:`.admission`).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")
REPO_BASELINE_PATH = os.path.join(REPO, "BASELINE.json")

#: envelope headroom: canonical-point peak may grow this fraction
#: before the gate fails (mirrors the perf gate's 15% discipline but
#: tighter — buffer assignment is deterministic, drift is a change)
DEFAULT_MAX_GROWTH = 0.10


@dataclass(frozen=True)
class Violation:
    kernel: str
    check: str    # build|donation-dropped|donation-copied|model-fit|
    #               envelope|admission|admission-underestimate|
    #               admission-overestimate|padding-mirror
    detail: str
    snippet: str = ""

    @property
    def key(self) -> str:
        return f"{self.kernel}:{self.check}:{self.detail}"

    def render(self) -> str:
        out = f"{self.kernel}: {self.check}: {self.detail}"
        if self.snippet:
            out += "\n    | " + self.snippet.replace("\n", "\n    | ")
        return out


@dataclass
class CheckReport:
    violations: list = field(default_factory=list)    # unbaselined
    baselined: list = field(default_factory=list)
    unused_baseline: list = field(default_factory=list)
    kernels_checked: int = 0
    facts: dict = field(default_factory=dict)     # kernel -> [MemFacts]
    models: dict = field(default_factory=dict)    # kernel -> FootprintModel

    @property
    def ok(self) -> bool:
        return not self.violations and not self.unused_baseline

    def render(self) -> str:
        lines = [f"mgmem: {self.kernels_checked} kernels checked, "
                 f"{len(self.models)} footprint models fitted"]
        for v in self.violations:
            lines.append("VIOLATION " + v.render())
        for v in self.baselined:
            lines.append("baselined " + v.render().splitlines()[0])
        for key in self.unused_baseline:
            lines.append(f"UNUSED baseline entry (fixed or drifted): "
                         f"{key}")
        lines.append("mgmem: " + ("OK" if self.ok else "FAILED"))
        return "\n".join(lines)


def load_memory_envelope(path: str | None = None) -> dict | None:
    """BASELINE.json ``envelopes.memory`` (None when not yet written —
    bootstrap via ``python -m tools.mgmem envelopes --write``)."""
    path = path or REPO_BASELINE_PATH
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return (doc.get("envelopes") or {}).get("memory")


def _check_kernel(kernel: str, report: CheckReport) -> None:
    from tools.mgxla.manifest import MANIFEST

    from . import facts as F
    from .model import FIT_TOLERANCE, fit
    try:
        fl = F.extract_all(kernel)
    except Exception as e:  # noqa: BLE001 — typed as a build violation
        report.violations.append(Violation(
            kernel, "build", type(e).__name__,
            snippet=str(e).splitlines()[0][:200] if str(e) else ""))
        return
    report.facts[kernel] = fl
    f0 = fl[0]
    if f0.donation_dropped > 0:
        report.violations.append(Violation(
            kernel, "donation-dropped", f"{f0.dropped_bytes}B",
            snippet=f"{f0.donation_dropped} declared donation(s) XLA "
                    f"silently copied ({f0.dropped_bytes} bytes at the "
                    f"canonical point) — the donated carry costs a "
                    f"full extra buffer on device"))
    min_donated = MANIFEST[kernel].min_donated if kernel in MANIFEST \
        else 0
    if min_donated > 0 and f0.alias_bytes <= 0:
        report.violations.append(Violation(
            kernel, "donation-copied",
            f"declared>={min_donated},aliased=0B",
            snippet="the contract declares donated params but the "
                    "compiled artifact aliased zero bytes"))
    model = fit(kernel, fl)
    if model.residual > FIT_TOLERANCE:
        report.violations.append(Violation(
            kernel, "model-fit", f"residual={model.residual:.4f}",
            snippet="peak bytes are not linear in (n, e) — a "
                    "super-linear intermediate joined the buffer "
                    "assignment; the footprint model cannot "
                    "extrapolate this kernel"))
    else:
        report.models[kernel] = model


def _check_envelopes(report: CheckReport, envelope: dict | None) -> None:
    if envelope is None:
        return
    kernels = envelope.get("kernels") or {}
    max_growth = float(envelope.get("max_growth", DEFAULT_MAX_GROWTH))
    for kernel, fl in sorted(report.facts.items()):
        peak = fl[0].peak_bytes
        ref = kernels.get(kernel)
        if ref is None:
            report.violations.append(Violation(
                kernel, "envelope", "missing",
                snippet=f"canonical peak {peak}B has no BASELINE.json "
                        f"memory envelope — add one via `python -m "
                        f"tools.mgmem envelopes --write`"))
            continue
        ceiling = int(ref * (1.0 + max_growth))
        if peak > ceiling:
            report.violations.append(Violation(
                kernel, "envelope",
                f"peak={peak}B>ceiling={ceiling}B",
                snippet=f"canonical-point peak grew "
                        f"{(peak / ref - 1) * 100:+.1f}% past the "
                        f"envelope reference {ref}B (allowed "
                        f"+{max_growth * 100:.0f}%)"))
    for kernel in sorted(set(kernels) - set(report.facts)):
        report.violations.append(Violation(
            kernel, "envelope", "stale",
            snippet="envelope names a kernel the manifest no longer "
                    "has — regenerate with `envelopes --write`"))


def run_check(only=None, baseline: dict | None = None,
              estimators=None, envelope: dict | None = "load",
              admission: bool = True) -> CheckReport:
    """Extract, fit, and gate. ``only`` restricts to named kernels
    (envelope staleness + admission checks then skip, like mgxla's
    structural checks). ``estimators`` injects an
    :class:`~.admission.Estimators` fixture."""
    from . import facts as F
    baseline = baseline or {}
    report = CheckReport()
    kernels = sorted(only) if only else F.manifest_kernels()
    partial = only is not None
    for kernel in kernels:
        _check_kernel(kernel, report)
    report.kernels_checked = len(kernels)
    if not partial:
        if envelope == "load":
            envelope = load_memory_envelope()
        _check_envelopes(report, envelope)
        if admission:
            from .admission import run_admission_checks
            report.violations += run_admission_checks(
                report.models, Violation, estimators)
    matched = set()
    unbaselined = []
    for v in report.violations:
        if v.key in baseline:
            matched.add(v.key)
            report.baselined.append(v)
        else:
            unbaselined.append(v)
    report.violations = unbaselined
    if not partial:
        report.unused_baseline = sorted(set(baseline) - matched)
    return report


def canonical_record(report: CheckReport) -> dict:
    """The committed MEM_r*.json record ``perf_gate.check_memory``
    re-enforces: per-kernel canonical-point facts + fitted models."""
    from .facts import SHAPE_POINTS
    kernels = {}
    for kernel, fl in sorted(report.facts.items()):
        f0 = fl[0]
        entry = {"peak_bytes": f0.peak_bytes,
                 "argument_bytes": f0.argument_bytes,
                 "output_bytes": f0.output_bytes,
                 "temp_bytes": f0.temp_bytes,
                 "alias_bytes": f0.alias_bytes,
                 "generated_code_bytes": f0.generated_code_bytes,
                 "donated_aliased": f0.donated_aliased,
                 "donation_dropped": f0.donation_dropped,
                 "dropped_bytes": f0.dropped_bytes}
        m = report.models.get(kernel)
        if m is not None:
            entry["model"] = {"const": m.const, "per_node": m.per_node,
                              "per_edge": m.per_edge,
                              "replicas": m.replicas, "lanes": m.lanes}
        kernels[kernel] = entry
    return {"schema": "mgmem-1",
            "canonical_point": [SHAPE_POINTS[0].n_pad,
                                SHAPE_POINTS[0].n_edges],
            "kernels_checked": report.kernels_checked,
            "ok": report.ok,
            "kernels": kernels}


def memory_envelope_from(report: CheckReport,
                         max_growth: float = DEFAULT_MAX_GROWTH) -> dict:
    """Fresh ``envelopes.memory`` content for BASELINE.json."""
    return {"_comment": "per-kernel compiled peak bytes at the mgmem "
                        "canonical point (n_pad=64, n_edges=256; "
                        "mesh kernels whole-mesh). Enforced by `python "
                        "-m tools.mgmem check` and perf_gate."
                        "check_memory over the committed MEM_r*.json "
                        "record. Regenerate: `python -m tools.mgmem "
                        "envelopes --write`.",
            "max_growth": max_growth,
            "kernels": {k: fl[0].peak_bytes
                        for k, fl in sorted(report.facts.items())}}
