"""mgmem: compiled-artifact HBM accounting for the device plane.

The admission guard (resident / streamed / shed, r21 mgtier) rests on
hand-written byte estimators in ``server/kernel_server.py`` and
``ops/tier.py``. Nobody verifies them: an underestimate OOMs a
production device, an overestimate sheds traffic that would have fit.
mgxla (r17) already abstractly lowers every manifest kernel — and
XLA's post-compile buffer assignment (``compiled.memory_analysis()``:
argument / output / temp / alias bytes) is the ground truth sitting
one call away.

mgmem closes the loop:

  * :mod:`.facts` lowers every manifest kernel at 2–3 shape points
    (reusing mgxla's builder registry via ``build_compiled``) and
    extracts the per-kernel compiled memory facts, including donation
    effectiveness — donated params XLA actually aliased vs silently
    copied;
  * :mod:`.model` fits a symbolic footprint model
    ``peak(n_pad, n_edges)`` per kernel from those points;
  * :mod:`.check` machine-checks the kernel server's admission
    estimators against the model (underestimate = hard gate failure,
    >2x overestimate = justified-baseline entry), verifies every
    declared donation actually aliased, and enforces the per-kernel
    peak-bytes envelopes in BASELINE.json.

Run it as ``python -m tools.mgmem check`` (the dev-gate stage) — the
same loader / justification discipline as mglint and mgxla applies to
``tools/mgmem/baseline.json``.
"""
