"""Admission cross-checks: the serving estimators vs the fitted models.

Every servable op's byte estimate must BOUND the modeled compiled peak
of every kernel the op can route to (an underestimate OOMs a production
device — hard gate failure) without exceeding 2x of it (an overestimate
sheds traffic that would have fit — justified-baseline entry). The
estimators live in ``server/kernel_server.py`` / ``ops/tier.py``; the
models come from XLA's buffer assignment via :mod:`.model`.

The checks run against an :class:`Estimators` namespace so the gate's
own self-test can inject a deliberately-broken fixture (estimator
halved) and assert the offending kernel + bytes surface in the report.

Scope: the resident fixpoint family (segment + mesh backends), the PPR
serving plane's bucketed lane pricing, and the streamed tier path. The
MXU route (``route_backend``: sum-semiring, >= MXU_MIN_EDGES edges,
non-CPU backend) compiles a Benes plan whose footprint is plan-shaped,
not linear in (n, e) — those kernels are reported as
``admission:unmodeled-mxu-route`` and carried as justified baseline
entries until spmv_mxu grows a plan-size accounting hook. Lane kernels
(``segment:lane_*``) serve the compiled Cypher lane, which stages its
arrays at plan-build time, not per request — no admission estimator
prices them yet (ROADMAP item 2 residual); they still get envelope +
donation coverage like every manifest kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

#: (n_nodes, n_edges) raw request shapes the estimators are checked at:
#: small serving, mid, just-past-a-power-of-two (worst padding), node-
#: heavy, edge-heavy. Ratios are evaluated at these concrete shapes —
#: coefficient asymptotes alone would miss constant-term effects.
CHECK_SHAPES = ((10_000, 80_000),
                (100_000, 1_500_000),
                (1_048_577, 4_194_305),
                (2_000_000, 2_000_000),
                (500_000, 30_000_000))

#: declared bound: estimate within [1x, OVERESTIMATE_FACTOR x] of the
#: modeled peak at every check shape
OVERESTIMATE_FACTOR = 2.0

#: serving-path algorithm name -> manifest registry entry
SERVABLE = {
    "pagerank": "pagerank",
    "katz": "katz",
    "wcc": "components",
    "labelprop": "labelprop",
    "bfs": "bfs_layers",
    "ppr": "personalized_pagerank",
}

#: minimal wire payload per edge a graph-shipping request carries
#: (src int32 + dst int32 + weights f32) — the floor of the estimate's
#: staging term, used for the overestimate bound
WIRE_BYTES_PER_EDGE = 12

#: streamed phase schedule per streamable algorithm (kernel, extra
#: per-node f32 slots live across the phase but NOT args of its jit:
#: sweeps keep dangling/valid/inv_wsum resident, epilogues inv_wsum)
STREAMED_PHASES = {
    "pagerank": (("tier:wsum", 3), ("tier:pagerank_sweep", 3),
                 ("tier:pagerank_epilogue", 1)),
    "katz": (("tier:katz_sweep", 3), ("tier:katz_epilogue", 1)),
    "wcc": (("tier:wcc_sweep", 3), ("tier:wcc_epilogue", 1)),
}

#: int8 rides the pagerank sweep's quantized variant; bf16/int8 katz
#: and wcc blocks decode through the same f32 sweep kernels
STREAMED_INT8_PHASES = (("tier:wsum", 3), ("tier:pagerank_sweep_int8", 3),
                        ("tier:pagerank_epilogue", 1))


@dataclass(frozen=True)
class Estimators:
    """The serving estimators under check — injectable so the gate's
    broken-fixture self-test can halve one and watch it get caught."""

    graph_footprint_bytes: object    # (algorithm, n_nodes, n_edges)
    lane_state_bytes: object         # (n_nodes, n_edges, n_lanes)
    streamed_request_bytes: object   # (n_nodes, n_edges, precision)
    padded_graph_dims: object        # (n_nodes, n_edges) -> (n_pad, e_pad)
    lane_buckets: tuple              # compile-time PPR lane buckets


def product_estimators() -> Estimators:
    """The real serving-path estimators."""
    from memgraph_tpu.ops import tier as T
    from memgraph_tpu.server import kernel_server as ks
    return Estimators(
        graph_footprint_bytes=ks._graph_footprint_bytes,
        lane_state_bytes=ks._lane_state_bytes,
        streamed_request_bytes=T.streamed_request_bytes,
        padded_graph_dims=ks._padded_graph_dims,
        lane_buckets=ks._PPR_LANE_BUCKETS)


def _mb(b: float) -> str:
    return f"{b / 1e6:.1f}MB"


def check_padding_mirror(est: Estimators, violation) -> list:
    """The estimator's padding/bucket mirrors must track the placement
    code exactly — a drifted mirror silently re-opens the boundary
    underestimates this tool exists to close."""
    from memgraph_tpu.ops.csr import _bucket
    from memgraph_tpu.ops.pagerank import _PPR_LANE_BUCKETS
    out = []
    for n, e in ((0, 0), (7, 9), (63, 64), (65, 257), (10_000, 80_000),
                 (1 << 20, (1 << 22) + 1)):
        got = est.padded_graph_dims(n, e)
        want = (_bucket(n + 1), _bucket(max(e, 1)))
        if got != want:
            out.append(violation(
                "server:kernel_server", "padding-mirror",
                f"_padded_graph_dims({n}, {e}) = {got} but from_coo "
                f"places {want} — the estimator prices a different "
                f"bucket than the device allocates"))
    if tuple(est.lane_buckets) != tuple(_PPR_LANE_BUCKETS):
        out.append(violation(
            "server:kernel_server", "padding-mirror",
            f"lane bucket mirror {tuple(est.lane_buckets)} != "
            f"ops.pagerank._PPR_LANE_BUCKETS {tuple(_PPR_LANE_BUCKETS)}"))
    return out


def _resident_kernels(registry_name: str, manifest) -> tuple[list, list]:
    """(modeled resident kernels, unmodeled mxu kernels) the resident
    path can route a registry algorithm to."""
    covered, mxu = [], []
    for k, c in manifest.items():
        if registry_name not in c.registry:
            continue
        if k.startswith("mxu:"):
            mxu.append(k)
        elif k.startswith(("segment:", "mesh:")) \
                and ":ppr_batch:" not in k and ":lane_" not in k:
            covered.append(k)
    return covered, mxu


def check_resident(models: dict, est: Estimators, violation) -> list:
    """The per-algorithm footprint table must bound every resident
    kernel's modeled peak within [1x, 2x] at every check shape."""
    from tools.mgxla.manifest import MANIFEST
    out = []
    for algo, reg in SERVABLE.items():
        if algo == "ppr":
            continue                      # bucketed pricing, below
        kernels, mxu = _resident_kernels(reg, MANIFEST)
        for k in mxu:
            out.append(violation(
                k, "admission", "unmodeled-mxu-route"))
        for n, e in CHECK_SHAPES:
            n_pad, e_pad = est.padded_graph_dims(n, e)
            floor = int(est.graph_footprint_bytes(algo, n, e))
            ceiling = floor + e * WIRE_BYTES_PER_EDGE
            peaks = {k: models[k].predict(n_pad, e_pad)
                     for k in kernels if k in models}
            for k, peak in peaks.items():
                if floor < peak:
                    out.append(violation(
                        k, "admission-underestimate",
                        f"{algo}@({n},{e})",
                        f"estimate {_mb(floor)} < modeled peak "
                        f"{_mb(peak)} at padded ({n_pad},{e_pad}) — "
                        f"short {_mb(peak - floor)}; admitting this "
                        f"request OOMs the device"))
            if peaks:
                worst = max(peaks.values())
                if ceiling > OVERESTIMATE_FACTOR * worst:
                    out.append(violation(
                        max(peaks, key=peaks.get),
                        "admission-overestimate",
                        f"{algo}@({n},{e})",
                        f"estimate {_mb(ceiling)} > "
                        f"{OVERESTIMATE_FACTOR:.0f}x modeled peak "
                        f"{_mb(worst)} — shedding traffic that fits"))
    return out


def check_ppr(models: dict, est: Estimators, violation) -> list:
    """The PPR plane's price (graph footprint + bucketed lane state)
    must bound every lane-bucket kernel's modeled peak within [1x, 2x]
    — including the warm-start variant riding the 8-wide bucket."""
    out = []
    bucket_kernels = {b: f"segment:ppr_batch:b{b}"
                      for b in est.lane_buckets}
    extra = {8: ("segment:ppr_batch:warm8",), 1: ("segment:ppr",)}
    for b, kernel in bucket_kernels.items():
        targets = (kernel,) + extra.get(b, ())
        for n, e in CHECK_SHAPES:
            n_pad, e_pad = est.padded_graph_dims(n, e)
            price = int(est.graph_footprint_bytes("ppr", n, e)
                        + est.lane_state_bytes(n, e, b))
            for t in targets:
                if t not in models:
                    continue
                peak = models[t].predict(n_pad, e_pad)
                if price < peak:
                    out.append(violation(
                        t, "admission-underestimate",
                        f"ppr:b{b}@({n},{e})",
                        f"priced chunk {_mb(price)} < modeled peak "
                        f"{_mb(peak)} at padded ({n_pad},{e_pad}) x "
                        f"{b} lanes — short {_mb(peak - price)}"))
            peak = models.get(kernel)
            if peak is not None:
                worst = peak.predict(n_pad, e_pad)
                if price > OVERESTIMATE_FACTOR * worst:
                    out.append(violation(
                        kernel, "admission-overestimate",
                        f"ppr:b{b}@({n},{e})",
                        f"priced chunk {_mb(price)} > "
                        f"{OVERESTIMATE_FACTOR:.0f}x modeled peak "
                        f"{_mb(worst)}"))
    return out


def check_streamed(models: dict, est: Estimators, violation) -> list:
    """The streamed working-set estimate must bound every phase of the
    block schedule: the active block at its DECODED sweep peak, the
    next block's wire payload in flight, and the O(n) vectors over the
    plan's padded node count."""
    from memgraph_tpu.ops import tier as T
    out = []
    plans = []
    for algo, phases in STREAMED_PHASES.items():
        plans.append((algo, "f32", phases))
    plans.append(("pagerank", "int8", STREAMED_INT8_PHASES))
    for algo, precision, phases in plans:
        ewb = T.edge_wire_bytes(precision, u16=True)
        for n, e in CHECK_SHAPES:
            p = T.plan_blocks(n, e, precision)
            n_pad2 = p * T._ceil8(-(-(n + 1) // p))
            e_blk = T._ceil8(-(-max(e, 1) // p))
            est_bytes = int(est.streamed_request_bytes(
                n, e, precision, algorithm=algo))
            required = {}
            for kernel, extra_slots in phases:
                if kernel not in models:
                    continue
                # tier models take TOTAL edges (PER = n_edges/8 inside
                # the builder); one block of e_blk edges prices as
                # n_edges = 8 * e_blk
                required[kernel] = (
                    models[kernel].predict(n_pad2, 8 * e_blk)
                    + extra_slots * 4 * n_pad2 + e_blk * ewb)
            for kernel, need in required.items():
                if est_bytes < need:
                    out.append(violation(
                        kernel, "admission-underestimate",
                        f"streamed:{algo}:{precision}@({n},{e})",
                        f"streamed estimate {_mb(est_bytes)} < phase "
                        f"working set {_mb(need)} (plan P={p}, "
                        f"block={e_blk} edges) — short "
                        f"{_mb(need - est_bytes)}"))
            if required:
                worst = max(required.values())
                if est_bytes > OVERESTIMATE_FACTOR * worst:
                    out.append(violation(
                        max(required, key=required.get),
                        "admission-overestimate",
                        f"streamed:{algo}:{precision}@({n},{e})",
                        f"streamed estimate {_mb(est_bytes)} > "
                        f"{OVERESTIMATE_FACTOR:.0f}x phase peak "
                        f"{_mb(worst)}"))
    return out


def run_admission_checks(models: dict, violation,
                         estimators: Estimators | None = None) -> list:
    est = estimators or product_estimators()
    out = []
    out += check_padding_mirror(est, violation)
    out += check_resident(models, est, violation)
    out += check_ppr(models, est, violation)
    out += check_streamed(models, est, violation)
    return out
