"""The symbolic footprint model: peak bytes as a function of shape.

Per kernel, the compiled peak is fitted as

    peak(n_pad, n_edges) = const + per_node * n_pad + per_edge * n_edges

from the 2–3 lowered shape points in :data:`tools.mgmem.facts
.SHAPE_POINTS`. XLA's buffer assignment for these kernels is linear in
the padded dims — every buffer is an O(n) vector, an O(e) edge array,
or a scalar — so three independent points pin the coefficients
exactly, and the fit residual doubles as a linearity check: a kernel
whose assignment grows super-linearly (a materialized n x n
intermediate, say) shows up as a negative/garbage coefficient or a fit
residual and fails loudly instead of extrapolating nonsense.

Lane-bucketed PPR kernels keep ``lanes`` in the KERNEL ID (one
manifest row per bucket, exactly like the compile-budget table), so
the per-bucket model stays linear in (n, e) and the lane dimension is
never interpolated — the power-of-two bucket the compile actually
allocates is priced, not the requested width.
"""

from __future__ import annotations

from dataclasses import dataclass

from .facts import MemFacts

#: tolerated relative fit residual before a kernel is declared
#: non-linear in its dims (violation "model-fit")
FIT_TOLERANCE = 0.02


@dataclass(frozen=True)
class FootprintModel:
    """peak(n_pad, n_edges) ~= const + per_node*n_pad + per_edge*e."""

    kernel: str
    lanes: int
    replicas: int
    const: float
    per_node: float
    per_edge: float
    points: tuple              # ((n_pad, n_edges, peak_bytes), ...)
    residual: float            # max relative error over the fit points

    def predict(self, n_pad: int, n_edges: int) -> int:
        return int(max(0.0, self.const + self.per_node * n_pad
                       + self.per_edge * n_edges))

    def as_dict(self) -> dict:
        return {"kernel": self.kernel, "lanes": self.lanes,
                "replicas": self.replicas, "const": self.const,
                "per_node": self.per_node, "per_edge": self.per_edge,
                "points": [list(p) for p in self.points],
                "residual": self.residual}


def fit(kernel: str, facts: list) -> FootprintModel:
    """Exact linear solve from the shape points (least squares when
    overdetermined). Negative coefficients from float noise are
    clipped at zero; materially negative ones surface through the
    residual and the check layer's model-fit violation."""
    import numpy as np
    pts = [(f.n_pad, f.n_edges, f.peak_bytes) for f in facts]
    lanes = facts[0].lanes
    replicas = facts[0].replicas
    if len(pts) == 1:
        n, e, peak = pts[0]
        return FootprintModel(kernel, lanes, replicas, float(peak),
                              0.0, 0.0, tuple(pts), 0.0)
    a = np.array([[1.0, n, e] for n, e, _ in pts])
    y = np.array([float(p) for _, _, p in pts])
    coef, *_ = np.linalg.lstsq(a, y, rcond=None)
    c0, cn, ce = (float(c) for c in coef)
    # clip float-noise negatives; keep material ones for the residual
    if -1.0 < c0 < 0.0:
        c0 = 0.0
    if -0.01 < cn < 0.0:
        cn = 0.0
    if -0.01 < ce < 0.0:
        ce = 0.0
    model = FootprintModel(kernel, lanes, replicas, c0, cn, ce,
                           tuple(pts), 0.0)
    resid = max(abs(model.predict(n, e) - p) / max(p, 1)
                for n, e, p in pts)
    return FootprintModel(kernel, lanes, replicas, c0, cn, ce,
                          tuple(pts), float(resid))


def fit_kernel(kernel: str) -> FootprintModel:
    """Lower, extract, fit — one kernel end to end."""
    from . import facts as F
    return fit(kernel, F.extract_all(kernel))
