"""Per-kernel contract manifest for the mgxla checker.

One :class:`KernelContract` per compiled artifact the device plane
ships. The checker (tools/mgxla/checker.py) abstractly lowers each
kernel through its registered builder and verifies the compiled HLO
against the contract:

  * ``collectives``        — the EXACT whole-program multiset of
    cross-device collectives (sorted). For iterating kernels the checker
    additionally asserts every one of them sits inside the while body
    (the one-collective-per-iteration invariant from PR 6, generalized).
  * ``min_donated``        — at least this many parameters must be
    donated (``input_output_alias`` in the executable): the fixpoint
    carry must not double its HBM residency.
  * zero ``f64`` ops and zero host callbacks / infeed / outfeed are
    implicit contracts on every kernel (no field needed — a silent
    upcast or a host round-trip inside a compiled program is never
    intentional here; genuinely deliberate cases go in baseline.json).

``registry`` names the ``ops/__init__.py:SPMV_ALGORITHMS`` entries the
kernel covers; the checker fails if any registry entry is covered by no
kernel, if a manifest entry names an unknown registry key, or if any of
the three semiring backends has no kernel at all.

Registering a NEW kernel = one KernelContract here + one ``@builder``
in checker.py that returns its lowered artifact(s). docs/architecture.md
§Device-plane static analysis walks through it.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field

# mgxla shares mglint's baseline loader (same justification-required
# format); its OWN baseline file holds compiled-artifact exceptions.
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

#: the three backends every ⊕-shaped algorithm can ride (ops/semiring.py
#: route_backend); the checker requires all three to be covered
BACKENDS = ("segment", "mxu", "mesh")


@dataclass(frozen=True)
class KernelContract:
    kernel: str                      # manifest id, e.g. "mesh:pagerank"
    backend: str                     # segment | mxu | mesh
    registry: tuple = ()             # SPMV_ALGORITHMS keys covered
    collectives: tuple = ()          # exact sorted collective multiset
    min_donated: int = 0             # donated-parameter floor
    iterates: bool = True            # has a while-loop iteration body
    note: str = ""

    def as_dict(self) -> dict:
        return asdict(self)


def contract_from_dict(doc: dict) -> KernelContract:
    return KernelContract(
        kernel=doc["kernel"], backend=doc["backend"],
        registry=tuple(doc.get("registry", ())),
        collectives=tuple(doc.get("collectives", ())),
        min_donated=int(doc.get("min_donated", 0)),
        iterates=bool(doc.get("iterates", True)),
        note=doc.get("note", ""))


def _c(kernel, backend, registry, collectives=(), min_donated=0,
       iterates=True, note=""):
    return KernelContract(kernel=kernel, backend=backend,
                          registry=tuple(registry),
                          collectives=tuple(sorted(collectives)),
                          min_donated=min_donated, iterates=iterates,
                          note=note)


#: PPR serving-plane lane buckets — mirrored from ops/pagerank.py
#: (the checker cross-validates the two are identical, so a bucket
#: added there without a manifest row fails the gate).
PPR_LANE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def _ppr_bucket_contracts():
    out = {}
    for b in PPR_LANE_BUCKETS:
        out[f"segment:ppr_batch:b{b}"] = _c(
            f"segment:ppr_batch:b{b}", "segment",
            ["personalized_pagerank"],
            note=f"coalesced multi-source SpMM fixpoint, {b}-lane bucket")
    # the warm-start variant donates the x0 seed matrix
    out["segment:ppr_batch:warm8"] = _c(
        "segment:ppr_batch:warm8", "segment", ["personalized_pagerank"],
        min_donated=1,
        note="warm-started 8-lane bucket; cached vectors seed x0 and the"
             " seed buffer is donated back to the iterate")
    return out


MANIFEST: dict[str, KernelContract] = {
    # ---- partition-centric mesh kernels (8-shard forced mesh) --------
    # the one-collective-per-iteration invariant, donation of the chunk
    # carry (state vector(s) + convergence partials + iteration counter)
    "mesh:pagerank": _c(
        "mesh:pagerank", "mesh", ["pagerank"],
        collectives=["reduce-scatter"], min_donated=4,
        note="rank sharded over vertex blocks; ONE fused psum_scatter "
             "rides dangling-mass + convergence-error piggyback lanes"),
    "mesh:pagerank_bf16": _c(
        "mesh:pagerank_bf16", "mesh", ["pagerank"],
        collectives=["reduce-scatter"], min_donated=4,
        note="bf16 contribution streaming must not change the "
             "collective structure (f32 payload) nor upcast"),
    "mesh:katz": _c(
        "mesh:katz", "mesh", ["katz"],
        collectives=["all-reduce"], min_donated=3,
        note="x replicated, one psum per iteration"),
    "mesh:labelprop": _c(
        "mesh:labelprop", "mesh", ["labelprop"],
        collectives=["all-reduce"], min_donated=3,
        note="dst-owned election; one int psum concatenates the "
             "disjoint blocks"),
    "mesh:wcc": _c(
        "mesh:wcc", "mesh", ["components"],
        collectives=["all-reduce"], min_donated=3,
        note="comp replicated, one pmin per round + pointer jumping"),
    "mesh:semiring_min_plus": _c(
        "mesh:semiring_min_plus", "mesh", ["sssp", "bfs_layers"],
        collectives=["all-reduce"], min_donated=3,
        note="the generic (semiring, x0, epilogue) mesh kernel that "
             "sssp_mesh / bfs_mesh ride (min-plus relaxation)"),

    # ---- segment (reference) backend ---------------------------------
    # single-device programs: zero collectives; x0-carrying fixpoints
    # donate the seed
    "segment:pagerank": _c(
        "segment:pagerank", "segment", ["pagerank"],
        note="fused damping update + L1 partial in the while body"),
    "segment:pagerank_warm": _c(
        "segment:pagerank_warm", "segment", ["pagerank"],
        min_donated=1,
        note="r19 mgdelta warm-start variant: the previous solution "
             "rides in as x0 and is DONATED into the iterate; the loop "
             "body must be structure-identical to the cold variant "
             "(same zero-collective, no-f64, no-host-callback "
             "contract — warm start is data, not structure)"),
    "segment:katz_warm": _c(
        "segment:katz_warm", "segment", ["katz"],
        min_donated=1,
        note="r19 mgdelta warm-start variant of segment:katz — "
             "donated x0 seed, structure-identical body"),
    "segment:ppr": _c(
        "segment:ppr", "segment", ["personalized_pagerank"],
        note="restart-vector fixpoint (single query, in-process path)"),
    "segment:katz": _c("segment:katz", "segment", ["katz"]),
    "segment:hits": _c(
        "segment:hits", "segment", ["hits"],
        note="two interleaved normalized matvecs per round (the "
             "registry's mesh exemption case — still contract-checked "
             "on one device)"),
    "segment:labelprop": _c(
        "segment:labelprop", "segment", ["labelprop"], min_donated=1),
    "segment:wcc": _c(
        "segment:wcc", "segment", ["components"], min_donated=1),
    "segment:sssp": _c(
        "segment:sssp", "segment", ["sssp"], min_donated=1),
    "segment:bfs": _c(
        "segment:bfs", "segment", ["bfs_layers"], min_donated=1,
        note="direction-optimizing push/pull min-plus fixpoint"),
    "segment:scc": _c(
        "segment:scc", "segment", ["scc"],
        note="one FW-BW coloring round (the host drives rounds; the "
             "host loop reuses the previous iterate for its progress "
             "check, so the round kernel deliberately does not donate)"),
    "segment:betweenness": _c(
        "segment:betweenness", "segment", ["betweenness"],
        note="Brandes source-chunk: forward + backward sweeps as two "
             "while loops over (B, n) state"),
    "segment:gnn": _c(
        "segment:gnn", "segment", ["gnn"], iterates=False,
        note="GraphSAGE forward: plus-first SpMM aggregation, no "
             "fixpoint loop"),

    # ---- MXU (gather-free Benes) backend ------------------------------
    "mxu:pagerank": _c(
        "mxu:pagerank", "mxu", ["pagerank"],
        note="expand -> Benes route -> MXU reduce/extract; x0 stays "
             "un-donated: callers retain warm-start vectors (DeltaPlan "
             "incremental reuse)"),
    "mxu:katz": _c(
        "mxu:katz", "mxu", ["katz"],
        note="same machinery, katz epilogue, zeros start"),

    # ---- compiled Cypher read lane (r20, mglane) ----------------------
    # single-shot (non-iterating) programs; the contract here is the
    # implicit one — zero collectives, zero f64, zero host callbacks —
    # plus the structural note: predicate masks are FUSED into every
    # reduction (where(mask, v, identity)), never a gather-then-filter
    # materialization of the selected rows
    "segment:lane_agg": _c(
        "segment:lane_agg", "segment", ["lane_agg"], iterates=False,
        note="scan/expand aggregate tail: stacked int32 columns -> "
             "fused predicate masks -> count/sum/min/max epilogues "
             "with int32 accumulation + f32 mass witnesses"),
    "segment:lane_hops:h1": _c(
        "segment:lane_hops:h1", "segment", ["lane_hops"],
        iterates=False,
        note="one-hop masked frontier count: plus_first spmv over the "
             "semiring core, target mask folded into the epilogue"),
    "segment:lane_hops:h2": _c(
        "segment:lane_hops:h2", "segment", ["lane_hops"],
        iterates=False,
        note="two-hop path count: chained masked plus_first spmv with "
             "the self-loop edge-uniqueness correction and the "
             "distinct-target (reachability popcount) epilogue"),
    "segment:lane_topk": _c(
        "segment:lane_topk", "segment", ["lane_topk"], iterates=False,
        note="ORDER BY <int key> LIMIT k: fused predicate mask + "
             "stable argsort; nulls ranked per openCypher, excluded "
             "rows sorted past every included row"),

    # ---- out-of-core streamed tier (r21, mgtier) ----------------------
    # Per-BLOCK step kernels: the HOST drives the block loop (that is
    # the point — only one compressed edge block is device-resident at
    # a time), so none of these iterate and none may hide a host
    # callback inside: a single infeed in the sweep would serialize the
    # double-buffered H2D schedule. The iterate/accumulator carries are
    # donated — the device-resident vector budget is VECTOR_SLOTS
    # (ops/tier.py), not 2x per fold.
    "tier:wsum": _c(
        "tier:wsum", "tier", ["pagerank"], min_donated=1,
        iterates=False,
        note="streamed out-weight accumulation: wire decode (uint16 "
             "offsets + shard base, per-row dst runs) then segment_sum "
             "into the donated f32 accumulator"),
    "tier:pagerank_sweep": _c(
        "tier:pagerank_sweep", "tier", ["pagerank"], min_donated=1,
        iterates=False,
        note="one edge-block fold of the streamed PageRank sweep: "
             "decode, x[src]*(w*inv_wsum[src]), sorted segment_sum "
             "into the donated accumulator; f32 accumulation"),
    "tier:pagerank_sweep_int8": _c(
        "tier:pagerank_sweep_int8", "tier", ["pagerank"],
        min_donated=1, iterates=False,
        note="int8 wire variant: symmetric per-block dequantize "
             "(w * scale) inside the kernel, f32 accumulate — only "
             "compressed bytes cross the host->device boundary"),
    "tier:pagerank_epilogue": _c(
        "tier:pagerank_epilogue", "tier", ["pagerank"], min_donated=1,
        iterates=False,
        note="end-of-sweep rank update: dangling mass, damping, L1 "
             "err; x aliases into the new rank vector (acc is also "
             "donated but the scalar err output cannot consume it)"),
    "tier:katz_sweep": _c(
        "tier:katz_sweep", "tier", ["katz"], min_donated=1,
        iterates=False,
        note="streamed Katz fold: decode + x[src]*w, sorted "
             "segment_sum into the donated accumulator"),
    "tier:katz_epilogue": _c(
        "tier:katz_epilogue", "tier", ["katz"], min_donated=1,
        iterates=False,
        note="alpha*acc + beta on valid rows, Linf err; x aliases into "
             "the new vector"),
    "tier:wcc_sweep": _c(
        "tier:wcc_sweep", "tier", ["components"], min_donated=1,
        iterates=False,
        note="streamed min-label fold, both directions; padding edges "
             "masked via the block's real-edge count (rc) so the sink "
             "row never merges unrelated components"),
    "tier:wcc_epilogue": _c(
        "tier:wcc_epilogue", "tier", ["components"], min_donated=1,
        iterates=False,
        note="min-merge + pointer jump + changed flag; comp aliases into "
             "the new labels"),

    # ---- PPR serving-plane lane buckets -------------------------------
    **_ppr_bucket_contracts(),
}


def manifest_registry_keys() -> set:
    out: set = set()
    for c in MANIFEST.values():
        out.update(c.registry)
    return out


def load_baseline(path: str | None = None) -> dict[str, str]:
    """Justification-required baseline, shared format with mglint."""
    from tools.mglint.core import load_baseline as _load
    return _load(path or DEFAULT_BASELINE)
