"""mgxla: device-plane static analysis for the compiled kernel surface.

Two halves:

  * :mod:`tools.mgxla.checker` — the compiled-artifact contract checker.
    Every kernel in :data:`tools.mgxla.manifest.MANIFEST` is abstractly
    lowered (``jax.jit(...).lower(...)`` on ``ShapeDtypeStruct``s over a
    forced multi-device mesh — nothing executes) and the compiled HLO is
    verified against machine-checkable contracts: the EXACT collective
    multiset per iteration body, zero f64 ops, zero host callbacks /
    infeed / outfeed, input-output aliasing (donation) of fixpoint
    carries, and a bounded compile count across the PPR lane buckets.
  * three mglint AST rules (MG008 recompile-hazard, MG009
    host-sync-in-hot-path, MG010 missing-donation) that live in
    ``tools/mglint/rules/`` and ride the ordinary mglint gate.

``python -m tools.mgxla check`` runs the full manifest; deliberate
exceptions carry justifications in ``tools/mgxla/baseline.json`` (same
contract as mglint's baseline: unexplained or unused entries fail).
"""
