"""Compiled-HLO text analysis: the machine-checkable side of mgxla.

Everything here works on ``lowered.compile().as_text()`` output — plain
post-optimization HLO text — so the checks stay independent of jax
internals: a contract violation is always demonstrable as a line of HLO
the developer can read.

The only structural assumption is the HLO text format itself:
computations print as ``%name (params...) -> type {`` blocks (the entry
computation prefixed with ``ENTRY``), ops reference other computations
via ``body=%name`` / ``condition=%name`` / ``calls=%name`` /
``to_apply=%name``, and the module header carries
``input_output_alias={ {out}: (param, {...}) ... }`` when inputs are
donated.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

#: cross-device collective ops (the complete set XLA can emit for the
#: SPMD programs this tree builds; extend deliberately, never loosely —
#: a new name appearing in a kernel should FAIL until it is understood)
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "collective-permute", "all-to-all", "collective-broadcast")

# matches the op NAME position of a def line ("= <type> all-reduce(...)",
# tuple types included); operand references ("%all-reduce.2") never have
# "(" directly after the name, so they cannot match
_COLLECTIVE_RE = re.compile(
    r"=\s.*?[\s)](" + "|".join(COLLECTIVE_OPS) + r")(?:-start)?\(")

#: host round-trip markers: python callbacks lower to custom-calls with
#: these targets; infeed/outfeed are the streaming variants
_CALLBACK_RE = re.compile(
    r"custom_call_target=\"[^\"]*(callback|host)[^\"]*\"|"
    r"=\s+\S+\s+(infeed|outfeed)\(")

_F64_RE = re.compile(r"\b(f64|c128)\[")

_COMPUTATION_HDR_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)"
                                 r"\s+->\s+.*\{\s*$")

_REF_RE = re.compile(r"(?:body|condition|calls|to_apply|branch_computations"
                     r"|called_computations)=\{?%?([\w.\-]+(?:,\s*%?"
                     r"[\w.\-]+)*)\}?")

_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+)")


def split_computations(text: str) -> dict[str, list[str]]:
    """HLO computation name -> its body lines (header excluded)."""
    out: dict[str, list[str]] = {}
    cur: str | None = None
    for line in text.splitlines():
        m = _COMPUTATION_HDR_RE.match(line)
        if m:
            cur = m.group(1)
            out[cur] = []
            continue
        if cur is not None:
            if line.strip() == "}":
                cur = None
                continue
            out[cur].append(line)
    return out


def _refs(lines: list[str]) -> set[str]:
    got: set[str] = set()
    for line in lines:
        for m in _REF_RE.finditer(line):
            for name in m.group(1).split(","):
                got.add(name.strip().lstrip("%"))
    return got


def collectives(text: str) -> list[str]:
    """Sorted multiset of cross-device collective ops in the program."""
    return sorted(_COLLECTIVE_RE.findall(text))


def while_body_collectives(text: str) -> list[str]:
    """Sorted multiset of collectives reachable from any while body
    (transitively through called computations) — the per-iteration cost."""
    comps = split_computations(text)
    bodies: set[str] = set()
    for lines in comps.values():
        for line in lines:
            if re.search(r"[\s)]while\(", line):
                m = re.search(r"body=%?([\w.\-]+)", line)
                if m:
                    bodies.add(m.group(1))
    seen: set[str] = set()
    work = list(bodies)
    while work:
        name = work.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        work.extend(_refs(comps[name]))
    got: list[str] = []
    for name in seen:
        for line in comps[name]:
            got.extend(_COLLECTIVE_RE.findall(line))
    return sorted(got)


def f64_lines(text: str) -> list[str]:
    """Lines carrying double-precision types (silent upcasts)."""
    return [ln.strip() for ln in text.splitlines() if _F64_RE.search(ln)]


def callback_lines(text: str) -> list[str]:
    """Lines carrying host callbacks / infeed / outfeed."""
    return [ln.strip() for ln in text.splitlines()
            if _CALLBACK_RE.search(ln)]


def donated_params(text: str) -> set[int]:
    """Parameter indices aliased to outputs (``donate_argnums`` made it
    through to the executable) from the module header."""
    for line in text.splitlines():
        if "input_output_alias=" in line:
            seg = line.split("input_output_alias=", 1)[1]
            seg = seg.split("entry_computation_layout")[0]
            return {int(m.group(1))
                    for m in _ALIAS_ENTRY_RE.finditer(seg)}
    return set()


def snippet_around(text: str, pattern: str, context: int = 2) -> str:
    """First match of `pattern` with `context` lines around it — the
    offending-HLO excerpt a violation report carries."""
    lines = text.splitlines()
    rx = re.compile(pattern)
    for i, ln in enumerate(lines):
        if rx.search(ln):
            lo, hi = max(0, i - context), min(len(lines), i + context + 1)
            return "\n".join(lines[lo:hi])
    return ""


@dataclass
class HloFacts:
    """Everything the contract checks need, extracted in one pass."""
    collectives: list[str] = field(default_factory=list)
    while_collectives: list[str] = field(default_factory=list)
    f64: list[str] = field(default_factory=list)
    callbacks: list[str] = field(default_factory=list)
    donated: set[int] = field(default_factory=set)


def analyze(text: str) -> HloFacts:
    return HloFacts(collectives=collectives(text),
                    while_collectives=while_body_collectives(text),
                    f64=f64_lines(text),
                    callbacks=callback_lines(text),
                    donated=donated_params(text))
