"""mgxla command line: ``python -m tools.mgxla check [--only K ...]``.

Exit codes: 0 clean (or everything baselined), 1 contract violations /
unused baseline entries, 2 bad invocation, broken baseline, or an
environment that cannot host the forced mesh.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.mgxla",
        description="device-plane static analysis: compiled-artifact "
                    "contract checker")
    sub = p.add_subparsers(dest="cmd")
    chk = sub.add_parser("check", help="lower + verify the manifest")
    chk.add_argument("--only", action="append", default=None,
                     metavar="KERNEL",
                     help="check only this manifest kernel (repeatable)")
    chk.add_argument("--json", action="store_true",
                     help="machine-readable JSON output")
    chk.add_argument("--baseline", default=None,
                     help="baseline file (default: tools/mgxla/"
                          "baseline.json)")
    chk.add_argument("--no-baseline", action="store_true",
                     help="ignore the baseline: show every violation")
    lst = sub.add_parser("list", help="list manifest kernels and exit")
    lst.add_argument("--json", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd is None:
        build_parser().print_help()
        return 2

    from .manifest import MANIFEST, load_baseline

    if args.cmd == "list":
        if args.json:
            print(json.dumps({k: c.as_dict()
                              for k, c in sorted(MANIFEST.items())},
                             indent=2))
        else:
            for k, c in sorted(MANIFEST.items()):
                cols = ",".join(c.collectives) or "-"
                print(f"{k:32s} {c.backend:8s} collectives={cols} "
                      f"donated>={c.min_donated}")
        return 0

    try:
        baseline = {} if args.no_baseline else load_baseline(args.baseline)
    except (ValueError, OSError) as e:
        print(f"mgxla: broken baseline: {e}", file=sys.stderr)
        return 2

    from .checker import CheckerEnvironmentError, run_check
    only = set(args.only) if args.only else None
    if only:
        unknown = only - set(MANIFEST)
        if unknown:
            print(f"mgxla: unknown kernels {sorted(unknown)}; "
                  "see `python -m tools.mgxla list`", file=sys.stderr)
            return 2
    try:
        report = run_check(only=only, baseline=baseline,
                           structural=only is None)
    except CheckerEnvironmentError as e:
        print(f"mgxla: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "kernels_checked": report.kernels_checked,
            "violations": [{"kernel": v.kernel, "check": v.check,
                            "detail": v.detail, "key": v.key,
                            "snippet": v.snippet}
                           for v in report.violations],
            "baselined": [v.key for v in report.baselined],
            "unused_baseline": report.unused_baseline,
            "ok": report.ok}, indent=2))
    else:
        print(report.render())
    return 0 if report.ok else 1
