"""The mgxla compiled-artifact contract checker.

For every kernel in :data:`tools.mgxla.manifest.MANIFEST` a registered
``@builder`` abstractly lowers the REAL product builder —
``jax.jit(...).lower(...)`` on ``ShapeDtypeStruct``s over a forced
8-device mesh; nothing executes — and the post-optimization HLO is
verified against the kernel's contract:

  * exact collective multiset, and (for iterating kernels) every
    collective located inside the while body — the generalization of
    the regex assertions tests/test_sharded_analytics.py carried
    before r17 (those tests now call this module as a library);
  * zero f64/c128 ops (nothing silently upcasts out of the
    mixed-precision streaming envelope);
  * zero host callbacks / infeed / outfeed (no host round trip hides
    inside a compiled hot path);
  * input-output aliasing of fixpoint carries (``min_donated``);
  * the PPR lane-bucket compile budget: batch widths 1..128 must fold
    onto exactly the declared bucket set (same bucket ⇒ cache hit — a
    silent recompile per width would melt the serving plane's latency).

Violations carry the offending HLO snippet. Deliberate exceptions go in
``tools/mgxla/baseline.json`` with a justification (mglint's format);
unused or unexplained entries fail, so the baseline only shrinks
honestly. The static budget's runtime witness is the
``jit.compile_total`` counter (utils/jax_cache.py) exported in
``GET /stats``.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from . import hlo
from .manifest import (BACKENDS, MANIFEST, PPR_LANE_BUCKETS,
                       KernelContract, load_baseline,
                       manifest_registry_keys)

#: the forced virtual mesh width every mesh contract lowers against
N_SHARDS = 8
#: abstract graph shapes (values never matter — nothing executes).
#: These are module globals ON PURPOSE: builders read them at call
#: time, so :func:`build_compiled` can rebind them per shape point and
#: the same builder registry serves both the contract checker (one
#: canonical point) and tools/mgmem's footprint-model fitter (several).
N_PAD = 64
N_EDGES = 256
BLOCK = N_PAD // N_SHARDS
PER = 32            # edges per shard in the partition-centric layout


@dataclass(frozen=True)
class Dims:
    """One abstract lowering shape point. ``n_pad`` must be a multiple
    of the forced mesh width (block = n_pad // N_SHARDS); ``per`` is
    the per-shard edge capacity (defaults to n_edges / N_SHARDS)."""

    n_pad: int = 64
    n_edges: int = 256
    per: int = 0

    def __post_init__(self):
        if self.n_pad % N_SHARDS:
            raise ValueError(f"n_pad={self.n_pad} must be a multiple "
                             f"of the {N_SHARDS}-wide mesh")
        if not self.per:
            object.__setattr__(self, "per",
                               max(8, self.n_edges // N_SHARDS))


DEFAULT_DIMS = Dims()

_dims_lock = threading.Lock()


@contextmanager
def _shape_dims(dims: Dims):
    """Rebind the module shape globals for one builder call."""
    global N_PAD, N_EDGES, BLOCK, PER
    old = (N_PAD, N_EDGES, BLOCK, PER)
    N_PAD, N_EDGES, PER = dims.n_pad, dims.n_edges, dims.per
    BLOCK = N_PAD // N_SHARDS
    try:
        yield
    finally:
        N_PAD, N_EDGES, BLOCK, PER = old


class CheckerEnvironmentError(RuntimeError):
    """The process cannot host the forced multi-device mesh."""


@dataclass(frozen=True)
class Violation:
    kernel: str
    check: str          # collectives|while-collectives|f64|host-callback|
    #                     donation|coverage|lane-buckets|build
    detail: str
    snippet: str = ""

    @property
    def key(self) -> str:
        return f"{self.kernel}:{self.check}:{self.detail}"

    def render(self) -> str:
        out = f"{self.kernel}: {self.check}: {self.detail}"
        if self.snippet:
            out += "\n    | " + self.snippet.replace("\n", "\n    | ")
        return out


@dataclass
class CheckReport:
    violations: list = field(default_factory=list)    # unbaselined
    baselined: list = field(default_factory=list)
    unused_baseline: list = field(default_factory=list)
    kernels_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations and not self.unused_baseline

    def render(self) -> str:
        lines = [f"mgxla: {self.kernels_checked} kernels checked"]
        for v in self.violations:
            lines.append("VIOLATION " + v.render())
        for v in self.baselined:
            lines.append("baselined " + v.render().splitlines()[0])
        for key in self.unused_baseline:
            lines.append(f"UNUSED baseline entry (fixed or drifted): "
                         f"{key}")
        lines.append("mgxla: " + ("OK" if self.ok else "FAILED"))
        return "\n".join(lines)


# --------------------------------------------------------------------------
# builders: kernel id -> compiled executable (abstract lowering only)
# --------------------------------------------------------------------------

BUILDERS: dict = {}


def builder(*kernels):
    def deco(fn):
        for k in kernels:
            BUILDERS[k] = fn
        return fn
    return deco


def _jax():
    import jax
    return jax


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _ctx():
    import jax
    if len(jax.devices()) < N_SHARDS:
        raise CheckerEnvironmentError(
            f"mgxla needs {N_SHARDS} devices for the forced mesh; "
            f"this process has {len(jax.devices())}. Run via "
            "`python -m tools.mgxla` (it sets "
            "--xla_force_host_platform_device_count before jax loads) "
            "or export XLA_FLAGS yourself.")
    from memgraph_tpu.parallel.mesh import get_mesh_context
    return get_mesh_context(N_SHARDS)


def _compiled(lowered):
    """Compile an abstract lowering. Returns the COMPILED executable —
    ``as_text()`` feeds the contract checks, ``memory_analysis()``
    feeds tools/mgmem's footprint model; both read the same artifact."""
    return lowered.compile()


def build_compiled(kernel: str, dims: Dims | None = None):
    """Compiled executable for one manifest kernel at abstract `dims`.

    ``dims=None`` lowers at the canonical contract-checker shapes.
    mxu:* kernels carry a fixed internal plan and ignore `dims`.
    Raises KeyError for kernels without a registered builder."""
    build = BUILDERS[kernel]
    if dims is None or dims == DEFAULT_DIMS:
        return build(kernel)
    with _dims_lock, _shape_dims(dims):
        return build(kernel)


# ---- partition-centric mesh kernels ---------------------------------------


def _mesh_pagerank(precision: str):
    from memgraph_tpu.parallel.distributed import _pc_pagerank_build
    fn = _pc_pagerank_build(_ctx(), BLOCK, N_SHARDS, precision)
    ep, vp = (N_SHARDS, PER), (N_SHARDS * BLOCK,)
    return _compiled(fn.lower(
        _sds(ep, "int32"), _sds(ep, "int32"), _sds(ep, "float32"),
        _sds((), "int32"), _sds((), "float32"), _sds((), "float32"),
        _sds(vp, "float32"), _sds((N_SHARDS,), "float32"),
        _sds((), "float32"), _sds((), "int32"), _sds((), "int32")))


@builder("mesh:pagerank")
def _b_mesh_pagerank(kernel):
    return _mesh_pagerank("f32")


@builder("mesh:pagerank_bf16")
def _b_mesh_pagerank_bf16(kernel):
    return _mesh_pagerank("bf16")


@builder("mesh:katz")
def _b_mesh_katz(kernel):
    from memgraph_tpu.parallel.distributed import _pc_katz_build
    fn = _pc_katz_build(_ctx(), BLOCK, N_SHARDS)
    ep = (N_SHARDS, PER)
    return _compiled(fn.lower(
        _sds(ep, "int32"), _sds(ep, "int32"), _sds(ep, "float32"),
        _sds((), "int32"), _sds((), "float32"), _sds((), "float32"),
        _sds((), "float32"), _sds((N_SHARDS * BLOCK,), "float32"),
        _sds((), "float32"), _sds((), "int32"), _sds((), "int32")))


@builder("mesh:labelprop")
def _b_mesh_labelprop(kernel):
    from memgraph_tpu.parallel.distributed import _pc_labelprop_build
    fn = _pc_labelprop_build(_ctx(), BLOCK, N_SHARDS, PER)
    ep = (N_SHARDS, PER)
    return _compiled(fn.lower(
        _sds(ep, "int32"), _sds(ep, "int32"), _sds(ep, "float32"),
        _sds((), "float32"), _sds((N_SHARDS * BLOCK,), "int32"),
        _sds((), "bool_"), _sds((), "int32"), _sds((), "int32")))


@builder("mesh:wcc")
def _b_mesh_wcc(kernel):
    from memgraph_tpu.parallel.distributed import _pc_wcc_build
    fn = _pc_wcc_build(_ctx(), BLOCK, N_SHARDS)
    ep = (N_SHARDS, PER)
    return _compiled(fn.lower(
        _sds(ep, "int32"), _sds(ep, "int32"),
        _sds((N_SHARDS * BLOCK,), "int32"), _sds((), "bool_"),
        _sds((), "int32"), _sds((), "int32")))


@builder("mesh:semiring_min_plus")
def _b_mesh_semiring(kernel):
    from memgraph_tpu.parallel.distributed import (
        _minplus_relax_epilogue, _pc_semiring_build)
    fn = _pc_semiring_build(_ctx(), BLOCK, N_SHARDS, "min_plus",
                            _minplus_relax_epilogue, "changed", "f32")
    ep = (N_SHARDS, PER)
    return _compiled(fn.lower(
        _sds(ep, "int32"), _sds(ep, "int32"), _sds(ep, "float32"),
        {}, _sds((N_SHARDS * BLOCK,), "float32"), _sds((), "bool_"),
        _sds((), "int32"), _sds((), "int32")))


# ---- out-of-core streamed tier (r21, mgtier) -------------------------------


def _tier_block(precision: str = "f32"):
    """Abstract wire block: the u16-compressed payload pack_block ships
    (ops/tier.py). P = N_SHARDS blocks of BLOCK rows, PER edges each."""
    wdt = {"f32": "float32", "bf16": "bfloat16", "int8": "int8"}
    out = {"rc": _sds((), "int32"),
           "src_off": _sds((PER,), "uint16"),
           "dst_off": _sds((PER,), "uint16"),
           "bounds": _sds((N_SHARDS + 1,), "int32"),
           "base": _sds((), "int32"),
           "w": _sds((PER,), wdt[precision])}
    if precision == "int8":
        out["scale"] = _sds((), "float32")
    return out


def _tier_v(dtype: str = "float32"):
    return _sds((N_PAD,), dtype)


@builder("tier:wsum")
def _b_tier_wsum(kernel):
    from memgraph_tpu.parallel.distributed import _tier_wsum_build
    fn = _tier_wsum_build(BLOCK, PER, N_PAD, "f32", True)
    return _compiled(fn.lower(_tier_v(), _tier_block()))


def _tier_pr_sweep(precision: str):
    from memgraph_tpu.parallel.distributed import (
        _tier_pagerank_sweep_build)
    fn = _tier_pagerank_sweep_build(BLOCK, PER, N_PAD, precision, True)
    return _compiled(fn.lower(
        _tier_v(), _tier_v(), _tier_v(), _tier_block(precision)))


@builder("tier:pagerank_sweep")
def _b_tier_pr_sweep(kernel):
    return _tier_pr_sweep("f32")


@builder("tier:pagerank_sweep_int8")
def _b_tier_pr_sweep_int8(kernel):
    return _tier_pr_sweep("int8")


@builder("tier:pagerank_epilogue")
def _b_tier_pr_epi(kernel):
    from memgraph_tpu.parallel.distributed import (
        _tier_pagerank_epilogue_build)
    fn = _tier_pagerank_epilogue_build(N_PAD)
    return _compiled(fn.lower(
        _tier_v(), _tier_v(), _tier_v(), _tier_v(),
        _sds((), "float32"), _sds((), "float32")))


@builder("tier:katz_sweep")
def _b_tier_katz_sweep(kernel):
    from memgraph_tpu.parallel.distributed import _tier_katz_sweep_build
    fn = _tier_katz_sweep_build(BLOCK, PER, N_PAD, "f32", True)
    return _compiled(fn.lower(_tier_v(), _tier_v(), _tier_block()))


@builder("tier:katz_epilogue")
def _b_tier_katz_epi(kernel):
    from memgraph_tpu.parallel.distributed import (
        _tier_katz_epilogue_build)
    fn = _tier_katz_epilogue_build(N_PAD)
    return _compiled(fn.lower(
        _tier_v(), _tier_v(), _tier_v(),
        _sds((), "float32"), _sds((), "float32")))


@builder("tier:wcc_sweep")
def _b_tier_wcc_sweep(kernel):
    from memgraph_tpu.parallel.distributed import _tier_wcc_sweep_build
    fn = _tier_wcc_sweep_build(BLOCK, PER, N_PAD, True)
    return _compiled(fn.lower(
        _tier_v("int32"), _tier_v("int32"), _tier_block()))


@builder("tier:wcc_epilogue")
def _b_tier_wcc_epi(kernel):
    from memgraph_tpu.parallel.distributed import (
        _tier_wcc_epilogue_build)
    fn = _tier_wcc_epilogue_build(N_PAD)
    return _compiled(fn.lower(_tier_v("int32"), _tier_v("int32")))


# ---- segment backend -------------------------------------------------------


def _segment_fixpoint(sr, *, arrays, params, x0, epilogue, setup=None,
                      step=None, metric="err", sorted=False,
                      sorted_backward=False, direction="fwd"):
    from memgraph_tpu.ops import semiring as S
    fn = S._build_fixpoint(
        S.resolve_semiring(sr), epilogue=epilogue, setup=setup, step=step,
        n_out=N_PAD, max_iterations=8, metric=metric, precision="f32",
        sorted=sorted, sorted_backward=sorted_backward,
        direction=direction)
    return _compiled(fn.lower(arrays, params, x0))


def _edge_arrays(w: bool = True, csr: bool = False):
    out = {"src": _sds((N_EDGES,), "int32"),
           "dst": _sds((N_EDGES,), "int32")}
    if w:
        out["w"] = _sds((N_EDGES,), "float32")
    if csr:
        out["csr_src"] = _sds((N_EDGES,), "int32")
        out["csr_w"] = _sds((N_EDGES,), "float32")
    return out


@builder("segment:pagerank")
def _b_seg_pagerank(kernel):
    from memgraph_tpu.ops.pagerank import (_pagerank_epilogue,
                                           _pagerank_setup)
    return _segment_fixpoint(
        "plus_times", arrays=_edge_arrays(csr=True),
        params={"n_nodes": _sds((), "int32"),
                "damping": _sds((), "float32"),
                "tol": _sds((), "float32")},
        x0=None, setup=_pagerank_setup, epilogue=_pagerank_epilogue,
        sorted=True)


@builder("segment:pagerank_warm")
def _b_seg_pagerank_warm(kernel):
    # r19 mgdelta: the commit-then-CALL warm start — identical program
    # modulo the donated x0 seed argument
    from memgraph_tpu.ops.pagerank import (_pagerank_epilogue,
                                           _pagerank_setup)
    return _segment_fixpoint(
        "plus_times", arrays=_edge_arrays(csr=True),
        params={"n_nodes": _sds((), "int32"),
                "damping": _sds((), "float32"),
                "tol": _sds((), "float32")},
        x0=_sds((N_PAD,), "float32"), setup=_pagerank_setup,
        epilogue=_pagerank_epilogue, sorted=True)


@builder("segment:katz_warm")
def _b_seg_katz_warm(kernel):
    from memgraph_tpu.ops.katz import _katz_epilogue, _katz_setup
    return _segment_fixpoint(
        "plus_times", arrays=_edge_arrays(),
        params={"n_nodes": _sds((), "int32"),
                "alpha": _sds((), "float32"),
                "beta": _sds((), "float32"),
                "tol": _sds((), "float32")},
        x0=_sds((N_PAD,), "float32"), setup=_katz_setup,
        epilogue=_katz_epilogue, sorted=True)


@builder("segment:ppr")
def _b_seg_ppr(kernel):
    from memgraph_tpu.ops.pagerank import _ppr_epilogue, _ppr_setup
    arrays = _edge_arrays(csr=True)
    arrays["personalization"] = _sds((N_PAD,), "float32")
    return _segment_fixpoint(
        "plus_times", arrays=arrays,
        params={"n_nodes": _sds((), "int32"),
                "damping": _sds((), "float32"),
                "tol": _sds((), "float32")},
        x0=None, setup=_ppr_setup, epilogue=_ppr_epilogue, sorted=True)


@builder("segment:katz")
def _b_seg_katz(kernel):
    from memgraph_tpu.ops.katz import _katz_epilogue, _katz_setup
    return _segment_fixpoint(
        "plus_times", arrays=_edge_arrays(),
        params={"n_nodes": _sds((), "int32"),
                "alpha": _sds((), "float32"),
                "beta": _sds((), "float32"),
                "tol": _sds((), "float32")},
        x0=None, setup=_katz_setup, epilogue=_katz_epilogue, sorted=True)


@builder("segment:hits")
def _b_seg_hits(kernel):
    from memgraph_tpu.ops.katz import (_hits_epilogue, _hits_setup,
                                       _hits_step)
    arrays = _edge_arrays()
    arrays.update(csrc=_sds((N_EDGES,), "int32"),
                  cdst=_sds((N_EDGES,), "int32"),
                  cw=_sds((N_EDGES,), "float32"))
    return _segment_fixpoint(
        "plus_times", arrays=arrays,
        params={"n_nodes": _sds((), "int32"),
                "tol": _sds((), "float32")},
        x0=None, setup=_hits_setup, step=_hits_step,
        epilogue=_hits_epilogue)


@builder("segment:labelprop")
def _b_seg_labelprop(kernel):
    from memgraph_tpu.ops.labelprop import (_labelprop_epilogue,
                                            _labelprop_step)
    return _segment_fixpoint(
        "max_min", arrays=_edge_arrays(),
        params={"self_weight": _sds((), "float32")},
        x0=_sds((N_PAD,), "int32"), step=_labelprop_step,
        epilogue=_labelprop_epilogue, metric="changed")


@builder("segment:wcc")
def _b_seg_wcc(kernel):
    from memgraph_tpu.ops.components import _wcc_epilogue
    return _segment_fixpoint(
        "min_first", arrays=_edge_arrays(w=False), params={},
        x0=_sds((N_PAD,), "int32"), epilogue=_wcc_epilogue,
        metric="changed", direction="both")


@builder("segment:sssp")
def _b_seg_sssp(kernel):
    from memgraph_tpu.ops.traversal import (_sssp_epilogue,
                                            _sssp_step_directed)
    return _segment_fixpoint(
        "min_plus", arrays=_edge_arrays(),
        params={}, x0=_sds((N_PAD,), "float32"),
        step=_sssp_step_directed, epilogue=_sssp_epilogue,
        metric="changed")


@builder("segment:bfs")
def _b_seg_bfs(kernel):
    from memgraph_tpu.ops.traversal import _bfs_epilogue, _bfs_step
    arrays = _edge_arrays()
    arrays["deg"] = _sds((N_PAD,), "float32")
    return _segment_fixpoint(
        "min_plus", arrays=arrays,
        params={"n_edges": _sds((), "float32")},
        x0=(_sds((N_PAD,), "float32"), _sds((N_PAD,), "bool_")),
        step=_bfs_step, epilogue=_bfs_epilogue, metric="changed")


@builder("segment:scc")
def _b_seg_scc(kernel):
    from memgraph_tpu.ops.components import _scc_round
    return _compiled(_scc_round.lower(
        _sds((N_EDGES,), "int32"), _sds((N_EDGES,), "int32"),
        _sds((N_PAD,), "int32"), n_pad=N_PAD, max_iterations=8))


@builder("segment:betweenness")
def _b_seg_betweenness(kernel):
    from memgraph_tpu.ops.betweenness import _brandes_chunk
    return _compiled(_brandes_chunk.lower(
        _sds((N_EDGES,), "int32"), _sds((N_EDGES,), "int32"),
        _sds((N_EDGES,), "bool_"), _sds((4,), "int32"),
        _sds((4,), "float32"), n_pad=N_PAD, max_levels=8))


@builder("segment:gnn")
def _b_seg_gnn(kernel):
    import jax
    from memgraph_tpu.ops.gnn import init_sage_params, sage_forward
    params = init_sage_params(jax.random.PRNGKey(0), 8, 16, 8)
    psds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    fn = jax.jit(sage_forward, static_argnames=("n_pad",))
    return _compiled(fn.lower(
        psds, _sds((N_PAD, 8), "float32"), _sds((N_EDGES,), "int32"),
        _sds((N_EDGES,), "int32"), n_pad=N_PAD))


# ---- MXU backend -----------------------------------------------------------


def _mxu_plan():
    import numpy as np
    from memgraph_tpu.ops import spmv_mxu
    rng = np.random.default_rng(7)
    n, e = 48, 160
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    w = rng.random(e).astype(np.float32)
    return spmv_mxu.build_plan(src, dst, w, n)


def _mxu_lower(run, params_sds):
    # make_semiring_kernel attaches the inner jitted program + the
    # device blob exactly so this checker can lower without executing
    jd, blob = run.jitted_default, run.blob
    return _compiled(jd.lower(
        _sds(tuple(blob.shape), str(blob.dtype)), params_sds, 8,
        _sds((), "float32")))


@builder("mxu:pagerank")
def _b_mxu_pagerank(kernel):
    from memgraph_tpu.ops import spmv_mxu
    run = spmv_mxu.make_semiring_kernel(
        _mxu_plan(), epilogue=spmv_mxu.pagerank_mxu_epilogue,
        x0_default="uniform")
    return _mxu_lower(run, {"damping": _sds((), "float32")})


@builder("mxu:katz")
def _b_mxu_katz(kernel):
    from memgraph_tpu.ops import spmv_mxu
    from memgraph_tpu.ops.katz import _katz_mxu_epilogue
    run = spmv_mxu.make_semiring_kernel(
        _mxu_plan(), epilogue=_katz_mxu_epilogue, x0_default="zeros")
    return _mxu_lower(run, {"alpha": _sds((), "float32"),
                            "beta": _sds((), "float32")})


# ---- PPR serving-plane lane buckets ---------------------------------------


def _ppr_batch_text(bucket: int, warm: bool):
    from memgraph_tpu.ops.pagerank import _build_ppr_batch
    fn = _build_ppr_batch(N_PAD, 8, "f32", warm)
    arrays = _edge_arrays(csr=True)
    arrays["personalization"] = _sds((N_PAD, bucket), "float32")
    x0 = _sds((N_PAD, bucket), "float32") if warm else None
    return _compiled(fn.lower(
        arrays, {"n_nodes": _sds((), "int32"),
                 "damping": _sds((), "float32"),
                 "tol": _sds((), "float32")}, x0))


def _make_bucket_builder(bucket: int):
    @builder(f"segment:ppr_batch:b{bucket}")
    def _b(kernel, _bucket=bucket):
        return _ppr_batch_text(_bucket, warm=False)
    return _b


for _bucket in PPR_LANE_BUCKETS:
    _make_bucket_builder(_bucket)


@builder("segment:ppr_batch:warm8")
def _b_ppr_warm(kernel):
    return _ppr_batch_text(8, warm=True)


# ---- compiled Cypher read lane (r20, mglane) ------------------------------


@builder("segment:lane_agg")
def _b_lane_agg(kernel):
    from memgraph_tpu.ops.pipeline import _build_agg_program
    fn = _build_agg_program(
        preds=((0, ">"), (1, "=")),
        aggs=(("count", None), ("sum", 0), ("min", 0), ("max", 1)))
    return _compiled(fn.lower(
        _sds((2, N_PAD), "int32"), _sds((2, N_PAD), "bool_"),
        _sds((N_PAD,), "bool_"), _sds((2,), "int32")))


def _lane_hops_text(hops: int):
    from memgraph_tpu.ops.pipeline import _build_hops_program
    fn = _build_hops_program(hops, False, True, True, hops == 2, N_PAD)
    return _compiled(fn.lower(
        _sds((N_EDGES,), "int32"), _sds((N_EDGES,), "int32"),
        _sds((N_EDGES,), "bool_"), _sds((N_PAD,), "bool_"),
        _sds((N_PAD,), "float32"), _sds((N_PAD,), "float32")))


@builder("segment:lane_hops:h1")
def _b_lane_hops1(kernel):
    return _lane_hops_text(1)


@builder("segment:lane_hops:h2")
def _b_lane_hops2(kernel):
    return _lane_hops_text(2)


@builder("segment:lane_topk")
def _b_lane_topk(kernel):
    from memgraph_tpu.ops.pipeline import _build_topk_program
    fn = _build_topk_program(preds=((0, ">"),), ascending=False)
    return _compiled(fn.lower(
        _sds((1, N_PAD), "int32"), _sds((1, N_PAD), "bool_"),
        _sds((N_PAD,), "int32"), _sds((N_PAD,), "bool_"),
        _sds((1,), "int32")))


# --------------------------------------------------------------------------
# contract checks
# --------------------------------------------------------------------------


def check_text(contract: KernelContract, text: str) -> list[Violation]:
    """Verify one compiled artifact against its contract."""
    facts = hlo.analyze(text)
    out: list[Violation] = []
    got = tuple(facts.collectives)
    want = tuple(sorted(contract.collectives))
    if got != want:
        pat = "|".join(hlo.COLLECTIVE_OPS)
        out.append(Violation(
            contract.kernel, "collectives",
            f"got={','.join(got) or 'none'} want={','.join(want) or 'none'}",
            hlo.snippet_around(text, pat)))
    elif want and contract.iterates:
        in_body = tuple(facts.while_collectives)
        if in_body != want:
            out.append(Violation(
                contract.kernel, "while-collectives",
                f"in-body={','.join(in_body) or 'none'} "
                f"want={','.join(want)}",
                hlo.snippet_around(text, "|".join(hlo.COLLECTIVE_OPS))))
    if facts.f64:
        out.append(Violation(contract.kernel, "f64",
                             f"{len(facts.f64)} double-precision ops",
                             facts.f64[0]))
    if facts.callbacks:
        out.append(Violation(contract.kernel, "host-callback",
                             f"{len(facts.callbacks)} host round-trips",
                             facts.callbacks[0]))
    if len(facts.donated) < contract.min_donated:
        out.append(Violation(
            contract.kernel, "donation",
            f"donated={len(facts.donated)} < min={contract.min_donated}",
            hlo.snippet_around(text, r"^HloModule")))
    return out


def check_kernel_by_id(kernel: str) -> list[Violation]:
    """Build + check one manifest kernel (library entry for tests)."""
    contract = MANIFEST[kernel]
    if kernel not in BUILDERS:
        return [Violation(kernel, "build", "no registered builder")]
    try:
        text = build_compiled(kernel).as_text()
    except CheckerEnvironmentError:
        raise
    except Exception as e:  # noqa: BLE001 — reported as a typed violation
        return [Violation(kernel, "build",
                          f"{type(e).__name__}: {e}")]
    return check_text(contract, text)


def check_lane_buckets() -> list[Violation]:
    """The compile-count budget across PPR lane buckets, statically:
    widths 1..128 fold onto exactly the declared bucket set (same bucket
    ⇒ same compiled program), every bucket has a manifest row, and the
    manifest mirror equals the product's bucket table."""
    from memgraph_tpu.ops.pagerank import _PPR_LANE_BUCKETS, _bucket_lanes
    out: list[Violation] = []
    if tuple(_PPR_LANE_BUCKETS) != tuple(PPR_LANE_BUCKETS):
        out.append(Violation(
            "lane-buckets", "lane-buckets",
            f"manifest mirror {PPR_LANE_BUCKETS} != product table "
            f"{tuple(_PPR_LANE_BUCKETS)}"))
        return out
    mapped = {b: _bucket_lanes(b) for b in range(1, 129)}
    distinct = sorted(set(mapped.values()))
    if distinct != sorted(PPR_LANE_BUCKETS):
        out.append(Violation(
            "lane-buckets", "lane-buckets",
            f"widths 1..128 compile {len(distinct)} distinct programs "
            f"{distinct}; budget is {sorted(PPR_LANE_BUCKETS)}"))
    bad = [b for b, cap in mapped.items() if cap < b]
    if bad:
        out.append(Violation(
            "lane-buckets", "lane-buckets",
            f"bucket smaller than batch for widths {bad[:4]} — lanes "
            "would be dropped"))
    for b in PPR_LANE_BUCKETS:
        if f"segment:ppr_batch:b{b}" not in MANIFEST:
            out.append(Violation(
                "lane-buckets", "coverage",
                f"bucket {b} has no manifest kernel"))
    return out


def check_coverage() -> list[Violation]:
    """Registry/backend coverage: every SPMV_ALGORITHMS entry covered,
    every declared registry key real, all three backends present, every
    sharded target contract-checked on the mesh backend."""
    from memgraph_tpu.ops import SPMV_ALGORITHMS
    out: list[Violation] = []
    covered = manifest_registry_keys()
    for name in SPMV_ALGORITHMS:
        if name not in covered:
            out.append(Violation(
                "coverage", "coverage",
                f"registry entry {name!r} has no manifest kernel"))
    for name in sorted(covered - set(SPMV_ALGORITHMS)):
        out.append(Violation(
            "coverage", "coverage",
            f"manifest names unknown registry entry {name!r}"))
    have_backends = {c.backend for c in MANIFEST.values()}
    for b in BACKENDS:
        if b not in have_backends:
            out.append(Violation(
                "coverage", "coverage",
                f"backend {b!r} has no contract-checked kernel"))
    mesh_covered = set()
    for c in MANIFEST.values():
        if c.backend == "mesh":
            mesh_covered.update(c.registry)
    for name, entry in SPMV_ALGORITHMS.items():
        if "sharded" in entry and name not in mesh_covered:
            out.append(Violation(
                "coverage", "coverage",
                f"{name!r} declares a sharded target but no mesh "
                "kernel contract covers it"))
    return out


def run_check(only=None, baseline: dict | None = None,
              structural: bool = True) -> CheckReport:
    """Check the full manifest (or `only` kernels). Returns a report
    with baseline applied; `report.ok` is the gate verdict."""
    if baseline is None:
        baseline = load_baseline()
    report = CheckReport()
    kernels = [k for k in sorted(MANIFEST)
               if only is None or k in only]
    found: list[Violation] = []
    for kernel in kernels:
        found.extend(check_kernel_by_id(kernel))
        report.kernels_checked += 1
    if structural:
        found.extend(check_coverage())
        found.extend(check_lane_buckets())
    seen = set()
    for v in found:
        seen.add(v.key)
        if v.key in baseline:
            report.baselined.append(v)
        else:
            report.violations.append(v)
    if only is None:
        report.unused_baseline = sorted(k for k in baseline
                                        if k not in seen)
    return report
