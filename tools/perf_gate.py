"""Perf-regression gate: bench output vs BASELINE.json envelopes.

The lesson of BENCH_r05 (a silent CPU fallback scored 0.64× while the
real kernel measured 3.03B edges/s): a perf number nobody can trust is
not a perf number. This gate makes the trajectory enforceable:

  * no accelerator present      -> LOUD skip, exit 0 (a CPU-only dev
                                   box must not fail the gate — but it
                                   must SAY it measured nothing);
  * bench record is degraded    -> FAIL (a degraded run can never
                                   stand in for the headline metric);
  * value under the envelope    -> FAIL on > max_regression (15%)
                                   against BASELINE.json's reference;
  * otherwise                   -> PASS with the measured margin.

Usage:
    python -m tools.perf_gate                 # probe; run bench.py; check
    python -m tools.perf_gate --json F.json   # check an existing record
    python -m tools.perf_gate --latest        # check newest BENCH_r*.json

`tools/gate.sh` runs `--latest` so the dev gate validates the freshest
recorded measurement without re-running the 9-minute bench; CI on real
hardware runs the bare form to measure fresh.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO, "BASELINE.json")
PROBE_TIMEOUT_SEC = 30
BENCH_TIMEOUT_SEC = 700

_PROBE_SNIPPET = (
    "import jax, sys; "
    "b = jax.default_backend(); "
    "print(b); "
    "sys.exit(0 if b != 'cpu' else 3)"
)


def log(msg: str) -> None:
    print(f"perf-gate: {msg}", flush=True)


def accelerator_present() -> bool:
    """Probe in a subprocess (a wedged device tunnel must not hang the
    gate); exit 3 from the child means 'jax is up but CPU-only'."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True, timeout=PROBE_TIMEOUT_SEC, text=True,
            env={k: v for k, v in os.environ.items()
                 if k != "JAX_PLATFORMS"})
        log(f"probe backend: {proc.stdout.strip() or '?'} "
            f"(rc={proc.returncode})")
        return proc.returncode == 0
    except (subprocess.TimeoutExpired, OSError) as e:
        log(f"probe failed: {e}")
        return False


def run_bench() -> dict | None:
    """Run bench.py and parse its single JSON stdout line."""
    log("running bench.py for a fresh measurement ...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            stdout=subprocess.PIPE, timeout=BENCH_TIMEOUT_SEC)
    except (subprocess.TimeoutExpired, OSError) as e:
        log(f"bench.py did not complete: {e}")
        return None
    for line in reversed(proc.stdout.decode(errors="replace")
                         .strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    log("bench.py produced no JSON record")
    return None


def latest_bench_json() -> str | None:
    records = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    return records[-1] if records else None


def latest_ppr_json() -> str | None:
    records = sorted(glob.glob(os.path.join(REPO, "BENCH_ppr_r*.json")))
    return records[-1] if records else None


def latest_oltp_json() -> str | None:
    records = sorted(glob.glob(os.path.join(REPO, "OLTP_r*.json")))
    return records[-1] if records else None


def latest_mem_json() -> str | None:
    records = sorted(glob.glob(os.path.join(REPO, "MEM_r*.json")))
    return records[-1] if records else None


def check_memory(record: dict | None, envelopes: dict) -> int:
    """mgmem memory-regression gate over the newest MEM_r*.json record:
    per-kernel canonical-point peak bytes vs the BASELINE.json memory
    envelope, plus the donation-effectiveness floor (zero silently
    copied donations). Buffer assignment is DETERMINISTIC — the record
    lowers on the forced CPU mesh — so unlike every perf envelope this
    check runs with or without an accelerator: a refactor that doubles
    a fixpoint's temp footprint or breaks a donated carry fails CI the
    way a 15% perf regression already does."""
    env = envelopes.get("memory")
    if env is None:
        return 0
    if record is None:
        log("FAIL: BASELINE.json declares a memory envelope but no "
            "MEM_r*.json record exists — run `python -m tools.mgmem "
            "check --record MEM_rN.json`")
        return 1
    kernels = env.get("kernels") or {}
    max_growth = float(env.get("max_growth", 0.10))
    got = record.get("kernels") or {}
    rc = 0
    worst = 1.0
    for kernel, ref in sorted(kernels.items()):
        entry = got.get(kernel)
        if entry is None:
            log(f"FAIL: memory record has no entry for {kernel} — "
                "regenerate with the current manifest")
            rc = 1
            continue
        peak = float(entry.get("peak_bytes", 0))
        ceiling = ref * (1.0 + max_growth)
        if peak > ceiling:
            log(f"FAIL: {kernel} canonical peak {peak:,.0f}B grew "
                f"{(peak / ref - 1) * 100:+.1f}% past the envelope "
                f"{ref:,.0f}B (allowed +{max_growth * 100:.0f}%)")
            rc = 1
        if ref:
            worst = max(worst, peak / ref)
        if int(entry.get("donation_dropped", 0)) > 0:
            log(f"FAIL: {kernel} has {entry['donation_dropped']} "
                f"dropped donation(s) — "
                f"{entry.get('dropped_bytes', '?')}B silently copied "
                "instead of aliased")
            rc = 1
    unenveloped = sorted(set(got) - set(kernels))
    if unenveloped:
        log(f"FAIL: kernels without a memory envelope: {unenveloped} "
            "— add them via `python -m tools.mgmem envelopes --write`")
        rc = 1
    if rc == 0:
        log(f"PASS: memory — {len(kernels)} kernel peaks within "
            f"+{max_growth * 100:.0f}% of envelope (worst "
            f"{(worst - 1) * 100:+.1f}%), 0 dropped donations")
    return rc


def check(record: dict, baseline: dict) -> int:
    envelopes = baseline.get("envelopes") or {}
    metric = record.get("metric", "")
    env = envelopes.get(metric)
    if env is None:
        log(f"NO ENVELOPE for metric {metric!r} in BASELINE.json — "
            "add one; gate cannot pass what it cannot compare")
        return 1
    if "degraded" not in record:
        log("FAIL: record predates the degraded-tagging format "
            "(pre-r06) — an untagged number cannot be trusted; "
            "regenerate with the current bench.py")
        return 1
    if record["degraded"]:
        log(f"FAIL: record is degraded (backend="
            f"{record.get('backend', '?')}); a degraded run can never "
            "stand in for the headline metric")
        return 1
    value = float(record.get("value", 0.0))
    ref = float(env["value"])
    max_reg = float(env.get("max_regression", 0.15))
    floor = ref * (1.0 - max_reg)
    if value < floor:
        log(f"FAIL: {metric} = {value:,.0f} is "
            f"{(1 - value / ref) * 100:.1f}% below the envelope "
            f"reference {ref:,.0f} (allowed regression "
            f"{max_reg * 100:.0f}%, floor {floor:,.0f})")
        return 1
    log(f"PASS: {metric} = {value:,.0f} vs envelope {ref:,.0f} "
        f"(margin {(value / ref - 1) * 100:+.1f}%, floor {floor:,.0f})")
    return check_semiring(record, envelopes, ref)


def check_semiring(record: dict, envelopes: dict, headline_ref: float) -> int:
    """r10 semiring-core ratio envelopes over the record's
    extra.semiring sweep.  Runs only for records whose main metric
    already passed (i.e. non-degraded, on-device): the sweep must be
    present, honestly tagged, and inside the f32-parity / bf16-speedup
    envelopes."""
    f32p = envelopes.get("semiring_pagerank_f32_parity")
    spd = envelopes.get("semiring_bf16_speedup")
    if not f32p and not spd:
        return 0
    sem = (record.get("extra") or {}).get("semiring")
    if sem is None:
        log("FAIL: BASELINE.json declares semiring envelopes but the "
            "record carries no extra.semiring sweep — regenerate with "
            "the current bench.py")
        return 1
    if sem.get("backend") == "cpu" and not sem.get("degraded"):
        log("FAIL: semiring sweep ran on cpu but is not tagged "
            "degraded — an untagged CPU fallback cannot stand in for "
            "the on-device core measurement")
        return 1
    if sem.get("degraded"):
        log("FAIL: the main metric is on-device but the semiring sweep "
            f"is degraded (backend={sem.get('backend', '?')}) — the "
            "core sweep must ride the same accelerator")
        return 1
    rc = 0
    if f32p:
        frac = float(f32p["min_fraction_of_headline"])
        f32_eps = float(sem.get("f32_eps", 0.0))
        floor = frac * headline_ref
        if f32_eps < floor:
            log(f"FAIL: semiring f32 pagerank = {f32_eps:,.0f} e/s is "
                f"below the parity floor {floor:,.0f} "
                f"({frac:.0%} of the headline envelope)")
            rc = 1
        else:
            log(f"PASS: semiring f32 parity {f32_eps:,.0f} e/s "
                f"(floor {floor:,.0f})")
    if spd:
        need = float(spd["min"])
        got = float(sem.get("bf16_speedup", 0.0))
        if got < need:
            log(f"FAIL: semiring bf16 speedup {got:.3f}x < required "
                f"{need:.2f}x — the reduced-precision path stopped "
                "paying for its rounding")
            rc = 1
        else:
            log(f"PASS: semiring bf16 speedup {got:.3f}x "
                f"(>= {need:.2f}x)")
    return rc


def check_ppr(record: dict, envelopes: dict) -> int:
    """r16 PPR-serving envelope over a BENCH_ppr_r*.json record: the
    coalescing plane's sustained QPS must beat the sequential baseline
    by the declared factor with a real coalescing ratio, and a
    degraded/untagged record can never stand in for the headline —
    exactly the honesty contract the main metric carries."""
    env = envelopes.get("ppr_qps")
    if env is None:
        return 0
    if record is None:
        log("FAIL: BASELINE.json declares a ppr_qps envelope but no "
            "BENCH_ppr_r*.json record exists — run "
            "benchmarks/ppr_serving_bench.py")
        return 1
    if "degraded" not in record:
        log("FAIL: ppr record carries no degraded tag — an untagged "
            "number cannot be trusted; regenerate with the current "
            "ppr_serving_bench.py")
        return 1
    if record["degraded"]:
        log(f"FAIL: ppr record is degraded (backend="
            f"{record.get('backend', '?')}); a degraded run can never "
            "stand in for the serving headline")
        return 1
    extra = record.get("extra") or {}
    rc = 0
    speedup = float(extra.get("speedup_vs_sequential", 0.0))
    need_speedup = float(env.get("min_speedup_vs_sequential", 5.0))
    if speedup < need_speedup:
        log(f"FAIL: ppr speedup {speedup:.2f}x over the sequential "
            f"baseline < required {need_speedup:.1f}x — coalescing "
            "stopped paying")
        rc = 1
    else:
        log(f"PASS: ppr speedup {speedup:.2f}x (>= {need_speedup:.1f}x)")
    ratio = float(extra.get("coalescing_ratio", 0.0))
    need_ratio = float(env.get("min_coalescing_ratio", 4.0))
    if ratio < need_ratio:
        log(f"FAIL: coalescing ratio {ratio:.2f} < required "
            f"{need_ratio:.1f} — requests are not sharing batches")
        rc = 1
    else:
        log(f"PASS: coalescing ratio {ratio:.2f} "
            f"(>= {need_ratio:.1f})")
    if not extra.get("f32_bit_exact_vs_sequential", False):
        log("FAIL: batched f32 results are not bit-exact vs sequential "
            "personalized_pagerank — the batch changed the answers")
        rc = 1
    return rc


def check_delta(record: dict, envelopes: dict) -> int:
    """r19 mgdelta envelope over the record's ``extra.delta`` stage:
    commit-then-CALL pagerank after a ≤1% edge churn on the resident
    graph must beat the cold full-rebuild path by the declared factor,
    at the same tol (residual-equivalent, the stage records the Linf
    gap), with warm iterations never exceeding cold. Same honesty
    contract as the other sweeps: a CPU (degraded) sub-record can never
    satisfy the on-device envelope, an untagged one FAILS."""
    env = envelopes.get("delta_speedup")
    if env is None:
        return 0
    delta = (record.get("extra") or {}).get("delta")
    if delta is None:
        log("FAIL: BASELINE.json declares a delta_speedup envelope but "
            "the record carries no extra.delta stage — regenerate with "
            "the current bench.py")
        return 1
    if "degraded" not in delta:
        log("FAIL: delta stage carries no degraded tag — an untagged "
            "number cannot be trusted")
        return 1
    if delta.get("backend") == "cpu" and not delta.get("degraded"):
        log("FAIL: delta stage ran on cpu but is not tagged degraded")
        return 1
    if delta["degraded"]:
        log(f"FAIL: delta stage is degraded (backend="
            f"{delta.get('backend', '?')}) — a CPU commit-then-CALL "
            "curve cannot stand in for the resident-graph headline")
        return 1
    rc = 0
    got = float(delta.get("delta_speedup", 0.0))
    need = float(env.get("min_speedup", 10.0))
    if got < need:
        log(f"FAIL: delta speedup {got:.2f}x < required {need:.1f}x — "
            "the incremental path stopped paying for its bookkeeping")
        rc = 1
    else:
        log(f"PASS: delta speedup {got:.2f}x (>= {need:.1f}x)")
    max_churn = float(env.get("max_churn", 0.01))
    if float(delta.get("churn", 1.0)) > max_churn:
        log(f"FAIL: delta stage churn {delta.get('churn')} exceeds the "
            f"envelope's ≤{max_churn:.0%} contract")
        rc = 1
    if int(delta.get("iters_warm", 1 << 30)) > int(
            delta.get("iters_cold", 0)):
        log("FAIL: warm-started fixpoint took MORE iterations than "
            "cold — the seed is hurting, not helping")
        rc = 1
    tol_linf = float(env.get("max_residual_linf", 1e-5))
    if float(delta.get("residual_linf", 1.0)) > tol_linf:
        log(f"FAIL: warm result diverges from cold by Linf "
            f"{delta.get('residual_linf')} > {tol_linf} — warm start "
            "is not residual-equivalent")
        rc = 1
    return rc


def check_tier(record: dict, envelopes: dict) -> int:
    """r21 mgtier envelope over the record's ``extra.tier`` stage: the
    double-buffered block schedule must actually HIDE the declared
    fraction of the H2D transfer behind the SpMV folds (else streaming
    degenerates to serial page-in and out-of-core stops paying), and
    the compressed wire formats must keep their byte-reduction floor.
    Same honesty contract as the other sweeps: a CPU host has no real
    H2D lane, so its sub-record carries ``degraded: true`` and can
    never stand in for the on-device overlap headline; untagged
    records FAIL."""
    env = envelopes.get("tier_overlap")
    if env is None:
        return 0
    tier = (record.get("extra") or {}).get("tier")
    if tier is None:
        log("FAIL: BASELINE.json declares a tier_overlap envelope but "
            "the record carries no extra.tier stage — regenerate with "
            "the current bench.py")
        return 1
    if "degraded" not in tier:
        log("FAIL: tier stage carries no degraded tag — an untagged "
            "number cannot be trusted")
        return 1
    if tier.get("backend") == "cpu" and not tier.get("degraded"):
        log("FAIL: tier stage ran on cpu but is not tagged degraded")
        return 1
    rc = 0
    # the wire codec is host-side and deterministic: its compression
    # floor holds on EVERY host, degraded or not
    ratio_floor = float(env.get("min_wire_ratio", 1.8))
    for prec in ("bf16", "int8"):
        got = float(tier.get(f"wire_ratio_{prec}", 0.0))
        if got < ratio_floor:
            log(f"FAIL: {prec} wire compression {got:.2f}x < required "
                f"{ratio_floor:.1f}x — the block codec stopped "
                "shrinking the transfer")
            rc = 1
        else:
            log(f"PASS: {prec} wire compression {got:.2f}x "
                f"(>= {ratio_floor:.1f}x)")
    if tier["degraded"]:
        log(f"FAIL: tier stage is degraded (backend="
            f"{tier.get('backend', '?')}) — a host-memcpy overlap "
            "curve cannot stand in for the H2D-hiding headline")
        return 1
    got = float(tier.get("transfer_hidden_fraction", 0.0))
    need = float(env.get("min_hidden_fraction", 0.6))
    if int(tier.get("n_blocks", 0)) < 2:
        log("FAIL: tier stage ran with fewer than 2 blocks — nothing "
            "was actually streamed")
        rc = 1
    if got < need:
        log(f"FAIL: hidden-transfer fraction {got:.0%} < required "
            f"{need:.0%} — the double-buffer schedule stopped "
            "overlapping")
        rc = 1
    else:
        log(f"PASS: hidden-transfer fraction {got:.0%} "
            f"(>= {need:.0%})")
    return rc


def check_stream(record: dict, envelopes: dict) -> int:
    """r17 mgstream envelope over the record's ``extra.stream_ingest``
    stage: the supervised FILE-stream consumer must sustain the
    declared ingest rate, keep fresh analytics reads under the latency
    ceiling while ingest runs, and — non-negotiably — survive the
    mid-stream consumer kill with ZERO duplicates and zero loss
    (``exactly_once``). The whole stage is host-side (the plane is the
    Cypher/WAL path, not a kernel), so like the tier wire-ratio floor
    it is deterministic and enforced on EVERY host — there is no
    degraded escape hatch for a broken exactly-once guarantee."""
    env = envelopes.get("stream_ingest")
    if env is None:
        return 0
    stream = (record.get("extra") or {}).get("stream_ingest")
    if stream is None:
        log("FAIL: BASELINE.json declares a stream_ingest envelope but "
            "the record carries no extra.stream_ingest stage — "
            "regenerate with the current bench.py")
        return 1
    rc = 0
    # correctness floors first: these are absolute, not envelopes
    if not stream.get("exactly_once"):
        log(f"FAIL: stream stage is not exactly-once across the "
            f"consumer kill ({int(stream.get('duplicates', -1))} "
            "duplicates) — the transactional-offset protocol is broken")
        rc = 1
    else:
        log(f"PASS: kill+cold-restart exactly-once "
            f"({int(stream.get('total_ingested', 0))} records, 0 dups)")
    if not stream.get("reads_monotone", False):
        log("FAIL: fresh reads regressed during live ingest — "
            "committed ingestion became invisible")
        rc = 1
    rate_floor = float(env.get("min_records_per_sec", 500.0))
    got = float(stream.get("records_per_sec", 0.0))
    if got < rate_floor:
        log(f"FAIL: sustained ingest {got:.0f} records/s < required "
            f"{rate_floor:.0f} — the supervised consumer loop "
            "stopped keeping up")
        rc = 1
    else:
        log(f"PASS: sustained ingest {got:.0f} records/s "
            f"(>= {rate_floor:.0f})")
    p95_ceiling = float(env.get("max_fresh_read_p95_ms", 50.0))
    got = float(stream.get("fresh_read_p95_ms", float("inf")))
    if got > p95_ceiling:
        log(f"FAIL: fresh-read p95 {got:.2f}ms under live ingest > "
            f"ceiling {p95_ceiling:.0f}ms — analytics stopped being "
            "always-fresh")
        rc = 1
    else:
        log(f"PASS: fresh-read p95 {got:.2f}ms under live ingest "
            f"(<= {p95_ceiling:.0f}ms)")
    return rc


def check_sharding(record: dict | None, envelopes: dict) -> int:
    """r18 shard-scaling envelope over the newest OLTP_r*.json record:
    the sharded point-read group must beat the single-process aggregate
    by the declared factor at the declared worker count, the
    cross-shard 2PC group must match its arithmetic oracle, and an
    untagged or degraded record can never stand as the scaling
    headline (a 1-core host's contention-bound curve carries
    ``degraded: true`` + its core count, and fails here exactly like a
    CPU-fallback device record would)."""
    env = envelopes.get("shard_scaling")
    if env is None:
        return 0
    if record is None:
        log("FAIL: BASELINE.json declares a shard_scaling envelope but "
            "no OLTP_r*.json record exists — run benchmarks/mgbench.py "
            "--out OLTP_rN.json")
        return 1
    if "degraded" not in record or "cores" not in record:
        log("FAIL: OLTP record predates the degraded/cores tagging — "
            "an untagged scaling number cannot be trusted; regenerate "
            "with the current mgbench.py")
        return 1
    if record["degraded"]:
        log(f"FAIL: OLTP record is degraded "
            f"({record.get('degraded_reason', 'no reason recorded')}); "
            "a contention-bound curve can never stand in for the "
            "shard-scaling headline")
        return 1
    workers = int(env.get("workers", 4))
    group = next((g for g in record.get("groups", [])
                  if g.get("name") == f"point_read_sharded_{workers}w"),
                 None)
    rc = 0
    if group is None or "speedup_vs_single_process" not in group:
        log(f"FAIL: record has no point_read_sharded_{workers}w group "
            "with a speedup_vs_single_process measurement")
        rc = 1
    else:
        got = float(group["speedup_vs_single_process"])
        need = float(env.get("min_speedup", 3.0))
        if got < need:
            log(f"FAIL: sharded point-read speedup {got:.2f}x at "
                f"{workers} workers < required {need:.1f}x — the "
                "plane stopped scaling")
            rc = 1
        else:
            log(f"PASS: sharded point-read speedup {got:.2f}x at "
                f"{workers} workers (>= {need:.1f}x)")
    twopc = next((g for g in record.get("groups", [])
                  if g.get("name") == "cross_shard_write_2pc"), None)
    if twopc is None or not twopc.get("oracle_match"):
        log("FAIL: cross_shard_write_2pc group missing or its "
            "arithmetic oracle did not match — cross-shard atomicity "
            "is broken or unmeasured")
        rc = 1
    else:
        log("PASS: cross-shard 2PC group matches its oracle")
    return rc


def check_lane(record: dict | None, envelopes: dict) -> int:
    """r20 mglane envelope over the newest OLTP_r*.json record: the
    compiled read lane must serve the aggregate and two-hop groups with
    the declared p99 reduction vs the serial interpreter path, on a
    non-degraded lane sub-record (a CPU lane curve carries
    ``lane.degraded: true`` and fails here exactly like every other
    CPU stand-in — the CPU record still documents the machinery, the
    gate defends the accelerator headline)."""
    env = envelopes.get("columnar_lane")
    if env is None:
        return 0
    if record is None:
        log("FAIL: BASELINE.json declares a columnar_lane envelope but "
            "no OLTP_r*.json record exists — run benchmarks/mgbench.py "
            "--out OLTP_rN.json")
        return 1
    lane = record.get("lane")
    if lane is None:
        log("FAIL: OLTP record carries no lane sub-record — regenerate "
            "with the current mgbench.py")
        return 1
    if "degraded" not in lane:
        log("FAIL: lane sub-record carries no degraded tag — an "
            "untagged number cannot be trusted")
        return 1
    if lane.get("backend") == "cpu" and not lane.get("degraded"):
        log("FAIL: lane groups ran on cpu but are not tagged degraded")
        return 1
    if lane["degraded"]:
        log(f"FAIL: lane sub-record is degraded (backend="
            f"{lane.get('backend', '?')}); a CPU lane curve can never "
            "stand in for the compiled-lane headline")
        return 1
    rc = 0
    if not lane.get("lane_served"):
        log("FAIL: lane groups did not actually serve from the "
            "compiled lane (lane.hit_total never moved)")
        rc = 1
    need = float(env.get("min_p99_speedup", 10.0))
    for group_name in env.get("groups", ("aggregate_lane_on",
                                         "two_hop_lane_on")):
        group = next((g for g in record.get("groups", [])
                      if g.get("name") == group_name), None)
        if group is None or "p99_speedup_vs_serial" not in group:
            log(f"FAIL: record has no {group_name} group with a "
                "p99_speedup_vs_serial measurement")
            rc = 1
            continue
        got = float(group["p99_speedup_vs_serial"])
        if got < need:
            log(f"FAIL: {group_name} p99 speedup {got:.1f}x < required "
                f"{need:.1f}x — the compiled lane stopped paying")
            rc = 1
        else:
            log(f"PASS: {group_name} p99 speedup {got:.1f}x "
                f"(>= {need:.1f}x)")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="perf_gate")
    ap.add_argument("--json", help="check an existing bench JSON record")
    ap.add_argument("--latest", action="store_true",
                    help="check the newest BENCH_r*.json in the repo")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    args = ap.parse_args(argv)

    with open(args.baseline) as f:
        baseline = json.load(f)

    # the memory gate is deterministic (forced CPU-mesh lowering), so
    # it runs BEFORE the accelerator probe can skip anything
    mem_path = latest_mem_json()
    mem_record = None
    if mem_path is not None:
        log(f"checking newest memory record "
            f"{os.path.basename(mem_path)}")
        with open(mem_path) as f:
            mem_record = json.load(f)
    rc_mem = check_memory(mem_record, baseline.get("envelopes") or {})

    if not accelerator_present():
        log("=" * 62)
        log("SKIPPED: no accelerator present — nothing was measured")
        log("(the deterministic memory gate above still ran).")
        log("This gate only defends the perf trajectory on real")
        log("hardware; do NOT read this skip as a pass.")
        log("=" * 62)
        return rc_mem

    if args.json:
        path = args.json
    elif args.latest:
        path = latest_bench_json()
        if path is None:
            log("no BENCH_r*.json records found")
            return 1
        log(f"checking newest record {os.path.basename(path)}")
    else:
        record = run_bench()
        if record is None:
            log("FAIL: could not obtain a bench measurement")
            return 1
        return (rc_mem
                or check(record, baseline)
                or check_delta(record, baseline.get("envelopes") or {})
                or check_tier(record, baseline.get("envelopes") or {})
                or check_stream(record, baseline.get("envelopes") or {}))

    with open(path) as f:
        record = json.load(f)
    rc = rc_mem or check(record, baseline)
    rc = rc or check_delta(record, baseline.get("envelopes") or {})
    rc = rc or check_tier(record, baseline.get("envelopes") or {})
    rc = rc or check_stream(record, baseline.get("envelopes") or {})
    if args.latest:
        # the serving-plane record rides the same --latest gate run
        ppr_path = latest_ppr_json()
        ppr_record = None
        if ppr_path is not None:
            log(f"checking newest ppr record "
                f"{os.path.basename(ppr_path)}")
            with open(ppr_path) as f:
                ppr_record = json.load(f)
        rc = rc or check_ppr(ppr_record,
                             baseline.get("envelopes") or {})
        # the OLTP shard-scaling record rides the same --latest run
        oltp_path = latest_oltp_json()
        oltp_record = None
        if oltp_path is not None:
            log(f"checking newest OLTP record "
                f"{os.path.basename(oltp_path)}")
            with open(oltp_path) as f:
                oltp_record = json.load(f)
        rc = rc or check_sharding(oltp_record,
                                  baseline.get("envelopes") or {})
        rc = rc or check_lane(oltp_record,
                              baseline.get("envelopes") or {})
    return rc


if __name__ == "__main__":
    sys.exit(main())
