#!/usr/bin/env bash
# Dev gate: everything tier-1 enforces, in one command.
#
#   tools/gate.sh          # mglint + mgsan smoke + mgchaos smoke + tier-1
#   tools/gate.sh --full   # additionally: full sanitize + chaos sweeps
#
# Run from anywhere; exits non-zero on the first failing stage.
set -u
cd "$(dirname "$0")/.."

FULL=0
[ "${1:-}" = "--full" ] && FULL=1

fail=0
stage() {
    echo
    echo "=== gate: $1 ==="
    shift
    "$@" || { echo "gate: FAILED: $*" >&2; fail=1; }
}

# 1. static analysis: all mglint rules (MG001-MG011) over the package;
#    unbaselined findings exit non-zero
stage "mglint (static analysis)" \
    python -m tools.mglint memgraph_tpu

# 1a. mgxla: compiled-artifact contract checker — every SPMV_ALGORITHMS
#     entry, all three semiring backends, and every PPR lane bucket
#     abstractly lowered (nothing executes) over the forced 8-device
#     mesh; exact collective multiset per iteration body, zero f64 ops,
#     zero host callbacks, donated fixpoint carries, bounded lane-bucket
#     compile count. Unbaselined violations exit non-zero.
stage "mgxla (device-plane contract checker)" \
    python -m tools.mgxla check

# 1aa. mgmem: compiled-artifact HBM accounting — every manifest kernel
#      lowered at 2-3 shape points, per-kernel linear footprint models
#      fitted from XLA buffer assignment, donation effectiveness
#      verified (dropped donations fail), and the kernel server's
#      admission estimators machine-checked against the models
#      (underestimate = hard failure, >2x overestimate needs a
#      justified baseline entry). Exit 2 = lowering unavailable on
#      this host: skip LOUDLY, never silently pass.
stage_mgmem() {
    echo
    echo "=== gate: mgmem (compiled HBM accounting) ==="
    python -m tools.mgmem check
    rc=$?
    if [ "$rc" = 2 ]; then
        echo "gate: SKIPPED: mgmem — lowering unavailable on this host;" \
             "NOTHING was memory-checked" >&2
    elif [ "$rc" != 0 ]; then
        echo "gate: FAILED: python -m tools.mgmem check" >&2
        fail=1
    fi
}
stage_mgmem

# 1ab. mgflow: interprocedural exception-flow & typed-outcome contract
#      checker — per-serving-root escape sets vs their raises=
#      contracts, wire outcome vocabularies drift-checked BOTH
#      directions, retry regions vs the IDEMPOTENCY registry; the
#      justification-required baseline discipline means unused entries
#      fail too. Exit 2 = bad invocation/no registry on this checkout:
#      skip LOUDLY, never silently pass.
stage_mgflow() {
    echo
    echo "=== gate: mgflow (exception-flow contracts) ==="
    python -m tools.mgflow check
    rc=$?
    if [ "$rc" = 2 ]; then
        echo "gate: SKIPPED: mgflow — registry/baseline unavailable on" \
             "this checkout; NO contracts were flow-checked" >&2
    elif [ "$rc" != 0 ]; then
        echo "gate: FAILED: python -m tools.mgflow check" >&2
        fail=1
    fi
}
stage_mgflow

# 1b. mgtrace smoke: one traced query end-to-end (parse → plan →
#     execute → MVCC commit → mesh-routed device stages), single
#     connected trace, Chrome-trace-event export validated structurally
stage "mgtrace smoke (traced query -> chrome export)" \
    python -m tools.trace_smoke

# 1c. mgstat smoke: one traced+profiled query end-to-end (PROFILE v2
#     operator rows + device attribution), SHOW QUERY STATS fingerprint
#     linkage, exposition + federation parse, health verdict trips on an
#     injected saturation fault and recovers
stage "stats-smoke (profiled query -> fingerprints -> health)" \
    python -m tools.stats_smoke

# 2. mgsan smoke: the invariant-holding scenarios over a few seeds (the
#    racy_counter true-positive is exercised by the test suite, not here)
stage "mgsan schedule-exploration smoke" \
    python -m tools.mgsan explore --seeds 3 \
        --scenario metrics_counter --scenario storage_commits \
        --scenario replica_health

# 3. mgsan MVCC workload: randomized concurrent history must check clean,
#    and the checker must flag the deliberately broken run
stage "mgsan MVCC isolation check" \
    python -m tools.mgsan workload --seed 0
stage "mgsan MVCC checker sensitivity (broken isolation)" \
    python -m tools.mgsan workload --seed 0 --break-isolation

# 4. mgchaos smoke: one seeded nemesis round (partition/churn →
#    failover → heal) through the cluster safety checker, plus the
#    checker-honesty gate (the fencing-disabled split-brain script MUST
#    be flagged; the fenced one MUST be clean)
stage "mgchaos seeded round + safety checker" \
    python -m tools.mgchaos run --seed 0 --rounds 1
stage "mgchaos checker honesty (split-brain script)" \
    python -m tools.mgchaos honesty

# 4b. device nemesis smoke: the full (fault x context) matrix — call/
#     oom/hang/lost injected mid-pagerank, mid-kernel-request and during
#     probe — through the supervised kernel plane; results must stay
#     bit-exact, resumes bounded by k, and every typed outcome observed.
#     Runs on the CPU backend (MGCHAOS_DEVICE_PLATFORM overrides).
stage "mgchaos device nemesis smoke (supervised kernel plane)" \
    python -m tools.mgchaos device-smoke --seed 0

# 4c. PPR serving-plane smoke: spawn the kernel server, fire 64
#     concurrent requests from threads, assert the coalescing ratio
#     beats 1 (requests really shared batches), cache hit on repeat,
#     clean shutdown. Functional on every host; perf is the bench's job.
stage "ppr-smoke (coalesced PPR serving plane)" \
    python -m tools.ppr_smoke

# 4cc. mgdelta smoke: kernel server import at v1 → delta-only request
#      at v2 (changed + incident edges, no full arrays) refreshing the
#      resident generation O(delta) with a warm-started, residual-
#      equivalent reply; WCC monotone gate (warm on adds-only, LOUD
#      typed cold on removal); change-log-wrap typed fallback.
#      Functional on every host; delta_speedup is the bench's job.
stage "delta-smoke (incremental resident analytics plane)" \
    python -m tools.delta_smoke

# 4cd. mglane smoke: a lane-eligible read pipeline compiles ONCE and
#      serves from the compiled program, refusal shapes fall back
#      LOUDLY (typed reason) with identical answers, and index DDL
#      drops every compiled lane with results bit-identical to the
#      serial interpreter (the stale-lane regression).
stage "lane-smoke (compiled Cypher read lane)" \
    python -m tools.lane_smoke

# 4d. shard-plane smoke: spawn 4 shard workers (own storage + WAL per
#     shard), routed point reads/writes, scatter-gather merge, a
#     cross-shard 2PC transaction, one LIVE shard-move (epoch bump +
#     cutover), a worker kill with typed-error respawn + per-shard WAL
#     recovery, clean shutdown. Functional on every host; scaling is
#     the bench's job (mgbench --shards -> OLTP_r*.json).
stage "shard-smoke (sharded OLTP execution plane)" \
    python -m tools.shard_smoke

# 4e. out-of-core tier smoke: an oversized graph under a tiny HBM
#     budget must flip onto the STREAMED path automatically (admission
#     third verdict), return a result bit-identical to the resident
#     comparator, shed non-streamable algorithms with the typed
#     verdict, and actually compress the wire (bf16/int8 >= 1.8x).
stage "tier-smoke (out-of-core streamed edge blocks)" \
    python -m tools.tier_smoke

# 4f. streaming-ingestion smoke: a WAL-backed FILE stream through the
#     Cypher surface — transactional-offset ingest, consumer kill +
#     cold restart resuming exactly-once from the durable offset,
#     poison-batch dead-letter quarantine with the loop alive, the
#     AFTER-COMMIT trigger metered, backpressure probe + the
#     stream_lag health flip. Functional on every host; sustained
#     throughput is the bench's job (stream_ingest -> BENCH_r*.json).
stage "stream-smoke (crash-safe exactly-once ingestion plane)" \
    python -m tools.stream_smoke

# 5. perf-regression gate: the newest BENCH_r*.json record must be
#    non-degraded and within BASELINE.json's envelope (>15% regression
#    fails). Hosts without an accelerator skip LOUDLY (exit 0): the
#    gate defends the trajectory on real hardware, it does not punish
#    CPU-only dev boxes — but it never silently passes either.
stage "perf regression gate (BASELINE.json envelopes)" \
    python -m tools.perf_gate --latest

# 6. tier-1 tests: arms the lock-order witness (MG_TRACK_LOCKS=1, from
#    conftest) and the vector-clock race detector (MG_SAN=1) suite-wide;
#    the session fails on any witnessed lock cycle or data race.
#    Optional-dep suites (hypothesis, cryptography) self-skip.
stage "tier-1 tests (MG_SAN=1)" \
    env MG_SAN=1 python -m pytest tests/ -q \
        -m "not slow and not crash and not sanitize"

if [ "$FULL" = 1 ]; then
    # 7. the full seeded sweeps: 25 mgsan seeds per scenario + 5
    #    workload seeds, and the 10-seed mgchaos nemesis sweep
    stage "mgsan full seeded sweep (-m sanitize)" \
        env MG_SAN=1 python -m pytest tests/test_mgsan.py -q -m sanitize
    stage "mgchaos full nemesis sweep (-m chaos)" \
        python -m pytest tests/test_chaos.py -q -m chaos
fi

echo
if [ "$fail" = 0 ]; then
    echo "gate: ALL STAGES PASSED"
else
    echo "gate: FAILURES ABOVE" >&2
fi
exit "$fail"
