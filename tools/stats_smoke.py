"""mgstat smoke: one traced+profiled query end-to-end, exposition
parses, health verdict sane.

The gate stage (`tools/gate.sh`) proving the workload-statistics plane
actually works:

  1. arm tracing (sample=1.0) and run real Cypher through a real
     Interpreter, including a PROFILE-d mesh-routed analytics CALL
     (mesh-of-1 degeneracy — same sharded path a TPU pod runs);
  2. assert PROFILE v2 rows carry hits/rows/peak-mem AND device
     attribution rows (transfer + compile/iterate stages);
  3. assert SHOW QUERY STATS surfaces the fingerprints with counts,
     plan-cache hits, and retained trace links;
  4. parse the Prometheus exposition line by line, then federate two
     labeled copies and re-parse — every sample must carry an instance
     label and every family exactly one TYPE line;
  5. evaluate the saturation plane: ready on a quiet instance, NOT
     ready (machine-readable reason) under an injected replication-lag
     fault, ready again once the fault clears.

Exit 0 only if every check passes.
"""

from __future__ import annotations

import os
import re
import sys


def fail(msg: str) -> None:
    print(f"stats-smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? [0-9eE.+-]+"
    r"( # \{.*\} [0-9eE.+-]+ [0-9.]+)?$")


def check_exposition(text: str, require_instance: bool = False) -> int:
    samples = 0
    type_lines: dict[str, int] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            family = line.split()[2]
            type_lines[family] = type_lines.get(family, 0) + 1
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if m is None:
            fail(f"unparseable exposition line: {line!r}")
        if require_instance and 'instance="' not in (m.group(2) or ""):
            fail(f"federated sample missing instance label: {line!r}")
        samples += 1
    for family, n in type_lines.items():
        if n != 1:
            fail(f"family {family} has {n} TYPE lines (want exactly 1)")
    return samples


def main() -> None:
    # mesh-of-1 so the analytics CALL rides the sharded device path and
    # attributes transfer/compile/iterate stages
    os.environ.setdefault("MEMGRAPH_TPU_MESH_DEVICES", "1")

    from memgraph_tpu.observability import stats as mgstats
    from memgraph_tpu.observability import trace as T
    from memgraph_tpu.observability.metrics import global_metrics
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage

    T.enable(sample=1.0)
    interp = Interpreter(InterpreterContext(InMemoryStorage()))
    interp.execute("UNWIND range(0, 63) AS i CREATE (:N {v: i})")
    interp.execute(
        "MATCH (a:N), (b:N) WHERE b.v = a.v + 1 OR b.v = a.v * 2 "
        "CREATE (a)-[:E]->(b)")

    # 1-2. traced + PROFILE-d device-routed query with attribution
    query = ("CALL pagerank.get() YIELD node, rank "
             "RETURN node.v, rank ORDER BY rank DESC LIMIT 5")
    interp.execute(query)                       # warm plan cache
    cols, rows, _ = interp.execute("PROFILE " + query)
    if cols[0] != "OPERATOR" or "ROWS" not in cols \
            or "PEAK MEM (BYTES)" not in cols:
        fail(f"PROFILE v2 columns wrong: {cols}")
    ops = [r for r in rows if r[0].lstrip("| ").startswith("*")]
    if not any(int(r[1]) > 0 and int(r[2]) > 0 for r in ops):
        fail(f"no operator row with hits+rows: {ops}")
    stages = {r[0].split(": ", 1)[1] for r in rows
              if r[0].startswith(">> device: ")}
    if not {"device_transfer", "device_compile"} <= stages:
        fail(f"PROFILE device attribution missing stages: {stages}")
    # r10 semiring core: the dispatch attributes time PER BACKEND, so a
    # PROFILE of a core-routed query says which backend served it
    # (mesh here — the mesh-of-1 CALL above)
    if "semiring_mesh" not in stages:
        fail(f"PROFILE missing per-backend semiring attribution "
             f"(want semiring_mesh): {stages}")

    # 3. fingerprint statistics with trace links
    cols, srows, _ = interp.execute("SHOW QUERY STATS")
    by_fp = {r[0]: r for r in srows}
    fp = mgstats.fingerprint_text(query)
    if fp not in by_fp:
        fail(f"fingerprint {fp!r} missing from SHOW QUERY STATS "
             f"({list(by_fp)})")
    entry = by_fp[fp]
    if entry[1] < 2:
        fail(f"expected >=2 recorded runs for {fp!r}: {entry}")
    if entry[6] < 1:
        fail(f"expected a plan-cache hit for {fp!r}: {entry}")
    if not entry[7]:
        fail(f"fingerprint entry has no retained trace link: {entry}")
    retained = {s["trace_id"] for t in T.traces_json() for s in t}
    if not set(entry[7]) & retained:
        fail(f"linked trace_ids {entry[7]} not in retained ring")

    # 4. exposition parses, federation labels every sample
    text = global_metrics.prometheus_text()
    n = check_exposition(text)
    if n == 0:
        fail("empty exposition")
    fed = mgstats.federate_expositions({"main": text, "replica1": text})
    fn = check_exposition(fed, require_instance=True)
    if fn < 2 * n * 0.9:
        fail(f"federated exposition lost samples: {fn} < 2x{n}")

    # 5. health verdict: sane, trips on injected lag, recovers
    verdict = mgstats.global_saturation.evaluate()
    if not verdict["ready"] or verdict["reasons"]:
        fail(f"quiet instance not ready: {verdict}")
    global_metrics.set_gauge("replication.replica_lag.smoke", 1e9)
    verdict = mgstats.global_saturation.evaluate()
    if verdict["ready"] or not any(
            r["check"] == "replication_lag" for r in verdict["reasons"]):
        fail(f"injected lag did not trip readiness: {verdict}")
    reason = verdict["reasons"][0]
    for key in ("check", "reason", "value", "threshold"):
        if key not in reason:
            fail(f"reason not machine-readable: {reason}")
    global_metrics.set_gauge("replication.replica_lag.smoke", 0.0)
    verdict = mgstats.global_saturation.evaluate()
    if not verdict["ready"]:
        fail(f"readiness did not recover after fault cleared: {verdict}")

    print(f"stats-smoke: OK — profile stages {sorted(stages)}, "
          f"{len(srows)} fingerprints, {n} exposition samples "
          f"({fn} federated), health verdict trips and recovers")


if __name__ == "__main__":
    main()
