"""Gate smoke for the streaming ingestion plane (r17, mgstream): a
WAL-backed FILE stream driven end-to-end through the Cypher surface —
CREATE/START STREAM, transactional-offset ingest, a consumer kill +
cold restart resuming from the durable offset (exactly-once), a poison
batch quarantined to the dead-letter buffer with the loop alive, an
AFTER-COMMIT trigger firing on ingested batches, the backpressure
probe, and the stream_lag health check flipping /health.

Functional counterpart of the mgbench stream_ingest scenario sized for
the dev gate (~seconds, any host): this proves the plane WORKS; the
bench proves it keeps up.

Usage: python -m tools.stream_smoke
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_FIRST = 8      # ingested before the kill
N_WHILE_DEAD = 5  # appended while the consumer is down


def log(msg: str) -> None:
    print(f"stream-smoke: {msg}", flush=True)


def fail(msg: str) -> int:
    log(f"FAIL: {msg}")
    return 1


def _produce(path: str, ids) -> None:
    with open(path, "a", encoding="utf-8") as f:
        for i in ids:
            f.write(json.dumps({
                "query": "CREATE (:Ev {id: $id})",
                "parameters": {"id": i}}) + "\n")


def _wait(pred, timeout: float = 15.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def main() -> int:
    from memgraph_tpu.observability import stats as mgstats
    from memgraph_tpu.observability.metrics import global_metrics
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.query.streams import streams_of
    from memgraph_tpu.storage import InMemoryStorage, StorageConfig
    from memgraph_tpu.storage.durability.recovery import (recover,
                                                          wire_durability)
    from memgraph_tpu.storage.kvstore import KVStore

    workdir = tempfile.mkdtemp(prefix="stream-smoke-")
    feed = os.path.join(workdir, "feed.jsonl")
    open(feed, "w").close()
    storage = InMemoryStorage(StorageConfig(
        durability_dir=os.path.join(workdir, "data"), wal_enabled=True))
    recover(storage)
    wal = wire_durability(storage)
    ictx = InterpreterContext(storage)
    ictx.kvstore = KVStore(os.path.join(workdir, "kv.db"))
    interp = Interpreter(ictx, system=True)

    def count() -> int:
        _c, rows, _s = interp.execute("MATCH (e:Ev) RETURN count(e)")
        return rows[0][0]

    try:
        # AFTER-COMMIT trigger riding the ingest path (satellite: its
        # failures are counted+logged, its firings metered)
        interp.execute(
            "CREATE TRIGGER audit ON CREATE AFTER COMMIT "
            "EXECUTE MERGE (c:Audit) SET c.n = coalesce(c.n, 0) + 1")
        interp.execute(
            f"CREATE FILE STREAM smoke TOPICS '{feed}' "
            f"TRANSFORM transform.cypher BATCH_SIZE 4 BATCH_INTERVAL 50")
        interp.execute("START STREAM smoke")
        _produce(feed, range(N_FIRST))
        if not _wait(lambda: count() >= N_FIRST):
            return fail(f"initial ingest stalled at {count()}/{N_FIRST}")
        log(f"{N_FIRST} records ingested through the FILE stream")

        if not storage.stream_offsets.get("smoke"):
            return fail("no transactional offset in storage.stream_offsets")
        if storage.stream_offsets["smoke"] != os.path.getsize(feed):
            return fail(
                f"WAL offset {storage.stream_offsets['smoke']} != file "
                f"size {os.path.getsize(feed)}")
        log(f"WAL offset record exact: {storage.stream_offsets['smoke']} "
            "bytes (rides the ingest commit)")

        # consumer kill mid-stream (the chaos hook: no graceful ack),
        # records appended while dead, cold restart resumes from the
        # durable offset — exactly-once
        stream = streams_of(ictx)._get("smoke")
        stream.kill()
        _produce(feed, range(N_FIRST, N_FIRST + N_WHILE_DEAD))
        interp.execute("START STREAM smoke")
        total = N_FIRST + N_WHILE_DEAD
        if not _wait(lambda: count() >= total):
            return fail(f"post-restart ingest stalled at {count()}/{total}")
        _c, rows, _s = interp.execute(
            "MATCH (e:Ev) RETURN e.id, count(*) ORDER BY e.id")
        ids = {r[0]: r[1] for r in rows}
        if ids != {i: 1 for i in range(total)}:
            return fail(f"exactly-once broken across kill/restart: {ids}")
        log(f"consumer kill -> cold restart -> {total} ids exactly once")

        # trigger fired on ingested batches, meters live
        _c, rows, _s = interp.execute("MATCH (c:Audit) RETURN c.n")
        if not rows or not rows[0][0]:
            return fail("AFTER COMMIT trigger never fired on ingest")
        snap = {n: v for n, _k, v in global_metrics.snapshot()}
        if not snap.get("trigger.fired_total"):
            return fail("trigger.fired_total not counted")
        if not snap.get("stream.batches_total"):
            return fail("stream.batches_total not counted")
        log(f"trigger fired {rows[0][0]}x on ingest; stream metrics live "
            f"(batches={snap['stream.batches_total']})")

        # poison batch: quarantined to the dead-letter buffer, offset
        # advanced, loop ALIVE — then a good record still ingests
        with open(feed, "a", encoding="utf-8") as f:
            f.write(json.dumps({"query": "THIS IS NOT CYPHER"}) + "\n")
        if not _wait(lambda: len(stream.dead_letter) >= 1):
            return fail("poison batch never quarantined")
        if not stream.running:
            return fail("stream wedged/stopped by the poison batch")
        _produce(feed, [total])
        if not _wait(lambda: count() >= total + 1):
            return fail("ingest after quarantine stalled")
        log("poison batch dead-lettered, offset advanced, loop alive")

        # backpressure probe + the stream_lag health check
        plane = mgstats.global_saturation
        if plane.ingest_pressure() is not None:
            return fail("ingest_pressure tripped on an idle plane")
        global_metrics.set_gauge("replication.replica_lag.smoketest",
                                 plane.max_replica_lag + 1)
        if plane.ingest_pressure() != "replication_lag":
            return fail("backpressure probe missed replication lag")
        global_metrics.set_gauge("replication.replica_lag.smoketest", 0.0)
        global_metrics.set_gauge("stream.lag.smoke",
                                 plane.max_stream_lag + 1)
        verdict = plane.evaluate(ictx)
        if verdict["ready"] or not any(
                "stream_lag" in r.get("check", "")
                for r in verdict["reasons"]):
            return fail(f"stream_lag did not flip /health: {verdict}")
        global_metrics.set_gauge("stream.lag.smoke", 0.0)
        if not plane.evaluate(ictx)["ready"]:
            return fail("health did not recover after lag cleared")
        log("backpressure probe + stream_lag health flip OK")

        interp.execute("STOP STREAM smoke")
        interp.execute("DROP STREAM smoke")
        interp.execute("DROP TRIGGER audit")
    finally:
        try:
            streams_of(ictx).stop_all()
        finally:
            wal.close()
            shutil.rmtree(workdir, ignore_errors=True)
    log("clean shutdown — PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
