"""Microbenchmarks for the Pallas PageRank kernel design (round 2).

Measures, on the real chip:
  1. dynamic_gather axis=0 (cross-sublane, per-lane column gather) on tall
     (R,128) operands — the core primitive of the fused kernel design.
  2. dynamic_gather axis=1 (per-sublane lane gather).
  3. Streaming bandwidth of a simple pallas grid kernel (HBM->VMEM->HBM).
  4. In-loop iteration cost (lax.fori_loop around a pallas_call vs grid).

Run: python benchmarks/pallas_micro.py [cpu]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

if len(sys.argv) > 1 and sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = jax.devices()[0].platform == "cpu"


def timeit(fn, *args, n=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n, out


def bench_col_gather(R):
    """out[s,l] = table[idx[s,l], l] via take_along_axis axis=0."""
    def kernel(tab_ref, idx_ref, out_ref):
        out_ref[:] = jnp.take_along_axis(
            tab_ref[:], idx_ref[:], axis=0, mode="promise_in_bounds")

    @jax.jit
    def run(tab, idx):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(tab, idx)

    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.random((R, 128), dtype=np.float32))
    idx = jnp.asarray(rng.integers(0, R, (R, 128)), dtype=jnp.int32)
    try:
        dt, out = timeit(run, tab, idx)
    except Exception as e:  # noqa: BLE001
        print(f"  col_gather R={R}: FAILED {type(e).__name__}: {str(e)[:200]}")
        return
    # correctness
    ref = np.take_along_axis(np.asarray(tab), np.asarray(idx), axis=0)
    ok = np.allclose(np.asarray(out), ref)
    n_elem = R * 128
    print(f"  col_gather R={R}: {dt*1e6:9.1f} us  {n_elem/dt/1e9:8.2f} Gelem/s  ok={ok}")


def bench_lane_gather(R):
    """out[s,l] = table[s, idx[s,l]] via take_along_axis axis=1."""
    def kernel(tab_ref, idx_ref, out_ref):
        out_ref[:] = jnp.take_along_axis(
            tab_ref[:], idx_ref[:], axis=1, mode="promise_in_bounds")

    @jax.jit
    def run(tab, idx):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(tab, idx)

    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.random((R, 128), dtype=np.float32))
    idx = jnp.asarray(rng.integers(0, 128, (R, 128)), dtype=jnp.int32)
    try:
        dt, out = timeit(run, tab, idx)
    except Exception as e:  # noqa: BLE001
        print(f"  lane_gather R={R}: FAILED {type(e).__name__}: {str(e)[:200]}")
        return
    ref = np.take_along_axis(np.asarray(tab), np.asarray(idx), axis=1)
    ok = np.allclose(np.asarray(out), ref)
    n_elem = R * 128
    print(f"  lane_gather R={R}: {dt*1e6:9.1f} us  {n_elem/dt/1e9:8.2f} Gelem/s  ok={ok}")


def bench_stream(MB):
    """x*2+1 over a big array, blocked grid: streaming bandwidth."""
    R = MB * 1024 * 1024 // (128 * 4)
    TILE = 2048

    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0 + 1.0

    @jax.jit
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            grid=(R // TILE,),
            in_specs=[pl.BlockSpec((TILE, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((TILE, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(x)

    x = jnp.ones((R, 128), jnp.float32)
    dt, _ = timeit(run, x)
    nbytes = R * 128 * 4 * 2  # read + write
    print(f"  stream {MB}MB: {dt*1e3:8.2f} ms  {nbytes/dt/1e9:8.1f} GB/s")


def bench_gather_loop(R, iters=50):
    """50 chained col-gathers inside ONE jit dispatch (iteration-loop shape)."""
    def kernel(tab_ref, idx_ref, out_ref):
        def body(_, acc):
            return jnp.take_along_axis(acc, idx_ref[:], axis=0,
                                       mode="promise_in_bounds")
        out_ref[:] = jax.lax.fori_loop(0, iters, body, tab_ref[:])

    @jax.jit
    def run(tab, idx):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(tab, idx)

    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.random((R, 128), dtype=np.float32))
    idx = jnp.asarray(rng.integers(0, R, (R, 128)), dtype=jnp.int32)
    try:
        dt, _ = timeit(run, tab, idx, n=5)
    except Exception as e:  # noqa: BLE001
        print(f"  gather_loop R={R}: FAILED {type(e).__name__}: {str(e)[:160]}")
        return
    per = dt / iters
    print(f"  gather_loop R={R} x{iters}: {per*1e6:9.1f} us/gather "
          f"{R*128/per/1e9:8.2f} Gelem/s")


if __name__ == "__main__":
    print(f"platform: {jax.devices()[0].platform} interpret={INTERPRET}")
    print("col gather (axis=0, cross-sublane):")
    for R in (8, 64, 512, 2048, 8192):
        bench_col_gather(R)
    print("lane gather (axis=1):")
    for R in (8, 512, 8192):
        bench_lane_gather(R)
    print("streaming:")
    for MB in (64, 256):
        bench_stream(MB)
    print("gather in-loop:")
    bench_gather_loop(8192)
