"""Per-distance Benes-stage cost on chip, dispatch overhead subtracted.

Compares formulations of the masked pairwise swap at distance d:
  flip:  y = reshape(x,(N/2d,2,d)); sw = flip(y,1);      out = where(m,sw,x)
  xroll: sw = where(bit_d(i), roll(x,d), roll(x,-d));    out = where(m,sw,x)
  concat: sw = concat(x[d:2d],x[0:d],...) via reshape+slice swap
Also: roll cost (flat & axis0), in-loop einsum cost.

Method: time a chain of K stages (distinct masks, no CSE) minus an empty
dispatch, divide by K. Sync via 1-element host transfer. Internal deadline.
"""
import json
import sys
import time

DEADLINE = float(sys.argv[1]) if len(sys.argv) > 1 else 420.0
T0 = time.perf_counter()
N_LOG2 = 24
N = 1 << N_LOG2
K = 8  # stages per chain


def left():
    return DEADLINE - (time.perf_counter() - T0)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    results = {"platform": jax.devices()[0].platform}

    def measure(fn, *args, reps=3):
        out = fn(*args)
        _ = float(jnp.ravel(out)[0])
        best = float("inf")
        for _ in range(reps):
            t = time.perf_counter()
            out = fn(*args)
            _ = float(jnp.ravel(out)[0])
            best = min(best, time.perf_counter() - t)
        return best

    # empty dispatch baseline
    @jax.jit
    def nop(x):
        return x + 0.0

    xsmall = jnp.ones(8, jnp.float32)
    disp = measure(nop, xsmall, reps=5)
    results["dispatch_ms"] = round(disp * 1e3, 2)
    print(f"dispatch: {disp*1e3:.1f} ms", file=sys.stderr, flush=True)

    packed_np = rng.integers(0, 256, (K, N // 8), dtype=np.uint8)
    packed = jnp.asarray(packed_np)
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)

    def unpack(p):
        return ((p[:, None] >> shifts) & 1).reshape(N) != 0

    def chain_flip(x, packed, d):
        for s in range(K):
            m = unpack(packed[s])
            y = x.reshape(N // (2 * d), 2, d)
            sw = jnp.flip(y, axis=1).reshape(N)
            x = jnp.where(m, sw, x)
        return x

    def chain_xroll(x, packed, d, bit):
        for s in range(K):
            m = unpack(packed[s])
            sw = jnp.where(bit, jnp.roll(x, -d), jnp.roll(x, d))
            x = jnp.where(m, sw, x)
        return x

    dists = [1, 2, 8, 32, 128, 1 << 10, 1 << 14, 1 << 18, 1 << 22, 1 << 23]
    for d in dists:
        if left() < 60:
            break
        x = jnp.ones(N, jnp.bfloat16)
        f = jax.jit(lambda x, p, d=d: chain_flip(x, p, d))
        t = measure(f, x, packed)
        per = (t - disp) / K
        results[f"flip_d{d}_ms"] = round(per * 1e3, 3)
        print(f"flip d={d}: {per*1e3:.2f} ms/stage", file=sys.stderr,
              flush=True)

    for d in dists:
        if left() < 60:
            break
        bit_np = ((np.arange(N, dtype=np.int64) // d) & 1).astype(bool)
        bit = jnp.asarray(bit_np)
        x = jnp.ones(N, jnp.bfloat16)
        f = jax.jit(lambda x, p, b, d=d: chain_xroll(x, p, d, b))
        t = measure(f, x, packed, bit)
        per = (t - disp) / K
        results[f"xroll_d{d}_ms"] = round(per * 1e3, 3)
        print(f"xroll d={d}: {per*1e3:.2f} ms/stage", file=sys.stderr,
              flush=True)

    # plain roll cost, flat
    for d in (1, 128, 1 << 14, 1 << 22):
        if left() < 45:
            break
        x = jnp.ones(N, jnp.bfloat16)

        def chain_roll(x, d=d):
            for s in range(K):
                x = jnp.roll(x, d + s)  # vary shift to prevent CSE
            return x

        t = measure(jax.jit(chain_roll), x)
        results[f"roll_d{d}_ms"] = round((t - disp) / K * 1e3, 3)
        print(f"roll d={d}: {(t-disp)/K*1e3:.2f} ms", file=sys.stderr,
              flush=True)

    # roll along axis0 of (R,128) — the reduce-tree shape
    R = N // 128
    x2 = jnp.ones((R, 128), jnp.float32)
    mask2 = jnp.asarray(rng.random((K, R)) < 0.5)

    def chain_roll0(x, mask2):
        for s in range(K):
            x = x + mask2[s][:, None] * jnp.roll(x, -(1 << s), axis=0)
        return x

    t = measure(jax.jit(chain_roll0), x2, mask2)
    results["rolltree_stage_ms"] = round((t - disp) / K * 1e3, 3)
    print(f"rolltree: {(t-disp)/K*1e3:.2f} ms/stage", file=sys.stderr,
          flush=True)

    # in-loop einsums (expand + extract), dispatch-corrected
    G, R_G = 62, 1280
    oh = jnp.asarray(rng.random((G, R_G, 128)) < 0.008, jnp.bfloat16)

    def chain_expand(rank, oh):
        for s in range(K):
            t_ = jnp.einsum("grw,gwl->grl", oh, rank,
                            preferred_element_type=jnp.float32)
            rank = rank + t_[:, :128, :].astype(jnp.bfloat16) * 1e-9
        return rank

    rank = jnp.ones((G, 128, 128), jnp.bfloat16)
    t = measure(jax.jit(chain_expand), rank, oh)
    results["expand_einsum_ms"] = round((t - disp) / K * 1e3, 3)
    print(f"expand einsum: {(t-disp)/K*1e3:.2f} ms", file=sys.stderr,
          flush=True)

    C, R_C, K_C = 350, 256, 256
    ohe = jnp.asarray(rng.random((C, R_C, K_C)) < 0.004, jnp.bfloat16)

    def chain_extract(xc, ohe):
        for s in range(K):
            pc = jnp.einsum("cik,cil->ckl", ohe, xc,
                            preferred_element_type=jnp.float32)
            xc = xc + pc[:, :R_C, :].astype(jnp.bfloat16) * 1e-9
        return xc

    xc = jnp.ones((C, R_C, 128), jnp.bfloat16)
    t = measure(jax.jit(chain_extract), xc, ohe)
    results["extract_einsum_ms"] = round((t - disp) / K * 1e3, 3)
    print(f"extract einsum: {(t-disp)/K*1e3:.2f} ms", file=sys.stderr,
          flush=True)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
