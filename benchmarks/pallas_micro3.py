"""Microbench 3: lane-gather + transpose throughput, amortized in-loop.

These two ops are the primitives of the radix-routed PageRank kernel:
  - sandwich [lane-perm][transpose][lane-perm][transpose][lane-perm]
    realizes an arbitrary permutation of a (128,128) tile
  - a 2-stage radix-32 split built from sandwiches realizes the fixed
    CSR->CSC edge permutation
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

if len(sys.argv) > 1 and sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = jax.devices()[0].platform == "cpu"


def _sync(out):
    # transfer ONE element only: the tunnel moves ~25MB/s, so a full-array
    # transfer would swamp the measurement
    return float(np.asarray(out[:1, :1]))


def timeit1(fn, *args, n=3):
    _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        _sync(fn(*args))
    return (time.perf_counter() - t0) / n


def bench_lane_gather_loop(R=4096, iters=500):
    """Chained lane-gathers on (R,128) inside one pallas call."""
    def kernel(x_ref, idx_ref, o_ref):
        def body(_, acc):
            return jnp.take_along_axis(acc, idx_ref[:], axis=1,
                                       mode="promise_in_bounds") + 1.0
        o_ref[:] = jax.lax.fori_loop(0, iters, body, x_ref[:])

    @jax.jit
    def run(x, idx):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(x, idx)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((R, 128), dtype=np.float32))
    idx = jnp.asarray(rng.integers(0, 128, (R, 128)), dtype=jnp.int32)
    try:
        dt = timeit1(run, x, idx) / iters
    except Exception as e:  # noqa: BLE001
        print(f"  lane_gather_loop: FAILED {type(e).__name__}: {str(e)[:200]}")
        return
    print(f"  lane_gather R={R}: {dt*1e6:9.1f} us/op  "
          f"{R*128/dt/1e9:7.2f} Gelem/s")


def bench_transpose_loop(R=8192, iters=500):
    """Per-(128,128)-tile transpose over an (R,128) array, chained."""
    T = R // 128

    def kernel(x_ref, o_ref):
        def body(_, acc):
            # transpose each (128,128) tile; static unroll over tiles would
            # be huge, use reshape trick: (T,128,128) transpose last two dims
            a = acc.reshape(T, 128, 128)
            return jnp.swapaxes(a, 1, 2).reshape(R, 128) + 1.0
        o_ref[:] = jax.lax.fori_loop(0, iters, body, x_ref[:])

    @jax.jit
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(x)

    x = jnp.ones((R, 128), jnp.float32)
    try:
        dt = timeit1(run, x) / iters
    except Exception as e:  # noqa: BLE001
        print(f"  transpose_loop: FAILED {type(e).__name__}: {str(e)[:200]}")
        return
    print(f"  tiled transpose R={R}: {dt*1e6:9.1f} us/op  "
          f"{R*128/dt/1e9:7.2f} Gelem/s")


def bench_sandwich(R=4096, iters=200):
    """Full within-tile permutation sandwich: 3 lane-gathers + 2 transposes."""
    T = R // 128

    def kernel(x_ref, s1_ref, s2_ref, s3_ref, o_ref):
        def tr(a):
            return jnp.swapaxes(a.reshape(T, 128, 128), 1, 2).reshape(R, 128)

        def body(_, acc):
            a = jnp.take_along_axis(acc, s1_ref[:], axis=1,
                                    mode="promise_in_bounds")
            a = tr(a)
            a = jnp.take_along_axis(a, s2_ref[:], axis=1,
                                    mode="promise_in_bounds")
            a = tr(a)
            a = jnp.take_along_axis(a, s3_ref[:], axis=1,
                                    mode="promise_in_bounds")
            return a
        o_ref[:] = jax.lax.fori_loop(0, iters, body, x_ref[:])

    @jax.jit
    def run(x, s1, s2, s3):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(x, s1, s2, s3)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((R, 128), dtype=np.float32))
    idx = [jnp.asarray(rng.integers(0, 128, (R, 128)), dtype=jnp.int32)
           for _ in range(3)]
    try:
        dt = timeit1(run, x, *idx) / iters
    except Exception as e:  # noqa: BLE001
        print(f"  sandwich: FAILED {type(e).__name__}: {str(e)[:200]}")
        return
    print(f"  sandwich R={R}: {dt*1e6:9.1f} us/op  "
          f"{R*128/dt/1e9:7.2f} Gelem/s  (full tile perms)")


def bench_big_matmul(iters=500):
    """Reference point: (1024,2048)@(2048,128) matmul rate."""
    def kernel(a_ref, b_ref, o_ref):
        def body(_, acc):
            return acc + jnp.dot(a_ref[:], b_ref[:],
                                 preferred_element_type=jnp.float32)[:1024]
        o_ref[:] = jax.lax.fori_loop(
            0, iters, body, jnp.zeros((1024, 128), jnp.float32))

    @jax.jit
    def run(a, b):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1024, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(a, b)

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.random((1024, 2048), dtype=np.float32))
    b = jnp.asarray(rng.random((2048, 128), dtype=np.float32))
    try:
        dt = timeit1(run, a, b) / iters
    except Exception as e:  # noqa: BLE001
        print(f"  big_matmul: FAILED {type(e).__name__}: {str(e)[:200]}")
        return
    fl = 1024 * 2048 * 128 * 2
    print(f"  matmul 1024x2048x128: {dt*1e6:9.1f} us  {fl/dt/1e12:6.2f} Tflop/s")


if __name__ == "__main__":
    print(f"platform: {jax.devices()[0].platform}")
    bench_lane_gather_loop()
    bench_transpose_loop()
    bench_sandwich()
    bench_big_matmul()
