"""Streaming-insert vector index micro-bench: O(delta) refresh vs full
rebuild (VERDICT r3 item 6 'Done' criterion).

Run: python benchmarks/bench_vector_delta.py [n_vectors] [dim]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

from memgraph_tpu.procedures import vector_search as vs
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


def main(n=20_000, dim=64):
    db = InterpreterContext(InMemoryStorage())
    interp = Interpreter(db)
    rng = np.random.default_rng(0)

    t0 = time.perf_counter()
    acc = db.storage.access()
    pid = db.storage.property_mapper.name_to_id("emb")
    lid = db.storage.label_mapper.name_to_id("V")
    for i in range(n):
        v = acc.create_vertex()
        v.add_label(lid)
        v.set_property(pid, [float(x) for x in rng.random(dim)])
    acc.commit()
    print(f"seeded {n} x {dim} in {time.perf_counter()-t0:.2f}s")

    q = [1.0] + [0.0] * (dim - 1)

    def search():
        _, rows, _ = interp.execute(
            "CALL vector_search.search('emb', $q, 10) YIELD node, similarity "
            "RETURN count(node)", {"q": q})
        return rows

    t0 = time.perf_counter()
    search()
    full_s = time.perf_counter() - t0
    print(f"cold search (full build): {full_s:.3f}s")

    # streaming inserts: one commit + search per batch
    deltas = []
    for i in range(20):
        interp.execute("CREATE (:V {emb: $e})",
                       {"e": [float(x) for x in rng.random(dim)]})
        t0 = time.perf_counter()
        search()
        deltas.append(time.perf_counter() - t0)
    delta_s = sorted(deltas)[len(deltas) // 2]
    print(f"streaming search (delta refresh, median of 20): {delta_s:.3f}s")
    print(f"stats: {vs.STATS}")
    print(f"speedup vs full rebuild per insert: {full_s / delta_s:.1f}x")


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
