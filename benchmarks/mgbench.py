"""Workload benchmark driver (the reference's tests/mgbench analog).

Measures the host query engine over a live Bolt server with
Pokec-flavored workloads (/root/reference/tests/mgbench/workloads/pokec.py
methodology: isolated query groups, latency percentiles + throughput):

  point_read        MATCH (n:User {id: $id}) RETURN n.age
  one_hop           MATCH (n:User {id: $id})-[:FRIEND]->(m) RETURN count(m)
  two_hop           ... -[:FRIEND*2..2]-> ...
  property_update   SET on a matched vertex
  aggregate         global count/avg
  analytical        CALL pagerank.get() (device path)

Round 5 additions (VERDICT r4 item 4): a supernode-skew workload
(/root/reference/tests/mgbench/workloads/supernode.py — one hub node
with CARDINALITY in-edges), a multiprocess read-executor group
(server/mp_executor.py), and `--out OLTP_rN.json` so every round ships
a tracked OLTP artifact, not prose.

Usage: python benchmarks/mgbench.py [--nodes 10000] [--edges 50000]
                                    [--supernode 20000] [--out FILE]
Prints a JSON report; the driver-tracked artifact is OLTP_r{N}.json.
"""

from __future__ import annotations

import argparse
import json
import random
import statistics
import sys
import time


def percentiles(samples):
    s = sorted(samples)

    def pct(p):
        return s[min(int(p * len(s)), len(s) - 1)] * 1000

    return {"p50_ms": round(pct(0.50), 3), "p90_ms": round(pct(0.90), 3),
            "p99_ms": round(pct(0.99), 3),
            "mean_ms": round(statistics.mean(samples) * 1000, 3)}


def run_group(client, name, query, param_fn, iterations, warmup=0):
    """Fault-isolated: an error (e.g. unreachable device) yields an error
    entry instead of discarding the whole report."""
    try:
        for _ in range(warmup):  # discarded (JIT compilation etc.)
            client.execute(query, param_fn() if param_fn else None)
        samples = []
        for _ in range(iterations):
            params = param_fn() if param_fn else None
            t0 = time.perf_counter()
            client.execute(query, params)
            samples.append(time.perf_counter() - t0)
    except Exception as e:
        return {"name": name, "error": f"{type(e).__name__}: {e}"}
    total = sum(samples)
    return {"name": name, "iterations": iterations,
            "throughput_qps": round(iterations / total, 1),
            **percentiles(samples)}


def _loader_worker(port, n_nodes, n_edges, batch, queue):
    """Dataset loader in its OWN process: parameter generation and
    packstream encoding run on a separate GIL, so the measured load rate
    reflects the server's ingest path, not the bench client's CPU
    stealing the server process's GIL."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np
    from memgraph_tpu.server.client import BoltClient
    client = BoltClient(port=port, timeout=600.0)
    try:
        client.execute("CREATE INDEX ON :User(id)")
        t0 = time.perf_counter()
        for start in range(0, n_nodes, batch):
            ids = list(range(start, min(start + batch, n_nodes)))
            client.execute(
                "UNWIND $ids AS i CREATE (:User {id: i, age: i % 80})",
                {"ids": ids})
        nodes_s = time.perf_counter() - t0
        nprng = np.random.default_rng(7)
        t0 = time.perf_counter()
        for start in range(0, n_edges, batch):
            pairs = nprng.integers(
                0, n_nodes,
                size=(min(batch, n_edges - start), 2)).tolist()
            client.execute(
                "UNWIND $pairs AS p "
                "MATCH (a:User {id: p[0]}), (b:User {id: p[1]}) "
                "CREATE (a)-[:FRIEND]->(b)", {"pairs": pairs})
        edges_s = time.perf_counter() - t0
        queue.put((nodes_s, edges_s))
    finally:
        client.close()


def _client_worker(port, n_iter, n_nodes, barrier, queue):
    """Point-read loop in a separate process (own GIL). Waits on the
    barrier after import+connect+warmup so measured time excludes
    process startup, then reports its own (start, end) window."""
    import os
    import random as _random
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from memgraph_tpu.server.client import BoltClient
    c = BoltClient(port=port)
    try:
        local = _random.Random()
        for _ in range(20):   # warmup
            c.execute("MATCH (n:User {id: $id}) RETURN n.age",
                      {"id": local.randrange(n_nodes)})
        barrier.wait()
        t0 = time.perf_counter()
        for _ in range(n_iter):
            c.execute("MATCH (n:User {id: $id}) RETURN n.age",
                      {"id": local.randrange(n_nodes)})
        queue.put((t0, time.perf_counter(), n_iter))
    finally:
        c.close()


def _shard_plane_groups(args, groups):
    """The mgshard groups: sharded bulk load, threaded point reads,
    routed updates, cross-shard 2PC with an oracle check."""
    import threading
    from collections import defaultdict

    from memgraph_tpu.sharding import ShardPlane, ShardedClient
    from memgraph_tpu.sharding.partition import shard_for_key

    out = []
    n = args.shards
    print(f"loading {args.nodes} users into {n} shard workers ...",
          file=sys.stderr)
    plane = ShardPlane(n_shards=n).start()
    try:
        client = ShardedClient(plane)
        client.ddl("CREATE INDEX ON :User(id)")
        client.ddl("CREATE INDEX ON :Acct(id)")
        batch = 10_000
        t0 = time.perf_counter()
        for start in range(0, args.nodes, batch):
            per_shard = defaultdict(list)
            for i in range(start, min(start + batch, args.nodes)):
                per_shard[shard_for_key(i, n)].append(i)
            for _sid, ids in per_shard.items():
                client.write(
                    "UNWIND $ids AS i "
                    "CREATE (:User {id: i, age: i % 80})",
                    {"ids": ids}, key=ids[0])
        load_s = time.perf_counter() - t0
        out.append({"name": f"shard_load_{n}w", "workers": n,
                    "records_per_sec": round(args.nodes / load_s, 1)})

        rng = random.Random(11)
        for _ in range(50):    # warmup (parse/plan caches per worker)
            i = rng.randrange(args.nodes)
            client.read("MATCH (n:User {id: $id}) RETURN n.age",
                        {"id": i}, key=i)

        def pump(fn, per_thread, threads_n):
            t0 = time.perf_counter()

            def worker():
                local = random.Random()
                c = ShardedClient(plane)
                for _ in range(per_thread):
                    fn(c, local.randrange(args.nodes))
            threads = [threading.Thread(target=worker)
                       for _ in range(threads_n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return per_thread * threads_n / (time.perf_counter() - t0)

        per_thread = max(args.iterations // 2, 50)
        qps = pump(lambda c, i: c.read(
            "MATCH (n:User {id: $id}) RETURN n.age", {"id": i}, key=i),
            per_thread, n)
        read_group = {"name": f"point_read_sharded_{n}w", "workers": n,
                      "aggregate_qps": round(qps, 1)}
        one = next((g for g in groups
                    if g["name"] == "point_read_1_clients"
                    and "aggregate_qps" in g), None)
        if one:
            read_group["speedup_vs_single_process"] = round(
                qps / one["aggregate_qps"], 2)
        out.append(read_group)

        qps = pump(lambda c, i: c.write(
            "MATCH (n:User {id: $id}) SET n.age = n.age + 1",
            {"id": i}, key=i), max(per_thread // 2, 25), n)
        out.append({"name": f"property_update_sharded_{n}w",
                    "workers": n, "aggregate_qps": round(qps, 1)})

        # cross-shard 2PC: transfer pairs between accounts on distinct
        # shards; the oracle is arithmetic — total balance conserved,
        # every per-account balance equal to the locally-computed value
        accts = list(range(64))
        for a in accts:
            client.write("CREATE (:Acct {id: $id, bal: 100})",
                         {"id": a}, key=a)
        expected = {a: 100 for a in accts}
        iters = max(args.iterations // 3, 30)
        samples = []
        for k in range(iters):
            a, b = rng.sample(accts, 2)
            t0 = time.perf_counter()
            client.write_multi([
                (a, "MATCH (x:Acct {id: $id}) SET x.bal = x.bal - 1",
                 {"id": a}),
                (b, "MATCH (x:Acct {id: $id}) SET x.bal = x.bal + 1",
                 {"id": b}),
            ])
            samples.append(time.perf_counter() - t0)
            expected[a] -= 1
            expected[b] += 1
        _cols, rows = client.read("MATCH (x:Acct) RETURN sum(x.bal)")
        oracle_match = rows == [[100 * len(accts)]]
        for a in rng.sample(accts, 8):
            _c, r = client.read(
                "MATCH (x:Acct {id: $id}) RETURN x.bal", {"id": a},
                key=a)
            oracle_match = oracle_match and r == [[expected[a]]]
        out.append({"name": "cross_shard_write_2pc",
                    "iterations": iters,
                    "oracle_match": bool(oracle_match),
                    **percentiles(samples)})
    finally:
        plane.close()
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=10_000)
    p.add_argument("--edges", type=int, default=50_000)
    p.add_argument("--iterations", type=int, default=300)
    p.add_argument("--port", type=int, default=0,
                   help="existing server port (0 = spawn in-process)")
    p.add_argument("--clients", type=int, default=8,
                   help="connections for the multi-client scaling group")
    p.add_argument("--supernode", type=int, default=20_000,
                   help="in-degree of the supernode hub (0 = skip)")
    p.add_argument("--mp-workers", type=int, default=4,
                   help="processes for the mp-executor group (0 = skip)")
    p.add_argument("--shards", type=int, default=4,
                   help="shard workers for the mgshard plane group "
                        "(0 = skip)")
    p.add_argument("--out", default=None,
                   help="also write the JSON report to this file")
    args = p.parse_args()

    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from memgraph_tpu.query.interpreter import InterpreterContext
    from memgraph_tpu.server.bolt import BoltServer
    from memgraph_tpu.server.client import BoltClient
    from memgraph_tpu.storage import InMemoryStorage

    if args.port:
        port = args.port
    else:
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        server = BoltServer(InterpreterContext(InMemoryStorage()),
                            "127.0.0.1", port)
        server.run_in_thread()

    # wide timeout: load batches at 1M+ nodes can stall on GC/index
    # growth well past the 30s default
    client = BoltClient(port=port, timeout=600.0)
    rng = random.Random(7)

    print(f"loading {args.nodes} users / {args.edges} friendships ...",
          file=sys.stderr)
    # 10k-row batches: the bulk-write fast lane amortizes per-batch costs
    # (gid reservation, WAL record, index merge), so bigger batches are
    # strictly better until packstream frames dominate client memory.
    # The loader runs in its own process (own GIL) — see _loader_worker.
    batch = 10_000
    import multiprocessing as _mp
    _mp_ctx = _mp.get_context("spawn")
    _loader_q = _mp_ctx.Queue()
    loader = _mp_ctx.Process(target=_loader_worker,
                             args=(port, args.nodes, args.edges, batch,
                                   _loader_q))
    loader.start()
    nodes_s, edges_s = _loader_q.get()
    loader.join()
    load_s = nodes_s + edges_s
    print(f"  loaded in {load_s:.1f}s "
          f"({(args.nodes + args.edges) / load_s:,.0f} records/s; "
          f"nodes {args.nodes / nodes_s:,.0f}/s, "
          f"edges {args.edges / max(edges_s, 1e-9):,.0f}/s)",
          file=sys.stderr)

    rand_id = lambda: {"id": rng.randrange(args.nodes)}
    groups = [
        run_group(client, "point_read",
                  "MATCH (n:User {id: $id}) RETURN n.age", rand_id,
                  args.iterations),
        run_group(client, "one_hop",
                  "MATCH (n:User {id: $id})-[:FRIEND]->(m) RETURN count(m)",
                  rand_id, args.iterations),
        run_group(client, "two_hop",
                  "MATCH (n:User {id: $id})-[:FRIEND*2..2]->(m) "
                  "RETURN count(m)", rand_id, max(args.iterations // 3, 10)),
        run_group(client, "property_update",
                  "MATCH (n:User {id: $id}) SET n.age = n.age + 1", rand_id,
                  args.iterations),
        run_group(client, "aggregate",
                  "MATCH (n:User) RETURN count(n), avg(n.age)", None,
                  max(args.iterations // 10, 5)),
        # intra-query parallel execution (columnar scan+filter+aggregate)
        # vs the same work through the serial Volcano path (`n.age + 0`
        # makes the filter ineligible for the columnar rewrite)
        run_group(client, "scan_aggregate_parallel",
                  "MATCH (n:User) WHERE n.age > 40 "
                  "RETURN count(*), sum(n.age)", None,
                  max(args.iterations // 10, 5), warmup=1),
        run_group(client, "scan_aggregate_serial",
                  "MATCH (n:User) WHERE n.age + 0 > 40 "
                  "RETURN count(*), sum(n.age)", None,
                  max(args.iterations // 30, 3)),
    ]
    par = next((g for g in groups if g["name"] == "scan_aggregate_parallel"
                and "mean_ms" in g), None)
    ser = next((g for g in groups if g["name"] == "scan_aggregate_serial"
                and "mean_ms" in g), None)
    if par and ser:
        par["speedup_vs_serial"] = round(ser["mean_ms"] / par["mean_ms"], 1)

    # compiled read lane (r20 mglane): the two groups the lane exists
    # for — a filtered aggregate tail and a set-oriented two-hop count —
    # measured lane-ON (compiled device program) vs lane-OFF (the
    # serial row-at-a-time interpreter). The env toggles change PLAN
    # shape, so plans are invalidated between modes; this needs the
    # in-process server (an external --port server keeps its own env).
    lane_report = None
    if not args.port:
        import jax

        from memgraph_tpu.ops import pipeline as lane_pl

        LANE_AGG_Q = ("MATCH (n:User) WHERE n.age > 40 "
                      "RETURN count(*), sum(n.age), min(n.age), "
                      "max(n.age)")
        LANE_HOP_Q = ("MATCH (a:User)-[:FRIEND]->(b)-[:FRIEND]->(m) "
                      "WHERE a.age < 2 RETURN count(m)")

        def _lane_mode(off: bool) -> None:
            for k in ("MEMGRAPH_TPU_DISABLE_LANE",
                      "MEMGRAPH_TPU_DISABLE_PARALLEL"):
                if off:
                    os.environ[k] = "1"
                else:
                    os.environ.pop(k, None)
            server.ictx.invalidate_plans()

        def _m(name):
            from memgraph_tpu.observability.metrics import global_metrics
            return {n: v for n, _k, v
                    in global_metrics.snapshot()}.get(name, 0.0)

        print("compiled-lane groups (lane on/off) ...", file=sys.stderr)
        _lane_mode(False)
        hits0 = _m("lane.hit_total")
        groups.append(run_group(client, "aggregate_lane_on", LANE_AGG_Q,
                                None, max(args.iterations // 10, 5),
                                warmup=1))
        groups.append(run_group(client, "two_hop_lane_on", LANE_HOP_Q,
                                None, max(args.iterations // 30, 5),
                                warmup=1))
        lane_served = _m("lane.hit_total") > hits0
        resident_after_on = lane_pl.resident_programs()
        _lane_mode(True)
        groups.append(run_group(client, "aggregate_lane_off",
                                LANE_AGG_Q, None, 3))
        groups.append(run_group(client, "two_hop_lane_off", LANE_HOP_Q,
                                None, 3))
        _lane_mode(False)
        for on_name, off_name in (("aggregate_lane_on",
                                   "aggregate_lane_off"),
                                  ("two_hop_lane_on",
                                   "two_hop_lane_off")):
            on = next((g for g in groups if g["name"] == on_name
                       and "p99_ms" in g), None)
            off = next((g for g in groups if g["name"] == off_name
                        and "p99_ms" in g), None)
            if on and off:
                on["p99_speedup_vs_serial"] = round(
                    off["p99_ms"] / max(on["p99_ms"], 1e-9), 1)
        backend = jax.default_backend()
        lane_report = {
            "backend": backend,
            # honesty: a CPU-host lane number is a machinery proof, not
            # the accelerator headline
            "degraded": backend == "cpu",
            "lane_served": bool(lane_served),
            "resident_programs": resident_after_on,
        }

    # multi-client scaling: N concurrent connections hammering point
    # reads. Clients run as separate PROCESSES so their encode/decode CPU
    # doesn't share the server's GIL; server-side execution runs on the
    # Bolt worker pool.
    import multiprocessing as mp

    mp_ctx = mp.get_context("spawn")
    for n_clients in (1, args.clients):
        barrier = mp_ctx.Barrier(n_clients)
        queue = mp_ctx.Queue()
        procs = [mp_ctx.Process(
            target=_client_worker,
            args=(port, args.iterations, args.nodes, barrier, queue))
            for _ in range(n_clients)]
        for t in procs:
            t.start()
        try:
            spans = [queue.get(timeout=120) for _ in range(n_clients)]
        except Exception as e:   # a dead worker must not hang the bench
            for t in procs:
                t.terminate()
            groups.append({
                "name": f"point_read_{n_clients}_clients",
                "clients": n_clients,
                "error": f"{type(e).__name__}: worker died or timed out"})
            continue
        finally:
            for t in procs:
                t.join(timeout=10)
        total = sum(s[2] for s in spans)
        wall = max(s[1] for s in spans) - min(s[0] for s in spans)
        groups.append({
            "name": f"point_read_{n_clients}_clients",
            "clients": n_clients,
            "aggregate_qps": round(total / wall, 1),
        })
    one = next((g for g in groups
                if g["name"] == "point_read_1_clients"
                and "aggregate_qps" in g), None)
    many = next((g for g in groups
                 if g["name"] == f"point_read_{args.clients}_clients"
                 and "aggregate_qps" in g), None)
    if one and many:
        many["scaling_vs_1_client"] = round(
            many["aggregate_qps"] / one["aggregate_qps"], 2)
    # supernode skew (reference workload: one hub, CARDINALITY spokes):
    # expansion over the hub, hub-touching writes, MERGE over the hub
    if args.supernode:
        print(f"loading supernode hub with {args.supernode} spokes ...",
              file=sys.stderr)
        client.execute("CREATE INDEX ON :SNode(id)")
        client.execute("CREATE INDEX ON :Supernode")
        client.execute("CREATE INDEX ON :Supernode(id)")
        client.execute("CREATE (:Supernode {id: 0})")
        for start in range(0, args.supernode, batch):
            ids = list(range(start, min(start + batch, args.supernode)))
            client.execute(
                "MATCH (s:Supernode {id: 0}) UNWIND $ids AS i "
                "CREATE (s)<-[:EDGE]-(:SNode {id: i})", {"ids": ids})
        groups += [
            run_group(client, "supernode_expand_count",
                      "MATCH (s:Supernode {id: 0})<-[:EDGE]-(n) "
                      "RETURN count(n)", None,
                      max(args.iterations // 10, 5), warmup=1),
            run_group(client, "supernode_two_hop",
                      "MATCH (n:SNode {id: $id})-[:EDGE]->(s)"
                      "<-[:EDGE]-(m) RETURN count(m)",
                      lambda: {"id": rng.randrange(args.supernode)},
                      max(args.iterations // 30, 3)),
            run_group(client, "supernode_unwind_writes",
                      f"UNWIND range(1, {args.supernode}) AS x "
                      "MATCH (s:Supernode {id: 0}) SET s.prop = x", None,
                      max(args.iterations // 30, 3)),
            run_group(client, "supernode_merge_edges",
                      "MATCH (s:Supernode {id: 0}), (n:SNode {id: $id}) "
                      "MERGE (s)<-[:EDGE]-(n)",
                      lambda: {"id": rng.randrange(args.supernode)},
                      max(args.iterations // 3, 10)),
        ]

    # multiprocess read executor (server/mp_executor.py): same point
    # reads dispatched over N forked workers with independent GILs —
    # the architectural answer to the GIL ceiling (1-core hosts show ~1x)
    if args.mp_workers and not args.port:
        import threading as _threading
        from memgraph_tpu.server.mp_executor import MPReadExecutor
        ex = MPReadExecutor(server.ictx, n_workers=args.mp_workers)
        try:
            for _ in range(20):
                ex.execute("MATCH (n:User {id: $id}) RETURN n.age",
                           {"id": rng.randrange(args.nodes)})
            per_thread = max(args.iterations // 2, 50)
            t0 = time.perf_counter()

            def _pump():
                local = random.Random()
                for _ in range(per_thread):
                    ex.execute("MATCH (n:User {id: $id}) RETURN n.age",
                               {"id": local.randrange(args.nodes)})
            threads = [_threading.Thread(target=_pump)
                       for _ in range(args.mp_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            groups.append({
                "name": f"point_read_mp_executor_{args.mp_workers}w",
                "workers": args.mp_workers,
                "aggregate_qps": round(per_thread * args.mp_workers / wall,
                                       1)})
        except Exception as e:  # noqa: BLE001
            groups.append({"name": "point_read_mp_executor",
                           "error": f"{type(e).__name__}: {e}"})
        finally:
            ex.close()

    # sharded OLTP execution plane (r18, mgshard): the same dataset
    # hash-sharded across N worker PROCESSES (each its own storage +
    # WAL + GIL), point reads/writes routed by key, plus the
    # cross-shard 2PC write group with an arithmetic oracle check.
    # The honest comparison target is the single-process 1-client Bolt
    # aggregate (point_read_1_clients) — the number the plane exists
    # to multiply past the GIL.
    if args.shards:
        groups += _shard_plane_groups(args, groups)

    client.close()
    # the analytical group gets its own client with a wide timeout (first
    # CALL pays XLA compilation) and one discarded warm-up run
    analytical = BoltClient(port=port, timeout=600.0)
    groups.append(run_group(
        analytical, "analytical_pagerank",
        "CALL pagerank.get() YIELD rank RETURN max(rank)", None, 3,
        warmup=1))
    analytical.close()
    # honesty tags (the r06 lesson, applied to OLTP): shard scaling on
    # fewer cores than workers measures contention, not the
    # architecture — such a record is DEGRADED and the perf gate must
    # never accept it as the scaling headline
    cores = os.cpu_count() or 1
    report = {"workload": "pokec-flavored+supernode", "nodes": args.nodes,
              "edges": args.edges, "supernode_degree": args.supernode,
              "cores": cores,
              "shard_workers": args.shards,
              "degraded": bool(args.shards and cores < args.shards),
              "load_records_per_sec":
              round((args.nodes + args.edges) / load_s, 1),
              "groups": groups}
    if lane_report is not None:
        report["lane"] = lane_report
    if report["degraded"]:
        report["degraded_reason"] = (
            f"host has {cores} core(s) for {args.shards} shard "
            "workers; scaling numbers are contention-bound")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
