"""Microbench round 2: the permutation cost question + pallas primitive costs.

Decides the fused-PageRank design: if XLA can apply a FIXED 12M-element
permutation fast (banded or not), the kernel is [pallas gather] -> [XLA
permute] -> [pallas scatter]. Otherwise the permute must be a pallas
routing network.

All timings amortized inside one jit dispatch via fori_loop where possible.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

if len(sys.argv) > 1 and sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = jax.devices()[0].platform == "cpu"
E = 12 * 1024 * 1024


def _sync(out):
    # host transfer forces completion; block_until_ready is unreliable on
    # the tunneled platform
    return float(np.asarray(out).ravel()[0])


def timeit1(fn, *args, n=3):
    _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        s = _sync(fn(*args))
    dt = (time.perf_counter() - t0) / n
    return dt


def bench_xla_take(name, idx, iters=10):
    """jnp.take looped inside one dispatch (cost amortized)."""
    idx = jnp.asarray(idx, dtype=jnp.int32)

    @jax.jit
    def run(x, idx):
        def body(_, acc):
            return jnp.take(acc, idx, unique_indices=False,
                            indices_are_sorted=False) * 1.0000001
        return jax.lax.fori_loop(0, iters, body, x)

    x = jnp.arange(E, dtype=jnp.float32)
    try:
        dt = timeit1(run, x, idx) / iters
    except Exception as e:  # noqa: BLE001
        print(f"  take/{name}: FAILED {type(e).__name__}: {str(e)[:160]}")
        return
    print(f"  take/{name}: {dt*1e3:8.2f} ms/pass  {E/dt/1e6:9.0f} Melem/s")


def bench_dynslice_gather(iters=200):
    """G2 primitive: per-tile 8-row dyn slice + axis-0 gather, looped over
    a big edge array: grid over tiles, fori inside for iterations."""
    R_EDGES = E // 128  # rows of edge slots
    TILE = 512          # rows per grid step (512*128 = 64K edges)
    RANK_R = 8192

    def kernel(grp_ref, row3_ref, rank_ref, out_ref):
        # grp_ref: (TILE//8, 1) int32 in SMEM-ish VMEM: src group per 8-row blk
        def do_block(b, _):
            g = grp_ref[b, 0]
            win = rank_ref[pl.ds(g * 8, 8), :]          # (8,128) dyn slice
            idx = row3_ref[pl.ds(b * 8, 8), :]
            vals = jnp.take_along_axis(win, idx, axis=0,
                                       mode="promise_in_bounds")
            out_ref[pl.ds(b * 8, 8), :] = vals
            return 0
        jax.lax.fori_loop(0, TILE // 8, do_block, 0)

    @jax.jit
    def run(grp, row3, rank):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R_EDGES, 128), jnp.float32),
            grid=(R_EDGES // TILE,),
            in_specs=[
                pl.BlockSpec((TILE // 8, 1), lambda i: (i, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((TILE, 128), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),  # rank fully resident
            ],
            out_specs=pl.BlockSpec((TILE, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(grp, row3, rank)

    rng = np.random.default_rng(0)
    grp = jnp.asarray(rng.integers(0, RANK_R // 8, (R_EDGES // 8, 1)),
                      dtype=jnp.int32)
    row3 = jnp.asarray(rng.integers(0, 8, (R_EDGES, 128)), dtype=jnp.int32)
    rank = jnp.asarray(rng.random((RANK_R, 128), dtype=np.float32))
    try:
        dt = timeit1(run, grp, row3, rank)
    except Exception as e:  # noqa: BLE001
        print(f"  g2_gather: FAILED {type(e).__name__}: {str(e)[:300]}")
        return
    print(f"  g2_gather: {dt*1e3:8.2f} ms/pass  {E/dt/1e6:9.0f} Melem/s")


def bench_onehot_scatter():
    """S3 primitive: per-tile one-hot matmul scatter into a dst-block row."""
    R_EDGES = E // 128
    TILE = 512  # 64K edges per grid step; 64 dst-block sub-tiles of 8 rows
    ACC_R = 8192

    def kernel(dblk_ref, lane_ref, val_ref, acc_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def do_block(b, _):
            d = dblk_ref[b, 0]
            lanes = lane_ref[pl.ds(b * 8, 8), :]          # (8,128) int32
            vals = val_ref[pl.ds(b * 8, 8), :]            # (8,128) f32
            cols = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
            # contribution of this 1024-edge block to dst-block d:
            # onehot.T @ vals — but batched per sublane won't matmul; use
            # the flat trick: sum over sublanes of per-sublane one-hot rows
            # expressed as (8,128) mask-multiply + matmul with ones.
            # out[l] = sum_{s,e} vals[s,e] * (lanes[s,e]==l)
            del cols
            # loop sublanes: build (128,128) one-hot via static slice +
            # transpose-free broadcast, then (1,128)@(128,128) on the MXU
            total = jnp.zeros((1, 128), jnp.float32)
            col_iota = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1)
            for s in range(8):
                lane_col = lanes[s:s+1, :].reshape(128, 1)    # (128,1)
                oh = (lane_col == col_iota).astype(jnp.float32)
                total = total + jnp.dot(vals[s:s+1, :], oh,
                                        preferred_element_type=jnp.float32)
            acc_ref[pl.ds(d, 1), :] += total
            return 0
        jax.lax.fori_loop(0, TILE // 8, do_block, 0)

    @jax.jit
    def run(dblk, lanes, vals):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((ACC_R, 128), jnp.float32),
            grid=(R_EDGES // TILE,),
            in_specs=[
                pl.BlockSpec((TILE // 8, 1), lambda i: (i, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((TILE, 128), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((TILE, 128), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=INTERPRET,
        )(dblk, lanes, vals)

    rng = np.random.default_rng(0)
    dblk = jnp.asarray(rng.integers(0, ACC_R, (R_EDGES // 8, 1)),
                       dtype=jnp.int32)
    lanes = jnp.asarray(rng.integers(0, 128, (R_EDGES, 128)), dtype=jnp.int32)
    vals = jnp.asarray(rng.random((R_EDGES, 128), dtype=np.float32))
    try:
        dt = timeit1(run, dblk, lanes, vals)
    except Exception as e:  # noqa: BLE001
        print(f"  s3_scatter: FAILED {type(e).__name__}: {str(e)[:300]}")
        return
    print(f"  s3_scatter: {dt*1e3:8.2f} ms/pass  {E/dt/1e6:9.0f} Melem/s")


if __name__ == "__main__":
    print(f"platform: {jax.devices()[0].platform}")
    rng = np.random.default_rng(1)
    print("XLA take on 12M elements (amortized in-loop):")
    bench_xla_take("random_dup", rng.integers(0, E, E))
    bench_xla_take("random_perm", rng.permutation(E))
    # banded perm: within blocks of 64K, a random permutation
    B = 65536
    banded = (np.arange(E) // B) * B + np.concatenate(
        [rng.permutation(B) for _ in range(E // B)])
    bench_xla_take("banded_perm_64K", banded)
    bench_xla_take("identity", np.arange(E))
    print("pallas primitives:")
    bench_dynslice_gather()
    bench_onehot_scatter()
