"""Betweenness at scale (VERDICT r4 item 10): sampled Brandes on a
1M-node / 10M-edge graph with the autotuned (B, n_pad) chunking,
correctness-anchored by exact parity at small scale.

Usage: python benchmarks/bench_betweenness.py [--nodes N] [--edges E]
       [--samples 64] [--out BETWEENNESS_r05.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--edges", type=int, default=10_000_000)
    ap.add_argument("--samples", type=int, default=64)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from memgraph_tpu.utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()
    import jax
    from memgraph_tpu.ops.betweenness import (autotune_chunk,
                                              betweenness_centrality)
    from memgraph_tpu.ops.csr import from_coo
    import bench as B

    report = {"nodes": args.nodes, "edges": args.edges,
              "samples": args.samples,
              "platform": jax.devices()[0].platform}

    # correctness anchor: exact parity vs networkx at small scale
    import networkx as nx
    rng = np.random.default_rng(0)
    sn, se = 300, 1500
    s_small = rng.integers(0, sn, se)
    d_small = rng.integers(0, sn, se)
    g_small = from_coo(s_small, d_small, n_nodes=sn)
    got = np.asarray(betweenness_centrality(g_small, directed=True))
    G = nx.DiGraph()
    G.add_nodes_from(range(sn))
    G.add_edges_from(zip(s_small.tolist(), d_small.tolist()))
    want = np.array([nx.betweenness_centrality(G)[i] for i in range(sn)])
    parity = bool(np.allclose(got, want, atol=1e-6))
    report["small_scale_exact_parity"] = parity
    print(f"small-scale parity vs networkx: {parity}", file=sys.stderr)

    # scale run
    src, dst = B.generate_graph(args.nodes, args.edges, seed=7)
    graph = from_coo(src, dst, n_nodes=args.nodes)
    chunk = autotune_chunk(args.edges, graph.n_pad)
    report["autotuned_chunk"] = chunk
    print(f"autotuned chunk at {args.edges:,} edges: B={chunk}",
          file=sys.stderr)
    t0 = time.perf_counter()
    bc = betweenness_centrality(graph, directed=True,
                                samples=args.samples, chunk=chunk,
                                max_levels=64)
    top = np.argsort(-np.asarray(bc))[:10]
    _ = float(np.asarray(bc)[0])
    elapsed = time.perf_counter() - t0
    report["seconds"] = round(elapsed, 2)
    report["sources_per_sec"] = round(args.samples / elapsed, 2)
    report["top10_nodes"] = [int(x) for x in top]
    report["ok"] = parity and elapsed > 0
    print(f"{args.samples} sources in {elapsed:.1f}s "
          f"({args.samples / elapsed:.2f} src/s)", file=sys.stderr)
    out = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    print(out)


if __name__ == "__main__":
    main()
