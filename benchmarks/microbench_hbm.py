"""On-chip microbench: large-array streaming rates for the kernel's primitive
mix (FMA stream, Benes masked-swap stage, roll, one-hot einsum, transpose).

Safety per docs/kernel_design_r2.md: runs with an internal deadline and
exits cleanly (never SIGTERM a process with in-flight TPU work). Sync via
1-element host transfer (block_until_ready unreliable on this platform).

Usage: python benchmarks/microbench_hbm.py [deadline_s]
"""
import json
import sys
import time

DEADLINE = float(sys.argv[1]) if len(sys.argv) > 1 else 240.0
T0 = time.perf_counter()


def left():
    return DEADLINE - (time.perf_counter() - T0)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    results = {"platform": jax.devices()[0].platform}

    def timeit(name, fn, *args, iters_in_loop=1, reps=2):
        """fn must be jitted and return an array; sync via 1-elem transfer."""
        if left() < 20:
            results[name] = None
            return None
        out = fn(*args)
        _ = float(jnp.ravel(out)[0])  # compile+warm
        best = float("inf")
        for _ in range(reps):
            t = time.perf_counter()
            out = fn(*args)
            _ = float(jnp.ravel(out)[0])
            best = min(best, time.perf_counter() - t)
        per = best / iters_in_loop
        results[name] = round(per * 1e3, 3)  # ms per inner iteration
        print(f"{name}: {per*1e3:.3f} ms", file=sys.stderr, flush=True)
        return per

    # 1) FMA stream at several working-set sizes: x = a*x + b, L loop iters
    for m_elems in (4, 16, 32, 64):
        n = m_elems * 1024 * 1024
        L = 20

        @partial(jax.jit, static_argnames=())
        def fma_loop(x):
            def body(i, x):
                return x * 1.000001 + 1e-9
            return jax.lax.fori_loop(0, L, body, x)

        x = jnp.ones(n, jnp.float32)
        per = timeit(f"fma_{m_elems}M_f32_ms", fma_loop, x, iters_in_loop=L)
        if per:
            gbs = 2 * 4 * n / per / 1e9
            results[f"fma_{m_elems}M_f32_gbs"] = round(gbs, 1)
            print(f"  -> {gbs:.0f} GB/s", file=sys.stderr, flush=True)

    # 2) Benes radix-2 stage chain at N=2^24, f32 vs bf16, bool masks
    N = 1 << 24
    rng = np.random.default_rng(0)
    nstages = 8  # representative distances, incl. small + large
    dists = [1 << k for k in (23, 20, 16, 12, 8, 4, 1, 0)]
    masks_np = rng.random((nstages, N)) < 0.5

    def benes_chain(x, masks):
        for s, d in enumerate(dists):
            d = max(d, 1)
            y = x.reshape(N // (2 * d), 2, d)
            sw = jnp.flip(y, axis=1).reshape(N)
            x = jnp.where(masks[s], sw, x)
        return x

    for dt, tag in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
        x = jnp.ones(N, dt)
        masks = jnp.asarray(masks_np)
        jitted = jax.jit(lambda x, m: benes_chain(x, m))
        per = timeit(f"benes8_{tag}_ms", jitted, x, masks, iters_in_loop=8)
        if per:
            results[f"benes8_{tag}_gbs"] = round(
                (2 * x.dtype.itemsize + 1) * N / per / 1e9, 1)

    # 2b) same but masks unpacked on the fly from packed bits
    packed_np = np.packbits(masks_np, axis=1)

    def benes_chain_packed(x, packed):
        shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
        for s, d in enumerate(dists):
            d = max(d, 1)
            bits = ((packed[s][:, None] >> shifts) & 1).reshape(N) != 0
            y = x.reshape(N // (2 * d), 2, d)
            sw = jnp.flip(y, axis=1).reshape(N)
            x = jnp.where(bits, sw, x)
        return x

    x = jnp.ones(N, jnp.bfloat16)
    packed = jnp.asarray(packed_np)
    timeit("benes8_bf16_packedmask_ms", jax.jit(benes_chain_packed), x,
           packed, iters_in_loop=8)

    # 2c) radix-4 stage: 4-way rotate + 2-bit select
    sel_np = rng.integers(0, 4, N).astype(np.int8)

    def radix4_chain(x, sel):
        for d in (1 << 22, 1 << 12, 1 << 2, 1):
            y = x.reshape(N // (4 * d), 4, d)
            r1 = jnp.roll(y, -1, axis=1).reshape(N)
            r2 = jnp.roll(y, -2, axis=1).reshape(N)
            r3 = jnp.roll(y, -3, axis=1).reshape(N)
            x0 = x
            lo = jnp.where((sel & 1) != 0, r1, x0)
            hi = jnp.where((sel & 1) != 0, r3, r2)
            x = jnp.where((sel & 2) != 0, hi, lo)
        return x

    x = jnp.ones(N, jnp.bfloat16)
    sel = jnp.asarray(sel_np)
    timeit("radix4x4_bf16_ms", jax.jit(radix4_chain), x, sel,
           iters_in_loop=4)

    # 3) one-hot extract einsum (C,R_C,K_C)x(C,R_C,128), static bf16 one-hot
    C, R_C, K_C = 350, 256, 256
    ohe = jnp.asarray(rng.random((C, R_C, K_C)) < 0.004, jnp.bfloat16)
    xc = jnp.ones((C, R_C, 128), jnp.bfloat16)

    @jax.jit
    def extract(ohe, xc):
        return jnp.einsum("cik,cil->ckl", ohe, xc,
                          preferred_element_type=jnp.float32)

    timeit("extract_einsum_bf16_ms", extract, ohe, xc)

    # 4) big transpose
    A = 4096
    xt = jnp.ones((A, A), jnp.float32)
    timeit("transpose_4096_ms", jax.jit(lambda x: x.T + 0.0), xt)

    # 5) expand einsum at real plan shape: oh (62,1280,128) x (62,128,128)
    G, R_G = 62, 1280
    oh = jnp.asarray(rng.random((G, R_G, 128)) < 0.008, jnp.bfloat16)
    rank = jnp.ones((G, 128, 128), jnp.bfloat16)

    @jax.jit
    def expand(oh, rank):
        return jnp.einsum("grw,gwl->grl", oh, rank,
                          preferred_element_type=jnp.float32)

    timeit("expand_einsum_bf16_ms", expand, oh, rank)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
