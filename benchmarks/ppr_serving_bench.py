"""PPR serving-plane load generator: concurrent clients vs the
coalescing kernel server.

Measures the tentpole claim of the serving plane (ISSUE 11 / ROADMAP
item 1): with >= 32 concurrent clients, request coalescing turns N
per-user PPR point queries into one (n, B) SpMM fixpoint per window, so
sustained QPS beats the sequential one-request-at-a-time baseline on
the SAME host by the batch amortization factor. Records (honest
``degraded``/``backend`` tags, same contract as bench.py):

  * sequential baseline QPS + p50/p99 (one client, one in-flight
    request, cold sources — the pre-serving-plane cost model);
  * concurrent QPS + p50/p99 with the measured COALESCING RATIO
    (requests per executed batch, from the server's ppr.* counters);
  * cache hit rate on a repeated working set (the per-user steady
    state);
  * batched-vs-sequential f32 BIT-EXACTNESS spot check.

Writes BENCH_ppr_r*.json (never BENCH_r*.json — the headline pagerank
record keeps its own series) and prints the record as one JSON line;
tools/perf_gate.py checks it against BASELINE.json's ``ppr_qps``
envelope on accelerator hosts.

Usage:
    python benchmarks/ppr_serving_bench.py [--clients 32] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "BENCH_ppr_r11.json")

# serving-shaped graph: large enough that a request is real device
# work, small enough that the sequential baseline finishes in seconds
N_NODES = 20_000
N_EDGES = 120_000
SEQ_REQUESTS = 80
CONC_REQUESTS_PER_CLIENT = 25
CACHE_POOL = 64
TOL = 1e-6


def _quantiles(lat_s):
    lat = np.sort(np.asarray(lat_s))
    if lat.size == 0:
        return 0.0, 0.0
    return (float(lat[int(0.50 * (lat.size - 1))] * 1e3),
            float(lat[int(0.99 * (lat.size - 1))] * 1e3))


def _metric(name):
    from memgraph_tpu.observability.metrics import global_metrics
    return dict((n, v) for n, _k, v in global_metrics.snapshot()).get(
        name, 0.0)


def _connect(kernel_client_cls, sock, timeout=600, attempts=50):
    """Connect with retry: a burst of simultaneous connects can briefly
    outrun even a deep accept queue."""
    for _ in range(attempts):
        try:
            return kernel_client_cls(sock, timeout=timeout)
        except OSError:
            time.sleep(0.05)
    return kernel_client_cls(sock, timeout=timeout)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--nodes", type=int, default=N_NODES)
    ap.add_argument("--edges", type=int, default=N_EDGES)
    args = ap.parse_args(argv)

    import jax

    from memgraph_tpu.ops import csr
    from memgraph_tpu.ops.pagerank import personalized_pagerank
    from memgraph_tpu.server.kernel_server import KernelClient, KernelServer

    backend = jax.default_backend()
    degraded = backend == "cpu"

    rng = np.random.default_rng(11)
    src = rng.integers(0, args.nodes, args.edges)
    dst = rng.integers(0, args.nodes, args.edges)

    sock = os.path.join(tempfile.mkdtemp(prefix="pprbench"), "ks.sock")
    srv = KernelServer(sock, wedge_after_s=120)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    deadline = time.monotonic() + 120
    seed_client = None
    while time.monotonic() < deadline:
        try:
            seed_client = KernelClient(sock, timeout=300)
            break
        except OSError:
            time.sleep(0.05)
    assert seed_client is not None, "kernel server never bound"

    # stage the graph + compile the batch kernels once (honest steady
    # state: serving traffic never pays the first-compile)
    print(f"ppr-bench: staging graph ({args.nodes} nodes, "
          f"{args.edges} edges) on backend={backend} ...", flush=True)
    seed_client.ppr([0], src=src, dst=dst, n_nodes=args.nodes,
                    graph_key="bench", graph_version=1, tol=TOL)
    warm_sources = [[int(s)] for s in
                    rng.choice(args.nodes, size=srv._ppr.max_batch,
                               replace=False)]
    warm_threads = []
    for s in warm_sources:     # compile the wide-batch buckets up front
        def _w(ss=s):
            c = _connect(KernelClient, sock)
            c.ppr(ss, graph_key="bench", graph_version=1,
                  n_nodes=args.nodes, tol=TOL)
            c.close()
        t = threading.Thread(target=_w)
        t.start()
        warm_threads.append(t)
    for t in warm_threads:
        t.join()

    # --- sequential baseline: one client, one in-flight request -----------
    print("ppr-bench: sequential baseline ...", flush=True)
    seq_lat = []
    seq_sources = rng.choice(args.nodes, size=SEQ_REQUESTS, replace=False)
    t0 = time.perf_counter()
    for s in seq_sources:
        t1 = time.perf_counter()
        seed_client.ppr([int(s) + 0], graph_key="bench", graph_version=1,
                        n_nodes=args.nodes, tol=TOL, top_k=10)
        seq_lat.append(time.perf_counter() - t1)
    seq_wall = time.perf_counter() - t0
    seq_qps = SEQ_REQUESTS / seq_wall
    seq_p50, seq_p99 = _quantiles(seq_lat)

    # --- concurrent phase: the coalescing claim ----------------------------
    print(f"ppr-bench: {args.clients} concurrent clients ...", flush=True)
    req_before = _metric("ppr.requests_total")
    batch_before = _metric("ppr.batches_total")
    conc_lat = []
    lat_lock = threading.Lock()
    total = args.clients * CONC_REQUESTS_PER_CLIENT
    conc_sources = rng.integers(0, args.nodes, size=(args.clients,
                                                     CONC_REQUESTS_PER_CLIENT,
                                                     2))
    barrier = threading.Barrier(args.clients + 1)
    check_pool: list = []

    def client_loop(ci):
        c = _connect(KernelClient, sock)
        mine = []
        try:
            barrier.wait(timeout=120)
            for ri in range(CONC_REQUESTS_PER_CLIENT):
                sources = sorted(int(s) for s in set(conc_sources[ci, ri]))
                t1 = time.perf_counter()
                _h, out = c.ppr(sources, graph_key="bench",
                                graph_version=1, n_nodes=args.nodes,
                                tol=TOL)
                mine.append(time.perf_counter() - t1)
                if ci == 0 and ri < 3:
                    check_pool.append((sources, out["ranks"]))
        finally:
            with lat_lock:
                conc_lat.extend(mine)
            c.close()

    threads = [threading.Thread(target=client_loop, args=(ci,))
               for ci in range(args.clients)]
    for t in threads:
        t.start()
    barrier.wait(timeout=120)
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    conc_wall = time.perf_counter() - t0
    conc_qps = len(conc_lat) / conc_wall
    conc_p50, conc_p99 = _quantiles(conc_lat)
    req_delta = _metric("ppr.requests_total") - req_before
    batch_delta = max(_metric("ppr.batches_total") - batch_before, 1.0)
    coalescing_ratio = req_delta / batch_delta

    # --- cache phase: repeated working set ---------------------------------
    print("ppr-bench: cache working set ...", flush=True)
    hit_before = _metric("ppr.cache_hit_total")
    pool = [[int(s)] for s in rng.choice(args.nodes, size=CACHE_POOL,
                                         replace=False)]
    cache_lat = []
    for _round in range(2):
        for sources in pool:
            t1 = time.perf_counter()
            seed_client.ppr(sources, graph_key="bench", graph_version=1,
                            n_nodes=args.nodes, tol=TOL, top_k=10)
            cache_lat.append(time.perf_counter() - t1)
    hits = _metric("ppr.cache_hit_total") - hit_before
    cache_hit_rate = hits / (2 * CACHE_POOL)
    cache_p50, cache_p99 = _quantiles(cache_lat)

    # --- bit-exactness spot check ------------------------------------------
    g = csr.from_coo(src, dst, n_nodes=args.nodes).to_device()
    bit_exact = True
    for sources, ranks in check_pool:
        want, _, _ = personalized_pagerank(g, sources, tol=TOL)
        if not np.array_equal(np.asarray(want),
                              np.asarray(ranks)[:args.nodes]):
            bit_exact = False
    seed_client.shutdown()
    seed_client.close()

    record = {
        "metric": "ppr_qps",
        "value": round(conc_qps, 2),
        "unit": "requests/sec sustained",
        "degraded": degraded,
        "backend": backend,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "extra": {
            "graph": {"nodes": args.nodes, "edges": args.edges},
            "clients": args.clients,
            "requests": {"sequential": SEQ_REQUESTS,
                         "concurrent": int(len(conc_lat)),
                         "cache": 2 * CACHE_POOL},
            "sequential": {"qps": round(seq_qps, 2),
                           "p50_ms": round(seq_p50, 3),
                           "p99_ms": round(seq_p99, 3)},
            "concurrent": {"qps": round(conc_qps, 2),
                           "p50_ms": round(conc_p50, 3),
                           "p99_ms": round(conc_p99, 3)},
            "cache": {"hit_rate": round(cache_hit_rate, 4),
                      "p50_ms": round(cache_p50, 3),
                      "p99_ms": round(cache_p99, 3)},
            "speedup_vs_sequential": round(conc_qps / max(seq_qps, 1e-9),
                                           3),
            "coalescing_ratio": round(coalescing_ratio, 3),
            "batch_window_ms": srv._ppr.window_s * 1e3,
            "max_batch": srv._ppr.max_batch,
            "f32_bit_exact_vs_sequential": bit_exact,
        },
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record))
    assert total == len(conc_lat), "lost requests under load"
    return 0


if __name__ == "__main__":
    sys.exit(main())
