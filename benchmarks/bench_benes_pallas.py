"""Micro-benchmark: Benes apply — XLA per-stage rolls vs pallas 3-pass.

Uses the REAL masks from the cached 10M-edge bench plan when present
(.bench_cache/mxu_plan_*.npz), else a random permutation at --n.

Usage:  python benchmarks/bench_benes_pallas.py [--n 24] [--iters 50]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=24)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--K", type=int, default=18)
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    ap.add_argument("--which", default="both",
                    choices=["both", "pallas", "xla"])
    args = ap.parse_args()

    from memgraph_tpu.utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()
    import jax
    import jax.numpy as jnp
    from memgraph_tpu.ops import spmv_mxu
    from memgraph_tpu.ops.benes_pallas import (build_pallas_masks,
                                               benes_apply_pallas)
    from memgraph_tpu.ops.spmv_mxu import _benes_apply_rolls, \
        _unpack_mask_words
    from memgraph_tpu.ops.blob import pack_blob, unblob

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cands = glob.glob(os.path.join(repo, ".bench_cache", "mxu_plan_*.npz"))
    masks_packed = None
    for c in cands:
        z = np.load(c)
        if int(z["net_log2"]) == args.n:
            masks_packed = z["masks_packed"]
            print(f"using real plan masks from {os.path.basename(c)}",
                  file=sys.stderr)
            break
    if masks_packed is None:
        from memgraph_tpu.ops.benes import benes_route, pack_masks
        print(f"routing random perm at 2^{args.n} (slow at large n)...",
              file=sys.stderr)
        rng = np.random.default_rng(0)
        from memgraph_tpu.ops.native import benes_route_native
        perm = rng.permutation(1 << args.n)
        masks_packed = benes_route_native(perm)
        if masks_packed is None:
            masks_packed = pack_masks(benes_route(perm))

    N = 1 << args.n
    rows = N // 128
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    rng = np.random.default_rng(1)
    x_np = rng.standard_normal(N).astype(np.float32).reshape(rows, 128)

    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", file=sys.stderr)

    def timeit(fn, x):
        t0 = time.perf_counter()
        out = fn(x)
        _ = float(out[0, 0])
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _i in range(3):
            t0 = time.perf_counter()
            out = fn(x)
            _ = float(out[0, 0])
            best = min(best, time.perf_counter() - t0)
        return compile_s, best

    x_dev = jax.device_put(x_np.astype(dtype))
    iters = args.iters

    if args.which in ("both", "pallas"):
        spec, midw, outw = build_pallas_masks(masks_packed, args.n, K=args.K)
        print(f"pallas spec: outer={len(spec.outer_down)}+"
              f"{len(spec.outer_up)} mid={len(spec.mid_stages)} "
              f"planes={spec.mid_planes}", file=sys.stderr)
        midw_d = jax.device_put(midw)
        outw_d = jax.device_put(outw) if outw is not None else None

        @jax.jit
        def run_pallas(x):
            def body(_, x):
                return benes_apply_pallas(x, midw_d, outw_d, spec)
            return jax.lax.fori_loop(0, iters, body, x)

        comp, best = timeit(run_pallas, x_dev)
        per = best / iters * 1e3
        print(f"pallas: compile={comp:.2f}s  {iters} iters best={best:.4f}s"
              f"  -> {per:.3f} ms/apply")

    if args.which in ("both", "xla"):
        live = [bool(r.any()) for r in masks_packed]
        blob_np, segs = pack_blob({"masks": ("bits", masks_packed)})
        blob_d = jax.device_put(blob_np)

        @jax.jit
        def run_xla(x):
            masks2 = _unpack_mask_words(unblob(blob_d, segs, "masks"),
                                        args.n)
            m2 = masks2.reshape(masks_packed.shape[0], rows, 128)

            def body(_, x):
                return _benes_apply_rolls(x, m2, args.n, live_stages=live)
            return jax.lax.fori_loop(0, iters, body, x)

        comp, best = timeit(run_xla, x_dev)
        per = best / iters * 1e3
        print(f"xla:    compile={comp:.2f}s  {iters} iters best={best:.4f}s"
              f"  -> {per:.3f} ms/apply")


if __name__ == "__main__":
    main()
