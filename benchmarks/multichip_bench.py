"""Multi-chip scaling bench: partition-centric sharded PageRank.

Produces the MULTICHIP_r0N.json record: one row per device count
(1/2/4/8 by default) with per-stage timings (plan/build, host->device
transfer, compile, iterate) and edges/s, over the partition-centric
pjit/shard_map pipeline (parallel/distributed.pagerank_partition_centric
— exactly one psum_scatter per power iteration).

Honesty contract (same as bench.py): the record carries "backend" and
"degraded". On a forced-host CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8) the 8 "devices"
share the host's cores, so scaling rows measure ORCHESTRATION overhead,
not speedup — the record says so (`degraded: true`) instead of letting
a flat curve masquerade as a TPU result.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/multichip_bench.py [out.json]

Env: MULTICHIP_N_NODES / MULTICHIP_N_EDGES / MULTICHIP_ITERATIONS /
MULTICHIP_DEVICE_COUNTS (comma-separated).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_NODES = int(os.environ.get("MULTICHIP_N_NODES", 100_000))
N_EDGES = int(os.environ.get("MULTICHIP_N_EDGES", 1_000_000))
ITERATIONS = int(os.environ.get("MULTICHIP_ITERATIONS", 20))
SEED = 7


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(out_path: str | None = None) -> dict:
    import jax
    import jax.numpy as jnp
    from memgraph_tpu.ops import csr
    from memgraph_tpu.parallel.mesh import get_mesh_context
    from memgraph_tpu.parallel.distributed import (
        pagerank_partition_centric)

    n_dev_avail = len(jax.devices())
    counts = [int(c) for c in os.environ.get(
        "MULTICHIP_DEVICE_COUNTS", "1,2,4,8").split(",")]
    counts = [c for c in counts if c <= n_dev_avail]
    backend = jax.devices()[0].platform
    forced_host = "host_platform_device_count" in \
        os.environ.get("XLA_FLAGS", "")
    degraded = backend == "cpu"

    rng = np.random.default_rng(SEED)
    src = rng.integers(0, N_NODES, N_EDGES, dtype=np.int64)
    dst = (rng.random(N_EDGES) ** 2 * N_NODES).astype(np.int64)
    log(f"graph: {N_NODES:,} nodes, {N_EDGES:,} edges; "
        f"backend={backend} devices={n_dev_avail} "
        f"forced_host={forced_host}")
    graph = csr.from_coo(src, dst, None, n_nodes=N_NODES)

    rows = []
    base_eps = None
    ref_ranks = None
    for nd in counts:
        ctx = get_mesh_context(nd)

        t0 = time.perf_counter()
        scsr_host = csr.shard_edges(src, dst, None, N_NODES, nd)
        build_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        scsr = scsr_host.to_device(ctx)
        # force materialization of the device rows
        _ = float(np.asarray(scsr.weights)[0, 0])
        transfer_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        ranks, err, iters = pagerank_partition_centric(
            scsr, ctx, max_iterations=ITERATIONS, tol=0.0)
        compile_s = time.perf_counter() - t0  # includes first run

        t0 = time.perf_counter()
        ranks, err, iters = pagerank_partition_centric(
            scsr, ctx, max_iterations=ITERATIONS, tol=0.0)
        ranks = np.asarray(ranks)
        iterate_s = time.perf_counter() - t0
        assert iters == ITERATIONS, (iters, ITERATIONS)

        if ref_ranks is None:
            ref_ranks = ranks
        else:
            np.testing.assert_allclose(ranks, ref_ranks, atol=1e-5)

        eps = N_EDGES * ITERATIONS / iterate_s
        if base_eps is None:
            base_eps = eps
        row = {
            "n_devices": nd,
            "build_s": round(build_s, 3),
            "transfer_s": round(transfer_s, 3),
            "compile_s": round(compile_s, 3),
            "iterate_s": round(iterate_s, 4),
            "edges_per_sec": round(eps, 1),
            "speedup_vs_1": round(eps / base_eps, 3),
        }
        rows.append(row)
        log(f"  {nd} device(s): build {build_s:.2f}s transfer "
            f"{transfer_s:.2f}s compile {compile_s:.2f}s iterate "
            f"{iterate_s:.3f}s -> {eps:,.0f} e/s "
            f"({row['speedup_vs_1']}x)")

    record = {
        "metric": "sharded_pagerank_edges_per_sec",
        "kernel": "partition_centric_psum_scatter",
        "backend": backend,
        "forced_host_devices": forced_host,
        "degraded": degraded,
        "n_nodes": N_NODES,
        "n_edges": N_EDGES,
        "iterations": ITERATIONS,
        "collectives_per_iteration": 1,
        "rows": rows,
        "notes": (
            "degraded=true: forced-host CPU mesh — all 'devices' share "
            "the host cores, so rows measure sharding overhead, not "
            "scaling; regenerate on a real TPU slice for the headline "
            "curve" if degraded else
            "real accelerator mesh; speedup_vs_1 is the scaling curve"),
    }
    out = json.dumps(record, indent=1)
    if out_path:
        with open(out_path, "w") as f:
            f.write(out + "\n")
        log(f"wrote {out_path}")
    print(out)
    return record


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
