"""Bolt server e2e tests over a real TCP socket.

Counterpart of the reference's bolt session tests
(tests/unit/bolt_session.cpp) and driver tests (tests/drivers/) — here the
shipped Python BoltClient plays the driver role against a live server.
"""

import socket
import threading

import pytest

from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.server.bolt import BoltServer
from memgraph_tpu.server.client import BoltClient, BoltClientError
from memgraph_tpu.server.packstream import Structure, pack, unpack
from memgraph_tpu.storage import InMemoryStorage


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def server():
    ictx = InterpreterContext(InMemoryStorage())
    port = _free_port()
    srv = BoltServer(ictx, "127.0.0.1", port)
    thread, loop = srv.run_in_thread()
    yield {"port": port, "ictx": ictx}
    loop.call_soon_threadsafe(loop.stop)


def test_max_sessions_cap_rejects_with_bolt_failure():
    """Beyond max_sessions the server answers a real Bolt FAILURE
    ("server overloaded") instead of accepting unboundedly, and counts
    the rejection."""
    from memgraph_tpu.observability.metrics import global_metrics
    ictx = InterpreterContext(InMemoryStorage())
    port = _free_port()
    srv = BoltServer(ictx, "127.0.0.1", port, max_sessions=2)
    thread, loop = srv.run_in_thread()
    try:
        rejected0 = dict(
            (n, v) for n, _t, v in global_metrics.snapshot()).get(
            "bolt.connections_rejected_total", 0.0)
        keep = [BoltClient(port=port) for _ in range(2)]
        with pytest.raises(BoltClientError) as exc:
            extra = BoltClient(port=port)
            extra.execute("RETURN 1")
        assert "ServerOverloaded" in exc.value.code
        assert "overloaded" in str(exc.value)
        rejected1 = dict(
            (n, v) for n, _t, v in global_metrics.snapshot()).get(
            "bolt.connections_rejected_total", 0.0)
        assert rejected1 == rejected0 + 1
        # live sessions still work, and freeing one readmits a newcomer
        _, rows, _ = keep[0].execute("RETURN 40 + 2")
        assert rows == [[42]]
        keep.pop().close()
        import time
        deadline = time.time() + 5
        admitted = None
        while time.time() < deadline and admitted is None:
            try:
                admitted = BoltClient(port=port)
            except (BoltClientError, OSError):
                time.sleep(0.1)
        assert admitted is not None, "slot was never released"
        admitted.close()
        for c in keep:
            c.close()
    finally:
        srv.stop()
        loop.call_soon_threadsafe(loop.stop)


def test_packstream_roundtrip():
    values = [None, True, False, 0, 1, -1, 127, -128, 1 << 20, -(1 << 40),
              3.14, "", "hello", "é" * 300, b"\x00\xff",
              [1, [2, "three"]], {"a": 1, "b": [True, None]},
              Structure(0x4E, [1, ["L"], {"k": "v"}])]
    for v in values:
        assert unpack(pack(v)) == v


def test_connect_and_query(server):
    client = BoltClient(port=server["port"])
    cols, rows, summary = client.execute("RETURN 1 + 1 AS two, 'x' AS s")
    assert cols == ["two", "s"]
    assert rows == [[2, "x"]]
    client.close()


def test_create_and_read_nodes(server):
    client = BoltClient(port=server["port"])
    client.execute("CREATE (:BoltTest {name: 'a', score: 1.5})")
    cols, rows, _ = client.execute(
        "MATCH (n:BoltTest) RETURN n, n.name, n.score")
    node = rows[0][0]
    assert isinstance(node, Structure) and node.tag == 0x4E
    assert node.fields[1] == ["BoltTest"]
    assert node.fields[2] == {"name": "a", "score": 1.5}
    assert rows[0][1] == "a"
    client.close()


def test_relationship_values(server):
    client = BoltClient(port=server["port"])
    client.execute("CREATE (:RA {k: 1})-[:REL {w: 2}]->(:RB)")
    _, rows, _ = client.execute(
        "MATCH (:RA)-[r:REL]->(:RB) RETURN r, type(r)")
    rel = rows[0][0]
    assert rel.tag == 0x52
    assert rows[0][1] == "REL"
    client.close()


def test_parameters_roundtrip(server):
    client = BoltClient(port=server["port"])
    _, rows, _ = client.execute("RETURN $a + 1 AS x, $m.k AS y",
                                {"a": 41, "m": {"k": "v"}})
    assert rows == [[42, "v"]]
    client.close()


def test_error_then_reset(server):
    client = BoltClient(port=server["port"])
    with pytest.raises(BoltClientError) as excinfo:
        client.execute("MATCH (n RETURN n")
    assert "SyntaxError" in excinfo.value.code
    client.reset()
    _, rows, _ = client.execute("RETURN 1 AS ok")
    assert rows == [[1]]
    client.close()


def test_explicit_transaction_bolt(server):
    client = BoltClient(port=server["port"])
    client.begin()
    client.execute("CREATE (:TxBolt)")
    client.rollback()
    _, rows, _ = client.execute("MATCH (n:TxBolt) RETURN count(n)")
    assert rows == [[0]]
    client.begin()
    client.execute("CREATE (:TxBolt)")
    client.commit()
    _, rows, _ = client.execute("MATCH (n:TxBolt) RETURN count(n)")
    assert rows == [[1]]
    client.close()


def test_streaming_pull_batches(server):
    client = BoltClient(port=server["port"])
    _, rows, _ = client.execute("UNWIND range(1, 2500) AS x RETURN x")
    assert len(rows) == 2500  # client pulls in batches of 1000
    assert rows[0] == [1] and rows[-1] == [2500]
    client.close()


def test_temporal_over_bolt(server):
    client = BoltClient(port=server["port"])
    _, rows, _ = client.execute(
        "RETURN date('2024-06-15') AS d, duration({hours: 1}) AS dur")
    d, dur = rows[0]
    assert isinstance(d, Structure) and d.tag == 0x44
    assert isinstance(dur, Structure) and dur.tag == 0x45
    client.close()


def test_call_procedure_over_bolt(server):
    client = BoltClient(port=server["port"])
    client.execute("CREATE (:PgA)-[:PgE]->(:PgB)")
    _, rows, _ = client.execute(
        "CALL pagerank.get() YIELD node, rank RETURN count(node)")
    assert rows[0][0] >= 2
    client.close()


def test_bolt_44_legacy_structures(server):
    """A 4.4-only client gets legacy 3-field Node / 5-field Relationship
    structures and legacy datetime tags."""
    client = BoltClient(port=server["port"], versions=((4, 4),))
    assert client.version == (4, 4)
    client.execute("CREATE (:Legacy {k: 1})-[:L]->(:Legacy)")
    _, rows, _ = client.execute(
        "MATCH (a:Legacy {k: 1})-[r:L]->(b) RETURN a, r")
    node, rel = rows[0]
    assert node.tag == 0x4E and len(node.fields) == 3  # no element_id
    assert rel.tag == 0x52 and len(rel.fields) == 5
    _, rows, _ = client.execute("RETURN datetime('2024-06-15T08:30:00+02:00')")
    assert rows[0][0].tag == 0x46  # legacy offset datetime
    client.close()


def test_auth_required():
    """With users defined, unauthenticated RUN must be rejected."""
    from memgraph_tpu.auth.auth import Auth
    auth = Auth()
    auth.create_user("admin", "secret")
    ictx = InterpreterContext(InMemoryStorage())
    port = _free_port()
    srv = BoltServer(ictx, "127.0.0.1", port, auth)
    thread, loop = srv.run_in_thread()
    try:
        with pytest.raises(BoltClientError) as excinfo:
            BoltClient(port=port, username="admin", password="wrong")
        assert "Unauthenticated" in excinfo.value.code
        # and with no/failed LOGON a raw RUN is refused (probe the bypass)
        import socket as socketlib
        from memgraph_tpu.server.bolt import BOLT_MAGIC, M_HELLO, M_RUN
        from memgraph_tpu.server.packstream import Structure, pack, unpack
        import struct as structlib
        s = socketlib.create_connection(("127.0.0.1", port), timeout=5)
        proposals = b"".join(bytes([0, 0, m, 5]) for m in (2, 1, 0, 0))
        s.sendall(BOLT_MAGIC + proposals)
        s.recv(4)

        def send(sig, *fields):
            data = pack(Structure(sig, list(fields)))
            s.sendall(structlib.pack(">H", len(data)) + data + b"\x00\x00")

        def read_msg():
            chunks = []
            while True:
                size = structlib.unpack(">H", s.recv(2))[0]
                if size == 0 and chunks:
                    return unpack(b"".join(chunks))
                if size:
                    chunks.append(s.recv(size))

        send(M_HELLO, {"user_agent": "probe"})
        read_msg()
        send(M_RUN, "MATCH (n) RETURN n", {}, {})
        reply = read_msg()
        assert reply.tag == 0x7F  # FAILURE
        assert "Unauthenticated" in reply.fields[0]["code"]
        s.close()
        # correct credentials work
        good = BoltClient(port=port, username="admin", password="secret")
        _, rows, _ = good.execute("RETURN 1")
        assert rows == [[1]]
        good.close()
    finally:
        loop.call_soon_threadsafe(loop.stop)


def test_port_in_use_raises(server):
    srv2 = BoltServer(server["ictx"], "127.0.0.1", server["port"])
    with pytest.raises(OSError):
        srv2.run_in_thread()


def test_concurrent_clients(server):
    errors = []

    def worker(i):
        try:
            client = BoltClient(port=server["port"])
            for _ in range(5):
                _, rows, _ = client.execute("RETURN $i AS i", {"i": i})
                assert rows == [[i]]
            client.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_slow_query_does_not_block_other_sessions(server):
    """Interpreter work runs on the Bolt worker pool, so one session's
    long-running PULL must not freeze the event loop for other sessions
    (reference analog: priority_thread_pool.hpp session scheduling)."""
    import threading
    import time as _time

    slow = BoltClient(port=server["port"], timeout=60.0)
    fast = BoltClient(port=server["port"])
    try:
        fast.execute("CREATE (:Fair {id: 1})")
        done = threading.Event()
        slow_elapsed = []

        def run_slow():
            t0 = _time.perf_counter()
            slow.execute(
                "UNWIND range(0, 2000000) AS x "
                "WITH sum(x) AS s RETURN s")
            slow_elapsed.append(_time.perf_counter() - t0)
            done.set()

        t = threading.Thread(target=run_slow)
        t.start()
        _time.sleep(0.1)          # ensure the slow PULL is in flight
        worst = 0.0
        while not done.is_set():
            t0 = _time.perf_counter()
            _, rows, _ = fast.execute(
                "MATCH (n:Fair {id: 1}) RETURN n.id")
            worst = max(worst, _time.perf_counter() - t0)
            assert rows == [[1]]
        t.join()
        assert slow_elapsed and slow_elapsed[0] > 0.3, \
            "slow query finished too fast to prove anything"
        # before the worker pool, the fast session waited for the ENTIRE
        # slow pull (>0.3s); now it interleaves at GIL granularity
        assert worst < slow_elapsed[0] / 2, \
            f"fast query blocked {worst:.3f}s behind a " \
            f"{slow_elapsed[0]:.3f}s query"
    finally:
        slow.close()
        fast.close()
