"""QUERY/PROCEDURE MEMORY LIMIT + per-query/global memory tracking.

Reference: src/memory/query_memory_control.cpp, utils/memory_tracker.cpp,
grammar Cypher.g4:134-138 (memoryLimit, queryMemoryLimit,
procedureMemoryLimit).
"""

import pytest

from memgraph_tpu.exceptions import SyntaxException
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage
from memgraph_tpu.utils.memory_tracker import (GLOBAL, MemoryLimitException,
                                               QueryMemoryTracker,
                                               approx_size)


@pytest.fixture()
def interp():
    return Interpreter(InterpreterContext(InMemoryStorage()))


class TestQueryMemoryLimit:
    def test_under_limit_succeeds(self, interp):
        _, rows, _ = interp.execute(
            "UNWIND range(0, 100) AS x RETURN count(x) AS c "
            "QUERY MEMORY LIMIT 10 MB")
        assert rows == [[101]]

    def test_sort_buffer_over_limit_aborts(self, interp):
        with pytest.raises(MemoryLimitException):
            interp.execute(
                "UNWIND range(0, 200000) AS x WITH x ORDER BY x DESC "
                "RETURN count(x) QUERY MEMORY LIMIT 100 KB")

    def test_collect_over_limit_aborts(self, interp):
        with pytest.raises(MemoryLimitException):
            interp.execute(
                "UNWIND range(0, 200000) AS x RETURN collect(x) AS c "
                "QUERY MEMORY LIMIT 100 KB")

    def test_distinct_over_limit_aborts(self, interp):
        with pytest.raises(MemoryLimitException):
            interp.execute(
                "UNWIND range(0, 200000) AS x RETURN DISTINCT x "
                "QUERY MEMORY LIMIT 100 KB")

    def test_aggregate_groups_over_limit_aborts(self, interp):
        with pytest.raises(MemoryLimitException):
            interp.execute(
                "UNWIND range(0, 200000) AS x "
                "RETURN x AS g, count(*) AS c QUERY MEMORY LIMIT 100 KB")

    def test_unlimited(self, interp):
        _, rows, _ = interp.execute("RETURN 1 AS one QUERY MEMORY UNLIMITED")
        assert rows == [[1]]

    def test_kb_unit(self, interp):
        _, rows, _ = interp.execute(
            "RETURN 1 AS one QUERY MEMORY LIMIT 512 KB")
        assert rows == [[1]]

    def test_bad_unit_rejected(self, interp):
        with pytest.raises(SyntaxException):
            interp.execute("RETURN 1 QUERY MEMORY LIMIT 10 GB")

    def test_streaming_query_unaffected(self, interp):
        # pure streaming (no materialization) passes even with a tiny
        # limit: only retained state is accounted
        _, rows, _ = interp.execute(
            "UNWIND range(0, 200000) AS x RETURN count(x) AS c "
            "QUERY MEMORY LIMIT 100 KB")
        assert rows == [[200001]]

    def test_released_after_query(self, interp):
        before = GLOBAL.current
        interp.execute(
            "UNWIND range(0, 50000) AS x RETURN collect(x) AS c")
        assert GLOBAL.current == before

    def test_released_after_failed_query(self, interp):
        before = GLOBAL.current
        with pytest.raises(MemoryLimitException):
            interp.execute(
                "UNWIND range(0, 200000) AS x RETURN collect(x) AS c "
                "QUERY MEMORY LIMIT 100 KB")
        assert GLOBAL.current == before


class TestProcedureMemoryLimit:
    def test_parse_and_pass(self, interp):
        _, rows, _ = interp.execute(
            "CALL util.md5(['x']) PROCEDURE MEMORY LIMIT 10 MB "
            "YIELD result RETURN result IS NOT NULL AS ok")
        assert rows == [[True]]


class TestGlobalTracker:
    def test_global_limit_enforced(self):
        tracker = QueryMemoryTracker(limit=None)
        old_limit = GLOBAL.limit
        GLOBAL.limit = GLOBAL.current + 1000
        try:
            with pytest.raises(MemoryLimitException):
                tracker.add(10_000)
        finally:
            GLOBAL.limit = old_limit
            tracker.release_all()

    def test_approx_size_containers(self):
        assert approx_size([1] * 1000) > 8000
        assert approx_size({"k" * 10: "v" * 100}) > 100


class TestTrackerSymmetry:
    """ADVICE r2: a limit breach must not desync query/global accounting —
    release_all() may only return bytes that were actually added globally."""

    def test_query_limit_breach_does_not_over_release(self):
        g_before = GLOBAL.current
        other = QueryMemoryTracker(limit=None)
        other.add(5_000)                      # a concurrent live query
        t = QueryMemoryTracker(limit=1_000)
        t.add(500)
        with pytest.raises(MemoryLimitException):
            t.add(10_000)                     # breaches per-query limit
        t.release_all()
        # other's 5_000 global bytes must still be tracked
        assert GLOBAL.current == g_before + 5_000
        other.release_all()
        assert GLOBAL.current == g_before

    def test_global_limit_breach_records_nothing_locally(self):
        t = QueryMemoryTracker(limit=None)
        g_before = GLOBAL.current
        old_limit = GLOBAL.limit
        GLOBAL.limit = GLOBAL.current + 100
        try:
            with pytest.raises(MemoryLimitException):
                t.add(10_000)
        finally:
            GLOBAL.limit = old_limit
        # neither side recorded the breaching chunk — no wedge, no leak
        assert t.current == 0
        assert GLOBAL.current == g_before
        t.release_all()
        assert GLOBAL.current == g_before


class TestColumnarCacheIsolation:
    """ADVICE r2: only SNAPSHOT_ISOLATION reads may populate the shared
    columnar cache — weaker levels resolve against the live commit ts."""

    def test_read_committed_not_cacheable(self):
        from memgraph_tpu.ops.columnar import ColumnarCache
        from memgraph_tpu.storage.storage import IsolationLevel
        s = InMemoryStorage()
        with s.access() as acc:
            v = acc.create_vertex()
            acc.commit()
        cache = ColumnarCache()
        acc_rc = s.access(IsolationLevel.READ_COMMITTED)
        acc_si = s.access(IsolationLevel.SNAPSHOT_ISOLATION)
        try:
            assert not cache._cacheable(acc_rc)
            assert cache._cacheable(acc_si)
        finally:
            acc_rc.abort()
            acc_si.abort()
