"""Bulk-write fast lane tests: batch detection, batch_insert semantics,
deferred index consistency, BATCH_INSERT WAL durability (incl. crash
recovery), replication equivalence, and supernode adjacency bookkeeping.
"""

import os
import random
import socket
import subprocess
import sys
import time

import pytest

from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.query.plan import operators as Op
from memgraph_tpu.storage import InMemoryStorage, StorageConfig
from memgraph_tpu.storage.common import View
from memgraph_tpu.storage.durability import wal as W
from memgraph_tpu.storage.durability.recovery import recover, wire_durability


def _db():
    ictx = InterpreterContext(InMemoryStorage())
    return ictx, Interpreter(ictx)


def _rows(interp, q, params=None):
    _, rows, _ = interp.execute(q, params)
    return rows


def _plan(ictx, q):
    plan, _cols, _hit = ictx.cached_plan(q, ictx.cached_parse(q))
    return plan


# --- plan-shape detection ---------------------------------------------------

def test_fast_lane_detection():
    ictx, interp = _db()
    interp.execute("CREATE INDEX ON :User(id)")
    assert isinstance(_plan(ictx, "UNWIND $ids AS i CREATE (:User {id: i})"),
                      Op.BatchCreateGraph)
    assert isinstance(_plan(ictx, "CREATE (:A {x: 1}), (:B {x: 2})"),
                      Op.BatchCreateGraph)
    assert isinstance(
        _plan(ictx, "UNWIND $p AS p MATCH (a:User {id: p[0]}), "
                    "(b:User {id: p[1]}) CREATE (a)-[:F]->(b)"),
        Op.BatchCreateGraph)
    # a RETURN means downstream consumers exist: no rewrite
    assert not isinstance(
        _plan(ictx, "UNWIND $ids AS i CREATE (n:User {id: i}) RETURN n"),
        Op.BatchCreateGraph)
    # property referencing a same-chain created node: no rewrite
    assert not isinstance(
        _plan(ictx, "CREATE (a:A {x: 1}) CREATE (b:B {y: a.x})"),
        Op.BatchCreateGraph)


def test_fast_lane_can_be_disabled():
    ictx = InterpreterContext(InMemoryStorage(),
                              {"bulk_fast_lane": False})
    assert not isinstance(_plan(ictx, "UNWIND $ids AS i CREATE (:U {id: i})"),
                          Op.BatchCreateGraph)


# --- batch create correctness ----------------------------------------------

def test_unwind_create_nodes_and_stats():
    _ictx, interp = _db()
    _cols, _rows_, summary = interp.execute(
        "UNWIND $ids AS i CREATE (:User {id: i, age: i % 7})",
        {"ids": list(range(500))})
    stats = summary["stats"]
    assert stats["nodes_created"] == 500
    assert stats["labels_added"] == 500
    assert stats["properties_set"] == 1000
    assert _rows(interp, "MATCH (n:User) RETURN count(n), min(n.id), "
                         "max(n.id), sum(n.age)") == \
        [[500, 0, 499, sum(i % 7 for i in range(500))]]


def test_multi_create_pattern_with_edges():
    _ictx, interp = _db()
    _c, _r, summary = interp.execute(
        "CREATE (:A {x: 1})-[:R {w: 2}]->(:B {y: 3})")
    assert summary["stats"]["nodes_created"] == 2
    assert summary["stats"]["relationships_created"] == 1
    assert _rows(interp, "MATCH (a:A)-[r:R]->(b:B) "
                         "RETURN a.x, r.w, b.y") == [[1, 2, 3]]


def test_edge_batch_matches_per_row_semantics():
    _ictx, interp = _db()
    interp.execute("CREATE INDEX ON :U(id)")
    interp.execute("UNWIND $ids AS i CREATE (:U {id: i})",
                   {"ids": list(range(50))})
    rng = random.Random(3)
    pairs = [[rng.randrange(50), rng.randrange(50)] for _ in range(200)]
    pairs.append(pairs[0])          # duplicate row → parallel edge
    interp.execute(
        "UNWIND $pairs AS p MATCH (a:U {id: p[0]}), (b:U {id: p[1]}) "
        "CREATE (a)-[:F]->(b)", {"pairs": pairs})
    assert _rows(interp, "MATCH ()-[r:F]->() RETURN count(r)") == \
        [[len(pairs)]]
    # spot-check endpoints
    a, b = pairs[5]
    got = _rows(interp, "MATCH (a:U {id: $a})-[:F]->(b) RETURN count(b)",
                {"a": a})
    assert got[0][0] == sum(1 for p in pairs if p[0] == a)


def test_missing_match_row_creates_nothing():
    _ictx, interp = _db()
    interp.execute("CREATE INDEX ON :U(id)")
    interp.execute("CREATE (:U {id: 1})")
    interp.execute(
        "UNWIND $pairs AS p MATCH (a:U {id: p[0]}), (b:U {id: p[1]}) "
        "CREATE (a)-[:F]->(b)", {"pairs": [[1, 1], [1, 99], [99, 1]]})
    assert _rows(interp, "MATCH ()-[r:F]->() RETURN count(r)") == [[1]]


def test_load_csv_create_goes_through_fast_lane(tmp_path):
    path = tmp_path / "people.csv"
    path.write_text("name,age\nana,30\nben,40\n")
    ictx, interp = _db()
    q = f'LOAD CSV FROM "{path}" WITH HEADER AS row ' \
        "CREATE (:Person {name: row.name})"
    assert isinstance(_plan(ictx, q), Op.BatchCreateGraph)
    interp.execute(q)
    assert _rows(interp, "MATCH (p:Person) RETURN p.name ORDER BY p.name") \
        == [["ana"], ["ben"]]


# --- transactionality -------------------------------------------------------

def test_batch_rollback_leaves_nothing():
    _ictx, interp = _db()
    interp.execute("BEGIN")
    interp.execute("UNWIND $ids AS i CREATE (:T {id: i})",
                   {"ids": list(range(100))})
    interp.execute("ROLLBACK")
    assert _rows(interp, "MATCH (n:T) RETURN count(n)") == [[0]]


def test_batch_invisible_until_commit():
    ictx, interp = _db()
    storage = ictx.storage
    interp.execute("BEGIN")
    interp.execute("UNWIND $ids AS i CREATE (:T {id: i})",
                   {"ids": list(range(64))})
    # a concurrent snapshot reader must not see the uncommitted batch
    acc = storage.access()
    try:
        assert sum(1 for _ in acc.vertices(View.OLD)) == 0
    finally:
        acc.abort()
    interp.execute("COMMIT")
    acc = storage.access()
    try:
        assert sum(1 for _ in acc.vertices(View.OLD)) == 64
    finally:
        acc.abort()


def test_batch_insert_abort_restores_hub_adjacency():
    storage = InMemoryStorage()
    acc = storage.access()
    hub_list, _ = acc.batch_insert(vertices=[((), {})])
    hub = hub_list[0]
    acc.commit()

    acc = storage.access()
    spokes, edges = acc.batch_insert(
        vertices=[((), {}) for _ in range(10)],
        edges=[(0, i, hub, None) for i in range(10)])
    assert len(hub.in_edges) == 10
    acc.abort()
    assert len(hub.in_edges) == 0
    # aborted batch objects are invisible
    acc = storage.access()
    try:
        assert sum(1 for _ in acc.vertices(View.OLD)) == 1
    finally:
        acc.abort()


# --- deferred index consistency ---------------------------------------------

def test_deferred_index_matches_per_row_insertion():
    rng = random.Random(11)
    bulk = InMemoryStorage()
    row = InMemoryStorage()
    for st in (bulk, row):
        lid = st.label_mapper.name_to_id("L")
        pid = st.property_mapper.name_to_id("k")
        st.create_label_index(lid)
        st.create_label_property_index(lid, (pid,))
    lid = bulk.label_mapper.name_to_id("L")
    pid = bulk.property_mapper.name_to_id("k")

    for _batch in range(5):
        specs = [((lid,), {pid: rng.randrange(40)})
                 for _ in range(rng.randrange(1, 80))]
        acc = bulk.access()
        acc.batch_insert(vertices=[(l, dict(p)) for l, p in specs])
        acc.commit()
        acc = row.access()
        for labels, props in specs:
            va = acc.create_vertex()
            for l in labels:
                va.add_label(l)
            for p, v in props.items():
                va.set_property(p, v)
        acc.commit()

    for value in range(40):
        b = bulk.indices.label_property.candidates_equal(lid, (pid,),
                                                         [value])
        r = row.indices.label_property.candidates_equal(lid, (pid,),
                                                        [value])
        assert sorted(v.properties[pid] for v in b) == \
            sorted(v.properties[pid] for v in r)
    b = bulk.indices.label_property.candidates_range(lid, (pid,), 10, 30)
    r = row.indices.label_property.candidates_range(lid, (pid,), 10, 30)
    assert sorted(v.properties[pid] for v in b) == \
        sorted(v.properties[pid] for v in r)
    assert bulk.indices.label.approx_count(lid) == \
        row.indices.label.approx_count(lid)


# --- durability: BATCH_INSERT WAL record ------------------------------------

def _wal_config(tmp_path):
    return StorageConfig(durability_dir=str(tmp_path), wal_enabled=True)


def test_batch_wal_record_roundtrip(tmp_path):
    storage = InMemoryStorage(_wal_config(tmp_path))
    wal = wire_durability(storage)
    ictx = InterpreterContext(storage)
    interp = Interpreter(ictx)
    interp.execute("UNWIND $ids AS i CREATE (:U {id: i, tag: 'x'})",
                   {"ids": list(range(200))})
    interp.execute(
        "MATCH (a:U {id: 0}), (b:U {id: 1}) "
        "UNWIND range(1, 3) AS i CREATE (a)-[:F {n: i}]->(b)")
    wal.close()

    kinds = [k for p in W.list_wal_files(storage)
             for k, _ in W.iter_wal_records(p)]
    assert kinds.count(W.OP_BATCH_INSERT) >= 2
    # the bulk vertices must NOT also appear as per-object records
    assert kinds.count(W.OP_CREATE_VERTEX) == 0

    restored = InMemoryStorage(_wal_config(tmp_path))
    recover(restored)
    interp2 = Interpreter(InterpreterContext(restored))
    assert _rows(interp2, "MATCH (n:U) RETURN count(n), sum(n.id)") == \
        [[200, sum(range(200))]]
    assert _rows(interp2, "MATCH (a:U {id: 0})-[r:F]->(b:U {id: 1}) "
                          "RETURN count(r), sum(r.n)") == [[3, 6]]


def test_truncated_batch_record_is_all_or_nothing(tmp_path):
    storage = InMemoryStorage(_wal_config(tmp_path))
    wal = wire_durability(storage)
    ictx = InterpreterContext(storage)
    interp = Interpreter(ictx)
    interp.execute("UNWIND $ids AS i CREATE (:U {id: i})",
                   {"ids": list(range(50))})
    interp.execute("UNWIND $ids AS i CREATE (:V {id: i})",
                   {"ids": list(range(70))})
    wal.close()
    # crash mid-write of the second transaction: truncate inside its frame
    path = W.list_wal_files(storage)[0]
    data = open(path, "rb").read()
    open(path, "wb").write(data[:len(data) - 37])

    restored = InMemoryStorage(_wal_config(tmp_path))
    recover(restored)
    interp2 = Interpreter(InterpreterContext(restored))
    # first batch fully present, torn batch fully absent
    assert _rows(interp2, "MATCH (n:U) RETURN count(n)") == [[50]]
    assert _rows(interp2, "MATCH (n:V) RETURN count(n)") == [[0]]


_CRASH_SCRIPT = r"""
import os, sys
sys.path.insert(0, {repo!r})
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage, StorageConfig
from memgraph_tpu.storage.durability.recovery import wire_durability

storage = InMemoryStorage(StorageConfig(durability_dir={ddir!r},
                                        wal_enabled=True))
wire_durability(storage)
interp = Interpreter(InterpreterContext(storage))
interp.execute("UNWIND $ids AS i CREATE (:C {{id: i}})",
               {{"ids": list(range(300))}})
# die WITHOUT closing anything the moment the batch commit returned
os.kill(os.getpid(), 9)
"""


def test_crash_recovery_after_batch_commit(tmp_path):
    script = _CRASH_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ddir=str(tmp_path))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, timeout=120)
    assert proc.returncode == -9, proc.stderr.decode()

    restored = InMemoryStorage(_wal_config(tmp_path))
    recover(restored)
    interp = Interpreter(InterpreterContext(restored))
    # the fsynced BATCH_INSERT record replays all-or-nothing: every row
    assert _rows(interp, "MATCH (n:C) RETURN count(n)") == [[300]]


# --- replication -------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_replica_applies_batch_like_per_row():
    main_ictx = InterpreterContext(InMemoryStorage())
    replica_ictx = InterpreterContext(InMemoryStorage())
    main = Interpreter(main_ictx)
    replica = Interpreter(replica_ictx)
    port = _free_port()
    replica.execute(f"SET REPLICATION ROLE TO REPLICA WITH PORT {port}")
    try:
        main.execute(f'REGISTER REPLICA r1 SYNC TO "127.0.0.1:{port}"')
        main.execute("CREATE INDEX ON :U(id)")
        main.execute("UNWIND $ids AS i CREATE (:U {id: i, b: i * 2})",
                     {"ids": list(range(150))})
        main.execute(
            "UNWIND $pairs AS p MATCH (a:U {id: p[0]}), (b:U {id: p[1]}) "
            "CREATE (a)-[:F]->(b)",
            {"pairs": [[i, (i + 1) % 150] for i in range(150)]})
        # SYNC replication: applied on commit; verify equivalence with the
        # per-row representation of the same data
        ref_ictx = InterpreterContext(InMemoryStorage())
        ref = Interpreter(ref_ictx)
        for i in range(150):
            ref.execute("CREATE (:U {id: $i, b: $b})",
                        {"i": i, "b": i * 2})
        for q in ("MATCH (n:U) RETURN count(n), sum(n.id), sum(n.b)",):
            assert _rows(replica, q) == _rows(ref, q) == _rows(main, q)
        assert _rows(replica, "MATCH (a)-[:F]->(b) "
                              "RETURN count(*), sum(a.id), sum(b.id)") == \
            _rows(main, "MATCH (a)-[:F]->(b) "
                        "RETURN count(*), sum(a.id), sum(b.id)")
        # replica scans through ITS indexes must see batch rows
        assert _rows(replica, "MATCH (n:U {id: 42}) RETURN n.b") == [[84]]
    finally:
        if getattr(replica_ictx, "replication", None) and \
                replica_ictx.replication.replica_server:
            replica_ictx.replication.replica_server.stop()
        if getattr(main_ictx, "replication", None):
            for c in main_ictx.replication.replicas.values():
                c.close()


# --- supernode adjacency ----------------------------------------------------

def test_supernode_adjacency_fast_path_consistency():
    from memgraph_tpu.storage.objects import ADJ_INDEX_THRESHOLD
    _ictx, interp = _db()
    interp.execute("CREATE INDEX ON :S(id)")
    interp.execute("CREATE INDEX ON :N(id)")
    interp.execute("CREATE (:S {id: 0})")
    n = ADJ_INDEX_THRESHOLD * 3
    interp.execute(
        "MATCH (s:S {id: 0}) UNWIND range(0, $n - 1) AS i "
        "CREATE (s)<-[:E]-(:N {id: i})", {"n": n})
    # bound-endpoint lookup (exercises the adjacency map)
    for i in (0, 7, n - 1):
        assert _rows(interp, "MATCH (s:S {id: 0})<-[r:E]-(n:N {id: $i}) "
                             "RETURN count(r)", {"i": i}) == [[1]]
    assert _rows(interp, "MATCH (s:S {id: 0})<-[r:E]-(n:N {id: $i}) "
                         "RETURN count(r)", {"i": n + 5}) == [[0]]
    # MERGE: existing edge is found (no duplicate), new edge is created
    interp.execute("MATCH (s:S {id: 0}), (n:N {id: 3}) MERGE (s)<-[:E]-(n)")
    assert _rows(interp, "MATCH (s:S {id: 0})<-[:E]-(m) "
                         "RETURN count(m)") == [[n]]
    interp.execute("CREATE (:N {id: $i})", {"i": n})
    interp.execute("MATCH (s:S {id: 0}), (n:N {id: $i}) "
                   "MERGE (s)<-[:E]-(n)", {"i": n})
    assert _rows(interp, "MATCH (s:S {id: 0})<-[:E]-(m) "
                         "RETURN count(m)") == [[n + 1]]
    # deletion keeps the map consistent
    interp.execute("MATCH (s:S {id: 0})<-[r:E]-(n:N {id: 5}) DELETE r")
    assert _rows(interp, "MATCH (s:S {id: 0})<-[r:E]-(n:N {id: 5}) "
                         "RETURN count(r)") == [[0]]
    assert _rows(interp, "MATCH (s:S {id: 0})<-[:E]-(m) "
                         "RETURN count(m)") == [[n]]


def test_props_only_materialization_keeps_edge_semantics():
    # labels/property reads skip adjacency copies; edge reads still work
    _ictx, interp = _db()
    interp.execute("CREATE (:A {x: 1})-[:R]->(:B {y: 2})")
    interp.execute("BEGIN")
    interp.execute("MATCH (a:A) SET a.x = 10")
    # own-transaction read sees the write AND the adjacency
    assert _rows(interp, "MATCH (a:A)-[:R]->(b:B) RETURN a.x, b.y") == \
        [[10, 2]]
    interp.execute("ROLLBACK")
    assert _rows(interp, "MATCH (a:A)-[:R]->(b) RETURN a.x") == [[1]]


def test_explicit_txn_multi_batch_wal_replay(tmp_path):
    """Two batch records in ONE transaction, the second's edges pointing
    at the first's vertices — replay must resolve across records."""
    storage = InMemoryStorage(_wal_config(tmp_path))
    wal = wire_durability(storage)
    interp = Interpreter(InterpreterContext(storage))
    interp.execute("CREATE INDEX ON :T(id)")
    interp.execute("BEGIN")
    interp.execute("UNWIND $ids AS i CREATE (:T {id: i})",
                   {"ids": list(range(20))})
    interp.execute(
        "UNWIND $pairs AS p MATCH (a:T {id: p[0]}), (b:T {id: p[1]}) "
        "CREATE (a)-[:F]->(b)",
        {"pairs": [[i, (i + 1) % 20] for i in range(20)]})
    interp.execute("COMMIT")
    wal.close()

    restored = InMemoryStorage(_wal_config(tmp_path))
    recover(restored)
    interp2 = Interpreter(InterpreterContext(restored))
    assert _rows(interp2, "MATCH (n:T) RETURN count(n)") == [[20]]
    assert _rows(interp2, "MATCH (a:T)-[:F]->(b:T) "
                          "RETURN count(*), sum(a.id)") == \
        [[20, sum(range(20))]]
