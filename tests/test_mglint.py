"""mglint: tier-1 gate + per-rule fixture tests + lock-order witness.

The gate test runs the analyzer over memgraph_tpu/ exactly like
`python -m tools.mglint memgraph_tpu/` and fails on any unbaselined
finding — so a new lock inversion, swallowed exception, impure kernel,
or unwired WAL opcode/fault point fails CI the commit it appears.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")

sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools.mglint.core import Project, load_baseline, run_rules  # noqa: E402


def _run(paths, baseline=None, only=None):
    project = Project([os.path.join(REPO, p) for p in paths], cwd=REPO)
    return run_rules(project, baseline or {}, only=only)


def _hits(result, rule):
    return [(f.path.split("/")[-1], f.line) for f in result.findings
            if f.rule == rule]


# --- the gate ---------------------------------------------------------------


def test_package_has_no_unbaselined_findings():
    result = _run(["memgraph_tpu"], baseline=load_baseline())
    assert not result.parse_errors, result.parse_errors
    assert not result.findings, \
        "unbaselined mglint findings:\n" + "\n".join(
            f.render() for f in result.findings)


def test_baseline_is_fully_used_and_justified():
    baseline = load_baseline()   # raises on missing justifications
    for key, justification in baseline.items():
        assert len(justification) >= 25, \
            f"baseline justification for {key} is too thin to mean much"
    result = _run(["memgraph_tpu"], baseline=baseline)
    assert not result.unused_baseline, \
        f"stale baseline entries (fixed or drifted): " \
        f"{result.unused_baseline}"


def test_cli_exits_zero_on_package():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mglint", "memgraph_tpu/",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert doc["files_scanned"] > 100


def test_cli_nonzero_on_fixtures():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mglint", "tests/lint_fixtures",
         "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    assert "MG001" in proc.stdout and "MG005" in proc.stdout
    assert "MG006" in proc.stdout and "MG007" in proc.stdout


# --- per-rule fixtures ------------------------------------------------------


def test_mg001_fires_on_inversion_only():
    result = _run(["tests/lint_fixtures"], only={"MG001"})
    hits = _hits(result, "MG001")
    assert ("mg001_lock_order.py", 13) in hits
    assert ("mg001_lock_order.py", 18) in hits
    # the consistently-ordered decoy class stays silent
    assert all(line in (13, 18) for _p, line in hits), hits


def test_mg002_fires_under_lock_only():
    result = _run(["tests/lint_fixtures"], only={"MG002"})
    hits = _hits(result, "MG002")
    assert ("mg002_blocking.py", 14) in hits            # fsync under lock
    # r12: device dispatches under a server lock are the wedge class
    # the kernel-server supervision contains — both the raw device_put
    # and the compiled-call fault boundary fire; the decoy that ships
    # the dispatch outside the lock stays silent
    assert ("mg002_device_dispatch.py", 18) in hits     # jax.device_put
    assert ("mg002_device_dispatch.py", 22) in hits     # fault boundary
    assert len(hits) == 3, hits


def test_mg003_fires_on_silent_swallow_only():
    result = _run(["tests/lint_fixtures"], only={"MG003"})
    hits = _hits(result, "MG003")
    # one silent swallow; the logging / exception-using handlers and the
    # suppressed one stay silent
    assert hits == [("mg003_swallowed.py", 11)], hits
    assert result.suppressed_count == 1


def test_mg004_fires_on_impurity_only():
    result = _run(["tests/lint_fixtures"], only={"MG004"})
    hits = _hits(result, "MG004")
    assert ("mg004_purity.py", 12) in hits   # print
    assert ("mg004_purity.py", 13) in hits   # np on traced arg
    assert ("mg004_purity.py", 26) in hits   # sleep via reachability
    assert len(hits) == 3, hits              # clean_kernel is silent


def test_mg005_fires_on_coverage_gaps_only():
    result = _run(["tests/lint_fixtures"], only={"MG005"})
    msgs = {f.fingerprint for f in result.findings}
    assert "wal-op:OP_ORPHAN" in msgs
    assert "fault-unregistered:wired.typo" in msgs
    assert "fault-dead:dead.point" in msgs
    # r12 device-nemesis wiring: an op without a fault point and a
    # device point no op can schedule both fire; the fully-wired
    # device_wired/device.wired pair stays silent
    assert "device-nemesis-dead:device_ghost" in msgs
    assert "device-point-unscheduled:device.orphan" in msgs
    # r13 span-registry wiring: an undeclared opened name, a declared
    # never-opened name, and a manual _begin_span call all fire; the
    # wired.span open sites (span + record_span) stay silent
    assert "span-unregistered:unregistered.span" in msgs
    assert "span-dead:dead.span" in msgs
    assert "span-manual:_begin_span" in msgs
    # r14 stat-registry wiring: an unregistered literal, an unmatched
    # dynamic prefix, a dead exact name, a dead family, and a duplicate
    # declaration all fire; wired.stat / wired.family.* stay silent
    assert "stat-unregistered:unregistered.stat" in msgs
    assert "stat-dynamic-unregistered:ghost.family." in msgs
    assert "stat-dead:dead.stat" in msgs
    assert "stat-dead-family:dead.family.*" in msgs
    assert "stat-duplicate:dup.stat" in msgs
    assert len(msgs) == 13, msgs             # OP_WIRED is fully covered


def test_mg006_fires_on_unguarded_access_only():
    result = _run(["tests/lint_fixtures"], only={"MG006"})
    hits = _hits(result, "MG006")
    assert ("mg006_shared_field.py", 25) in hits   # unguarded write
    assert ("mg006_shared_field.py", 28) in hits   # unguarded read
    assert ("mg006_shared_field.py", 31) in hits   # mutator call = write
    # construction + the lock-guarded decoy stay silent
    assert len([h for h in hits
                if h[0] == "mg006_shared_field.py"]) == 3, hits
    # the dynamic race fixtures agree with the static view: the
    # unguarded one is flagged, the TrackedLock-guarded one is clean
    assert ("race_unguarded.py", 18) in hits
    assert ("race_unguarded.py", 22) in hits
    assert all(p != "race_guarded.py" for p, _l in hits), hits
    assert result.suppressed_count == 1   # Hot.suppressed


def test_mg007_fires_on_split_regions_only():
    result = _run(["tests/lint_fixtures"], only={"MG007"})
    hits = _hits(result, "MG007")
    # atomic + revalidated decoys silent; only the split check-then-act
    assert hits == [("mg007_check_then_act.py", 36)], hits
    assert result.suppressed_count == 1   # Registry.suppressed_split


def test_mg008_fires_on_recompile_hazards_only():
    result = _run(["tests/lint_fixtures"], only={"MG008"})
    hits = _hits(result, "MG008")
    assert ("mg008_recompile.py", 19) in hits   # per-call jit
    assert ("mg008_recompile.py", 37) in hits   # traced branch
    assert ("mg008_recompile.py", 52) in hits   # unhashable static
    # the cached builder, structural branches (is None / .ndim) and the
    # hashable static stay silent; the suppressed rebuild counts
    assert len([h for h in hits
                if h[0] == "mg008_recompile.py"]) == 3, hits
    assert all(p == "mg008_recompile.py" for p, _l in hits), hits


def test_mg009_fires_on_hot_path_syncs_only():
    result = _run(["tests/lint_fixtures"], only={"MG009"})
    hits = _hits(result, "MG009")
    assert ("mg009_host_sync.py", 17) in hits   # np.asarray on device
    assert ("mg009_host_sync.py", 18) in hits   # .item() sync
    # wire bytes, the post-sync host value, the non-hot cold_path and
    # the suppressed reply transfer stay silent
    assert len(hits) == 2, hits
    assert result.suppressed_count == 1


def test_mg010_fires_on_missing_donation_only():
    result = _run(["tests/lint_fixtures"], only={"MG010"})
    hits = _hits(result, "MG010")
    assert ("mg010_donation.py", 21) in hits    # decorator form
    assert ("mg010_donation.py", 40) in hits    # wrapper call form
    # donated variants, the loop-free jit and the suppressed one silent
    assert len(hits) == 2, hits
    assert result.suppressed_count == 1


def test_mg011_fires_on_unaccounted_allocations_only():
    result = _run(["tests/lint_fixtures"], only={"MG011"})
    hits = _hits(result, "MG011")
    assert ("mg011_device_alloc.py", 41) in hits  # jnp.ones, unpriced
    assert ("mg011_device_alloc.py", 42) in hits  # device_put, unpriced
    # the deliberately dead exemption entry is reported at line 1
    assert ("mg011_device_alloc.py", 1) in hits
    # the admission-guarded dispatch (device_put under the verdict, the
    # forward-closure helper), the table-exempted staging, the non-root
    # cold path and the suppressed placement all stay silent
    assert len(hits) == 3, hits
    assert result.suppressed_count == 1
    dead = [f for f in result.findings
            if f.fingerprint.startswith("unused-exemption:")]
    assert len(dead) == 1 and "gone_function" in dead[0].fingerprint


def test_mg011_package_serving_paths_are_accounted():
    # the real tree must be MG011-clean WITHOUT baseline help: every
    # serving-path allocation is either inside an estimator-routed
    # scope or carries a justified EXEMPTIONS entry
    result = _run(["memgraph_tpu"], only={"MG011"})
    assert not result.findings, "\n".join(
        f.render() for f in result.findings)


def test_mg012_fires_on_contract_escapes_only():
    result = _run(["tests/lint_fixtures"], only={"MG012"})
    hits = _hits(result, "MG012")
    # witness lines: the known-raising json.loads in the helper and the
    # undeclared raise — NOT the root function's def line
    assert ("mg012_escape.py", 44) in hits
    assert ("mg012_escape.py", 55) in hits
    prints = {f.fingerprint for f in result.findings}
    assert "escape:fixture.serve:ValueError" in prints
    assert "escape:fixture.serve:CrashError" in prints
    # dead registry entry reported at its own declaration
    assert "dead-root:fixture.dead" in prints
    # the declared AppError narrowing and the total decoy stay silent
    assert len(hits) == 3, hits


def test_mg012_package_roots_hold_their_contracts():
    # the real tree's serving roots must be clean modulo the justified
    # mgflow baseline (shared keys live in tools/mglint/baseline.json)
    result = _run(["memgraph_tpu"], baseline=load_baseline(),
                  only={"MG012"})
    assert not result.findings, "\n".join(
        f.render() for f in result.findings)


def test_mg013_fires_on_unsafe_retries_only():
    result = _run(["tests/lint_fixtures"], only={"MG013"})
    hits = _hits(result, "MG013")
    assert ("mg013_unsafe_retry.py", 48) in hits   # blind-retry
    assert ("mg013_unsafe_retry.py", 50) in hits   # unsafe class
    assert ("mg013_unsafe_retry.py", 61) in hits   # unclassified loop
    assert ("mg013_unsafe_retry.py", 22) in hits   # dead registration
    prints = {f.fingerprint for f in result.findings}
    assert "blind-retry:Client.send_write:TransportError" in prints
    assert "retry-unsafe-class:Client.send_write:ShedError" in prints
    assert "unclassified:Client.unregistered_spin" in prints
    assert "idem-unused:Client.ghost_op" in prints
    # the retryable fetch loop swallowing a retryable class is silent
    assert len(hits) == 4, hits


def test_mg013_package_retries_respect_idempotency():
    result = _run(["memgraph_tpu"], baseline=load_baseline(),
                  only={"MG013"})
    assert not result.findings, "\n".join(
        f.render() for f in result.findings)


def test_new_rules_are_registered_in_catalog():
    from tools.mglint import rules as _rules  # noqa: F401
    from tools.mglint.registry import RULES
    for rule_id in ("MG008", "MG009", "MG010", "MG011", "MG012",
                    "MG013"):
        assert rule_id in RULES
    assert RULES["MG008"].name == "recompile-hazard"
    assert RULES["MG009"].name == "host-sync-in-hot-path"
    assert RULES["MG010"].name == "missing-donation"
    assert RULES["MG011"].name == "unaccounted-device-allocation"
    assert RULES["MG012"].name == "undeclared-escape"
    assert RULES["MG013"].name == "unsafe-retry"


def test_suppression_comment_scopes_to_one_handler():
    # remove the suppression and the second handler must fire too
    path = os.path.join(FIXTURES, "mg003_swallowed.py")
    with open(path) as f:
        text = f.read()
    stripped = text.replace(
        "  # mglint: disable=MG003 — fixture: deliberate", "")
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        alt = os.path.join(tmp, "mg003_swallowed.py")
        with open(alt, "w") as f:
            f.write(stripped)
        project = Project([alt], cwd=tmp)
        result = run_rules(project, {}, only={"MG003"})
        assert len([f for f in result.findings
                    if f.rule == "MG003"]) == 2


def test_finding_keys_are_line_stable():
    """Baseline keys must not change when code above a finding moves."""
    import tempfile
    src = ("def f():\n    try:\n        pass\n"
           "    except Exception:\n        pass\n")
    shifted = "import os\n\n\n" + src
    keys = []
    for body in (src, shifted):
        with tempfile.TemporaryDirectory() as tmp:
            p = os.path.join(tmp, "m.py")
            with open(p, "w") as f:
                f.write(body)
            result = run_rules(Project([p], cwd=tmp), {},
                               only={"MG003"})
            assert len(result.findings) == 1
            keys.append(result.findings[0].key)
    assert keys[0] == keys[1]


# --- runtime witness (TrackedLock) ------------------------------------------


def test_tracked_lock_witnesses_cycle():
    from memgraph_tpu.utils import locks
    with locks.isolated_witness():
        a = locks.TrackedLock("Fix.A")
        b = locks.TrackedLock("Fix.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(locks.violations()) == 1
        with pytest.raises(locks.LockOrderViolation) as exc:
            locks.assert_acyclic()
        assert "Fix.A" in str(exc.value) and "Fix.B" in str(exc.value)
    # the surrounding session's witness state is restored
    assert all("Fix.A" not in f for f, _t in locks.edges())


def test_tracked_lock_consistent_order_is_clean():
    from memgraph_tpu.utils import locks
    with locks.isolated_witness():
        a = locks.TrackedLock("Fix.C")
        b = locks.TrackedLock("Fix.D")
        for _ in range(3):
            with a:
                with b:
                    pass
        locks.assert_acyclic()
        assert ("Fix.C", "Fix.D") in locks.edges()


def test_tracked_rlock_reentry_records_no_self_edge():
    from memgraph_tpu.utils import locks
    with locks.isolated_witness():
        r = locks.TrackedLock("Fix.R", reentrant=True)
        with r:
            with r:
                pass
        assert locks.edges() == {}
        locks.assert_acyclic()


def test_factory_unarmed_returns_plain_lock(monkeypatch):
    import threading
    from memgraph_tpu.utils import locks
    monkeypatch.setenv(locks.ENV_VAR, "0")
    lk = locks.tracked_lock("X.Y")
    assert isinstance(lk, type(threading.Lock()))
    monkeypatch.setenv(locks.ENV_VAR, "1")
    assert isinstance(locks.tracked_lock("X.Y"), locks.TrackedLock)


def test_suite_witness_is_armed_and_recording():
    """conftest arms MG_TRACK_LOCKS for the tier-1 run; storage commits
    must actually produce witnessed edges."""
    from memgraph_tpu.utils import locks
    if not locks.armed():
        pytest.skip("witness disarmed via MG_TRACK_LOCKS=0")
    from memgraph_tpu.storage import InMemoryStorage
    storage = InMemoryStorage()
    acc = storage.access()
    v = acc.create_vertex()
    v.add_label(1)
    acc.commit()
    edges = locks.edges()
    assert any(frm.startswith("Storage.") for frm, _to in edges), edges
    locks.assert_acyclic()
