"""TPU kernel parity tests vs NetworkX/scipy oracles.

This is the SURVEY.md §4 test strategy step (1): pure-function kernel tests
against host reference implementations, with rank-match tolerances.
"""

import numpy as np
import pytest

import networkx as nx

from memgraph_tpu.ops import csr
from memgraph_tpu.ops.pagerank import pagerank, personalized_pagerank
from memgraph_tpu.ops.katz import katz_centrality, hits, degree_centrality
from memgraph_tpu.ops.components import (weakly_connected_components,
                                         strongly_connected_components)
from memgraph_tpu.ops.labelprop import label_propagation
from memgraph_tpu.ops.traversal import sssp, bfs_levels, khop_neighborhood
from memgraph_tpu.ops.knn import knn, IvfIndex
from memgraph_tpu.ops.walks import random_walks, walks_to_skipgram_pairs


def _random_digraph(n=60, p=0.08, seed=7, weights=False):
    rng = np.random.default_rng(seed)
    g = nx.gnp_random_graph(n, p, seed=seed, directed=True)
    src = np.array([u for u, v in g.edges()], dtype=np.int64)
    dst = np.array([v for u, v in g.edges()], dtype=np.int64)
    w = None
    if weights:
        w = rng.uniform(0.5, 2.0, size=len(src)).astype(np.float32)
        for (u, v), wi in zip(g.edges(), w):
            g[u][v]["weight"] = float(wi)
    graph = csr.from_coo(src, dst, w, n_nodes=n)
    return g, graph


def test_csr_padding_and_degrees():
    src = np.array([0, 0, 1, 2])
    dst = np.array([1, 2, 2, 0])
    g = csr.from_coo(src, dst)
    assert g.n_nodes == 3 and g.n_edges == 4
    assert g.n_pad >= 4 and (g.n_pad & (g.n_pad - 1)) == 0
    rp = np.asarray(g.row_ptr)
    assert rp[0] == 0 and rp[3] == 4  # 3 real rows cover all 4 edges
    deg = np.asarray(g.out_degree)
    assert list(deg[:3]) == [2, 1, 1]
    assert deg[3:].sum() == 0
    # rows sorted by destination for binary-search membership
    ci = np.asarray(g.col_idx)
    assert list(ci[rp[0]:rp[1]]) == [1, 2]


def test_pagerank_matches_networkx():
    g, graph = _random_digraph()
    ranks, err, iters = pagerank(graph, damping=0.85, tol=1e-10,
                                 max_iterations=200)
    expected = nx.pagerank(g, alpha=0.85, tol=1e-12, max_iter=500)
    got = np.asarray(ranks)
    exp = np.array([expected[i] for i in range(graph.n_nodes)])
    np.testing.assert_allclose(got, exp, atol=1e-5)
    assert abs(got.sum() - 1.0) < 1e-4


def test_pagerank_weighted_matches_networkx():
    g, graph = _random_digraph(weights=True)
    ranks, _, _ = pagerank(graph, damping=0.85, tol=1e-10, max_iterations=300)
    expected = nx.pagerank(g, alpha=0.85, tol=1e-12, max_iter=500,
                           weight="weight")
    exp = np.array([expected[i] for i in range(graph.n_nodes)])
    np.testing.assert_allclose(np.asarray(ranks), exp, atol=1e-5)


def test_pagerank_dangling_nodes():
    # node 2 dangles; mass must redistribute, ranks sum to 1
    graph = csr.from_coo(np.array([0, 1]), np.array([1, 2]), n_nodes=4)
    ranks, _, _ = pagerank(graph, tol=1e-12, max_iterations=300)
    got = np.asarray(ranks)
    g = nx.DiGraph()
    g.add_nodes_from(range(4))
    g.add_edges_from([(0, 1), (1, 2)])
    exp_d = nx.pagerank(g, alpha=0.85, tol=1e-12, max_iter=500)
    np.testing.assert_allclose(got, [exp_d[i] for i in range(4)], atol=1e-5)


def test_personalized_pagerank():
    g, graph = _random_digraph()
    ranks, _, _ = pagerank(graph, tol=1e-10)
    pranks, _, _ = personalized_pagerank(graph, [0], tol=1e-10,
                                         max_iterations=300)
    expected = nx.pagerank(g, alpha=0.85, personalization={0: 1.0},
                           tol=1e-12, max_iter=500)
    exp = np.array([expected[i] for i in range(graph.n_nodes)])
    np.testing.assert_allclose(np.asarray(pranks), exp, atol=1e-4)


def test_katz_matches_networkx():
    g, graph = _random_digraph(n=40, p=0.06)
    got, _, _ = katz_centrality(graph, alpha=0.05, beta=1.0, tol=1e-10,
                                max_iterations=500, normalized=True)
    expected = nx.katz_centrality(g, alpha=0.05, beta=1.0, tol=1e-12,
                                  max_iter=1000)
    exp = np.array([expected[i] for i in range(graph.n_nodes)])
    np.testing.assert_allclose(np.asarray(got), exp, atol=1e-5)


def test_hits_matches_networkx():
    g, graph = _random_digraph(n=30, p=0.15, seed=3)
    hub, auth, _, _ = hits(graph, tol=1e-12, max_iterations=500)
    eh, ea = nx.hits(g, tol=1e-12, max_iter=1000)
    # networkx normalizes by sum; ours by l2 — compare up to scale
    hub = np.asarray(hub)
    auth = np.asarray(auth)
    exp_h = np.array([eh[i] for i in range(graph.n_nodes)])
    exp_a = np.array([ea[i] for i in range(graph.n_nodes)])
    np.testing.assert_allclose(hub / max(hub.sum(), 1e-12), exp_h, atol=1e-4)
    np.testing.assert_allclose(auth / max(auth.sum(), 1e-12), exp_a, atol=1e-4)


def test_degree_centrality():
    g, graph = _random_digraph(n=25, p=0.2, seed=11)
    got = np.asarray(degree_centrality(graph, "total"))
    exp = np.array([(g.in_degree(i) + g.out_degree(i)) / (25 - 1)
                    for i in range(25)])
    np.testing.assert_allclose(got, exp, atol=1e-6)


def test_wcc_matches_networkx():
    g, graph = _random_digraph(n=80, p=0.02, seed=5)
    comp, _ = weakly_connected_components(graph)
    comp = np.asarray(comp)
    for component in nx.weakly_connected_components(g):
        ids = {comp[v] for v in component}
        assert len(ids) == 1
    # distinct components get distinct labels
    assert len(set(comp.tolist())) == nx.number_weakly_connected_components(g)


def test_scc_matches_networkx():
    g, graph = _random_digraph(n=50, p=0.06, seed=9)
    comp = np.asarray(strongly_connected_components(graph))
    nx_comps = list(nx.strongly_connected_components(g))
    for component in nx_comps:
        ids = {comp[v] for v in component}
        assert len(ids) == 1, f"SCC split: {component} -> {ids}"
    assert len(set(comp.tolist())) == len(nx_comps)


def test_scc_chain_of_cycles():
    # C0: 0-1-2, C1: 3-4-5, bridge 2->3; two SCCs
    src = np.array([0, 1, 2, 3, 4, 5, 2])
    dst = np.array([1, 2, 0, 4, 5, 3, 3])
    graph = csr.from_coo(src, dst, n_nodes=6)
    comp = np.asarray(strongly_connected_components(graph))
    assert comp[0] == comp[1] == comp[2]
    assert comp[3] == comp[4] == comp[5]
    assert comp[0] != comp[3]


def test_scc_long_cycle():
    """Regression: one 500-node directed cycle is ONE SCC (needs inner
    propagation to run to fixpoint, beyond any small iteration cap)."""
    n = 500
    src = np.arange(n)
    dst = (np.arange(n) + 1) % n
    graph = csr.from_coo(src, dst, n_nodes=n)
    comp = np.asarray(strongly_connected_components(graph))
    assert len(set(comp.tolist())) == 1


def test_ivf_small_corpus():
    rng = np.random.default_rng(4)
    corpus = rng.normal(size=(10, 8)).astype(np.float32)  # < default clusters
    index = IvfIndex(corpus)
    _, ids = index.search(corpus[:2], k=3)
    assert ids.shape == (2, 3)


def test_label_propagation_two_cliques():
    # two 5-cliques joined by a single bridge edge
    edges = []
    for base in (0, 5):
        for i in range(5):
            for j in range(i + 1, 5):
                edges.append((base + i, base + j))
    edges.append((0, 5))
    src = np.array([e[0] for e in edges])
    dst = np.array([e[1] for e in edges])
    graph = csr.from_coo(src, dst, n_nodes=10)
    labels, _ = label_propagation(graph, max_iterations=50)
    labels = np.asarray(labels)
    assert len(set(labels[:5])) == 1
    assert len(set(labels[5:])) == 1
    assert labels[0] != labels[5]


def test_sssp_matches_networkx():
    g, graph = _random_digraph(n=40, p=0.1, seed=13, weights=True)
    dist, _ = sssp(graph, source=0, weighted=True, directed=True)
    dist = np.asarray(dist)
    exp = nx.single_source_dijkstra_path_length(g, 0, weight="weight")
    for v in range(40):
        if v in exp:
            assert abs(dist[v] - exp[v]) < 1e-4, v
        else:
            assert np.isinf(dist[v]), v


def test_bfs_levels():
    g, graph = _random_digraph(n=40, p=0.1, seed=13)
    levels, _ = bfs_levels(graph, source=0)
    levels = np.asarray(levels)
    exp = nx.single_source_shortest_path_length(g, 0)
    for v in range(40):
        assert levels[v] == exp.get(v, -1), v


def test_khop_neighborhood():
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 2, 3, 4])
    graph = csr.from_coo(src, dst, n_nodes=6)
    mask = np.asarray(khop_neighborhood(graph, [0], k=2, directed=True))
    assert list(mask[:6]) == [True, True, True, False, False, False]


def test_knn_cosine():
    rng = np.random.default_rng(0)
    corpus = rng.normal(size=(100, 16)).astype(np.float32)
    queries = corpus[:3] + 0.001 * rng.normal(size=(3, 16)).astype(np.float32)
    scores, idx = knn(corpus, queries, k=5, metric="cosine", use_bf16=False)
    idx = np.asarray(idx)
    for qi in range(3):
        assert idx[qi, 0] == qi  # nearest neighbor of a near-copy is itself


def test_knn_l2():
    rng = np.random.default_rng(1)
    corpus = rng.normal(size=(50, 8)).astype(np.float32)
    q = rng.normal(size=(4, 8)).astype(np.float32)
    _, idx = knn(corpus, q, k=3, metric="l2sq", use_bf16=False)
    idx = np.asarray(idx)
    d = ((corpus[None, :, :] - q[:, None, :]) ** 2).sum(-1)
    exp = np.argsort(d, axis=1)[:, :3]
    # the 2q·x - ||x||^2 formulation can swap float near-ties; compare the
    # achieved distances, not the indices
    got_d = np.take_along_axis(d, idx, axis=1)
    exp_d = np.take_along_axis(d, exp, axis=1)
    np.testing.assert_allclose(got_d, exp_d, atol=1e-2)


def test_ivf_recall():
    rng = np.random.default_rng(2)
    corpus = rng.normal(size=(500, 16)).astype(np.float32)
    q = rng.normal(size=(5, 16)).astype(np.float32)
    index = IvfIndex(corpus, n_clusters=8)
    _, ids = index.search(q, k=10, n_probe=8)  # probe all cells → exact
    _, exact = knn(corpus, q, k=10, metric="cosine", use_bf16=False)
    exact = np.asarray(exact)
    for qi in range(5):
        assert set(ids[qi]) == set(exact[qi])


def test_random_walks_follow_edges():
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 2, 3, 0])  # directed cycle
    graph = csr.from_coo(src, dst, n_nodes=4)
    walks = np.asarray(random_walks(graph, [0, 1, 2, 3], length=8))
    assert walks.shape == (4, 9)
    for b in range(4):
        for t in range(8):
            assert walks[b, t + 1] == (walks[b, t] + 1) % 4


def test_random_walks_stall_at_sink():
    graph = csr.from_coo(np.array([0]), np.array([1]), n_nodes=2)
    walks = np.asarray(random_walks(graph, [0], length=5))
    assert list(walks[0]) == [0, 1, 1, 1, 1, 1]


def test_skipgram_pairs():
    import jax.numpy as jnp
    walks = jnp.array([[0, 1, 2, 3]])
    pairs = np.asarray(walks_to_skipgram_pairs(walks, window=1))
    real = {tuple(p) for p in pairs if p[0] != -1 and p[1] != -1}
    assert real == {(1, 0), (2, 1), (3, 2), (0, 1), (1, 2), (2, 3)}
