"""Process-level e2e + Jepsen-lite chaos tests.

Counterpart of the reference's e2e replication suite and the Jepsen bank
workload (/root/reference/tests/jepsen/src/memgraph/replication/bank.clj):
real server processes, real sockets, kill/restart nemesis, invariant checks.
"""

import json
import time

import pytest

from e2e_runner import Cluster, free_port


@pytest.fixture
def cluster(tmp_path):
    c = Cluster({}, base_dir=tmp_path)
    yield c
    c.shutdown()


def test_single_instance_lifecycle(cluster):
    inst = cluster.start_instance("solo")
    client = inst.client()
    client.execute("CREATE (:T {v: 1})")
    _, rows, _ = client.execute("MATCH (n:T) RETURN n.v")
    assert rows == [[1]]
    client.close()
    # durability across a hard kill (WAL fsync'd per commit)
    inst.kill()
    inst2 = cluster.restart_instance("solo")
    client = inst2.client()
    _, rows, _ = client.execute("MATCH (n:T) RETURN n.v")
    assert rows == [[1]]
    client.close()


def test_replicated_cluster_processes(cluster):
    main = cluster.start_instance("main")
    replica = cluster.start_instance("replica")
    repl_port = free_port()
    rc = replica.client()
    rc.execute(f"SET REPLICATION ROLE TO REPLICA WITH PORT {repl_port}")
    mc = main.client()
    mc.execute("CREATE (:Pre {v: 0})")
    mc.execute(f'REGISTER REPLICA r1 SYNC TO "127.0.0.1:{repl_port}"')
    mc.execute("CREATE (:Live {v: 1})")
    _, rows, _ = rc.execute("MATCH (n) RETURN count(n)")
    assert rows == [[2]]
    mc.close()
    rc.close()


def test_ha_cluster_processes(cluster):
    """Coordinator + 2 data instances as REAL processes; explicit
    promotion then failover after killing the MAIN."""
    coord_raft = free_port()
    m1, m2 = free_port(), free_port()
    r1, r2 = free_port(), free_port()
    coord = cluster.start_instance("coord", {"args": [
        "--coordinator-id", "c1", "--coordinator-port", str(coord_raft),
        "--no-storage-wal-enabled"]})
    i1 = cluster.start_instance("data1", {"args": [
        "--management-port", str(m1), "--no-storage-wal-enabled"]})
    i2 = cluster.start_instance("data2", {"args": [
        "--management-port", str(m2), "--no-storage-wal-enabled"]})
    cc = coord.client()
    c1 = i1.client()
    c2 = i2.client()
    # single-coordinator raft elects itself quickly
    deadline = time.time() + 30
    registered = False
    last_error = None
    while time.time() < deadline:
        try:
            cc.execute(f'REGISTER INSTANCE i1 ON "127.0.0.1:{m1}" '
                       f'WITH "127.0.0.1:{r1}"')
            registered = True
            break
        except Exception as e:
            last_error = e
            try:
                cc.reset()
            except Exception:
                pass
            time.sleep(0.3)
    assert registered, f"REGISTER INSTANCE never succeeded: {last_error}"
    cc.execute(f'REGISTER INSTANCE i2 ON "127.0.0.1:{m2}" '
               f'WITH "127.0.0.1:{r2}"')
    cc.execute("SET INSTANCE i1 TO MAIN")
    _, rows, _ = cc.execute("SHOW INSTANCES")
    roles = {r[0]: r[2] for r in rows}
    assert roles["i1"] == "main" and roles["i2"] == "replica"
    # write on MAIN replicates to the demoted replica process
    c1.execute("CREATE (:HAP {v: 1})")
    deadline = time.time() + 10
    while time.time() < deadline:
        _, rows, _ = c2.execute("MATCH (n:HAP) RETURN count(n)")
        if rows == [[1]]:
            break
        time.sleep(0.2)
    assert rows == [[1]]
    # kill the MAIN process → automatic failover to i2
    c1.close()
    i1.kill()
    deadline = time.time() + 30
    promoted = False
    while time.time() < deadline:
        _, rows, _ = cc.execute("SHOW INSTANCES")
        roles = {r[0]: r[2] for r in rows}
        if roles.get("i2") == "main":
            promoted = True
            break
        time.sleep(0.3)
    assert promoted, f"failover did not happen: {roles}"
    # promoted instance accepts writes and kept the data
    deadline = time.time() + 10
    wrote = False
    while time.time() < deadline:
        try:
            c2.execute("CREATE (:HAP {v: 2})")
            wrote = True
            break
        except Exception:
            try:
                c2.reset()
            except Exception:
                pass
            time.sleep(0.3)
    assert wrote, "promoted instance never accepted the write"
    _, rows, _ = c2.execute("MATCH (n:HAP) RETURN count(n)")
    assert rows == [[2]]
    cc.close()
    c2.close()


def test_bank_transfer_chaos(cluster):
    """Jepsen-lite bank workload: concurrent transfers + process kill/restart;
    total balance must be conserved after recovery."""
    import threading

    inst = cluster.start_instance("bank")
    setup = inst.client()
    N_ACCOUNTS, TOTAL = 5, 500
    setup.execute("UNWIND range(0, 4) AS i CREATE (:Account {id: i, "
                  "balance: 100})")
    setup.close()

    stop = threading.Event()
    errors = []

    def transfer_loop():
        from memgraph_tpu.server.client import BoltClient, BoltClientError
        while not stop.is_set():
            try:
                c = BoltClient(port=inst2_holder[0].bolt_port, timeout=5)
            except OSError:
                time.sleep(0.2)
                continue
            try:
                while not stop.is_set():
                    c.execute(
                        "MATCH (a:Account {id: toInteger(rand() * 5)}), "
                        "      (b:Account {id: toInteger(rand() * 5)}) "
                        "WHERE a.id <> b.id AND a.balance >= 10 "
                        "SET a.balance = a.balance - 10, "
                        "    b.balance = b.balance + 10")
            except Exception:
                pass  # conflicts / kills are the point
            finally:
                try:
                    c.close()
                except Exception:
                    pass

    inst2_holder = [inst]
    threads = [threading.Thread(target=transfer_loop) for _ in range(3)]
    for t in threads:
        t.start()

    # nemesis: kill and restart twice while transfers run
    try:
        for _ in range(2):
            time.sleep(1.0)
            inst2_holder[0].kill()
            time.sleep(0.3)
            inst2_holder[0] = cluster.restart_instance("bank")
            inst2_holder[0].client().close()  # wait until it serves
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)

    check = inst2_holder[0].client()
    _, rows, _ = check.execute(
        "MATCH (a:Account) RETURN count(a), sum(a.balance)")
    check.close()
    assert rows[0][0] == N_ACCOUNTS
    assert rows[0][1] == TOTAL  # balance conserved through crashes


def test_coordinator_route_and_reroute(cluster):
    """3 REAL coordinator processes share cluster state through raft;
    Bolt ROUTE serves it with ALL coordinators in the ROUTE role; after
    the bootstrap coordinator is killed, a client re-routes using only
    addresses learned from the routing table (reference:
    coordinator_instance.cpp routing + NuRaft failover)."""
    raft_ports = [free_port() for _ in range(3)]
    bolt_ports = [free_port() for _ in range(3)]
    ids = ["c1", "c2", "c3"]
    coords = []
    for i, cid in enumerate(ids):
        peers = ",".join(
            f"{ids[j]}=127.0.0.1:{raft_ports[j]}@{bolt_ports[j]}"
            for j in range(3) if j != i)
        coords.append(cluster.start_instance(f"coord{i + 1}", {
            "bolt_port": bolt_ports[i],
            "args": [
                "--coordinator-id", cid,
                "--coordinator-port", str(raft_ports[i]),
                "--coordinator-peers", peers,
                "--no-storage-wal-enabled"]}))
    m1 = free_port()
    r1 = free_port()
    data1 = cluster.start_instance("rdata1", {"args": [
        "--management-port", str(m1), "--no-storage-wal-enabled"]})

    # find the raft leader by trying REGISTER on each coordinator
    clients = {}
    leader_idx = None
    deadline = time.time() + 40
    while time.time() < deadline and leader_idx is None:
        for i, co in enumerate(coords):
            try:
                c = clients.get(i) or co.client()
                clients[i] = c
                c.execute(
                    f'REGISTER INSTANCE i1 ON "127.0.0.1:{m1}" '
                    f'WITH "127.0.0.1:{r1}" '
                    f'BOLT "127.0.0.1:{data1.bolt_port}"')
                leader_idx = i
                break
            except Exception:
                try:
                    clients[i].reset()
                except Exception:
                    clients.pop(i, None)
        time.sleep(0.3)
    assert leader_idx is not None, "no raft leader accepted REGISTER"
    clients[leader_idx].execute("SET INSTANCE i1 TO MAIN")

    # the routing table: MAIN as WRITE, every coordinator as ROUTE
    rt = clients[leader_idx].route()
    roles = {s["role"]: s["addresses"] for s in rt["servers"]}
    assert roles.get("WRITE") == [f"127.0.0.1:{data1.bolt_port}"]
    # own entry is the advertised address (localhost), peers by host
    route_ports = sorted(int(a.rpartition(":")[2]) for a in roles["ROUTE"])
    assert route_ports == sorted(bolt_ports)

    # kill the bootstrap coordinator; re-route like a driver would, using
    # ONLY the router addresses learned from the table
    killed_addr = f"127.0.0.1:{bolt_ports[leader_idx]}"
    coords[leader_idx].kill()
    for c in clients.values():
        try:
            c.close()
        except Exception:
            pass
    from memgraph_tpu.server.client import BoltClient
    survivor_write = None
    deadline = time.time() + 40
    while time.time() < deadline and survivor_write is None:
        for router in roles["ROUTE"]:
            host, _, port = router.rpartition(":")
            if int(port) == int(killed_addr.rpartition(":")[2]):
                continue
            try:
                rc = BoltClient(host=host, port=int(port))
                rt3 = rc.route()
                rc.close()
            except Exception:
                continue
            roles3 = {s["role"]: s["addresses"] for s in rt3["servers"]}
            if roles3.get("WRITE"):
                survivor_write = roles3["WRITE"][0]
                break
        time.sleep(0.3)
    assert survivor_write == f"127.0.0.1:{data1.bolt_port}"
    # the routed WRITE address accepts a write
    host, _, port = survivor_write.rpartition(":")
    wc = BoltClient(host=host, port=int(port))
    wc.execute("CREATE (:Routed {ok: 1})")
    _, rows, _ = wc.execute("MATCH (n:Routed) RETURN count(n)")
    assert rows == [[1]]
    wc.close()
