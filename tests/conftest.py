"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is a single chip in this environment; all sharding/
multi-chip tests run against 8 virtual CPU devices, exactly how the driver's
dryrun validates the multi-chip path.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def storage():
    from memgraph_tpu.storage import InMemoryStorage
    return InMemoryStorage()
