"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is a single chip in this environment; all sharding/
multi-chip tests run against 8 virtual CPU devices, exactly how the driver's
dryrun validates the multi-chip path.
"""

import os

# force-override: the environment pins JAX_PLATFORMS=axon (one real TPU chip)
# and /root/.axon_site pre-initializes jax, so both the env var AND the jax
# config must be set.
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def storage():
    from memgraph_tpu.storage import InMemoryStorage
    return InMemoryStorage()
