"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is a single chip in this environment; all sharding/
multi-chip tests run against 8 virtual CPU devices, exactly how the driver's
dryrun validates the multi-chip path.
"""

import os

# force-override: the environment pins JAX_PLATFORMS=axon (one real TPU chip)
# and /root/.axon_site pre-initializes jax, so both the env var AND the jax
# config must be set.
os.environ["JAX_PLATFORMS"] = "cpu"

# Arm the runtime lock-order witness (memgraph_tpu/utils/locks.py) for the
# whole suite: every lock the package creates becomes a TrackedLock, the
# actual acquisition graph is recorded, and the session fails if any cycle
# was witnessed (the dynamic validation of mglint's static MG001 rule).
# Must happen BEFORE any memgraph_tpu import creates a lock; opt out with
# MG_TRACK_LOCKS=0.
os.environ.setdefault("MG_TRACK_LOCKS", "1")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

import pytest  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pytest_configure(config):
    """MG_SAN=1: arm the vector-clock race detector for the whole suite.

    Every TrackedLock acquire/release and every shared_field annotation
    feeds the process-global detector; the session fails if any access
    pair is unordered by happens-before. Tests that arm their own
    detector via `mgsan.detecting()` stack on top and restore this one
    on exit."""
    from memgraph_tpu.utils import sanitize
    if not sanitize.armed():
        return
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    from tools.mgsan import racedetect
    config._mgsan_detector = racedetect.arm()


@pytest.fixture
def storage():
    from memgraph_tpu.storage import InMemoryStorage
    return InMemoryStorage()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Lock-order witness verdict for the whole session."""
    from memgraph_tpu.utils import locks
    if not locks.armed():
        return
    edges = locks.edges()
    bad = locks.violations()
    terminalreporter.write_line(
        f"lock-order witness: {len(edges)} edge(s) recorded, "
        f"{len(bad)} cycle(s)"
        + (" — ACYCLIC" if not bad else " — VIOLATIONS BELOW"))
    for cycle, site in bad:
        terminalreporter.write_line(
            f"  CYCLE {' -> '.join(cycle)} closed at {site}", red=True)
    det = getattr(config, "_mgsan_detector", None)
    if det is not None:
        terminalreporter.write_line(
            f"mgsan race detector: {len(det.races)} race(s)"
            + (" — CLEAN" if not det.races else " — RACES BELOW"))
        for race in det.races:
            terminalreporter.write_line(f"  {race.render()}", red=True)


def pytest_sessionfinish(session, exitstatus):
    """Fail the run on witnessed lock-order cycles or data races."""
    from memgraph_tpu.utils import locks
    if locks.armed() and locks.violations():
        session.exitstatus = 1
    det = getattr(session.config, "_mgsan_detector", None)
    if det is not None and det.races:
        session.exitstatus = 1
