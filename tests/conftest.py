"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is a single chip in this environment; all sharding/
multi-chip tests run against 8 virtual CPU devices, exactly how the driver's
dryrun validates the multi-chip path.
"""

import os

# force-override: the environment pins JAX_PLATFORMS=axon (one real TPU chip)
# and /root/.axon_site pre-initializes jax, so both the env var AND the jax
# config must be set.
os.environ["JAX_PLATFORMS"] = "cpu"

# Arm the runtime lock-order witness (memgraph_tpu/utils/locks.py) for the
# whole suite: every lock the package creates becomes a TrackedLock, the
# actual acquisition graph is recorded, and the session fails if any cycle
# was witnessed (the dynamic validation of mglint's static MG001 rule).
# Must happen BEFORE any memgraph_tpu import creates a lock; opt out with
# MG_TRACK_LOCKS=0.
os.environ.setdefault("MG_TRACK_LOCKS", "1")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def storage():
    from memgraph_tpu.storage import InMemoryStorage
    return InMemoryStorage()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Lock-order witness verdict for the whole session."""
    from memgraph_tpu.utils import locks
    if not locks.armed():
        return
    edges = locks.edges()
    bad = locks.violations()
    terminalreporter.write_line(
        f"lock-order witness: {len(edges)} edge(s) recorded, "
        f"{len(bad)} cycle(s)"
        + (" — ACYCLIC" if not bad else " — VIOLATIONS BELOW"))
    for cycle, site in bad:
        terminalreporter.write_line(
            f"  CYCLE {' -> '.join(cycle)} closed at {site}", red=True)


def pytest_sessionfinish(session, exitstatus):
    """Fail the run if the witness recorded any lock-order cycle."""
    from memgraph_tpu.utils import locks
    if locks.armed() and locks.violations():
        session.exitstatus = 1
