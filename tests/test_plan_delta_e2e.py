"""Commit-then-CALL plan refresh: a topology-mutating commit must NOT
force a full MXU replan — the next pagerank call derives an O(delta)
side-plan from the storage change log (VERDICT r4 item 2).
"""

import numpy as np
import pytest

from memgraph_tpu.ops import pagerank as pr_mod
from memgraph_tpu.ops.csr import GraphCache
from memgraph_tpu.storage import InMemoryStorage, StorageConfig, StorageMode


def _scipy_pagerank(src, dst, n, iters=60, damping=0.85):
    import scipy.sparse as sp
    w = np.ones(len(src))
    wsum = np.bincount(src, weights=w, minlength=n)
    inv = np.where(wsum > 0, 1.0 / np.maximum(wsum, 1e-300), 0.0)
    m = sp.csr_matrix((w * inv[src], (dst, src)), shape=(n, n))
    dang = wsum <= 0
    rank = np.full(n, 1.0 / n)
    for _ in range(iters):
        dm = rank[dang].sum()
        rank = (1 - damping) / n + damping * (m @ rank + dm / n)
    return rank


@pytest.fixture
def setup(monkeypatch):
    # force the MXU path at test scale (and on the CPU backend)
    monkeypatch.setattr(pr_mod, "MXU_MIN_EDGES", 1)
    monkeypatch.setenv("MEMGRAPH_TPU_FORCE_MXU", "1")
    storage = InMemoryStorage(StorageConfig(
        storage_mode=StorageMode.IN_MEMORY_TRANSACTIONAL))
    rng = np.random.default_rng(3)
    n, e = 1500, 9000
    acc = storage.access()
    et = storage.edge_type_mapper.name_to_id("E")
    vs = [acc.create_vertex() for _ in range(n)]
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    for s, d in zip(src, dst):
        acc.create_edge(vs[s], vs[d], et)
    acc.commit()
    return storage, vs, et, src.tolist(), dst.tolist(), n


def _ranks(storage, cache):
    acc = storage.access()
    g = cache.get(acc)
    r, _, _ = pr_mod.pagerank(g, max_iterations=60, tol=0.0)
    acc.abort()
    return g, np.asarray(r)


def test_commit_then_call_uses_delta(setup):
    storage, vs, et, src, dst, n = setup
    cache = GraphCache()
    g1, r1 = _ranks(storage, cache)
    assert getattr(g1, "_mxu_base_self", False)
    base_plan = g1._mxu_state[0]

    # mutate: add 40 edges, remove 10 (topology-bumping commit)
    acc = storage.access()
    rng = np.random.default_rng(7)
    added = []
    for _ in range(40):
        s, d = int(rng.integers(0, n)), int(rng.integers(0, n))
        acc.create_edge(vs[s], vs[d], et)
        added.append((s, d))
    removed = []
    victims = set()
    for ve in list(storage._edges.values()):
        if len(removed) >= 10 or ve.gid in victims:
            continue
        victims.add(ve.gid)
        from memgraph_tpu.storage.storage import EdgeAccessor
        ea = EdgeAccessor(ve, acc)
        acc.delete_edge(ea)
        removed.append((g1.gid_to_idx[ve.from_vertex.gid],
                        g1.gid_to_idx[ve.to_vertex.gid]))
    acc.commit()

    g2, r2 = _ranks(storage, cache)
    # the second snapshot must have refreshed via delta, not a full build
    assert g2._mxu_state[0] is base_plan, "full replan happened"
    assert not getattr(g2, "_mxu_base_self", False)

    # and the numbers must be exact for the mutated graph (oracle from
    # the snapshot's own edge list — the MVCC-visible set)
    s2, d2, _w2 = g2.host_coo
    want = _scipy_pagerank(s2.astype(np.int64), d2.astype(np.int64), n)
    np.testing.assert_allclose(r2, want, rtol=3e-4, atol=1e-9)
    assert not np.allclose(r1, r2)     # the mutation actually changed ranks


def test_edge_weight_change_invalidates_plan(setup):
    """A transactional SET on an edge property must enter the change
    log (via the edge's endpoints) so weighted pagerank never serves
    stale multipliers (r5 review finding)."""
    storage, vs, et, src, dst, n = setup
    wprop = storage.property_mapper.name_to_id("w")
    acc = storage.access()
    from memgraph_tpu.storage.storage import EdgeAccessor
    for ve in list(storage._edges.values())[:50]:
        EdgeAccessor(ve, acc).set_property(wprop, 5.0)
    acc.commit()
    cache = GraphCache()
    acc = storage.access()
    g1 = cache.get(acc, weight_property=wprop)
    r1, _, _ = pr_mod.pagerank(g1, max_iterations=40, tol=0.0)
    acc.abort()
    # transactional edge-property write, then re-CALL
    acc = storage.access()
    victim = next(iter(storage._edges.values()))
    EdgeAccessor(victim, acc).set_property(wprop, 250.0)
    acc.commit()
    acc = storage.access()
    g2 = cache.get(acc, weight_property=wprop)
    r2, _, _ = pr_mod.pagerank(g2, max_iterations=40, tol=0.0)
    acc.abort()
    s2, d2, w2 = g2.host_coo
    import scipy.sparse as sp
    wsum = np.bincount(s2, weights=w2.astype(np.float64), minlength=n)
    inv = np.where(wsum > 0, 1.0 / np.maximum(wsum, 1e-300), 0.0)
    m = sp.csr_matrix((w2 * inv[s2], (d2, s2)), shape=(n, n))
    dang = wsum <= 0
    rank = np.full(n, 1.0 / n)
    for _ in range(40):
        dm = rank[dang].sum()
        rank = 0.15 / n + 0.85 * (m @ rank + dm / n)
    np.testing.assert_allclose(r2, rank, rtol=3e-4, atol=1e-9)
    assert not np.allclose(r1, r2)


def test_huge_delta_recompacts(setup):
    storage, vs, et, src, dst, n = setup
    cache = GraphCache()
    g1, _ = _ranks(storage, cache)
    base_plan = g1._mxu_state[0]
    # add 30% more edges: beyond DELTA_RECOMPACT_FRACTION -> full replan
    acc = storage.access()
    rng = np.random.default_rng(9)
    for _ in range(2700):
        acc.create_edge(vs[int(rng.integers(0, n))],
                        vs[int(rng.integers(0, n))], et)
    acc.commit()
    g2, r2 = _ranks(storage, cache)
    assert g2._mxu_state[0] is not base_plan
    assert getattr(g2, "_mxu_base_self", False)


def test_chained_commits_delta_from_original_base(setup):
    """Two successive commits: the second delta still anchors on the
    ORIGINAL full plan (cumulative diff), not on the first delta."""
    storage, vs, et, src, dst, n = setup
    cache = GraphCache()
    g1, _ = _ranks(storage, cache)
    base_plan = g1._mxu_state[0]
    rng = np.random.default_rng(11)
    for _round in range(2):
        acc = storage.access()
        for _ in range(25):
            acc.create_edge(vs[int(rng.integers(0, n))],
                            vs[int(rng.integers(0, n))], et)
        acc.commit()
        g, r = _ranks(storage, cache)
        assert g._mxu_state[0] is base_plan
    s2, d2, _w2 = g.host_coo
    want = _scipy_pagerank(s2.astype(np.int64), d2.astype(np.int64), n)
    np.testing.assert_allclose(r, want, rtol=3e-4, atol=1e-9)
