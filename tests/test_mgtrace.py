"""mgtrace: span model, retention policy, cross-boundary propagation,
Chrome export, and the disarmed-overhead guard.

The propagation tests are the satellite contract: child spans recorded
on the far side of the kernel-server socket and the mp_executor fork
boundary must carry the parent's trace_id and ship home into ONE
connected trace.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from memgraph_tpu.observability import trace as T
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def tracer():
    """Armed tracer with a clean buffer; disarmed + cleared afterwards."""
    T.TRACER.reset()
    T.enable(sample=1.0, slow_ms=250.0)
    yield T.TRACER
    T.disable()
    T.TRACER.reset()


@pytest.fixture
def interp():
    return Interpreter(InterpreterContext(InMemoryStorage()))


def _names(spans):
    return {s["name"] for s in spans}


def _one_connected(spans):
    """Single trace_id, every parent link resolves, exactly one root."""
    assert len({s["trace_id"] for s in spans}) == 1, spans
    ids = {s["span_id"] for s in spans}
    for s in spans:
        if s["parent_id"]:
            assert s["parent_id"] in ids, (s["name"], spans)
    roots = [s for s in spans if not s["parent_id"]]
    assert len(roots) == 1, [s["name"] for s in roots]
    return roots[0]


# --- span model -------------------------------------------------------------


def test_query_yields_one_connected_trace(tracer, interp):
    interp.execute("CREATE (:N {v: 1})")
    traces = T.traces_json()
    assert len(traces) == 1
    spans = traces[0]
    assert {"query", "query.parse", "query.plan", "query.execute",
            "query.commit", "mvcc.begin", "mvcc.commit"} <= _names(spans)
    root = _one_connected(spans)
    assert root["name"] == "query"
    # phase durations ride the root span for the slow-log linkage
    assert "parse_ms" in root["attrs"] and "plan_ms" in root["attrs"]
    # literals are redacted before a query text reaches a trace
    interp.execute("CREATE (:N {s: 'secret-literal'})")
    root2 = _one_connected(T.traces_json()[-1])
    assert "secret-literal" not in root2["attrs"]["query"]


def test_every_product_span_name_is_declared(tracer, interp):
    interp.execute("RETURN 1")
    for spans in T.traces_json():
        for s in spans:
            assert s["name"] in T.SPAN_NAMES, s["name"]


def test_head_sampling_drops_fast_ok_traces(tracer, interp):
    T.enable(sample=0.0)
    interp.execute("RETURN 1")
    assert T.traces_json() == []
    counts = T.TRACER.counts()
    assert counts["dropped"] >= 1 and counts["kept"] == 0


def test_errored_trace_always_kept(tracer, interp):
    T.enable(sample=0.0)
    with pytest.raises(Exception):
        interp.execute("MATCH (n) RETURN n.v + 'x' <<<")
    traces = T.traces_json()
    assert len(traces) == 1
    root = [s for s in traces[0] if s["name"] == "query"][0]
    assert root["status"] == "error"


def test_slow_trace_always_kept(tracer, interp):
    T.enable(sample=0.0, slow_ms=0.0)   # everything counts as slow
    interp.execute("RETURN 1")
    assert len(T.traces_json()) == 1


def test_sampling_decision_is_deterministic_per_trace_id():
    assert T._sample_decision("00000000" + "0" * 24, 0.5)
    assert not T._sample_decision("ffffffff" + "0" * 24, 0.5)
    for rate in (0.0, 0.25, 1.0):
        tid = "8a3b0c1d" + "0" * 24
        assert T._sample_decision(tid, rate) == \
            T._sample_decision(tid, rate)


def test_disarmed_api_is_inert():
    T.disable()
    assert T.begin_trace("query") is None
    assert T.inject() is None
    with T.span("query.parse") as sp:
        assert not sp
        sp.set(anything=1)
    with T.activate(None):
        pass
    with T.adopt({"trace_id": "x"}):
        pass
    assert T.traces_json() == []


def test_chrome_export_is_valid(tracer, interp):
    interp.execute("CREATE (:C)")
    doc = json.loads(json.dumps(T.chrome_trace()))
    events = doc["traceEvents"]
    assert events
    for ev in events:
        assert ev["ph"] == "X"
        assert ev["ts"] > 0 and ev["dur"] > 0
        assert ev["cat"] == "mgtrace"
        assert "trace_id" in ev["args"]
    jsonl = T.to_jsonl()
    parsed = [json.loads(line) for line in jsonl.splitlines()]
    assert len(parsed) == len(events)


def test_slow_query_log_links_trace(tracer, caplog):
    import logging
    ctx = InterpreterContext(InMemoryStorage(),
                             {"log_min_duration_ms": 0.0001})
    interp = Interpreter(ctx)
    with caplog.at_level(logging.INFO,
                         logger="memgraph_tpu.query.interpreter"):
        interp.execute("CREATE (:S {v: 'sekrit'})")
    slow = [r.message for r in caplog.records
            if "slow query" in r.message]
    assert slow, caplog.records
    msg = slow[0]
    assert "trace_id=" in msg
    trace_id = msg.split("trace_id=")[1].split(",")[0]
    assert trace_id != "-"
    # every phase named, literals redacted
    for phase in ("parse=", "plan=", "execute=", "commit="):
        assert phase in msg, msg
    assert "sekrit" not in msg
    # the named trace is retained and retrievable by id
    kept = T.traces_json(trace_id)
    assert kept and kept[0][0]["trace_id"] == trace_id


def test_active_buffer_bounded(tracer):
    for i in range(T.TRACER.MAX_ACTIVE + 50):
        with T.adopt({"trace_id": f"{i:032x}", "span_id": "00",
                      "sampled": True}):
            with T.span("query.parse"):
                pass
    assert len(T.TRACER._active) <= T.TRACER.MAX_ACTIVE


# --- cross-boundary propagation --------------------------------------------


def test_kernel_server_socket_propagation(tracer, tmp_path):
    """Spans recorded on the far side of the kernel-server request
    protocol carry the parent trace_id and ship home on the reply."""
    from memgraph_tpu.server.kernel_server import (KernelClient,
                                                   KernelServer)
    sock = str(tmp_path / "ks.sock")
    server = KernelServer(sock, idle_timeout_s=0.0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    deadline = time.monotonic() + 120
    client = None
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=60)
            if client.ping():
                break
            client.close()
        except OSError:
            time.sleep(0.05)
    assert client is not None and client.ping()
    try:
        rng = np.random.default_rng(3)
        src = rng.integers(0, 50, 300)
        dst = rng.integers(0, 50, 300)
        handle = T.begin_trace("query")
        with T.activate(handle.ctx):
            ranks, err, iters = client.pagerank(
                src=src, dst=dst, n_nodes=50, max_iterations=5)
        handle.finish()
        assert len(ranks) == 50
        traces = T.traces_json(handle.trace_id)
        assert traces, "traced kernel request was not retained"
        spans = traces[0]
        got = _names(spans)
        assert {"query", "kernel.dispatch", "device.transfer",
                "device.chunk"} <= got, got
        _one_connected(spans)
        dispatch = [s for s in spans if s["name"] == "kernel.dispatch"][0]
        assert dispatch["trace_id"] == handle.trace_id
        # parent chain: kernel.dispatch hangs off the carrier span
        assert dispatch["parent_id"]
    finally:
        client.shutdown()
        client.close()
        t.join(timeout=10)


def test_mp_executor_fork_propagation(tracer, interp):
    """The mp_executor job envelope carries the trace across the fork;
    the worker's spans (its own query trace included) come home in the
    response and join the parent's retained trace."""
    from memgraph_tpu.server.mp_executor import MPReadExecutor
    interp.execute("UNWIND range(1, 5) AS i CREATE (:M {v: i})")
    T.TRACER.reset()   # drop the setup queries' traces
    pool = MPReadExecutor(interp.ctx, n_workers=1)
    try:
        handle = T.begin_trace("query")
        with T.activate(handle.ctx):
            cols, rows = pool.execute("MATCH (m:M) RETURN count(m)")
        handle.finish()
        assert rows == [[5]]
        traces = T.traces_json(handle.trace_id)
        assert traces, "traced mp query was not retained"
        spans = traces[0]
        got = _names(spans)
        assert {"query", "mp.execute", "mp.worker",
                "query.parse"} <= got, got
        _one_connected(spans)
        worker = [s for s in spans if s["name"] == "mp.worker"][0]
        assert worker["trace_id"] == handle.trace_id
        assert worker["pid"] != os.getpid()   # recorded across the fork
    finally:
        pool.close()


def test_replication_system_txn_carries_trace(tracer):
    """The replication wire (JSON system txns) propagates the context;
    the replica-side apply span joins the originating trace."""
    from memgraph_tpu.replication.replica import ReplicaServer
    storage = InMemoryStorage()
    replica = ReplicaServer(storage, port=0)
    replica.start()
    try:
        from memgraph_tpu.replication.main_role import (ReplicaClient,
                                                        ReplicationMode)
        client = ReplicaClient(
            "r1", f"127.0.0.1:{replica.port}", ReplicationMode.SYNC,
            InMemoryStorage(), epoch_fn=lambda: 0)
        client.connect_and_catch_up()
        handle = T.begin_trace("query")
        with T.activate(handle.ctx):
            ok = client.send_system(
                {"seq": 1, "kind": "auth", "data": {}})
        handle.finish()
        assert ok
        # the replica finalized its half locally (retain=True): an
        # adopted repl.apply span under the same trace id
        applied = [spans for spans in T.traces_json()
                   if any(s["name"] == "repl.apply" for s in spans)]
        assert applied, T.traces_json()
        apply_span = [s for s in applied[0]
                      if s["name"] == "repl.apply"][0]
        assert apply_span["trace_id"] == handle.trace_id
        client.close()
    finally:
        replica.stop()


# --- overhead guard ---------------------------------------------------------


def test_disarmed_overhead_under_two_percent(interp):
    """Disarmed tracing must add ≤2% to a tier-1 micro-benchmark.

    Deterministic form of the bound: (trace-API calls per query) x
    (measured per-call disarmed cost) must stay under 2% of the
    measured per-query time. The call-count budget (40) is ~4x the
    real per-query count, so the assertion holds with margin even if
    future hops add sites.
    """
    assert not T.armed()
    # a representative OLTP micro-benchmark: a 200-row indexed-label
    # scan with a filter + aggregate (the disarmed overhead is a FIXED
    # ~10 API calls per query, so the bound is against a real query,
    # not the cheapest statement imaginable)
    interp.execute("UNWIND range(1, 200) AS i CREATE (:B {v: i})")

    # per-call cost of the disarmed fast path (min over batches)
    def span_batch():
        t0 = time.perf_counter()
        for _ in range(2000):
            with T.span("query.parse"):
                pass
        return (time.perf_counter() - t0) / 2000

    per_call = min(span_batch() for _ in range(5))

    # per-query cost of the micro-benchmark (min over runs: the same
    # estimator bench.py uses against scheduler noise)
    query = "MATCH (b:B) WHERE b.v > 100 RETURN count(b)"
    interp.execute(query)                   # warm plan cache

    def query_batch():
        t0 = time.perf_counter()
        for _ in range(20):
            interp.execute(query)
        return (time.perf_counter() - t0) / 20

    per_query = min(query_batch() for _ in range(3))

    budget_calls = 40                       # ~4x the real per-query count
    overhead = per_call * budget_calls
    assert overhead <= 0.02 * per_query, (
        f"disarmed tracing overhead {overhead * 1e6:.2f}µs "
        f"({budget_calls} sites x {per_call * 1e9:.0f}ns) exceeds 2% "
        f"of the {per_query * 1e6:.1f}µs micro-benchmark query")


def test_disarmed_span_is_allocation_free_singleton():
    T.disable()
    a = T.span("query.parse")
    b = T.span("query.plan", anything=1)
    assert a is b is T._NOOP


def test_bolt_session_trace_end_to_end(tracer):
    """A Bolt RUN..PULL against a live server yields one connected
    retained trace (session -> interpreter -> storage txn), the client
    carrier in the `extra` metadata field parents the whole thing, and
    the SUCCESS metadata names the trace_id."""
    import socket

    from memgraph_tpu.server.bolt import BoltServer
    from memgraph_tpu.server.client import BoltClient

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ictx = InterpreterContext(InMemoryStorage())
    srv = BoltServer(ictx, "127.0.0.1", port)
    thread, loop = srv.run_in_thread()
    try:
        client = BoltClient(port=port)
        # drive RUN with a client-side carrier in the extra field
        client_carrier = {"trace_id": "c" * 32, "span_id": "d" * 16,
                          "sampled": True}
        from memgraph_tpu.server.client import (M_PULL, M_RECORD,
                                                M_RUN)
        client._send_message(M_RUN, "CREATE (:T {v: 1}) RETURN 1", {},
                             {"trace": client_carrier})
        run_meta = client._expect_success()
        assert run_meta.get("trace_id") == "c" * 32
        client._send_message(M_PULL, {"n": -1})
        pull_meta = None
        while True:
            msg = client._read_message()
            if msg.tag == M_RECORD:
                continue
            pull_meta = msg.fields[0] if msg.fields else {}
            break
        assert pull_meta.get("trace_id") == "c" * 32
        client.close()
        traces = T.traces_json("c" * 32)
        assert traces, "bolt session trace was not retained"
        spans = traces[0]
        got = _names(spans)
        assert {"bolt.run", "query", "query.parse", "query.execute",
                "query.commit", "mvcc.commit"} <= got, got
        # bolt.run is the local root, parented on the CLIENT's span
        bolt_root = [s_ for s_ in spans if s_["name"] == "bolt.run"][0]
        assert bolt_root["parent_id"] == "d" * 16
        q = [s_ for s_ in spans if s_["name"] == "query"][0]
        assert q["parent_id"] == bolt_root["span_id"]
        # chrome export of exactly this trace parses
        doc = json.loads(json.dumps(T.chrome_trace(traces)))
        assert all(ev["args"]["trace_id"] == "c" * 32
                   for ev in doc["traceEvents"])
    finally:
        srv.stop()
        loop.call_soon_threadsafe(loop.stop)
