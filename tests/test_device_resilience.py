"""Resilient accelerator plane (ISSUE 7): seeded device-nemesis matrix.

Four layers of coverage:

1. Checkpoint/resume core (parallel/checkpoint.py): chunked kernels are
   bit-exact vs monolithic; a device fault (call/oom/lost) mid-pagerank
   resumes from the last checkpoint — bit-exact vs an unfaulted run,
   re-executing at most k iterations; a hang is observed as a slow
   chunk; a persistent fault exhausts the retry budget loudly.
2. Supervised kernel server: typed outcomes (completed /
   deadline_exceeded / device_error / oom / shed / invalid) end to end
   over the wire, the HBM admission guard, health/wedge reporting, and
   the client-side supervisor's retry + restart logic. Includes the
   CHECKER-HONESTY case: with supervision disabled a device hang wedges
   the client — and the harness detects and flags exactly that.
3. Seeded device-nemesis schedules (tools/mgchaos/device.py): byte
   identity, full (op x context) matrix coverage, and — device_chaos
   marked — the 10-seed sweep of the whole matrix plus the real
   subprocess kill/respawn path.
4. RetryPolicy deadline semantics (utils/retry.py) and bench.py's typed
   probe classification.
"""

import os
import pathlib
import sys
import threading
import time

import numpy as np
import pytest

from memgraph_tpu.ops import csr
from memgraph_tpu.parallel import analytics
from memgraph_tpu.parallel.checkpoint import (Checkpoint, CheckpointStore,
                                              RunReport, default_store)
from memgraph_tpu.parallel.mesh import get_mesh_context
from memgraph_tpu.server.kernel_server import (
    AdmissionRejected, KernelClient, KernelDeadlineExceeded,
    KernelDeviceError, KernelOom, KernelServer, SupervisedKernelClient,
    probe_device)
from memgraph_tpu.utils import faultinject as FI
from memgraph_tpu.utils.devicefault import (DeviceLostError, DeviceOomError,
                                            classify_device_error)
from memgraph_tpu.utils.retry import RetryPolicy

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO)) if str(REPO) not in sys.path else None

from tools.mgchaos.device import (DEVICE_CONTEXTS, device_schedule,  # noqa: E402
                                  device_schedule_text, run_device_matrix)

K = 4              # checkpoint interval the resume tests run with
ITERS = 16         # tol=-1 pins runs to exactly this many iterations
SWEEP_SEEDS = range(10)


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset()
    yield
    FI.reset()


@pytest.fixture(scope="module")
def graph():
    rng = np.random.default_rng(0)
    n, e = 203, 1200           # uneven n: pads the last vertex block
    return csr.from_coo(rng.integers(0, n, e), rng.integers(0, n, e),
                        n_nodes=n)


@pytest.fixture(scope="module")
def ctx4():
    return get_mesh_context(4)


def _pagerank(graph, ctx, k=K, report=None, **kw):
    return analytics.pagerank_mesh(graph, ctx, max_iterations=ITERS,
                                   tol=-1.0, checkpoint_every=k,
                                   report=report, **kw)


# ==========================================================================
# 1. checkpoint/resume core
# ==========================================================================


def test_chunked_pagerank_bit_exact_vs_monolithic(graph, ctx4):
    mono, err_m, it_m = _pagerank(graph, ctx4, k=0)
    chunk, err_c, it_c = _pagerank(graph, ctx4, k=3)
    assert it_m == it_c == ITERS
    assert err_m == err_c
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(chunk))


def test_chunked_katz_labelprop_wcc_bit_exact(graph, ctx4):
    km, _, ikm = analytics.katz_mesh(graph, ctx4, alpha=0.05,
                                     max_iterations=30, tol=1e-8,
                                     normalized=True)
    kc, _, ikc = analytics.katz_mesh(graph, ctx4, alpha=0.05,
                                     max_iterations=30, tol=1e-8,
                                     normalized=True, checkpoint_every=4)
    assert ikm == ikc
    np.testing.assert_array_equal(np.asarray(km), np.asarray(kc))
    lm, ilm = analytics.label_propagation_mesh(graph, ctx4,
                                               max_iterations=20)
    lc, ilc = analytics.label_propagation_mesh(graph, ctx4,
                                               max_iterations=20,
                                               checkpoint_every=3)
    assert ilm == ilc
    np.testing.assert_array_equal(np.asarray(lm), np.asarray(lc))
    cm, icm = analytics.components_mesh(graph, ctx4)
    cc, icc = analytics.components_mesh(graph, ctx4, checkpoint_every=2)
    assert icm == icc
    np.testing.assert_array_equal(np.asarray(cm), np.asarray(cc))


@pytest.mark.parametrize("point,expect", [
    ("device.call", "device_error"),
    ("device.oom", "oom"),
    ("device.lost", "device_lost"),
])
@pytest.mark.parametrize("hit", [1, 3])
def test_fault_mid_pagerank_resumes_bit_exact(graph, ctx4, point, expect,
                                              hit):
    """A device fault at chunk `hit` resumes from the last checkpoint:
    result bit-exact vs the unfaulted run, at most k iterations redone."""
    ref, _, _ = _pagerank(graph, ctx4)
    FI.arm(point, "raise", at=hit)
    report = RunReport()
    out, _, iters = _pagerank(graph, ctx4, report=report)
    assert iters == ITERS
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert report.resumes == 1
    assert report.faults == [expect]
    assert report.lost_spans and max(report.lost_spans) <= K
    if expect == "device_lost":
        assert report.rebuilds == 1    # inputs were re-placed


def test_hang_mid_pagerank_completes_and_is_observed(graph, ctx4):
    from memgraph_tpu.parallel.distributed import pagerank_partition_centric
    ref, _, _ = _pagerank(graph, ctx4)
    scsr = csr.shard_csr(graph, ctx4, by="src")
    FI.arm("device.hang", "delay", arg=0.3, at=2)
    report = RunReport()
    out, _, _ = pagerank_partition_centric(
        scsr, ctx4, max_iterations=ITERS, tol=-1.0, checkpoint_every=K,
        chunk_deadline_s=0.05, report=report)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert report.slow_chunks >= 1
    assert report.resumes == 0         # a hang completes, late


def test_persistent_fault_exhausts_retry_budget(graph, ctx4):
    FI.arm("device.call", "raise")     # every hit
    report = RunReport()
    with pytest.raises(Exception) as ei:
        _pagerank(graph, ctx4, report=report)
    assert classify_device_error(ei.value) == "device_error"
    assert report.resumes >= 1         # it DID try before giving up


def test_fault_during_first_chunk_resumes_from_start(graph, ctx4):
    ref, _, _ = _pagerank(graph, ctx4)
    FI.arm("device.oom", "raise", at=1)
    report = RunReport()
    out, _, _ = _pagerank(graph, ctx4, report=report)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert report.resumes == 1 and report.lost_spans == [K]


def test_resumable_metrics_counted(graph, ctx4):
    from memgraph_tpu.observability.metrics import global_metrics

    def counter(name):
        return dict((n, v) for n, _k, v in global_metrics.snapshot()
                    ).get(name, 0.0)

    saved0 = counter("analytics.checkpoint.saved_total")
    resumed0 = counter("analytics.resume_total")
    FI.arm("device.call", "raise", at=2)
    _pagerank(graph, ctx4)
    assert counter("analytics.checkpoint.saved_total") > saved0
    assert counter("analytics.resume_total") == resumed0 + 1
    assert counter("analytics.device_fault.device_error_total") >= 1


def test_checkpoint_store_roundtrip_and_lru():
    store = CheckpointStore()
    for i in range(store.MAX_JOBS + 5):
        store.put(f"job{i}", Checkpoint("pagerank", i, (np.arange(3),)))
    assert len(store.jobs()) == store.MAX_JOBS
    assert store.get("job0") is None          # evicted
    got = store.get(f"job{store.MAX_JOBS + 4}")
    assert got.iteration == store.MAX_JOBS + 4
    store.drop(f"job{store.MAX_JOBS + 4}")
    assert store.get(f"job{store.MAX_JOBS + 4}") is None
    assert default_store() is default_store()


def test_named_job_resume_across_callers(graph, ctx4):
    """A caller that died mid-run resumes from the named job's
    checkpoint: the second run starts at the stored iteration."""
    store = CheckpointStore()
    FI.arm("device.call", "raise")     # permanent: first run must die
    with pytest.raises(Exception):
        _pagerank(graph, ctx4, job="resume-me", store=store,
                  retry=RetryPolicy(max_retries=0, base_delay=0.01))
    ck = store.get("resume-me")
    assert ck is not None and ck.iteration == 0
    FI.reset()
    ref, _, _ = _pagerank(graph, ctx4)
    report = RunReport()
    out, _, _ = _pagerank(graph, ctx4, job="resume-me", store=store,
                          report=report)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert store.get("resume-me") is None     # completed → dropped


# ==========================================================================
# 2. supervised kernel server (in-thread daemon)
# ==========================================================================


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("devres") / "ks.sock")
    srv = KernelServer(sock, wedge_after_s=0.4, checkpoint_every=K)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    client = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=30)
            break
        except OSError:
            time.sleep(0.05)
    assert client is not None, "in-thread kernel server never bound"
    yield srv, client, sock
    client.shutdown()
    client.close()


@pytest.fixture(scope="module")
def served_graph(server):
    """A graph preloaded into the server cache + its unfaulted ranks."""
    _, client, _ = server
    rng = np.random.default_rng(1)
    n, e = 300, 1800
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    ranks, _, _ = client.pagerank(src=src, dst=dst, n_nodes=n,
                                  graph_key="devres",
                                  max_iterations=ITERS, tol=1e-12)
    return np.asarray(ranks), (src, dst, n)


@pytest.mark.parametrize("point,exc,outcome", [
    ("device.call", KernelDeviceError, "device_error"),
    ("device.oom", KernelOom, "oom"),
    ("device.lost", KernelDeviceError, "device_error"),
])
def test_typed_outcome_mid_kernel_request(server, served_graph, point,
                                          exc, outcome):
    """A device fault at the dispatch boundary surfaces as a TYPED
    client exception; the server survives and the next request works."""
    _, client, _ = server
    ref, _ = served_graph
    FI.arm(point, "raise", at=1)
    with pytest.raises(exc) as ei:
        client.pagerank(graph_key="devres", max_iterations=ITERS,
                        tol=1e-12)
    assert ei.value.outcome == outcome
    FI.reset()
    assert client.ping()
    ranks, _, _ = client.pagerank(graph_key="devres",
                                  max_iterations=ITERS, tol=1e-12)
    np.testing.assert_array_equal(np.asarray(ranks), ref)


def test_fault_mid_compute_is_resumed_server_side(server, served_graph):
    """Armed past the dispatch boundary, the fault lands inside the
    resumable loop: the SERVER resumes from its checkpoint and the
    client sees a completed, bit-exact reply — no error at all."""
    _, client, _ = server
    ref, _ = served_graph
    FI.arm("device.call", "raise", at=2)     # hit 2 = first chunk
    ranks, _, _ = client.pagerank(graph_key="devres",
                                  max_iterations=ITERS, tol=1e-12)
    np.testing.assert_array_equal(np.asarray(ranks), ref)


def test_dispatch_deadline_exceeded_then_recovers(server, served_graph):
    _, client, _ = server
    ref, _ = served_graph
    FI.arm("device.hang", "delay", arg=0.8, at=1)
    t0 = time.monotonic()
    with pytest.raises(KernelDeadlineExceeded):
        client.pagerank(graph_key="devres", deadline_s=0.15,
                        max_iterations=ITERS, tol=1e-12)
    assert time.monotonic() - t0 < 0.6       # typed failure, not a wedge
    h = client.health()
    assert h["in_flight"] >= 1               # the dispatch is still stuck
    time.sleep(0.9)                          # let the hang drain
    FI.reset()
    ranks, _, _ = client.pagerank(graph_key="devres",
                                  max_iterations=ITERS, tol=1e-12)
    np.testing.assert_array_equal(np.asarray(ranks), ref)


def test_admission_guard_sheds_typed_and_counts(server, served_graph):
    srv, client, _ = server
    _, (src, dst, n) = served_graph
    before = client.health()["counters"].get(
        "kernel_server.admission_rejected_total", 0)
    old_budget = srv.hbm_budget_bytes
    srv.hbm_budget_bytes = 1024
    try:
        with pytest.raises(AdmissionRejected) as ei:
            client.pagerank(src=src, dst=dst, n_nodes=n)
        assert ei.value.outcome == "shed"
        assert not ei.value.retryable
    finally:
        srv.hbm_budget_bytes = old_budget
    h = client.health()
    assert h["counters"]["kernel_server.admission_rejected_total"] \
        == before + 1
    assert h["counters"]["kernel_server.dispatch.shed_total"] >= 1


def test_supervised_client_retries_transient_device_error(server,
                                                          served_graph):
    _, _, sock = server
    ref, _ = served_graph
    FI.arm("device.call", "raise", at=1)     # first attempt fails typed
    sup = SupervisedKernelClient(
        sock, spawn=False, deadline_s=30.0,
        retry=RetryPolicy(base_delay=0.05, max_retries=3,
                          attempt_timeout=30.0))
    try:
        ranks, _, _ = sup.pagerank(graph_key="devres",
                                   max_iterations=ITERS, tol=1e-12)
        np.testing.assert_array_equal(np.asarray(ranks), ref)
    finally:
        sup.close()


def test_supervised_client_does_not_retry_shed_or_oom(server,
                                                      served_graph):
    srv, _, sock = server
    _, (src, dst, n) = served_graph
    sup = SupervisedKernelClient(
        sock, spawn=False,
        retry=RetryPolicy(base_delay=0.05, max_retries=3,
                          attempt_timeout=30.0))
    old_budget = srv.hbm_budget_bytes
    srv.hbm_budget_bytes = 1024
    t0 = time.monotonic()
    try:
        with pytest.raises(AdmissionRejected):
            sup.pagerank(src=src, dst=dst, n_nodes=n)
        assert time.monotonic() - t0 < 1.0   # immediate, not retried
        srv.hbm_budget_bytes = old_budget
        FI.arm("device.oom", "raise")        # persistent oom
        with pytest.raises(KernelOom):
            sup.pagerank(graph_key="devres", max_iterations=ITERS,
                         tol=1e-12)
    finally:
        srv.hbm_budget_bytes = old_budget
        sup.close()


def test_health_reports_wedged_during_overdue_dispatch(server,
                                                       served_graph):
    """wedge_after_s=0.4: a hang longer than that flips health.wedged
    even when the CLIENT asked for no deadline (supervision off)."""
    _, client, sock = server
    FI.arm("device.hang", "delay", arg=1.2, at=1)

    errs = []

    def hung_call():
        c2 = KernelClient(sock, timeout=5)
        try:
            c2.pagerank(graph_key="devres", max_iterations=ITERS,
                        tol=1e-12)
        except Exception as e:  # noqa: BLE001 — recorded for the caller
            errs.append(e)
        finally:
            c2.close()

    t = threading.Thread(target=hung_call, daemon=True)
    t.start()
    time.sleep(0.7)                          # > wedge_after_s, < hang
    h = client.health()
    assert h["wedged"] is True
    assert h["in_flight"] >= 1
    t.join(timeout=10)
    assert not errs                          # it completed, late
    h = client.health()
    assert h["wedged"] is False


def test_wedge_honesty_supervision_disabled_is_detected(server,
                                                        served_graph):
    """CHECKER HONESTY: with supervision disabled (no deadline) a hang
    WEDGES the client — and the harness must detect exactly that (the
    socket-level watchdog trips, health shows the stuck dispatch).
    With supervision enabled the same fault is a typed outcome."""
    _, client, sock = server
    FI.arm("device.hang", "delay", arg=1.0, at=1)
    unsupervised = KernelClient(sock, timeout=0.25)
    wedged = False
    try:
        unsupervised.pagerank(graph_key="devres", max_iterations=ITERS,
                              tol=1e-12)   # NO deadline_s: supervision off
    except OSError:                        # socket timeout = wedged client
        wedged = True
    finally:
        unsupervised.close()
    assert wedged, "supervision-off hang was NOT flagged as a wedge"
    h = client.health()
    assert h["in_flight"] >= 1
    time.sleep(1.1)                        # drain
    FI.reset()
    FI.arm("device.hang", "delay", arg=1.0, at=1)
    with pytest.raises(KernelDeadlineExceeded):   # supervision on: typed
        client.pagerank(graph_key="devres", deadline_s=0.2,
                        max_iterations=ITERS, tol=1e-12)
    time.sleep(1.1)


def test_supervisor_check_once_restarts_wedged(monkeypatch):
    sup = SupervisedKernelClient("/nonexistent.sock", spawn=False)
    restarts = []
    monkeypatch.setattr(sup, "restart_server",
                        lambda reason, pid=None: restarts.append(reason))
    monkeypatch.setattr(sup, "health", lambda timeout=5.0: None)
    assert sup.check_once() == "restarted"
    monkeypatch.setattr(sup, "health",
                        lambda timeout=5.0: {"wedged": True, "pid": 4242})
    assert sup.check_once() == "restarted"
    monkeypatch.setattr(sup, "health",
                        lambda timeout=5.0: {"wedged": False, "pid": 7})
    assert sup.check_once() == "ok"
    assert restarts == ["unreachable", "wedged"]
    sup.close()


def test_probe_op_typed_outcomes(server):
    _, client, _ = server
    assert client.probe()["outcome"] == "completed"
    FI.arm("device.oom", "raise", at=1)
    reply = client.probe()
    assert reply["ok"] is False and reply["outcome"] == "oom"
    FI.reset()
    assert client.probe()["outcome"] == "completed"


def test_health_reply_shape(server):
    _, client, _ = server
    h = client.health()
    for field in ("pid", "uptime_s", "in_flight", "wedged",
                  "graphs_cached", "hbm_budget_bytes", "counters",
                  "platform", "checkpoint_every"):
        assert field in h, field
    assert h["pid"] == os.getpid()           # in-thread daemon


# ==========================================================================
# 3. seeded device-nemesis schedules
# ==========================================================================


def test_device_schedule_byte_identical_per_seed():
    for seed in SWEEP_SEEDS:
        assert device_schedule_text(seed) == device_schedule_text(seed)
    assert device_schedule_text(1) != device_schedule_text(2)


def test_device_schedule_covers_full_matrix():
    """The default schedule enumerates every (op, context) pair — the
    dynamic half of the MG005 device-nemesis coverage contract."""
    for seed in SWEEP_SEEDS:
        pairs = {(op.kind, op.context) for op in device_schedule(seed)}
        want = {(op, ctx) for op in FI.DEVICE_NEMESIS_OPS
                for ctx in DEVICE_CONTEXTS}
        assert pairs == want


def test_device_op_point_mapping():
    for op in FI.DEVICE_NEMESIS_OPS:
        point = FI.device_point_for_op(op)
        assert point in FI.KNOWN_POINTS
    with pytest.raises(ValueError):
        FI.device_point_for_op("device_typo")
    with pytest.raises(ValueError):
        device_schedule(0, ops=("device_call", "typo"))


def test_classify_device_error_taxonomy():
    assert classify_device_error(DeviceOomError("x")) == "oom"
    assert classify_device_error(DeviceLostError("x")) == "device_lost"
    assert classify_device_error(ValueError("x")) is None
    from memgraph_tpu.utils.devicefault import make_device_call_error
    assert classify_device_error(make_device_call_error("y")) \
        == "device_error"
    try:
        from jaxlib.xla_extension import XlaRuntimeError
    except ImportError:
        return
    assert classify_device_error(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "oom"
    assert classify_device_error(
        XlaRuntimeError("UNAVAILABLE: device lost")) == "device_lost"


def test_probe_device_fault_injectable():
    FI.arm("device.call", "raise", at=1)
    with pytest.raises(Exception) as ei:
        probe_device()
    assert classify_device_error(ei.value) == "device_error"
    FI.reset()
    checksum, platform = probe_device()
    assert checksum == 128.0 * 128 * 128 and platform == "cpu"


# ==========================================================================
# 4. RetryPolicy deadlines + bench probe classification
# ==========================================================================


def test_retry_attempts_budget_and_deadline():
    p = RetryPolicy(base_delay=0.01, jitter=0.0, max_retries=3)
    assert list(p.attempts()) == [0, 1, 2, 3]
    p = RetryPolicy(base_delay=10.0, jitter=0.0, max_retries=5,
                    deadline=0.05)
    t0 = time.monotonic()
    assert list(p.attempts()) == [0]         # next backoff would cross
    assert time.monotonic() - t0 < 1.0


def test_retry_attempt_timeout_clips_to_deadline():
    p = RetryPolicy(attempt_timeout=5.0, deadline=1.0)
    t0 = time.monotonic()
    assert p.attempt_timeout_at(t0) <= 1.0
    p2 = RetryPolicy(attempt_timeout=5.0)
    assert p2.attempt_timeout_at(time.monotonic()) == 5.0
    p3 = RetryPolicy()
    assert p3.attempt_timeout_at(time.monotonic()) is None


def test_retry_call_honors_deadline():
    p = RetryPolicy(base_delay=10.0, jitter=0.0, max_retries=5,
                    deadline=0.05)
    calls = []

    def boom():
        calls.append(1)
        raise ConnectionError("nope")

    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        p.call(boom)
    assert len(calls) == 1                   # no 10s sleep happened
    assert time.monotonic() - t0 < 1.0


def test_bench_probe_classification():
    import bench
    assert bench._classify_probe(0) == "ok"
    assert bench._classify_probe(None) == "probe_timeout"
    assert bench._classify_probe(137) == "probe_killed"
    assert bench._classify_probe(2) == "probe_error_rc_2"


def test_bench_resident_probe_consults_server(server):
    """bench's probe consult reads the resident daemon's health and
    typed probe — here against the in-thread server's socket."""
    import bench
    _, _, sock = server
    monkey_sock = sock

    import memgraph_tpu.server.kernel_server as ks
    old = ks.DEFAULT_SOCKET
    ks.DEFAULT_SOCKET = monkey_sock
    try:
        health, probe_reply = bench._resident_probe(timeout=10.0)
    finally:
        ks.DEFAULT_SOCKET = old
    assert health is not None and health["wedged"] is False
    assert probe_reply is not None and probe_reply["ok"] is True


# ==========================================================================
# 5. the sweeps (device_chaos marked; run: pytest -m device_chaos)
# ==========================================================================


@pytest.mark.slow
@pytest.mark.device_chaos
@pytest.mark.parametrize("seed", list(SWEEP_SEEDS))
def test_device_nemesis_matrix_sweep(seed):
    """Acceptance: the full (fault x context) matrix per seed — correct
    (bit-exact) analytics results, zero wedged clients, resume ≤ k
    redone iterations, every typed outcome observed."""
    failures, observed = run_device_matrix(seed, echo=lambda *_: None)
    assert not failures, "\n".join(failures)
    for op in FI.DEVICE_NEMESIS_OPS:
        assert observed.get(op), f"{op} produced no observable outcome"


@pytest.mark.slow
@pytest.mark.device_chaos
def test_device_lost_process_kill_supervisor_respawns(tmp_path):
    """The REAL device.lost story: the daemon process dies (SIGKILL —
    what an armed kill action or a lost backend does to it); the
    supervisor detects the loss, respawns, and the retried idempotent
    request completes."""
    from memgraph_tpu.observability.metrics import global_metrics
    from memgraph_tpu.server.kernel_server import ensure_server
    import signal as _signal

    sock = str(tmp_path / "ks.sock")
    env_backup = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        client = ensure_server(sock, spawn_timeout_s=240,
                               idle_timeout_s=120)
    finally:
        if env_backup is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = env_backup
    if client is None:
        pytest.skip("kernel server daemon starved during spawn "
                    "(1-core host under full-suite load)")
    h, _ = client.call({"op": "ping"})
    daemon_pid = h["pid"]
    assert daemon_pid != os.getpid()
    client.close()

    sup = SupervisedKernelClient(
        sock, spawn=True, spawn_timeout_s=240, idle_timeout_s=120,
        retry=RetryPolicy(base_delay=0.2, max_retries=3,
                          attempt_timeout=240.0))
    rng = np.random.default_rng(2)
    n, e = 200, 1000
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    try:
        ref, _, _ = sup.pagerank(src=src, dst=dst, n_nodes=n,
                                 graph_key="kill-test")
        os.kill(daemon_pid, _signal.SIGKILL)     # the backend is LOST
        time.sleep(0.3)
        restarts0 = dict((nm, v) for nm, _k, v
                         in global_metrics.snapshot()).get(
            "kernel_server.client.retries_total", 0.0)
        # the graph cache died with the daemon: resend arrays
        ranks, _, _ = sup.pagerank(src=src, dst=dst, n_nodes=n,
                                   graph_key="kill-test")
        np.testing.assert_allclose(np.asarray(ranks), np.asarray(ref),
                                   rtol=1e-6)
        retries1 = dict((nm, v) for nm, _k, v
                        in global_metrics.snapshot()).get(
            "kernel_server.client.retries_total", 0.0)
        assert retries1 > restarts0              # the loss WAS retried
        h2 = sup.health()
        assert h2 is not None and h2["pid"] != daemon_pid
    finally:
        try:
            c = KernelClient(sock, timeout=10)
            c.shutdown()
            c.close()
        except OSError:
            pass
        sup.close()
