"""Deterministic fault injection + crash-consistency harness.

Three layers of coverage:

1. Unit: the faultinject registry (env grammar, hit schedules, torn
   writes, seeded determinism) and the shared RetryPolicy.
2. In-process fault points: dropped replication frames heal via
   reconnect catch-up, STRICT_SYNC degrades to ASYNC after the retry
   budget, Raft survives injected RPC loss, seedable election timeouts.
3. Crash harness: a subprocess workload (tests/crash_child.py) killed
   at armed fault points mid-WAL / mid-snapshot; the parent recovers
   and asserts the acknowledged-commit prefix survives exactly — no
   acked transaction lost, no partial transaction visible.

The full kill matrix is marked slow+crash (`pytest -m crash`); a
3-point smoke subset runs in tier-1.
"""

import os
import pathlib
import socket
import subprocess
import sys
import time

import pytest

from memgraph_tpu.utils import faultinject as FI
from memgraph_tpu.utils.retry import RetryPolicy

REPO = pathlib.Path(__file__).resolve().parent.parent
CHILD = REPO / "tests" / "crash_child.py"


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset()
    yield
    FI.reset()


# --- faultinject unit coverage ---------------------------------------------


def test_env_grammar_parses_actions():
    FI.arm_from_string("wal.write=torn:7+kill@3,repl.send=drop@2;5,"
                       "raft.rpc=delay:0.01,kvstore.put=raise@1")
    assert FI._SPECS["wal.write"][0].action == "torn"
    assert FI._SPECS["wal.write"][0].arg == 7
    assert FI._SPECS["wal.write"][0].then == "kill"
    assert FI._SPECS["repl.send"][0].hits == frozenset({2, 5})
    assert FI._SPECS["raft.rpc"][0].hits is None  # every hit


def test_unknown_point_rejected():
    with pytest.raises(ValueError):
        FI.arm("wal.wrte", "raise")
    with pytest.raises(ValueError):
        FI.arm_from_string("wal.write=explode@1")


def test_fire_raises_only_at_armed_hit():
    FI.arm("kvstore.put", "raise", at=2)
    assert FI.fire("kvstore.put") is None           # hit 1
    with pytest.raises(FI.FaultInjected):
        FI.fire("kvstore.put")                      # hit 2
    assert FI.fire("kvstore.put") is None           # hit 3
    assert FI.hit_count("kvstore.put") == 3


def test_fire_drop_returns_directive():
    FI.arm("raft.rpc", "drop", at=1)
    assert FI.fire("raft.rpc") == "drop"
    assert FI.fire("raft.rpc") is None


def test_faulty_write_tears_at_exact_offset():
    from io import BytesIO
    buf = BytesIO()
    FI.arm("wal.write", "torn", arg=3, at=2)
    FI.faulty_write("wal.write", buf, b"aaaa")      # hit 1: full write
    with pytest.raises(FI.FaultInjected):
        FI.faulty_write("wal.write", buf, b"bbbbbb")  # hit 2: 3 bytes land
    FI.faulty_write("wal.write", buf, b"cc")        # hit 3: full write
    assert buf.getvalue() == b"aaaa" + b"bbb" + b"cc"


def test_seeded_schedule_replays_exactly():
    s1 = FI.seeded_schedule(1234)
    s2 = FI.seeded_schedule(1234)
    assert s1 == s2
    assert set(s1) == set(FI.KNOWN_POINTS)
    assert all(1 <= hit <= 16 for hit in s1.values())


def test_retry_policy_backoff_caps_and_budget():
    p = RetryPolicy(base_delay=0.1, factor=2.0, max_delay=0.5,
                    max_retries=4, jitter=0.0)
    delays = list(p.delays())
    assert delays == [0.1, 0.2, 0.4, 0.5]           # capped at max_delay
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("nope")

    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        RetryPolicy(base_delay=0.01, max_retries=2, jitter=0.0).call(flaky)
    assert len(calls) == 3                          # 1 try + 2 retries
    assert time.monotonic() - t0 >= 0.02

    # seeded jitter replays exactly
    a = list(RetryPolicy(seed=9, max_retries=5).delays())
    b = list(RetryPolicy(seed=9, max_retries=5).delays())
    assert a == b


# --- crash harness ----------------------------------------------------------


def _run_child(tmp_path, faults, n=30, snapshot_every=0):
    dur = tmp_path / "data"
    dur.mkdir(exist_ok=True)
    acked = tmp_path / "acked.txt"
    env = os.environ.copy()
    env["MEMGRAPH_TPU_FAULTS"] = faults
    env["JAX_PLATFORMS"] = "cpu"
    # lock-order witness armed in the child too: the kill-matrix drives
    # the WAL/snapshot/replication paths PR 2 added, exactly where a
    # nesting inversion would bite
    env.setdefault("MG_TRACK_LOCKS", "1")
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    if snapshot_every:
        env["CRASH_CHILD_SNAPSHOT"] = str(snapshot_every)
    proc = subprocess.run(
        [sys.executable, str(CHILD), str(dur), str(acked), str(n)],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300)
    acked_ids = ([int(x) for x in acked.read_text().split()]
                 if acked.exists() else [])
    return proc, dur, acked_ids


def _recover_pairs(dur):
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage, StorageConfig
    from memgraph_tpu.storage.durability.recovery import recover
    storage = InMemoryStorage(StorageConfig(durability_dir=str(dur),
                                            wal_enabled=True))
    recover(storage)
    _, rows, _ = Interpreter(InterpreterContext(storage)).execute(
        "MATCH (p:P) RETURN p.pair, count(*) ORDER BY p.pair")
    return {r[0]: r[1] for r in rows}


def _assert_crash_consistent(proc, dur, acked_ids):
    assert proc.returncode != 0, (
        f"child should have crashed, got rc=0\n{proc.stdout}{proc.stderr}")
    pairs = _recover_pairs(dur)
    for i in acked_ids:
        assert pairs.get(i) == 2, (
            f"acked txn {i} lost or torn after recovery: "
            f"{pairs.get(i)} of 2 vertices\n{proc.stderr}")
    for pair, cnt in pairs.items():
        assert cnt == 2, f"partial txn {pair} visible after recovery"
    # the recovered state is the acked prefix plus at most the one
    # in-flight txn that was durable but unacked at the kill
    unacked = set(pairs) - set(acked_ids)
    assert len(unacked) <= 1, f"phantom txns recovered: {sorted(unacked)}"


# ≥10 distinct crash points: torn WAL writes at several byte offsets,
# kills before WAL write / before fsync, snapshot-rename crashes (with
# WAL retention riding the snapshot), all crossing segment rotations
# (CRASH_CHILD_SEGMENT=4096 rotates every few txns).
CRASH_MATRIX = [
    ("wal.write=kill@1", 0),
    ("wal.write=kill@7", 0),
    ("wal.write=torn:1+kill@2", 0),
    ("wal.write=torn:9+kill@5", 0),
    ("wal.write=torn:64+kill@11", 0),
    ("wal.write=torn:300+kill@13", 0),
    ("wal.fsync=kill@1", 0),
    ("wal.fsync=kill@9", 0),
    ("snapshot.rename=kill@1", 5),
    ("snapshot.rename=kill@2", 3),
    ("wal.write=torn:5+kill@17", 4),
]


@pytest.mark.slow
@pytest.mark.crash
@pytest.mark.parametrize("faults,snap", CRASH_MATRIX)
def test_crash_kill_matrix(tmp_path, faults, snap):
    proc, dur, acked = _run_child(tmp_path, faults, n=30,
                                  snapshot_every=snap)
    _assert_crash_consistent(proc, dur, acked)


# tier-1 smoke: three fault points from the matrix (kill before write,
# torn write, kill before fsync)
CRASH_SMOKE = [
    ("wal.write=kill@2", 0),
    ("wal.write=torn:6+kill@3", 0),
    ("wal.fsync=kill@4", 0),
]


@pytest.mark.parametrize("faults,snap", CRASH_SMOKE)
def test_crash_smoke(tmp_path, faults, snap):
    proc, dur, acked = _run_child(tmp_path, faults, n=8,
                                  snapshot_every=snap)
    _assert_crash_consistent(proc, dur, acked)


def test_child_completes_with_no_faults(tmp_path):
    proc, dur, acked = _run_child(tmp_path, "", n=6)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert _recover_pairs(dur) == {i: 2 for i in range(6)}


# --- replication fault points ----------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def cluster():
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage
    main_ictx = InterpreterContext(InMemoryStorage())
    replica_ictx = InterpreterContext(InMemoryStorage())
    main = Interpreter(main_ictx)
    replica = Interpreter(replica_ictx)
    port = _free_port()
    replica.execute(f"SET REPLICATION ROLE TO REPLICA WITH PORT {port}")
    yield {"main": main, "replica": replica, "port": port,
           "main_ictx": main_ictx, "replica_ictx": replica_ictx}
    if getattr(replica_ictx, "replication", None):
        if replica_ictx.replication.replica_server:
            replica_ictx.replication.replica_server.stop()
    if getattr(main_ictx, "replication", None):
        for c in main_ictx.replication.replicas.values():
            c.close()


def _rows(interp, q):
    _, rows, _ = interp.execute(q)
    return rows


def test_dropped_replication_frame_heals_via_catchup(cluster):
    main, replica = cluster["main"], cluster["replica"]
    main.execute(
        f"REGISTER REPLICA r1 SYNC TO \"127.0.0.1:{cluster['port']}\"")
    main.execute("CREATE (:R {v: 1})")
    client = cluster["main_ictx"].replication.replicas["r1"]
    # lose exactly the next shipped frame on the MAIN side
    FI.arm("repl.send", "drop", at=FI.hit_count("repl.send") + 1)
    main.execute("CREATE (:R {v: 2})")        # ship fails, commit stands
    assert client.status.name == "INVALID"
    assert _rows(main, "MATCH (n:R) RETURN count(n)") == [[2]]
    client.connect_and_catch_up()             # the heartbeat would do this
    assert client.catchup_used == "wal_delta"
    rows = _rows(replica, "MATCH (n:R) RETURN n.v ORDER BY n.v")
    assert rows == [[1], [2]]


def test_replica_recv_fault_heals_via_catchup(cluster):
    main, replica = cluster["main"], cluster["replica"]
    main.execute(
        f"REGISTER REPLICA r1 SYNC TO \"127.0.0.1:{cluster['port']}\"")
    # sever the replica-side connection on the next received frame
    FI.arm("repl.recv", "raise", at=FI.hit_count("repl.recv") + 1)
    main.execute("CREATE (:S {v: 1})")
    client = cluster["main_ictx"].replication.replicas["r1"]
    assert client.status.name == "INVALID"
    client.connect_and_catch_up()
    rows = _rows(replica, "MATCH (n:S) RETURN count(n)")
    assert rows == [[1]]


def test_strict_sync_degrades_to_async_after_budget(cluster):
    from memgraph_tpu.exceptions import TransactionException
    from memgraph_tpu.observability.metrics import global_metrics
    from memgraph_tpu.replication.main_role import ReplicationMode
    main = cluster["main"]
    main.execute(
        f"REGISTER REPLICA r1 STRICT_SYNC TO \"127.0.0.1:{cluster['port']}\"")
    client = cluster["main_ictx"].replication.replicas["r1"]
    client.retry_policy = RetryPolicy(max_retries=0, base_delay=0.01)
    cluster["replica_ictx"].replication.replica_server.stop()
    # budget not yet exhausted: the strict guarantee aborts the commit
    with pytest.raises(TransactionException):
        main.execute("CREATE (:D {v: 1})")
    # budget exhausted now (failures > max_retries=0): the replica is
    # demoted to ASYNC catch-up and commits flow again
    main.execute("CREATE (:D {v: 2})")
    assert client.mode is ReplicationMode.ASYNC
    assert client.degraded_from_strict
    assert _rows(main, "MATCH (n:D) RETURN count(n)") == [[1]]
    text = global_metrics.prometheus_text()
    assert "replication_strict_sync_demotions" in text
    assert "replication_replica_degraded_r1 1.0" in text


def test_replica_lag_and_fsync_metrics_exported(cluster, tmp_path):
    from memgraph_tpu.observability.metrics import global_metrics
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage, StorageConfig
    from memgraph_tpu.storage.durability.recovery import wire_durability
    main = cluster["main"]
    main.execute(
        f"REGISTER REPLICA r1 SYNC TO \"127.0.0.1:{cluster['port']}\"")
    main.execute("CREATE (:M {v: 1})")
    # a durable commit records WAL fsync latency
    storage = InMemoryStorage(StorageConfig(durability_dir=str(tmp_path),
                                            wal_enabled=True))
    wire_durability(storage)
    Interpreter(InterpreterContext(storage)).execute("CREATE (:W)")
    text = global_metrics.prometheus_text()
    assert "replication_replica_lag_r1" in text
    assert "replication_replica_health_r1 1.0" in text
    assert "wal_fsync_latency_sec_count" in text
    assert 'wal_fsync_latency_sec_bucket{le="+Inf"}' in text


# --- raft fault points ------------------------------------------------------


def test_raft_election_timeouts_are_seedable():
    from memgraph_tpu.coordination.raft import RaftNode
    a = RaftNode("n", "127.0.0.1", 0, {}, election_seed=7)
    b = RaftNode("n", "127.0.0.1", 0, {}, election_seed=7)
    c = RaftNode("n", "127.0.0.1", 0, {}, election_seed=8)
    seq = [a._rng.uniform(*RaftNode.ELECTION_TIMEOUT) for _ in range(8)]
    assert seq == [b._rng.uniform(*RaftNode.ELECTION_TIMEOUT)
                   for _ in range(8)]
    assert seq != [c._rng.uniform(*RaftNode.ELECTION_TIMEOUT)
                   for _ in range(8)]


def test_raft_survives_injected_rpc_loss():
    from memgraph_tpu.coordination.raft import RaftNode
    ports = [_free_port() for _ in range(3)]
    ids = ["f1", "f2", "f3"]
    applied = {i: [] for i in ids}
    nodes = []
    for i, nid in enumerate(ids):
        peers = {ids[j]: ("127.0.0.1", ports[j])
                 for j in range(3) if j != i}
        nodes.append(RaftNode(nid, "127.0.0.1", ports[i], peers,
                              apply_fn=lambda cmd, _n=nid:
                              applied[_n].append(cmd),
                              election_seed=100 + i))
    # the first 8 RPCs in the whole cluster are lost on the wire
    FI.arm("raft.rpc", "drop", at=list(range(1, 9)))
    for n in nodes:
        n.start()
    try:
        deadline = time.monotonic() + 20
        leader = None
        while time.monotonic() < deadline and leader is None:
            leader = next((n for n in nodes if n.is_leader()), None)
            time.sleep(0.05)
        assert leader is not None, "no leader elected despite RPC loss"
        assert leader.propose({"op": "set", "v": 1}, timeout=10)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(len(applied[i]) == 1 for i in ids):
                break
            time.sleep(0.05)
        assert all(len(applied[i]) == 1 for i in ids)
    finally:
        for n in nodes:
            n.stop()


def test_kvstore_put_fault_point(tmp_path):
    from memgraph_tpu.storage.kvstore import KVStore
    kv = KVStore(str(tmp_path / "kv.db"))
    kv.put("a", "1")
    FI.arm("kvstore.put", "raise", at=FI.hit_count("kvstore.put") + 1)
    with pytest.raises(FI.FaultInjected):
        kv.put("b", "2")
    kv.put("c", "3")          # the store keeps working after the fault
    assert kv.get_str("a") == "1"
    assert kv.get_str("b") is None
    assert kv.get_str("c") == "3"
    kv.close()
