"""Stream offset discipline: offsets committed ONLY after transaction
success — exactly-once-per-committed-batch for file and Kafka sources.

Reference: /root/reference/src/integrations/kafka/consumer.hpp:99 (the
consumer commits after the transform transaction), memgraph.cpp:652.
"""

import json
import time

import pytest

from memgraph_tpu.query import streams as S
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


# --------------------------------------------------------------------------
# fake confluent_kafka with the surface KafkaSource touches
# --------------------------------------------------------------------------

class _FakeMsg:
    def __init__(self, value, topic="t", partition=0, offset=0):
        self._value = value
        self._topic = topic
        self._partition = partition
        self._offset = offset

    def error(self):
        return None

    def value(self):
        return self._value

    def topic(self):
        return self._topic

    def partition(self):
        return self._partition

    def offset(self):
        return self._offset

    def key(self):
        return None

    def timestamp(self):
        return (0, 0)


class _FakeTopicPartition:
    def __init__(self, topic, partition, offset):
        self.topic, self.partition, self.offset = topic, partition, offset


class _FakeConsumer:
    def __init__(self, config):
        self.config = config
        self.queue = []
        self.position = 0
        self.committed_offset = 0
        self.commits = []
        self.seeks = []

    def subscribe(self, topics):
        self.topics = topics

    def consume(self, n, timeout):
        out = self.queue[self.position:self.position + n]
        self.position += len(out)
        return out

    def commit(self, asynchronous=True):
        self.commits.append(self.position)
        self.committed_offset = self.position

    def seek(self, tp):
        self.seeks.append((tp.topic, tp.partition, tp.offset))
        self.position = tp.offset

    def close(self):
        pass


class _FakeKafkaModule:
    TopicPartition = _FakeTopicPartition

    def __init__(self):
        self.consumers = []

    def Consumer(self, config):
        c = _FakeConsumer(config)
        self.consumers.append(c)
        return c


def test_kafka_source_disables_autocommit_and_commits_after_txn():
    mod = _FakeKafkaModule()
    src = S.KafkaSource(["t"], "broker:9092", "g", client_module=mod)
    consumer = mod.consumers[0]
    assert consumer.config["enable.auto.commit"] is False
    consumer.queue = [_FakeMsg(b"a", offset=0), _FakeMsg(b"b", offset=1)]
    batch = src.poll(10, 0.01)
    assert [m.payload for m in batch] == [b"a", b"b"]
    assert consumer.commits == []       # nothing committed yet
    src.commit()
    assert consumer.commits == [2]      # only after the txn succeeded


def test_kafka_source_rollback_seeks_to_batch_start():
    mod = _FakeKafkaModule()
    src = S.KafkaSource(["t"], "broker:9092", "g", client_module=mod)
    consumer = mod.consumers[0]
    consumer.queue = [_FakeMsg(b"a", offset=0), _FakeMsg(b"b", offset=1),
                      _FakeMsg(b"c", offset=2)]
    src.poll(2, 0.01)
    src.rollback()                      # failed txn
    assert consumer.seeks == [("t", 0, 0)]
    # the broker redelivers the same batch
    batch = src.poll(2, 0.01)
    assert [m.payload for m in batch] == [b"a", b"b"]
    src.commit()
    assert consumer.commits == [2]


# --------------------------------------------------------------------------
# file stream e2e: exactly-once per committed batch, incl. a failing batch
# --------------------------------------------------------------------------

def _write_lines(path, docs):
    with open(path, "a") as f:
        for d in docs:
            f.write(json.dumps(d) + "\n")


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_file_stream_exactly_once_with_failing_batch(tmp_path):
    """A batch whose transaction fails is redelivered, not lost; after 3
    failures the stream stops without advancing the offset; committed
    batches advance it exactly once."""
    ictx = InterpreterContext(InMemoryStorage())
    interp = Interpreter(ictx)
    path = str(tmp_path / "in.jsonl")

    # transform that turns each json line into a CREATE; a line with
    # "boom" produces an invalid query -> the batch's txn fails
    def transform(batch):
        out = []
        for m in batch:
            doc = json.loads(m.payload_str())
            if doc.get("boom"):
                out.append({"query": "THIS IS NOT CYPHER"})
            else:
                out.append({"query": "CREATE (:Msg {id: $id})",
                            "parameters": {"id": doc["id"]}})
        return out

    S.TRANSFORMATIONS["test_exactly_once"] = transform
    try:
        spec = S.StreamSpec(name="s1", kind="file", topics=[path],
                            transform="test_exactly_once", batch_size=100,
                            batch_interval_sec=0.05)
        stream = S.Stream(spec, ictx)
        _write_lines(path, [{"id": 1}, {"id": 2}])
        stream.start()
        assert _wait(lambda: stream.processed_messages >= 2)
        _, rows, _ = interp.execute("MATCH (m:Msg) RETURN count(m)")
        assert rows == [[2]]
        committed_after_good = stream._thread and True
        good_offset = None

        # failing batch: txn aborts 3x -> stream stops, offset NOT moved
        _write_lines(path, [{"id": 3, "boom": True}])
        assert _wait(lambda: not stream.running, timeout=15)
        assert stream.last_error
        _, rows, _ = interp.execute("MATCH (m:Msg) RETURN count(m)")
        assert rows == [[2]]            # nothing from the failed batch

        # no duplicates from the earlier committed batch either
        _, rows, _ = interp.execute(
            "MATCH (m:Msg) RETURN m.id ORDER BY m.id")
        assert rows == [[1], [2]]
    finally:
        stream.stop()
        S.TRANSFORMATIONS.pop("test_exactly_once", None)


def test_file_stream_offset_survives_restart(tmp_path):
    """Committed offsets persist in the kvstore: a restarted stream
    resumes AFTER the committed batch (no replay, no loss)."""
    from memgraph_tpu.storage.kvstore import KVStore
    ictx = InterpreterContext(InMemoryStorage())
    ictx.kvstore = KVStore(str(tmp_path / "kv.db"))
    interp = Interpreter(ictx)
    path = str(tmp_path / "in.jsonl")

    def transform(batch):
        return [{"query": "CREATE (:R {id: $id})",
                 "parameters": {"id": json.loads(m.payload_str())["id"]}}
                for m in batch]

    S.TRANSFORMATIONS["test_restart"] = transform
    try:
        spec = S.StreamSpec(name="s2", kind="file", topics=[path],
                            transform="test_restart", batch_size=10,
                            batch_interval_sec=0.05)
        stream = S.Stream(spec, ictx)
        _write_lines(path, [{"id": 1}, {"id": 2}])
        stream.start()
        assert _wait(lambda: stream.processed_messages >= 2)
        stream.stop()

        # new lines arrive while "down"; a fresh stream resumes from the
        # PERSISTED committed offset: processes only the new lines
        _write_lines(path, [{"id": 3}])
        stream2 = S.Stream(spec, ictx)
        stream2.start()
        assert _wait(lambda: stream2.processed_messages >= 1)
        stream2.stop()
        _, rows, _ = interp.execute("MATCH (r:R) RETURN r.id ORDER BY r.id")
        assert rows == [[1], [2], [3]]  # 1,2 exactly once; 3 arrived
    finally:
        S.TRANSFORMATIONS.pop("test_restart", None)


def test_confluent_kafka_integration_if_available():
    pytest.importorskip("confluent_kafka")
    # real-broker integration is exercised in environments that ship
    # confluent-kafka + a reachable broker (CI profile); the commit/seek
    # discipline above runs against the same KafkaSource code
