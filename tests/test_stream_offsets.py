"""Stream offset discipline: offsets committed ONLY after transaction
success — exactly-once-per-committed-batch for file and Kafka sources.

Reference: /root/reference/src/integrations/kafka/consumer.hpp:99 (the
consumer commits after the transform transaction), memgraph.cpp:652.
"""

import json
import time

import pytest

from memgraph_tpu.query import streams as S
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


# --------------------------------------------------------------------------
# fake confluent_kafka with the surface KafkaSource touches
# --------------------------------------------------------------------------

class _FakeMsg:
    def __init__(self, value, topic="t", partition=0, offset=0):
        self._value = value
        self._topic = topic
        self._partition = partition
        self._offset = offset

    def error(self):
        return None

    def value(self):
        return self._value

    def topic(self):
        return self._topic

    def partition(self):
        return self._partition

    def offset(self):
        return self._offset

    def key(self):
        return None

    def timestamp(self):
        return (0, 0)


class _FakeTopicPartition:
    def __init__(self, topic, partition, offset):
        self.topic, self.partition, self.offset = topic, partition, offset


class _FakeConsumer:
    def __init__(self, config):
        self.config = config
        self.queue = []
        self.position = 0
        self.committed_offset = 0
        self.commits = []
        self.seeks = []

    def subscribe(self, topics):
        self.topics = topics

    def consume(self, n, timeout):
        out = self.queue[self.position:self.position + n]
        self.position += len(out)
        return out

    def commit(self, asynchronous=True):
        self.commits.append(self.position)
        self.committed_offset = self.position

    def seek(self, tp):
        self.seeks.append((tp.topic, tp.partition, tp.offset))
        self.position = tp.offset

    def close(self):
        pass


class _FakeKafkaModule:
    TopicPartition = _FakeTopicPartition

    def __init__(self):
        self.consumers = []

    def Consumer(self, config):
        c = _FakeConsumer(config)
        self.consumers.append(c)
        return c


def test_kafka_source_disables_autocommit_and_commits_after_txn():
    mod = _FakeKafkaModule()
    src = S.KafkaSource(["t"], "broker:9092", "g", client_module=mod)
    consumer = mod.consumers[0]
    assert consumer.config["enable.auto.commit"] is False
    consumer.queue = [_FakeMsg(b"a", offset=0), _FakeMsg(b"b", offset=1)]
    batch = src.poll(10, 0.01)
    assert [m.payload for m in batch] == [b"a", b"b"]
    assert consumer.commits == []       # nothing committed yet
    src.commit()
    assert consumer.commits == [2]      # only after the txn succeeded


def test_kafka_source_rollback_seeks_to_batch_start():
    mod = _FakeKafkaModule()
    src = S.KafkaSource(["t"], "broker:9092", "g", client_module=mod)
    consumer = mod.consumers[0]
    consumer.queue = [_FakeMsg(b"a", offset=0), _FakeMsg(b"b", offset=1),
                      _FakeMsg(b"c", offset=2)]
    src.poll(2, 0.01)
    src.rollback()                      # failed txn
    assert consumer.seeks == [("t", 0, 0)]
    # the broker redelivers the same batch
    batch = src.poll(2, 0.01)
    assert [m.payload for m in batch] == [b"a", b"b"]
    src.commit()
    assert consumer.commits == [2]


# --------------------------------------------------------------------------
# file stream e2e: exactly-once per committed batch, incl. a failing batch
# --------------------------------------------------------------------------

def _write_lines(path, docs):
    with open(path, "a") as f:
        for d in docs:
            f.write(json.dumps(d) + "\n")


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_file_stream_poison_batch_quarantined_exactly_once(tmp_path):
    """A batch whose transaction fails is redelivered, not lost; after
    max_batch_retries failures it is QUARANTINED into the dead-letter
    buffer with its offset advanced transactionally — the stream keeps
    running and later good batches still ingest exactly once."""
    ictx = InterpreterContext(InMemoryStorage())
    interp = Interpreter(ictx)
    path = str(tmp_path / "in.jsonl")

    # transform that turns each json line into a CREATE; a line with
    # "boom" produces an invalid query -> the batch's txn fails
    def transform(batch):
        out = []
        for m in batch:
            doc = json.loads(m.payload_str())
            if doc.get("boom"):
                out.append({"query": "THIS IS NOT CYPHER"})
            else:
                out.append({"query": "CREATE (:Msg {id: $id})",
                            "parameters": {"id": doc["id"]}})
        return out

    S.TRANSFORMATIONS["test_exactly_once"] = transform
    try:
        spec = S.StreamSpec(name="s1", kind="file", topics=[path],
                            transform="test_exactly_once", batch_size=100,
                            batch_interval_sec=0.05)
        stream = S.Stream(spec, ictx)
        _write_lines(path, [{"id": 1}, {"id": 2}])
        stream.start()
        assert _wait(lambda: stream.processed_messages >= 2)
        _, rows, _ = interp.execute("MATCH (m:Msg) RETURN count(m)")
        assert rows == [[2]]

        # poison batch: txn aborts max_batch_retries times -> quarantined
        # (offset advanced, loop alive), NOT a wedged/stopped stream
        _write_lines(path, [{"id": 3, "boom": True}])
        assert _wait(lambda: len(stream.dead_letter) == 1, timeout=15)
        assert stream.running
        assert stream.last_outcome == S.BatchOutcome.DEAD_LETTERED
        (_key, payloads, reason), = stream.dead_letter
        assert b'"boom"' in payloads[0]
        assert reason == S.BatchOutcome.TXN_ERROR
        _, rows, _ = interp.execute("MATCH (m:Msg) RETURN count(m)")
        assert rows == [[2]]            # nothing from the poison batch

        # the offset moved PAST the quarantined batch: a later good line
        # ingests exactly once and the poison line never replays
        _write_lines(path, [{"id": 4}])
        assert _wait(lambda: stream.processed_messages >= 3)
        _, rows, _ = interp.execute(
            "MATCH (m:Msg) RETURN m.id ORDER BY m.id")
        assert rows == [[1], [2], [4]]
    finally:
        stream.stop()
        S.TRANSFORMATIONS.pop("test_exactly_once", None)


def test_file_stream_offset_survives_restart(tmp_path):
    """Committed offsets persist in the kvstore: a restarted stream
    resumes AFTER the committed batch (no replay, no loss)."""
    from memgraph_tpu.storage.kvstore import KVStore
    ictx = InterpreterContext(InMemoryStorage())
    ictx.kvstore = KVStore(str(tmp_path / "kv.db"))
    interp = Interpreter(ictx)
    path = str(tmp_path / "in.jsonl")

    def transform(batch):
        return [{"query": "CREATE (:R {id: $id})",
                 "parameters": {"id": json.loads(m.payload_str())["id"]}}
                for m in batch]

    S.TRANSFORMATIONS["test_restart"] = transform
    try:
        spec = S.StreamSpec(name="s2", kind="file", topics=[path],
                            transform="test_restart", batch_size=10,
                            batch_interval_sec=0.05)
        stream = S.Stream(spec, ictx)
        _write_lines(path, [{"id": 1}, {"id": 2}])
        stream.start()
        assert _wait(lambda: stream.processed_messages >= 2)
        stream.stop()

        # new lines arrive while "down"; a fresh stream resumes from the
        # PERSISTED committed offset: processes only the new lines
        _write_lines(path, [{"id": 3}])
        stream2 = S.Stream(spec, ictx)
        stream2.start()
        assert _wait(lambda: stream2.processed_messages >= 1)
        stream2.stop()
        _, rows, _ = interp.execute("MATCH (r:R) RETURN r.id ORDER BY r.id")
        assert rows == [[1], [2], [3]]  # 1,2 exactly once; 3 arrived
    finally:
        S.TRANSFORMATIONS.pop("test_restart", None)


# --------------------------------------------------------------------------
# r17 exactly-once: the offset is part of the ingest transaction (WAL
# OP_STREAM_OFFSET), replayed on recovery — the consumer-side ack is an
# optimization, not the correctness boundary
# --------------------------------------------------------------------------

def test_stream_offset_rides_the_ingest_commit_and_wal_replay(tmp_path):
    """The batch's data and its source position commit ATOMICALLY: after
    a crash-restart (WAL replay, kvstore copy lost) the recovered
    storage.stream_offsets points past every committed batch, and a
    fresh FILE stream resumes there — zero duplicates, zero loss."""
    from memgraph_tpu.storage import StorageConfig
    from memgraph_tpu.storage.durability.recovery import (recover,
                                                          wire_durability)
    d = str(tmp_path / "dur")
    storage = InMemoryStorage(StorageConfig(durability_dir=d,
                                            wal_enabled=True))
    wal = wire_durability(storage)
    ictx = InterpreterContext(storage)
    interp = Interpreter(ictx)
    path = str(tmp_path / "in.jsonl")

    def transform(batch):
        return [{"query": "CREATE (:W {id: $id})",
                 "parameters": {"id": json.loads(m.payload_str())["id"]}}
                for m in batch]

    S.TRANSFORMATIONS["test_wal_offsets"] = transform
    try:
        spec = S.StreamSpec(name="sw", kind="file", topics=[path],
                            transform="test_wal_offsets", batch_size=10,
                            batch_interval_sec=0.05)
        stream = S.Stream(spec, ictx)
        _write_lines(path, [{"id": 1}, {"id": 2}])
        stream.start()
        assert _wait(lambda: stream.processed_messages >= 2)
        stream.kill()                      # SIGKILL-style: no graceful ack
        wal.close()
        assert storage.stream_offsets.get("sw", 0) > 0

        # crash-restart: fresh storage, WAL replay only (NO kvstore —
        # the consumer-side persisted copy is gone)
        restored = InMemoryStorage(StorageConfig(durability_dir=d,
                                                 wal_enabled=True))
        recover(restored)
        assert restored.stream_offsets.get("sw") == \
            storage.stream_offsets["sw"]
        ictx2 = InterpreterContext(restored)
        interp2 = Interpreter(ictx2)
        _, rows, _ = interp2.execute("MATCH (w:W) RETURN count(w)")
        assert rows == [[2]]

        _write_lines(path, [{"id": 3}])
        stream2 = S.Stream(spec, ictx2)
        stream2.start()                    # resumes at the WAL offset
        assert _wait(lambda: stream2.processed_messages >= 1)
        stream2.stop()
        _, rows, _ = interp2.execute(
            "MATCH (w:W) RETURN w.id ORDER BY w.id")
        assert rows == [[1], [2], [3]]     # 1,2 exactly once; 3 fresh
    finally:
        S.TRANSFORMATIONS.pop("test_wal_offsets", None)


def test_kafka_recovered_positions_dedup_redelivery():
    """A crash between the data commit and the broker ack makes the
    broker redeliver the batch; the WAL-recovered per-partition position
    drops the already-ingested messages client-side (exactly-once with
    zero broker cooperation)."""
    mod = _FakeKafkaModule()
    src = S.KafkaSource(["t"], "broker:9092", "g", client_module=mod)
    consumer = mod.consumers[0]
    consumer.queue = [_FakeMsg(b"a", offset=0), _FakeMsg(b"b", offset=1)]
    batch = src.poll(10, 0.01)
    assert [m.payload for m in batch] == [b"a", b"b"]
    # the position staged into the ingest txn (what lands in the WAL)
    assert src.pending_position() == {"t:0": 2}
    # CRASH before src.commit(): broker still has committed_offset 0.
    # Restart seeds the source from the recovered WAL position:
    src2 = S.KafkaSource(["t"], "broker:9092", "g", client_module=mod,
                         start_positions={"t:0": 2})
    consumer2 = mod.consumers[1]
    consumer2.queue = [_FakeMsg(b"a", offset=0), _FakeMsg(b"b", offset=1),
                       _FakeMsg(b"c", offset=2)]
    batch = src2.poll(10, 0.01)
    assert [m.payload for m in batch] == [b"c"]   # a,b deduped
    assert src2.pending_position() == {"t:0": 3}
    src2.rollback()
    # rollback keeps the recovered floor: redelivered a,b still dedup
    assert src2.pending_position() == {"t:0": 2}


def test_failed_txn_stages_no_offset(tmp_path):
    """An aborted ingest transaction publishes NEITHER its data NOR its
    staged offset — the two are one atom."""
    ictx = InterpreterContext(InMemoryStorage())
    interp = Interpreter(ictx, system=True)
    interp.execute("BEGIN")
    interp.execute("CREATE (:A {id: 1})")
    interp.stage_stream_offset("sx", 10)
    interp.execute("ROLLBACK")
    assert ictx.storage.stream_offsets == {}
    _, rows, _ = interp.execute("MATCH (a:A) RETURN count(a)")
    assert rows == [[0]]
    # and staging outside an explicit txn is a typed error
    from memgraph_tpu.exceptions import TransactionException
    with pytest.raises(TransactionException):
        interp.stage_stream_offset("sx", 11)


def test_confluent_kafka_integration_if_available():
    pytest.importorskip("confluent_kafka")
    # real-broker integration is exercised in environments that ship
    # confluent-kafka + a reachable broker (CI profile); the commit/seek
    # discipline above runs against the same KafkaSource code
