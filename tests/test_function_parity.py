"""Builtin-function parity against the reference's registration list.

Diffs our registry (query/functions.py FUNCTIONS) against the
`builtin_functions` map in the reference's
src/query/interpret/awesome_memgraph_functions.cpp. Skipped when the
reference checkout is absent.
"""

import os
import re

import pytest

REF = "/root/reference/src/query/interpret/awesome_memgraph_functions.cpp"

# reference entries that are deliberately not applicable here
KNOWN_NA: set = set()


@pytest.mark.skipif(not os.path.exists(REF),
                    reason="reference checkout not available")
def test_every_reference_builtin_is_registered():
    src = open(REF, encoding="utf-8", errors="replace").read()
    start = src.index("builtin_functions")
    end = src.index("NameToFunction")
    names = set(re.findall(r'\{"([A-Z0-9_]+)"', src[start:end]))
    assert len(names) > 70, "reference parse failed"

    from memgraph_tpu.query.functions import FUNCTIONS
    ours = {f.upper() for f in FUNCTIONS}
    missing = sorted(names - ours - KNOWN_NA)
    assert not missing, f"reference builtins not registered: {missing}"


def test_registry_sanity():
    from memgraph_tpu.query.functions import FUNCTIONS
    assert len(FUNCTIONS) >= 100
    # every registered function is callable
    for name, fn in FUNCTIONS.items():
        assert callable(fn), name
