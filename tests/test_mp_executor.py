"""Multiprocess read executor (server/mp_executor.py): snapshot
semantics, parallel dispatch, error transport, refresh."""

import threading

import pytest

from memgraph_tpu.query import Interpreter
from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.server.mp_executor import MPReadExecutor
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def ictx():
    ictx = InterpreterContext(InMemoryStorage())
    Interpreter(ictx).execute(
        "UNWIND range(0, 99) AS i CREATE (:User {id: i, age: i % 50})")
    return ictx


def test_reads_match_in_process(ictx):
    ex = MPReadExecutor(ictx, n_workers=2)
    try:
        cols, rows = ex.execute(
            "MATCH (n:User {id: 7}) RETURN n.age")
        assert rows == [[7]]
        cols, rows = ex.execute("MATCH (n:User) RETURN count(n)")
        assert rows == [[100]]
    finally:
        ex.close()


def test_snapshot_staleness_and_refresh(ictx):
    ex = MPReadExecutor(ictx, n_workers=2)
    try:
        Interpreter(ictx).execute("CREATE (:User {id: 1000, age: 1})")
        # workers still see the fork-time snapshot
        _, rows = ex.execute("MATCH (n:User) RETURN count(n)")
        assert rows == [[100]]
        ex.refresh()
        _, rows = ex.execute("MATCH (n:User) RETURN count(n)")
        assert rows == [[101]]
    finally:
        ex.close()


def test_concurrent_dispatch(ictx):
    ex = MPReadExecutor(ictx, n_workers=4)
    results = []
    errors = []

    def worker():
        try:
            for _ in range(25):
                _, rows = ex.execute(
                    "MATCH (n:User) WHERE n.age > 10 RETURN count(n)")
                results.append(rows[0][0])
        except Exception as e:  # noqa: BLE001
            errors.append(e)
    try:
        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(results) == 100 and len(set(results)) == 1
    finally:
        ex.close()


def test_worker_error_transport(ictx):
    """Worker-side errors cross the fork boundary TYPED: the parent
    re-raises the taxonomy class the worker named, not a stringly
    RuntimeError."""
    from memgraph_tpu.exceptions import SyntaxException
    ex = MPReadExecutor(ictx, n_workers=1)
    try:
        with pytest.raises(SyntaxException):
            ex.execute("MATCH (n RETURN n")
        # the worker survives the error
        _, rows = ex.execute("RETURN 1")
        assert rows == [[1]]
    finally:
        ex.close()


def test_write_queries_rejected_loudly(ictx):
    """Misrouted writes must fail, not vanish into the forked snapshot."""
    from memgraph_tpu.exceptions import QueryException
    ex = MPReadExecutor(ictx, n_workers=1)
    try:
        with pytest.raises(QueryException, match="read-only"):
            ex.execute("CREATE (:Ghost {id: 1})")
        with pytest.raises(QueryException, match="read-only"):
            ex.execute("MATCH (n:User {id: 1}) SET n.age = 99")
        # non-Cypher statements (auth/DDL) are refused before prepare
        with pytest.raises(QueryException, match="read-only"):
            ex.execute("CREATE INDEX ON :User(id)")
        with pytest.raises(QueryException, match="read-only"):
            ex.execute("CREATE USER ghost IDENTIFIED BY 'pw'")
        # worker still serves reads afterwards
        _, rows = ex.execute("MATCH (n:User) RETURN count(n)")
        assert rows == [[100]]
    finally:
        ex.close()
    # nothing leaked into the parent either
    _, rows, _ = Interpreter(ictx).execute(
        "MATCH (n:Ghost) RETURN count(n)")
    assert rows == [[0]]


def test_worker_crash_respawns_with_typed_retryable_error(ictx):
    """A SIGKILLed worker must not wedge its queue: the in-flight job
    fails with the typed retryable WorkerCrashedError, the worker is
    respawned in place, and the respawn counter moves."""
    import os
    import signal

    from memgraph_tpu.exceptions import WorkerCrashedError
    from memgraph_tpu.observability.metrics import global_metrics

    def metric(name):
        return {n: v for n, _k, v
                in global_metrics.snapshot()}.get(name, 0.0)

    ex = MPReadExecutor(ictx, n_workers=2)
    try:
        assert ex.execute("MATCH (n:User) RETURN count(n)")[1] == [[100]]
        respawns0 = metric("mp_executor.worker_respawn_total")
        for pid, _rq, _rs in list(ex._workers):
            os.kill(pid, signal.SIGKILL)
            os.waitpid(pid, 0)
        crashes = 0
        for _ in range(2):
            try:
                ex.execute("MATCH (n:User) RETURN count(n)")
            except WorkerCrashedError as e:
                # RetryPolicy-compatible: ConnectionError is in the MRO
                assert isinstance(e, ConnectionError)
                crashes += 1
        assert crashes == 2
        assert metric("mp_executor.worker_respawn_total") == \
            respawns0 + 2
        # both workers are fresh and serving again
        for _ in range(4):
            assert ex.execute(
                "MATCH (n:User) RETURN count(n)")[1] == [[100]]
    finally:
        ex.close()


def test_worker_crash_is_retry_policy_compatible(ictx):
    """RetryPolicy.call's default retry_on catches the crash error —
    the dispatch loop heals without special-casing."""
    import os
    import signal

    from memgraph_tpu.utils.retry import RetryPolicy

    ex = MPReadExecutor(ictx, n_workers=1)
    try:
        pid = ex._workers[0][0]
        os.kill(pid, signal.SIGKILL)
        os.waitpid(pid, 0)
        policy = RetryPolicy(base_delay=0.01, max_retries=3)
        _cols, rows = policy.call(
            lambda: ex.execute("MATCH (n:User) RETURN count(n)"))
        assert rows == [[100]]
    finally:
        ex.close()


def test_close_idempotent(ictx):
    ex = MPReadExecutor(ictx, n_workers=1)
    ex.close()
    ex.close()
    with pytest.raises(RuntimeError):
        ex.execute("RETURN 1")
