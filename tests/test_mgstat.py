"""mgstat: PROFILE v2, query fingerprint statistics, per-index usage,
the saturation/readiness plane, and scrape federation.

The satellite contracts live here too: attach_profiling must not
deep-copy (a PROFILE of a plan-cache-hit query neither poisons the
cache nor changes results), an mp_executor-routed query and a
kernel-server-routed analytics query must both return populated profile
rows and increment the same fingerprint registry, and disarmed stats
collection must fit the ≤2% overhead budget.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from memgraph_tpu.observability import stats as S
from memgraph_tpu.observability.metrics import Metrics, global_metrics
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture(autouse=True)
def _reset_stats():
    S.global_query_stats.reset()
    yield
    S.global_query_stats.reset()


@pytest.fixture
def interp():
    return Interpreter(InterpreterContext(InMemoryStorage()))


def _seed(interp, n=16):
    interp.execute(f"UNWIND range(1, {n}) AS i CREATE (:P {{v: i}})")


# --- fingerprinting ---------------------------------------------------------


def test_fingerprint_strips_literals_params_numbers():
    fp = S.fingerprint_text
    assert fp("MATCH (n:P) WHERE n.v = 42 RETURN n") == \
        fp("MATCH (n:P)  WHERE n.v = 7\n RETURN n")
    assert fp("CREATE (:U {name: 'ana'})") == \
        fp('CREATE (:U {name: "bob"})')
    assert fp("MATCH (n) WHERE n.v = $x RETURN n") == \
        fp("MATCH (n) WHERE n.v = $other RETURN n")
    # label identity is case-sensitive and must survive
    assert fp("MATCH (n:Person) RETURN n") != fp("MATCH (n:person) RETURN n")
    # no literal values leak into the shape
    assert "ana" not in fp("CREATE (:U {name: 'ana'})")
    # PROFILE/EXPLAIN wrap a shape — same fingerprint as the plain query
    assert fp("PROFILE MATCH (n) RETURN n") == fp("MATCH (n) RETURN n")


def test_topk_is_bounded_and_space_saving():
    reg = S.QueryStatsRegistry(capacity=8)
    reg.enable()
    for i in range(32):
        for _ in range(i + 1):        # shape i recorded i+1 times
            reg.record(f"shape-{i}", 0.001, rows=1)
    snap = reg.snapshot()
    assert len(snap) <= 8
    # the hottest shapes survive, counts at least their true frequency
    assert snap[0]["fingerprint"] == "shape-31"
    assert snap[0]["count"] >= 32
    # evicted-inheritance is documented per entry
    assert all("overcount_bound" in s for s in snap)


# --- PROFILE v2 -------------------------------------------------------------


def _walk_types(op, out):
    from memgraph_tpu.query.plan.profile import CHILD_ATTRS
    out.add(type(op).__name__)
    for attr in CHILD_ATTRS:
        child = getattr(op, attr, None)
        if child is not None and hasattr(child, "cursor"):
            _walk_types(child, out)


def test_profile_does_not_poison_plan_cache_or_change_results(interp):
    """Satellite: attach_profiling wraps without cloning the cached plan
    and a PROFILE of a cache-hit query leaves cache + results intact."""
    _seed(interp)
    query = "MATCH (p:P) WHERE p.v > 4 RETURN p.v ORDER BY p.v"
    _, before, _ = interp.execute(query)
    key = query.strip()
    cached = interp.ctx._plan_cache[key]
    plan_id = id(cached[0])
    types_before = set()
    _walk_types(cached[0], types_before)
    assert "ProfiledOp" not in types_before

    _, prows, _ = interp.execute("PROFILE " + query)
    assert prows

    cached_after = interp.ctx._plan_cache[key]
    assert id(cached_after[0]) == plan_id          # same object, not replaced
    types_after = set()
    _walk_types(cached_after[0], types_after)
    assert types_after == types_before             # no wrapper leaked in
    _, after, _ = interp.execute(query)
    assert after == before


def test_profile_v2_columns_hits_rows_memory(interp):
    _seed(interp)
    cols, rows, _ = interp.execute(
        "PROFILE MATCH (p:P) WHERE p.v > 4 RETURN p.v")
    assert cols == ["OPERATOR", "ACTUAL HITS", "ROWS", "RELATIVE TIME",
                    "ABSOLUTE TIME", "PEAK MEM (BYTES)"]
    scan = next(r for r in rows if "ScanAllByLabel" in r[0])
    assert scan[1] >= scan[2] >= 12               # hits >= rows produced
    assert scan[5] > 0                            # sampled frame memory
    produce = next(r for r in rows if "Produce" in r[0])
    assert produce[2] == 12


def test_profile_mesh_routed_query_attributes_device_stages(
        interp, monkeypatch):
    """PROFILE on an analytics-routed query shows where the device
    seconds went (transfer + compile/iterate) — mesh-of-1 degeneracy."""
    monkeypatch.setenv("MEMGRAPH_TPU_MESH_DEVICES", "1")
    _seed(interp, 32)
    interp.execute("MATCH (a:P), (b:P) WHERE b.v = a.v + 1 "
                   "CREATE (a)-[:E]->(b)")
    _, rows, _ = interp.execute(
        "PROFILE CALL pagerank.get() YIELD node, rank RETURN rank "
        "ORDER BY rank DESC LIMIT 3")
    stages = {r[0].split(": ", 1)[1] for r in rows
              if r[0].startswith(">> device: ")}
    assert "device_transfer" in stages
    assert "device_compile" in stages
    ops = [r for r in rows if not r[0].startswith(">>")]
    assert any(r[2] > 0 for r in ops)


# --- SHOW QUERY STATS -------------------------------------------------------


def test_show_query_stats_counts_and_plan_cache_hits(interp):
    _seed(interp)
    # same text twice (plan-cache hits) + a different literal (same
    # FINGERPRINT, different cache key — a miss by design)
    for v in (1, 1, 9):
        interp.execute(f"MATCH (p:P) WHERE p.v > {v} RETURN count(p)")
    cols, rows, _ = interp.execute("SHOW QUERY STATS")
    assert cols[0] == "fingerprint"
    fp = S.fingerprint_text("MATCH (p:P) WHERE p.v > 1 RETURN count(p)")
    entry = next(r for r in rows if r[0] == fp)
    assert entry[1] == 3                          # count
    assert entry[2] == 0                          # errors
    assert entry[6] == 1                          # plan-cache hit (2nd run)
    assert entry[5] == 3                          # one count() row each


def test_errored_queries_count_against_their_fingerprint(interp):
    from memgraph_tpu.exceptions import MemgraphTpuError
    _seed(interp, 4)
    with pytest.raises(MemgraphTpuError):
        interp.execute("MATCH (p:P) RETURN p.v / 0")
    fp = S.fingerprint_text("MATCH (p:P) RETURN p.v / ?")
    entry = next(s for s in S.global_query_stats.snapshot()
                 if s["fingerprint"] == fp)
    assert entry["errors"] == 1


def test_concurrent_clients_agree_on_counts_and_trace_links():
    """Acceptance: bounded top-K with correct counts under a concurrent
    multi-client workload; entries link to retained trace_ids."""
    from memgraph_tpu.observability import trace as T
    ictx = InterpreterContext(InMemoryStorage())
    Interpreter(ictx).execute(
        "UNWIND range(1, 32) AS i CREATE (:C {v: i})")
    T.TRACER.reset()
    T.enable(sample=1.0)
    n_threads, per_thread = 4, 15
    errors = []

    def client(tid):
        interp = Interpreter(ictx)
        try:
            for i in range(per_thread):
                interp.execute(
                    f"MATCH (c:C) WHERE c.v > {i % 7} RETURN count(c)")
                interp.execute(f"MATCH (c:C) WHERE c.v = {i % 5} "
                               "RETURN c.v")
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        rows = {r[0]: r for r in S.global_query_stats.rows()}
        fp_a = S.fingerprint_text(
            "MATCH (c:C) WHERE c.v > 0 RETURN count(c)")
        fp_b = S.fingerprint_text("MATCH (c:C) WHERE c.v = 0 RETURN c.v")
        assert rows[fp_a][1] == n_threads * per_thread
        assert rows[fp_b][1] == n_threads * per_thread
        # sample=1.0 retains every trace: the linked ids must resolve
        retained = {s["trace_id"] for tr in T.traces_json() for s in tr}
        assert rows[fp_a][7] and set(rows[fp_a][7]) <= retained
    finally:
        T.disable()
        T.TRACER.reset()


# --- cross-process propagation (satellite) ----------------------------------


def test_mp_executor_profile_rows_and_shared_fingerprint():
    """An mp-routed query returns populated PROFILE rows, and the plain
    shape increments the SAME fingerprint entry as an in-process run."""
    from memgraph_tpu.server.mp_executor import MPReadExecutor
    ictx = InterpreterContext(InMemoryStorage())
    interp = Interpreter(ictx)
    interp.execute("UNWIND range(1, 12) AS i CREATE (:M {v: i})")
    executor = MPReadExecutor(ictx, n_workers=2)
    try:
        query = "MATCH (m:M) WHERE m.v > 2 RETURN m.v"
        cols, prows = executor.execute("PROFILE " + query)
        assert cols[0] == "OPERATOR"
        assert any(r[1] > 0 and r[2] == 10 for r in prows), prows

        interp.execute(query)                 # in-process
        executor.execute(query)               # mp-routed
        fp = S.fingerprint_text(query)
        entry = next(s for s in S.global_query_stats.snapshot()
                     if s["fingerprint"] == fp)
        # in-process + mp-routed + the PROFILE run all land on ONE entry
        assert entry["count"] == 3
    finally:
        executor.close()


@pytest.fixture(scope="module")
def kernel_server(tmp_path_factory):
    """In-thread resident kernel server on a private socket."""
    from memgraph_tpu.server.kernel_server import KernelClient, KernelServer
    sock = str(tmp_path_factory.mktemp("ks") / "ks.sock")
    server = KernelServer(sock, idle_timeout_s=0.0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    deadline = time.monotonic() + 120
    client = None
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=60)
            if client.ping():
                break
            client.close()
            client = None
        except OSError:
            time.sleep(0.1)
    assert client is not None, "kernel server never came up"
    client.close()
    yield sock
    server._shutdown.set()


def test_kernel_routed_query_profile_attribution_and_fingerprint(
        kernel_server):
    """Acceptance + satellite: a kernel-server-routed analytics query
    returns profile rows with kernel dispatch/transfer/compile
    attribution and increments the shared fingerprint registry."""
    before = None
    for name, _k, v in global_metrics.snapshot():
        if name == "analytics.kernel_routed_total":
            before = v
    ictx = InterpreterContext(
        InMemoryStorage(), {"kernel_server_socket": kernel_server})
    interp = Interpreter(ictx)
    interp.execute("UNWIND range(0, 31) AS i CREATE (:K {v: i})")
    interp.execute("MATCH (a:K), (b:K) WHERE b.v = a.v + 1 "
                   "CREATE (a)-[:E]->(b)")
    query = ("CALL pagerank.get() YIELD node, rank "
             "RETURN node.v, rank ORDER BY rank DESC LIMIT 5")
    _, rows, _ = interp.execute("PROFILE " + query)
    stages = {r[0].split(": ", 1)[1] for r in rows
              if r[0].startswith(">> device: ")}
    # client-observed dispatch + the server-side splits shipped home on
    # the reply (transfer/compile/iterate measured IN the daemon thread)
    assert "kernel_dispatch" in stages
    assert {"device_transfer", "device_compile"} <= stages
    after = next(v for name, _k, v in global_metrics.snapshot()
                 if name == "analytics.kernel_routed_total")
    assert before is None or after > before
    fp = S.fingerprint_text(query)
    entry = next(s for s in S.global_query_stats.snapshot()
                 if s["fingerprint"] == fp)
    assert entry["count"] >= 1


# --- index usage (satellite) ------------------------------------------------


def test_index_usage_counters_and_show_index_info(interp):
    _seed(interp)
    interp.execute("CREATE INDEX ON :P(v)")
    interp.execute("CREATE INDEX ON :P(unused)")
    for v in (3, 7, 7):
        interp.execute(f"MATCH (p:P) WHERE p.v = {v} RETURN p.v")
    cols, rows, _ = interp.execute("SHOW INDEX INFO")
    assert cols[4:] == ["lookups", "rows_returned", "last_used"]
    used = next(r for r in rows if r[2] == ["v"])
    assert used[4] == 3 and used[5] == 3
    assert used[6] is not None
    # the index that only absorbs writes is visibly idle
    unused = next(r for r in rows if r[2] == ["unused"])
    assert unused[4] == 0 and unused[5] == 0 and unused[6] is None


def test_index_usage_counts_abandoned_scans(storage):
    """A LIMIT-abandoned iterator still flushes what it served."""
    ictx = InterpreterContext(storage)
    interp = Interpreter(ictx)
    _seed(interp, 20)
    interp.execute("CREATE INDEX ON :P(v)")
    interp.execute("MATCH (p:P) WHERE p.v > 0 RETURN p.v LIMIT 3")
    lid = storage.label_mapper.maybe_name_to_id("P")
    pid = storage.property_mapper.maybe_name_to_id("v")
    usage = storage.indices.label_property.usage(lid, (pid,))
    assert usage is not None and usage.lookups == 1
    assert 0 < usage.rows <= 20


def test_index_usage_cleared_on_drop(interp):
    _seed(interp, 4)
    interp.execute("CREATE INDEX ON :P(v)")
    interp.execute("MATCH (p:P) WHERE p.v = 1 RETURN p")
    interp.execute("DROP INDEX ON :P(v)")
    interp.execute("CREATE INDEX ON :P(v)")
    _, rows, _ = interp.execute("SHOW INDEX INFO")
    fresh = next(r for r in rows if r[2] == ["v"])
    assert fresh[4] == 0                           # usage died with the drop


# --- saturation plane -------------------------------------------------------


def test_health_verdict_trips_on_shed_and_recovers():
    plane = S.SaturationPlane()
    assert plane.evaluate()["ready"]
    global_metrics.increment("kernel_server.dispatch.shed_total")
    verdict = plane.evaluate()
    assert not verdict["ready"]
    reason = next(r for r in verdict["reasons"]
                  if r["check"] == "kernel_server_admission")
    assert reason["value"] >= 1
    # pressure stopped: the next evaluation recovers (rate semantics)
    assert plane.evaluate()["ready"]


def test_health_verdict_trips_on_replication_lag():
    plane = S.SaturationPlane()
    plane.evaluate()
    global_metrics.set_gauge("replication.replica_lag.r1", 5000.0)
    try:
        verdict = plane.evaluate()
        assert not verdict["ready"]
        reason = next(r for r in verdict["reasons"]
                      if r["check"] == "replication_lag")
        assert reason["value"] == 5000.0
        assert reason["threshold"] == plane.max_replica_lag
    finally:
        global_metrics.set_gauge("replication.replica_lag.r1", 0.0)


def test_health_verdict_trips_on_wal_backlog_and_wedge():
    plane = S.SaturationPlane()
    plane.evaluate()
    global_metrics.set_gauge("wal.fsync_backlog_bytes", 1e12)
    global_metrics.set_gauge("kernel_server.daemon.wedged", 1.0)
    try:
        verdict = plane.evaluate()
        checks = {r["check"] for r in verdict["reasons"]}
        assert {"wal_fsync_backlog", "kernel_server"} <= checks
    finally:
        global_metrics.set_gauge("wal.fsync_backlog_bytes", 0.0)
        global_metrics.set_gauge("kernel_server.daemon.wedged", 0.0)


# --- HTTP surfaces ----------------------------------------------------------


@pytest.fixture
def monitoring(interp):
    import asyncio
    import socket
    from memgraph_tpu.observability.http import start_monitoring_server
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(
            start_monitoring_server("127.0.0.1", port, interp.ctx))
        started.set()
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    assert started.wait(10)
    yield port, interp
    loop.call_soon_threadsafe(loop.stop)


def test_get_stats_endpoint(monitoring):
    port, interp = monitoring
    _seed(interp, 4)
    interp.execute("MATCH (p:P) RETURN count(p)")
    doc = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/stats", timeout=5).read())
    assert doc["enabled"] and doc["capacity"] >= 8
    fps = {e["fingerprint"]: e for e in doc["fingerprints"]}
    fp = S.fingerprint_text("MATCH (p:P) RETURN count(p)")
    assert fps[fp]["count"] == 1
    assert "latency_p99_ms" in fps[fp]


def test_get_stats_flow_section_and_gauges(monitoring):
    """The /stats body carries the exception-flow contract surface and
    the GET itself refreshes the mgflow.* gauges from the registry."""
    from memgraph_tpu.flowspec import SERVING_ROOTS
    port, _interp = monitoring
    doc = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/stats", timeout=5).read())
    flow = doc["flow"]
    assert flow["contract_roots"] == len(SERVING_ROOTS) >= 10
    assert set(flow["wires"]) == {"kernel", "mp_executor", "twopc"}
    assert flow["roots"]["twopc.prepare"] == ["MemgraphTpuError"]
    gauges = {n: v for n, _k, v in global_metrics.snapshot()}
    assert gauges["mgflow.contract_roots"] == float(len(SERVING_ROOTS))
    assert gauges["mgflow.escapes_total"] == float(flow["escapes_total"])


def test_get_health_flips_to_503_with_reason(monitoring):
    """Acceptance: /health goes not-ready with a machine-readable
    reason under an injected saturation fault, then recovers."""
    port, _interp = monitoring
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/health", timeout=5).read()
    assert json.loads(body)["ready"] is True
    global_metrics.set_gauge("replication.replica_lag.inj", 1e9)
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=5)
        assert err.value.code == 503
        doc = json.loads(err.value.read())
        assert doc["ready"] is False
        reason = next(r for r in doc["reasons"]
                      if r["check"] == "replication_lag")
        assert reason["value"] == 1e9 and "threshold" in reason
    finally:
        global_metrics.set_gauge("replication.replica_lag.inj", 0.0)
    doc = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/health", timeout=5).read())
    assert doc["ready"] is True


# --- federation -------------------------------------------------------------


def test_federate_expositions_labels_and_type_dedupe():
    m = Metrics()
    m.increment("demo.counter", 3)
    m.set_gauge("demo.gauge", 1.5)
    m.observe("demo.latency", 0.01, trace_id="cafe1234")
    text = m.prometheus_text()
    fed = S.federate_expositions({"main": text, "replica-1": text})
    lines = fed.splitlines()
    type_lines = [ln for ln in lines if ln.startswith("# TYPE ")]
    assert len(type_lines) == len({ln for ln in type_lines})
    samples = [ln for ln in lines if not ln.startswith("#")]
    assert all('instance="' in ln for ln in samples)
    assert any('demo_counter{instance="main"} 3.0' == ln
               for ln in samples)
    # histogram bucket labels merge with the instance label and the
    # OpenMetrics exemplar survives federation
    assert any(ln.startswith('demo_latency_bucket{instance="replica-1",'
                             'le=') for ln in samples)
    assert any('trace_id="cafe1234"' in ln for ln in samples)


def test_coordinator_federates_main_replica_and_kernel_daemon(
        kernel_server):
    """Acceptance: the coordinator's federated exposition carries main +
    replica + kernel-daemon series, each with its instance label."""
    import socket as _socket
    from memgraph_tpu.coordination.coordinator import CoordinatorInstance
    from memgraph_tpu.coordination.data_instance import (
        DataInstanceManagementServer)

    def free_port():
        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    mgmt1, repl1, mgmt2, repl2, raft = (free_port() for _ in range(5))
    ictx1 = InterpreterContext(
        InMemoryStorage(), {"kernel_server_socket": kernel_server})
    ictx2 = InterpreterContext(InMemoryStorage())
    m1 = DataInstanceManagementServer(ictx1, "127.0.0.1", mgmt1)
    m2 = DataInstanceManagementServer(ictx2, "127.0.0.1", mgmt2)
    m1.start()
    m2.start()
    coord = CoordinatorInstance("coord1", "127.0.0.1", raft, {})
    coord.start()
    try:
        deadline = time.monotonic() + 10
        while not coord.raft.is_leader() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert coord.raft.is_leader()
        assert coord.register_instance("main1", f"127.0.0.1:{mgmt1}",
                                       f"127.0.0.1:{repl1}")
        assert coord.register_instance("replica1", f"127.0.0.1:{mgmt2}",
                                       f"127.0.0.1:{repl2}")
        assert coord.set_instance_to_main("main1")
        global_metrics.increment("query.finished", 0)  # ensure series
        fed = coord.federated_prometheus_text()
        assert 'instance="main1"' in fed
        assert 'instance="replica1"' in fed
        assert 'instance="coord1"' in fed
        # the resident daemon appears as its own federated instance
        assert 'instance="main1-kernel-daemon"' in fed
        assert "kernel_server_daemon_in_flight" in fed
    finally:
        coord.stop()
        m1.stop()
        m2.stop()


# --- overhead guard ---------------------------------------------------------


def test_default_stats_overhead_under_two_percent(interp):
    """Per-query stat collection (fingerprint memo hit + one record)
    must fit the same deterministic ≤2% bound mgtrace holds itself to:
    (stat calls per query) x (measured per-call cost) vs the measured
    per-query time of a representative micro-benchmark."""
    _seed(interp, 200)
    reg = S.global_query_stats
    text = "MATCH (p:P) WHERE p.v > 100 RETURN count(p)"
    reg.fingerprint(text)                     # memo warm (plan-cache analog)

    # Deterministic clock (PR 13 review deflake): both micro-benchmarks
    # are CPU-bound in THIS thread, and the 2% claim is about CPU cost
    # per call, so measure with thread_time — scheduler preemption and
    # leftover daemon threads from earlier tests inflated the
    # wall-clock per_call batches under full-suite load (the test
    # passed alone, flaked in-suite) while the per-query batches could
    # land in a quiet window, flipping the ratio.
    def stat_batch():
        t0 = time.thread_time()
        for _ in range(2000):
            fp = reg.fingerprint(text)
            reg.record(fp, 0.001, rows=1, plan_cache_hit=True)
        return (time.thread_time() - t0) / 2000

    per_call = min(stat_batch() for _ in range(5))
    reg.reset()

    interp.execute(text)                      # warm plan cache

    def query_batch():
        t0 = time.thread_time()
        for _ in range(30):
            interp.execute(text)
        return (time.thread_time() - t0) / 30

    per_query = min(query_batch() for _ in range(3))
    budget_calls = 2                          # fingerprint + record
    overhead = per_call * budget_calls
    assert overhead <= 0.02 * per_query, (
        f"stat collection overhead {overhead * 1e6:.2f}µs exceeds 2% of "
        f"the {per_query * 1e6:.1f}µs micro-benchmark query")
