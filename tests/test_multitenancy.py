"""Multi-tenancy tests (reference: src/dbms/, tests/e2e multi-tenancy)."""

import pytest

from memgraph_tpu.dbms.dbms import DbmsHandler
from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.query.interpreter import Interpreter
from memgraph_tpu.storage import StorageConfig


@pytest.fixture
def dbms():
    return DbmsHandler()


def test_isolated_databases(dbms):
    interp = Interpreter(dbms.default())
    interp.execute("CREATE DATABASE tenant1")
    interp.execute("CREATE (:InDefault)")
    interp.execute("USE DATABASE tenant1")
    interp.execute("CREATE (:InTenant)")
    _, rows, _ = interp.execute("MATCH (n) RETURN count(n)")
    assert rows == [[1]]
    _, rows, _ = interp.execute("MATCH (n:InDefault) RETURN count(n)")
    assert rows == [[0]]  # isolation
    interp.execute("USE DATABASE memgraph")
    _, rows, _ = interp.execute("MATCH (n:InDefault) RETURN count(n)")
    assert rows == [[1]]


def test_show_databases(dbms):
    interp = Interpreter(dbms.default())
    interp.execute("CREATE DATABASE t2")
    _, rows, _ = interp.execute("SHOW DATABASES")
    assert [r[0] for r in rows] == ["memgraph", "t2"]
    current = {r[0]: r[1] for r in rows}
    assert current["memgraph"] is True
    interp.execute("USE DATABASE t2")
    _, rows, _ = interp.execute("SHOW DATABASES")
    current = {r[0]: r[1] for r in rows}
    assert current["t2"] is True


def test_drop_database_rules(dbms):
    interp = Interpreter(dbms.default())
    with pytest.raises(QueryException):
        interp.execute("DROP DATABASE memgraph")
    with pytest.raises(QueryException):
        interp.execute("DROP DATABASE nonexistent")
    interp.execute("CREATE DATABASE temp")
    interp.execute("DROP DATABASE temp")
    _, rows, _ = interp.execute("SHOW DATABASES")
    assert [r[0] for r in rows] == ["memgraph"]


def test_duplicate_database(dbms):
    interp = Interpreter(dbms.default())
    interp.execute("CREATE DATABASE dup")
    with pytest.raises(QueryException):
        interp.execute("CREATE DATABASE dup")


def test_per_database_durability(tmp_path):
    cfg = StorageConfig(durability_dir=str(tmp_path), wal_enabled=True)
    dbms = DbmsHandler(cfg)
    interp = Interpreter(dbms.default())
    interp.execute("CREATE DATABASE t1")
    interp.execute("CREATE (:RootData)")
    interp.execute("USE DATABASE t1")
    interp.execute("CREATE (:TenantData)")

    # new handler over the same directory recovers both
    dbms2 = DbmsHandler(cfg)
    dbms2.create("t1") if "t1" not in dbms2.names() else None
    interp2 = Interpreter(dbms2.default())
    _, rows, _ = interp2.execute("MATCH (n:RootData) RETURN count(n)")
    assert rows == [[1]]
    interp2.execute("USE DATABASE t1")
    _, rows, _ = interp2.execute("MATCH (n:TenantData) RETURN count(n)")
    assert rows == [[1]]
