"""Raft + coordinator failover tests.

Modeled on the reference's in-process coordination tests
(tests/unit/coordinator_raft_state.cpp, e2e/high_availability/): 3-node
Raft clusters on localhost ports; full failover e2e with real data
instances (storage + replication + mgmt servers) and a killed MAIN.
"""

import socket
import time

import pytest

from memgraph_tpu.coordination.coordinator import CoordinatorInstance
from memgraph_tpu.coordination.data_instance import (
    DataInstanceManagementServer, mgmt_call)
from memgraph_tpu.coordination.raft import RaftNode
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


def _ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait(pred, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _leader(nodes):
    for n in nodes:
        if n.is_leader():
            return n
    return None


@pytest.fixture
def raft3():
    ports = _ports(3)
    ids = ["c1", "c2", "c3"]
    applied = {i: [] for i in ids}
    nodes = []
    for i, nid in enumerate(ids):
        peers = {ids[j]: ("127.0.0.1", ports[j])
                 for j in range(3) if j != i}
        node = RaftNode(nid, "127.0.0.1", ports[i], peers,
                        apply_fn=lambda cmd, _n=nid: applied[_n].append(cmd))
        nodes.append(node)
    for n in nodes:
        n.start()
    yield nodes, applied
    for n in nodes:
        n.stop()


def test_raft_elects_single_leader(raft3):
    nodes, _ = raft3
    assert _wait(lambda: _leader(nodes) is not None)
    time.sleep(0.5)
    leaders = [n for n in nodes if n.is_leader()]
    assert len(leaders) == 1


def test_raft_replicates_and_applies(raft3):
    nodes, applied = raft3
    assert _wait(lambda: _leader(nodes) is not None)
    leader = _leader(nodes)
    assert leader.propose({"op": "x", "v": 1})
    assert leader.propose({"op": "x", "v": 2})
    assert _wait(lambda: all(len(applied[n.node_id]) == 2 for n in nodes))
    for n in nodes:
        assert [c["v"] for c in applied[n.node_id]] == [1, 2]


def test_raft_leader_failover(raft3):
    nodes, applied = raft3
    assert _wait(lambda: _leader(nodes) is not None)
    leader = _leader(nodes)
    leader.propose({"op": "x", "v": 1})
    leader.stop()
    rest = [n for n in nodes if n is not leader]
    assert _wait(lambda: _leader(rest) is not None, timeout=15)
    new_leader = _leader(rest)
    assert new_leader.propose({"op": "x", "v": 2}, timeout=10)
    assert _wait(lambda: all(
        [c["v"] for c in applied[n.node_id]] == [1, 2] for n in rest))


def test_follower_rejects_propose(raft3):
    nodes, _ = raft3
    assert _wait(lambda: _leader(nodes) is not None)
    followers = [n for n in nodes if not n.is_leader()]
    result = followers[0].propose({"op": "x"})
    assert not result                     # falsy: nothing committed
    assert result.outcome == "not_leader"
    assert result.retryable               # safe to retry on the leader


def test_propose_result_is_typed(raft3):
    """Committed proposals report a truthy, index-carrying result."""
    nodes, _ = raft3
    assert _wait(lambda: _leader(nodes) is not None)
    leader = _leader(nodes)
    result = leader.propose({"op": "x", "v": 1})
    assert result and result.outcome == "committed"
    assert result.index is not None and result.term is not None
    assert not result.retryable


class _DataInstance:
    def __init__(self, mgmt_port, repl_port):
        self.ictx = InterpreterContext(InMemoryStorage())
        self.interp = Interpreter(self.ictx)
        self.mgmt = DataInstanceManagementServer(
            self.ictx, "127.0.0.1", mgmt_port)
        self.mgmt.start()
        self.mgmt_address = f"127.0.0.1:{mgmt_port}"
        self.repl_address = f"127.0.0.1:{repl_port}"
        self.repl_port = repl_port

    def stop(self):
        self.mgmt.stop()
        replication = getattr(self.ictx, "replication", None)
        if replication is not None:
            if replication.replica_server:
                replication.replica_server.stop()
            for c in replication.replicas.values():
                c.close()


def test_full_failover_e2e():
    """Coordinator + 2 data instances; kill the MAIN; the replica is
    promoted and accepts writes with the replicated data intact."""
    mgmt1, repl1, mgmt2, repl2, raft_port = _ports(5)
    i1 = _DataInstance(mgmt1, repl1)
    i2 = _DataInstance(mgmt2, repl2)
    coord = CoordinatorInstance("coord1", "127.0.0.1", raft_port, {})
    coord.HEALTH_CHECK_INTERVAL = 0.2
    coord.start()
    try:
        assert _wait(lambda: coord.raft.is_leader(), timeout=10)
        assert coord.register_instance("i1", i1.mgmt_address,
                                       i1.repl_address)
        assert coord.register_instance("i2", i2.mgmt_address,
                                       i2.repl_address)
        assert coord.set_instance_to_main("i1")
        # i2 was demoted to replica listening on its replication port,
        # i1 promoted with i2 registered
        assert _wait(lambda: getattr(i1.ictx, "replication", None)
                     is not None and i1.ictx.replication.role == "main")
        assert i2.ictx.replication.role == "replica"
        # write on MAIN replicates
        i1.interp.execute("CREATE (:HA {v: 1})")
        _wait(lambda: Interpreter(i2.ictx).execute(
            "MATCH (n:HA) RETURN count(n)")[1] == [[1]])
        _, rows, _ = Interpreter(i2.ictx).execute(
            "MATCH (n:HA) RETURN count(n)")
        assert rows == [[1]]

        # kill the MAIN
        i1.stop()
        # failover: coordinator promotes i2
        assert _wait(lambda: coord.main_name == "i2", timeout=20)
        assert _wait(lambda: i2.ictx.replication.role == "main", timeout=10)
        # promoted instance has the data and accepts writes
        _, rows, _ = i2.interp.execute("MATCH (n:HA) RETURN count(n)")
        assert rows == [[1]]
        i2.interp.execute("CREATE (:HA {v: 2})")
        _, rows, _ = i2.interp.execute("MATCH (n:HA) RETURN count(n)")
        assert rows == [[2]]
    finally:
        coord.stop()
        i1.stop()
        i2.stop()


def test_coordinator_cypher_surface():
    """REGISTER INSTANCE / SET INSTANCE TO MAIN / SHOW INSTANCES via Cypher."""
    mgmt1, repl1, raft_port = _ports(3)
    inst = _DataInstance(mgmt1, repl1)
    coord_ictx = InterpreterContext(InMemoryStorage())
    coord = CoordinatorInstance("c1", "127.0.0.1", raft_port, {})
    coord_ictx.coordinator = coord
    coord.start()
    interp = Interpreter(coord_ictx)
    try:
        assert _wait(lambda: coord.raft.is_leader(), timeout=10)
        interp.execute(f'REGISTER INSTANCE i1 ON "{inst.mgmt_address}" '
                       f'WITH "{inst.repl_address}"')
        interp.execute("SET INSTANCE i1 TO MAIN")
        _, rows, _ = interp.execute("SHOW INSTANCES")
        by_name = {r[0]: r for r in rows}
        assert by_name["i1"][2] == "main"
        assert by_name["c1"][2] == "leader"
        # non-coordinator instances reject coordinator queries
        from memgraph_tpu.exceptions import QueryException
        with pytest.raises(QueryException):
            inst.interp.execute("SHOW INSTANCES")
    finally:
        coord.stop()
        inst.stop()


def test_mgmt_state_check():
    (mgmt_port,) = _ports(1)
    inst = _DataInstance(mgmt_port, 0)
    try:
        resp = mgmt_call(inst.mgmt_address, {"kind": "state_check"})
        assert resp["ok"] and resp["role"] == "main"
    finally:
        inst.stop()


def test_raft_state_survives_restart(tmp_path):
    """Raft persistent state (term, vote, log) restores via the kvstore."""
    from memgraph_tpu.storage.kvstore import KVStore
    port, port2 = _ports(2)
    kv = KVStore(str(tmp_path / "raft.db"))
    applied = []
    node = RaftNode("solo", "127.0.0.1", port, {},
                    apply_fn=applied.append, kvstore=kv)
    node.start()
    assert _wait(lambda: node.is_leader(), timeout=10)
    assert node.propose({"op": "a"})
    assert node.propose({"op": "b"})
    term_before = node.current_term
    node.stop()

    node2 = RaftNode("solo", "127.0.0.1", port2, {},
                     apply_fn=applied.append, kvstore=kv)
    assert node2.current_term == term_before
    assert [e.command["op"] for e in node2.log
            if "_noop" not in e.command] == ["a", "b"]
    node2.start()
    assert _wait(lambda: node2.is_leader(), timeout=10)
    assert node2.propose({"op": "c"})
    assert [e.command["op"] for e in node2.log
            if "_noop" not in e.command] == ["a", "b", "c"]
    node2.stop()


# --------------------------------------------------------------------------
# log compaction + install-snapshot (reference: coordinator_log_store.cpp,
# raft_state.cpp:370)
# --------------------------------------------------------------------------

class _KVStateMachine:
    """Tiny snapshot-able state machine: applies {'k':..,'v':..} sets."""

    def __init__(self):
        self.state = {}
        self.applied = 0

    def apply(self, cmd):
        self.state[cmd["k"]] = cmd["v"]
        self.applied += 1

    def snapshot(self):
        return dict(self.state)

    def restore(self, snap):
        self.state = dict(snap)


def _mk_compacting_cluster(ports, ids, threshold, kvs=None):
    sms = {i: _KVStateMachine() for i in ids}
    nodes = []
    for i, nid in enumerate(ids):
        peers = {ids[j]: ("127.0.0.1", ports[j])
                 for j in range(len(ids)) if j != i}
        sm = sms[nid]
        nodes.append(RaftNode(
            nid, "127.0.0.1", ports[i], peers, apply_fn=sm.apply,
            snapshot_fn=sm.snapshot, restore_fn=sm.restore,
            compaction_threshold=threshold,
            kvstore=kvs[nid] if kvs else None))
    return nodes, sms


def test_raft_log_compaction_bounds_log():
    """The in-memory (and persisted) log stays bounded under load."""
    ports = _ports(3)
    ids = ["c1", "c2", "c3"]
    nodes, sms = _mk_compacting_cluster(ports, ids, threshold=16)
    for n in nodes:
        n.start()
    try:
        assert _wait(lambda: _leader(nodes) is not None)
        leader = _leader(nodes)
        for i in range(100):
            assert leader.propose({"k": f"x{i % 7}", "v": i})
        # every node converges (followers may receive part of the history
        # as a snapshot rather than entry-by-entry apply)
        assert _wait(lambda: all(sm.state.get("x6") == 97
                                 for sm in sms.values()), timeout=15)
        # every node compacted: nobody holds the full 100-entry log
        assert _wait(lambda: all(len(n.log) < 60 for n in nodes),
                     timeout=10), [len(n.log) for n in nodes]
    finally:
        for n in nodes:
            n.stop()


def test_raft_install_snapshot_catches_up_lagging_peer():
    """A peer that missed the compaction window is restored via
    install-snapshot, not log replay."""
    ports = _ports(3)
    ids = ["c1", "c2", "c3"]
    nodes, sms = _mk_compacting_cluster(ports, ids, threshold=8)
    # start only two: majority commits + compacts while c3 is down
    for n in nodes[:2]:
        n.start()
    try:
        assert _wait(lambda: _leader(nodes[:2]) is not None)
        leader = _leader(nodes[:2])
        for i in range(40):
            assert leader.propose({"k": f"k{i}", "v": i})
        assert _wait(lambda: leader.log_start > 0, timeout=10)
        # now bring up the empty third node
        nodes[2].start()
        assert _wait(lambda: sms["c3"].state.get("k39") == 39, timeout=15)
        # c3 received a snapshot: its log does not start at 0
        assert nodes[2].log_start > 0
        assert sms["c3"].state == sms[leader.node_id].state
    finally:
        for n in nodes:
            n.stop()


def test_raft_compacted_state_survives_restart(tmp_path):
    """Restart replays a BOUNDED log: snapshot + tail, not the full
    history."""
    from memgraph_tpu.storage.kvstore import KVStore
    port, port2 = _ports(2)
    kv = KVStore(str(tmp_path / "raft.db"))
    sm = _KVStateMachine()
    node = RaftNode("solo", "127.0.0.1", port, {}, apply_fn=sm.apply,
                    snapshot_fn=sm.snapshot, restore_fn=sm.restore,
                    compaction_threshold=10, kvstore=kv)
    node.start()
    try:
        assert _wait(lambda: node.is_leader(), timeout=10)
        for i in range(50):
            assert node.propose({"k": "count", "v": i})
        assert node.log_start > 0
    finally:
        node.stop()

    sm2 = _KVStateMachine()
    node2 = RaftNode("solo", "127.0.0.1", port2, {}, apply_fn=sm2.apply,
                     snapshot_fn=sm2.snapshot, restore_fn=sm2.restore,
                     compaction_threshold=10, kvstore=kv)
    # restored WITHOUT replaying all 50 entries: snapshot covered the bulk
    assert sm2.applied < 50
    node2.start()
    try:
        assert _wait(lambda: node2.is_leader(), timeout=10)
        assert _wait(lambda: sm2.state.get("count") == 49, timeout=5)
        assert node2.propose({"k": "count", "v": 50})
        assert sm2.state["count"] == 50
    finally:
        node2.stop()


def test_coordinator_route_table():
    """ROUTE is served from live replicated cluster state."""
    from memgraph_tpu.coordination.coordinator import CoordinatorInstance
    (raft_port,) = _ports(1)
    coord = CoordinatorInstance("c1", "127.0.0.1", raft_port, {})
    coord.start()
    try:
        assert _wait(lambda: coord.raft.is_leader(), timeout=10)
        assert coord.register_instance(
            "i1", "127.0.0.1:20011", "127.0.0.1:20021",
            bolt_address="127.0.0.1:20031")
        assert coord.register_instance(
            "i2", "127.0.0.1:20012", "127.0.0.1:20022",
            bolt_address="127.0.0.1:20032")
        # no main yet: writers empty, readers = replicas
        table = coord.route_table()
        assert table["writers"] == []
        assert sorted(table["readers"]) == ["127.0.0.1:20031",
                                            "127.0.0.1:20032"]
        # promotion via raft updates the table (skip the data-instance
        # reconfiguration: there are no real instances behind the addrs)
        assert coord.raft.propose({"op": "set_main", "name": "i1"})
        table = coord.route_table()
        assert table["writers"] == ["127.0.0.1:20031"]
        assert table["readers"] == ["127.0.0.1:20032"]
    finally:
        coord.stop()
