"""Louvain, node similarity (MXU dense path), bridges/cycles, point index."""

import numpy as np
import pytest

from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def db():
    return InterpreterContext(InMemoryStorage())


def run(db, q, params=None):
    _, rows, _ = Interpreter(db).execute(q, params)
    return rows


def _two_cliques(db):
    run(db, """
        UNWIND range(0, 4) AS i UNWIND range(0, 4) AS j
        WITH i, j WHERE i < j
        MERGE (a:N {id: i}) MERGE (b:N {id: j}) CREATE (a)-[:E]->(b)""")
    run(db, """
        UNWIND range(5, 9) AS i UNWIND range(5, 9) AS j
        WITH i, j WHERE i < j
        MERGE (a:N {id: i}) MERGE (b:N {id: j}) CREATE (a)-[:E]->(b)""")
    run(db, "MATCH (a:N {id: 0}), (b:N {id: 5}) CREATE (a)-[:E]->(b)")


def test_louvain_two_cliques(db):
    _two_cliques(db)
    rows = run(db, "CALL community_detection.louvain() "
                   "YIELD node, community_id, modularity "
                   "RETURN node.id, community_id, modularity")
    comm = {r[0]: r[1] for r in rows}
    assert len({comm[i] for i in range(5)}) == 1
    assert len({comm[i] for i in range(5, 10)}) == 1
    assert comm[0] != comm[5]
    assert rows[0][2] > 0.3  # decent modularity


def test_louvain_matches_networkx_quality(db):
    import networkx as nx
    _two_cliques(db)
    rows = run(db, "CALL community_detection.louvain() "
                   "YIELD modularity RETURN modularity LIMIT 1")
    assert rows[0][0] >= 0.3


def test_node_similarity_jaccard(db):
    # a -> {x, y}; b -> {x, y}; c -> {x}
    run(db, """CREATE (a:S {k:'a'}), (b:S {k:'b'}), (c:S {k:'c'}),
                      (x:S {k:'x'}), (y:S {k:'y'}),
                      (a)-[:E]->(x), (a)-[:E]->(y),
                      (b)-[:E]->(x), (b)-[:E]->(y),
                      (c)-[:E]->(x)""")
    rows = run(db, "CALL node_similarity.jaccard() "
                   "YIELD node1, node2, similarity "
                   "RETURN node1.k, node2.k, similarity")
    sim = {(min(a, b), max(a, b)): s for a, b, s in rows}
    assert sim[("a", "b")] == pytest.approx(1.0, abs=0.05)
    assert sim[("a", "c")] == pytest.approx(0.5, abs=0.05)


def test_node_similarity_pairwise(db):
    run(db, """CREATE (a:P {k:'a'}), (b:P {k:'b'}), (x:P), (y:P),
                      (a)-[:E]->(x), (a)-[:E]->(y), (b)-[:E]->(x)""")
    rows = run(db, "MATCH (a:P {k:'a'}), (b:P {k:'b'}) "
                   "CALL node_similarity.pairwise([[a, b]], 'overlap') "
                   "YIELD similarity RETURN similarity")
    assert rows[0][0] == pytest.approx(1.0)


def test_bridges(db):
    # two triangles joined by one bridge edge
    run(db, """CREATE (a:B {i:0}), (b:B {i:1}), (c:B {i:2}),
                      (d:B {i:3}), (e:B {i:4}), (f:B {i:5}),
                      (a)-[:E]->(b), (b)-[:E]->(c), (c)-[:E]->(a),
                      (d)-[:E]->(e), (e)-[:E]->(f), (f)-[:E]->(d),
                      (c)-[:E]->(d)""")
    rows = run(db, "CALL bridges.get() YIELD node_from, node_to "
                   "RETURN node_from.i, node_to.i")
    assert len(rows) == 1
    assert sorted(rows[0]) == [2, 3]


def test_cycles(db):
    run(db, """CREATE (a:C), (b:C), (c:C),
                      (a)-[:E]->(b), (b)-[:E]->(c), (c)-[:E]->(a)""")
    rows = run(db, "CALL cycles.get() YIELD cycle RETURN size(cycle)")
    assert rows == [[3]]


def test_point_index(db):
    run(db, """CREATE (:Place {name: 'near', loc: point({x: 1.0, y: 1.0})}),
                      (:Place {name: 'far', loc: point({x: 100.0, y: 100.0})})""")
    run(db, "CALL point_index.create('Place', 'loc') YIELD status "
            "RETURN status")
    rows = run(db, "CALL point_index.within_distance('Place', 'loc', "
                   "point({x: 0.0, y: 0.0}), 5.0) "
                   "YIELD node, distance RETURN node.name, distance")
    assert len(rows) == 1
    assert rows[0][0] == "near"
    # index tracks later commits
    run(db, "CREATE (:Place {name: 'also-near', loc: point({x: 2.0, y: 0.0})})")
    rows = run(db, "CALL point_index.within_distance('Place', 'loc', "
                   "point({x: 0.0, y: 0.0}), 5.0) YIELD node "
                   "RETURN count(node)")
    assert rows == [[2]]


def test_nxalg_betweenness(db):
    run(db, """CREATE (a:X), (b:X), (c:X),
                      (a)-[:E]->(b), (b)-[:E]->(c)""")
    rows = run(db, "CALL nxalg.betweenness_centrality() "
                   "YIELD node, betweenness RETURN betweenness "
                   "ORDER BY betweenness DESC")
    assert rows[0][0] > 0  # the middle node carries the path


def test_native_betweenness_matches_networkx(db):
    """Device Brandes kernel vs NetworkX exact, directed + undirected."""
    import networkx as nx
    import numpy as np
    rng = np.random.default_rng(4)
    n, e = 40, 160
    edges = {(int(a), int(b)) for a, b in
             zip(rng.integers(0, n, e), rng.integers(0, n, e))
             if a != b}
    for i in range(n):
        run(db, "CREATE (:B {id: $i})", {"i": i})
    for a, b in edges:
        run(db, "MATCH (x:B {id: $a}), (y:B {id: $b}) CREATE (x)-[:E]->(y)",
            {"a": a, "b": b})

    rows = run(db, "CALL betweenness_centrality.get() "
                   "YIELD node, betweenness_centrality "
                   "RETURN node.id AS id, betweenness_centrality AS bc")
    got = {r[0]: r[1] for r in rows}
    g = nx.DiGraph(sorted(edges))
    g.add_nodes_from(range(n))
    expect = nx.betweenness_centrality(g, normalized=True)
    for i in range(n):
        assert abs(got[i] - expect[i]) < 1e-4, (i, got[i], expect[i])

    rows = run(db, "CALL betweenness_centrality.get(false, true) "
                   "YIELD node, betweenness_centrality "
                   "RETURN node.id AS id, betweenness_centrality AS bc")
    got_u = {r[0]: r[1] for r in rows}
    gu = nx.Graph(sorted(edges))
    gu.add_nodes_from(range(n))
    expect_u = nx.betweenness_centrality(gu, normalized=True)
    for i in range(n):
        assert abs(got_u[i] - expect_u[i]) < 1e-4, (i, got_u[i],
                                                    expect_u[i])


def test_sampled_betweenness_approximates(db):
    import numpy as np
    rng = np.random.default_rng(9)
    n = 60
    for i in range(n):
        run(db, "CREATE (:S {id: $i})", {"i": i})
    # star + chain: node 0 is a hub with high betweenness
    for i in range(1, n):
        run(db, "MATCH (a:S {id: 0}), (b:S {id: $i}) "
                "CREATE (a)-[:E]->(b), (b)-[:E]->(a)", {"i": i})
    rows = run(db, "CALL betweenness_centrality.get(true, true, 20) "
                   "YIELD node, betweenness_centrality "
                   "RETURN node.id AS id, betweenness_centrality AS bc "
                   "ORDER BY bc DESC LIMIT 1")
    assert rows[0][0] == 0              # the hub dominates even sampled
