"""RECOVER SNAPSHOT FROM <uri>: explicit local, http, s3 sources + the
ISSU story (older on-disk format versions load in the current build).

References: /root/reference/src/storage/v2/inmemory/storage.hpp:158-168,
tests/issu/test_upgrade.sh.
"""

import http.server
import os
import shutil
import threading

import pytest

from memgraph_tpu.exceptions import DurabilityError
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage, StorageConfig


def _mk(tmp_path, sub):
    d = tmp_path / sub
    d.mkdir(exist_ok=True)
    return InterpreterContext(InMemoryStorage(StorageConfig(
        durability_dir=str(d), wal_enabled=True)))


def run(ictx, q, params=None):
    _, rows, _ = Interpreter(ictx).execute(q, params)
    return rows


def test_recover_from_local_path(tmp_path):
    src = _mk(tmp_path, "src")
    run(src, "CREATE (:T {v: 1}), (:T {v: 2})")
    run(src, "CREATE SNAPSHOT")
    snap = max((tmp_path / "src" / "snapshots").glob("*.mgsnap"))

    dst = _mk(tmp_path, "dst")
    run(dst, "CREATE (:Junk)")
    run(dst, f'RECOVER SNAPSHOT FROM "{snap}"')
    assert run(dst, "MATCH (t:T) RETURN sum(t.v)") == [[3]]
    assert run(dst, "MATCH (j:Junk) RETURN count(j)") == [[0]]


def test_recover_from_http(tmp_path):
    src = _mk(tmp_path, "src")
    run(src, "CREATE (:H {v: 41}), (:H {v: 1})")
    run(src, "CREATE SNAPSHOT")
    snap = max((tmp_path / "src" / "snapshots").glob("*.mgsnap"))
    serve_dir = tmp_path / "www"
    serve_dir.mkdir()
    shutil.copy(snap, serve_dir / "backup.mgsnap")

    import functools

    class _Quiet(http.server.SimpleHTTPRequestHandler):
        def log_message(self, *a, **k):
            pass

    handler = functools.partial(_Quiet, directory=str(serve_dir))
    httpd = http.server.HTTPServer(("127.0.0.1", 0), handler)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        dst = _mk(tmp_path, "dst")
        run(dst, f'RECOVER SNAPSHOT FROM '
                 f'"http://127.0.0.1:{port}/backup.mgsnap"')
        assert run(dst, "MATCH (h:H) RETURN sum(h.v)") == [[42]]
        # the downloaded snapshot joined the local retention set
        assert list((tmp_path / "dst" / "snapshots").glob("*.mgsnap"))
    finally:
        httpd.shutdown()


def test_recover_missing_source_fails_cleanly(tmp_path):
    dst = _mk(tmp_path, "dst")
    with pytest.raises(Exception, match="not found"):
        run(dst, 'RECOVER SNAPSHOT FROM "/nope/missing.mgsnap"')
    with pytest.raises(Exception, match="boto3"):
        run(dst, 'RECOVER SNAPSHOT FROM "s3://bucket/key.mgsnap"')


def test_issu_v1_format_upgrades_in_place(tmp_path):
    """ISSU: a data dir written by the PREVIOUS format version (v1
    unchunked snapshots) starts cleanly under the current build."""
    import struct
    from memgraph_tpu.storage.durability import snapshot as snap

    src = _mk(tmp_path, "old")
    run(src, "CREATE (:Old {v: 7})-[:E {w: 1}]->(:Old {v: 35})")
    run(src, "CREATE SNAPSHOT")
    new_path = max((tmp_path / "old" / "snapshots").glob("*.mgsnap"))
    data = snap.load_snapshot(str(new_path))

    # rewrite as a faithful v1 file (unchunked sections)
    from io import BytesIO
    buf = BytesIO()
    buf.write(snap.MAGIC)
    buf.write(struct.pack("<HQQ", 1, data["timestamp"], data["wall_time"]))
    buf.write(bytes((snap.SEC_MAPPERS,)))
    for names in (data["labels"], data["properties"], data["edge_types"]):
        snap._write_varint(buf, len(names))
        for name in names:
            raw = name.encode()
            snap._write_varint(buf, len(raw))
            buf.write(raw)
    buf.write(bytes((snap.SEC_VERTICES,)))
    snap._write_varint(buf, len(data["vertices"]))
    for gid, labels, props in data["vertices"]:
        snap._write_varint(buf, gid)
        snap._write_varint(buf, len(labels))
        for l in labels:
            snap._write_varint(buf, l)
        snap._write_varint(buf, len(props))
        for pid in sorted(props):
            snap._write_varint(buf, pid)
            snap.encode_value(buf, props[pid])
    buf.write(bytes((snap.SEC_EDGES,)))
    snap._write_varint(buf, len(data["edges"]))
    for gid, etype, f, t, props in data["edges"]:
        for x in (gid, etype, f, t):
            snap._write_varint(buf, x)
        snap._write_varint(buf, len(props))
        for pid in sorted(props):
            snap._write_varint(buf, pid)
            snap.encode_value(buf, props[pid])
    buf.write(bytes((snap.SEC_END,)))

    old_dir = tmp_path / "upgraded"
    (old_dir / "snapshots").mkdir(parents=True)
    (old_dir / "snapshots" / "snapshot_1_1.mgsnap").write_bytes(
        buf.getvalue())

    # "new version" boots on the old-format directory
    upgraded = InterpreterContext(InMemoryStorage(StorageConfig(
        durability_dir=str(old_dir), wal_enabled=True)))
    from memgraph_tpu.storage.durability.recovery import recover
    recover(upgraded.storage)
    assert run(upgraded, "MATCH (o:Old) RETURN sum(o.v)") == [[42]]
    # and writing a NEW snapshot from the upgraded instance emits v2
    run(upgraded, "CREATE SNAPSHOT")
    latest = max((old_dir / "snapshots").glob("*.mgsnap"),
                 key=os.path.getmtime)
    version = struct.unpack(
        "<H", latest.read_bytes()[len(snap.MAGIC):len(snap.MAGIC) + 2])[0]
    assert version == snap.VERSION    # rewritten at the current format


def test_corrupt_remote_download_does_not_poison_recovery(tmp_path):
    """A 200 response with garbage must neither load nor become the
    newest local snapshot."""
    import functools

    serve_dir = tmp_path / "www2"
    serve_dir.mkdir()
    (serve_dir / "garbage.mgsnap").write_bytes(b"<html>not a snapshot")

    class _Quiet(http.server.SimpleHTTPRequestHandler):
        def log_message(self, *a, **k):
            pass

    httpd = http.server.HTTPServer(
        ("127.0.0.1", 0), functools.partial(_Quiet,
                                            directory=str(serve_dir)))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        dst = _mk(tmp_path, "dst2")
        run(dst, "CREATE (:Live {v: 1})")
        run(dst, "CREATE SNAPSHOT")
        port = httpd.server_address[1]
        with pytest.raises(Exception, match="magic"):
            run(dst, f'RECOVER SNAPSHOT FROM '
                     f'"http://127.0.0.1:{port}/garbage.mgsnap"')
        # the corrupt file was discarded; plain recovery still works
        run(dst, "RECOVER SNAPSHOT")
        assert run(dst, "MATCH (l:Live) RETURN count(l)") == [[1]]
    finally:
        httpd.shutdown()


def test_recover_from_source_starts_new_wal_epoch(tmp_path):
    """Old local WAL must not replay on top of a foreign snapshot after
    a restart."""
    src = _mk(tmp_path, "srcw")
    run(src, "CREATE (:F {v: 10})")
    run(src, "CREATE SNAPSHOT")
    snap = max((tmp_path / "srcw" / "snapshots").glob("*.mgsnap"))

    dst_dir = tmp_path / "dstw"
    dst = _mk(tmp_path, "dstw")
    for i in range(5):
        run(dst, "CREATE (:LocalJunk {i: $i})", {"i": i})
    run(dst, f'RECOVER SNAPSHOT FROM "{snap}"')
    assert run(dst, "MATCH (f:F) RETURN sum(f.v)") == [[10]]

    # restart: recovery must yield the foreign state, not resurrect junk
    from memgraph_tpu.storage.durability.recovery import recover
    fresh = InterpreterContext(InMemoryStorage(StorageConfig(
        durability_dir=str(dst_dir), wal_enabled=True)))
    recover(fresh.storage)
    assert run(fresh, "MATCH (f:F) RETURN sum(f.v)") == [[10]]
    assert run(fresh, "MATCH (j:LocalJunk) RETURN count(j)") == [[0]]
