"""tools/mgmem: compiled-artifact HBM accounting.

Three layers under test:

* the footprint model (fit/predict, linearity residual) and the fact
  extractor against REAL lowerings of a few cheap manifest kernels;
* the admission cross-checks — the machine-check of the kernel
  server's estimators against the models, including the gate's own
  sensitivity: a deliberately-broken fixture (estimator halved,
  donation dropped) MUST be caught with the offending kernel + bytes;
* the runtime surfacing — the ``kernel_server.hbm_modeled_peak_bytes``
  gauge and the health reply's ``memory`` section.

The full 42-kernel sweep is the dev gate's job (`python -m tools.mgmem
check`, wired into tools/gate.sh); here only a handful of kernels are
lowered so the suite stays tier-1 fast.
"""

import numpy as np
import pytest

from memgraph_tpu.ops.csr import _bucket
from memgraph_tpu.ops import tier as T
from memgraph_tpu.server import kernel_server as ks
from tools.mgmem.admission import (
    CHECK_SHAPES, Estimators, check_ppr, check_resident,
    check_streamed, product_estimators)
from tools.mgmem.check import (
    CheckReport, Violation, canonical_record, memory_envelope_from,
    run_check)
from tools.mgmem.facts import MemFacts
from tools.mgmem.model import FIT_TOLERANCE, FootprintModel, fit


# --- the footprint model (no lowering) --------------------------------------


def _facts(kernel, pts, replicas=1, **over):
    base = dict(kernel=kernel, lanes=1, replicas=replicas,
                output_bytes=0, temp_bytes=0, alias_bytes=0,
                generated_code_bytes=0, donated_aliased=1,
                donation_dropped=0, dropped_bytes=0)
    base.update(over)
    return [MemFacts(n_pad=n, n_edges=e, argument_bytes=a, **base)
            for n, e, a in pts]


def test_fit_is_exact_on_linear_points():
    # peak = 100 + 16n + 32e, synthesized at the real shape points
    fl = _facts("segment:x", [(64, 256, 100 + 16 * 64 + 32 * 256),
                              (128, 256, 100 + 16 * 128 + 32 * 256),
                              (128, 512, 100 + 16 * 128 + 32 * 512)])
    m = fit("segment:x", fl)
    assert m.residual <= 1e-3
    assert (round(m.const), round(m.per_node), round(m.per_edge)) \
        == (100, 16, 32)
    want = 100 + 16 * (1 << 20) + 32 * (1 << 22)
    assert abs(m.predict(1 << 20, 1 << 22) - want) / want < 1e-6


def test_fit_flags_nonlinear_growth():
    # quadratic in n: the residual must blow past FIT_TOLERANCE
    fl = _facts("segment:bad", [(64, 256, 64 * 64),
                                (128, 256, 128 * 128),
                                (256, 256, 256 * 256)])
    m = fit("segment:bad", fl)
    assert m.residual > FIT_TOLERANCE


def test_single_point_model_is_constant():
    fl = _facts("mxu:pagerank", [(64, 256, 13_723_560)])
    m = fit("mxu:pagerank", fl)
    assert m.predict(10, 10) == m.predict(1 << 20, 1 << 25) == 13_723_560


def test_mesh_peak_budgets_whole_request():
    f = _facts("mesh:x", [(64, 256, 1000)], replicas=8)[0]
    assert f.peak_bytes == 8000


# --- estimator padding mirrors (no lowering) --------------------------------


def test_padded_graph_dims_mirror_csr_bucket_exactly():
    for n, e in ((0, 0), (7, 9), (63, 64), (64, 64), (65, 257),
                 (10_000, 80_000), ((1 << 20) + 1, (1 << 22) + 1)):
        assert ks._padded_graph_dims(n, e) \
            == (_bucket(n + 1), _bucket(max(e, 1)))


def test_lane_state_prices_the_power_of_two_bucket():
    n, e = 100_000, 1_500_000
    one = ks._lane_state_bytes(n, e, 1)
    # 33 requested lanes build the 64-wide kernel: same price as 64
    assert ks._lane_state_bytes(n, e, 33) \
        == ks._lane_state_bytes(n, e, 64) == 64 * one
    assert ks._lane_state_bytes(n, e, 65) == 128 * one
    # boundary stays on its own bucket
    assert ks._lane_state_bytes(n, e, 32) == 32 * one


def test_ppr_chunk_lanes_fits_the_budget():
    n, e = 100_000, 1_500_000
    graph = ks._graph_footprint_bytes("ppr", n, e)
    for b in (1, 8, 64):
        budget = graph + ks._lane_state_bytes(n, e, b)
        assert ks._ppr_chunk_lanes(n, e, budget) == b
        # one byte short of the bucket drops to the previous one
        if b > 1:
            assert ks._ppr_chunk_lanes(n, e, budget - 1) < b


def test_estimate_request_bytes_cached_generation_path():
    # a graph_key-only request ships no arrays (the r16 cached-
    # generation sizing path): the estimate is the padded-graph
    # fixpoint footprint alone, not zero
    n, e = 50_000, 400_000
    est = ks._estimate_request_bytes(
        {"algorithm": "pagerank", "n_nodes": n, "n_edges": e}, {})
    assert est == ks._graph_footprint_bytes("pagerank", n, e)
    # with wire arrays the staging copy is priced on top
    src = np.zeros(e, np.int64)
    est_wire = ks._estimate_request_bytes(
        {"algorithm": "pagerank", "n_nodes": n}, {"src": src})
    assert est_wire == src.nbytes + ks._graph_footprint_bytes(
        "pagerank", n, e)


def test_unknown_algorithm_prices_at_column_max():
    n, e = 10_000, 80_000
    worst = max(ks._graph_footprint_bytes(a, n, e)
                for a in ks._ALGO_FOOTPRINT)
    assert ks._graph_footprint_bytes("not-an-algo", n, e) >= worst


# --- real lowerings: facts -> model -> admission matrix ---------------------


@pytest.fixture(scope="module")
def pagerank_model():
    from tools.mgmem.model import fit_kernel
    return fit_kernel("segment:pagerank")


@pytest.fixture(scope="module")
def mesh_pagerank_model():
    from tools.mgmem.model import fit_kernel
    return fit_kernel("mesh:pagerank")


@pytest.fixture(scope="module")
def tier_models():
    from tools.mgmem.model import fit_kernel
    return {k: fit_kernel(k) for k in
            ("tier:wsum", "tier:pagerank_sweep",
             "tier:pagerank_sweep_int8", "tier:pagerank_epilogue")}


def _estimators(**over):
    base = product_estimators()
    return Estimators(**{**{
        "graph_footprint_bytes": base.graph_footprint_bytes,
        "lane_state_bytes": base.lane_state_bytes,
        "streamed_request_bytes": base.streamed_request_bytes,
        "padded_graph_dims": base.padded_graph_dims,
        "lane_buckets": base.lane_buckets}, **over})


def test_model_fits_real_lowering_exactly(pagerank_model):
    m = pagerank_model
    assert m.residual <= FIT_TOLERANCE
    # XLA's buffer assignment for the fixpoint is O(n) + O(e)
    assert m.per_node > 0 and m.per_edge > 0


def test_admission_matrix_product_estimator_bounds(pagerank_model,
                                                   mesh_pagerank_model):
    # both backends the resident route can pick: the estimate must
    # bound the worst of them without exceeding 2x of it
    models = {"segment:pagerank": pagerank_model,
              "mesh:pagerank": mesh_pagerank_model}
    out = check_resident(models, product_estimators(), Violation)
    bad = [v for v in out if v.check.startswith("admission-")]
    assert not bad, "\n".join(v.render() for v in bad)


def test_broken_fixture_halved_estimator_is_caught(pagerank_model):
    models = {"segment:pagerank": pagerank_model}
    halved = _estimators(
        graph_footprint_bytes=lambda a, n, e:
            ks._graph_footprint_bytes(a, n, e) // 2)
    out = check_resident(models, halved, Violation)
    under = [v for v in out if v.check == "admission-underestimate"
             and v.kernel == "segment:pagerank"]
    assert under, "halved estimator escaped the gate"
    # the report names the kernel and quantifies the shortfall
    assert "short" in under[0].snippet and "MB" in under[0].snippet


def test_admission_flip_point_from_fitted_coefficients(pagerank_model):
    # scale the estimator down until it JUST crosses the model at an
    # edge-heavy shape: the gate must flip exactly there
    m = pagerank_model
    n, e = 500_000, 30_000_000
    n_pad, e_pad = ks._padded_graph_dims(n, e)
    floor = ks._graph_footprint_bytes("pagerank", n, e)
    peak = m.predict(n_pad, e_pad)
    assert floor >= peak
    scale_ok = 1.0
    scale_bad = peak / floor * 0.99       # just below the modeled peak
    for scale, expect in ((scale_ok, 0), (scale_bad, 1)):
        est = _estimators(
            graph_footprint_bytes=lambda a, nn, ee, s=scale:
                int(ks._graph_footprint_bytes(a, nn, ee) * s))
        out = check_resident({"segment:pagerank": m}, est, Violation)
        under = [v for v in out
                 if v.check == "admission-underestimate"
                 and v.detail == f"pagerank@({n},{e})"]
        assert len(under) == expect, (scale, [v.render() for v in out])


def test_streamed_estimator_bounds_phases(tier_models):
    out = check_streamed(tier_models, product_estimators(), Violation)
    assert not out, "\n".join(v.render() for v in out)


def test_broken_fixture_halved_streamed_estimator(tier_models):
    halved = _estimators(
        streamed_request_bytes=lambda n, e, p, **kw:
            T.streamed_request_bytes(n, e, p, **kw) // 2)
    out = check_streamed(tier_models, halved, Violation)
    under = [v for v in out if v.check == "admission-underestimate"]
    assert under and under[0].kernel.startswith("tier:")
    assert "short" in under[0].snippet


def test_ppr_pricing_bounds_one_real_bucket():
    from tools.mgmem.model import fit_kernel
    m = fit_kernel("segment:ppr_batch:b4")
    models = {"segment:ppr_batch:b4": m}
    out = check_ppr(models, product_estimators(), Violation)
    assert not out, "\n".join(v.render() for v in out)
    halved = _estimators(
        graph_footprint_bytes=lambda a, n, e:
            ks._graph_footprint_bytes(a, n, e) // 2,
        lane_state_bytes=lambda n, e, b:
            ks._lane_state_bytes(n, e, b) // 2)
    out = check_ppr(models, halved, Violation)
    under = [v for v in out if v.check == "admission-underestimate"]
    assert under and under[0].kernel == "segment:ppr_batch:b4"


def test_admission_verdict_matrix_from_streamed_model():
    # budgets straddling the two estimates flip the verdict exactly:
    # resident -> streamed -> shed
    n, e = 2_000_000, 16_000_000
    res = ks._graph_footprint_bytes("pagerank", n, e)
    stream = T.streamed_request_bytes(n, e, "f32",
                                      algorithm="pagerank")
    assert stream < res
    for budget, want in ((res, "resident"), (res - 1, "streamed"),
                         (stream, "streamed"), (stream - 1, "shed")):
        verdict, est = T.admission_verdict(
            res, budget, n_nodes=n, n_edges=e, algorithm="pagerank")
        assert verdict == want, (budget, verdict)
    # a non-streamable op can only shed past the resident budget
    verdict, _ = T.admission_verdict(res, res - 1, n_nodes=n,
                                     n_edges=e, streamable=False)
    assert verdict == "shed"


# --- the check driver + record + perf gate ----------------------------------


def test_run_check_partial_reports_build_violation():
    report = run_check(only={"no:such:kernel"})
    assert not report.ok
    assert report.violations[0].kernel == "no:such:kernel"
    assert report.violations[0].check == "build"


def test_donation_violations_surface_with_bytes():
    report = CheckReport()
    report.facts["tier:pagerank_epilogue"] = _facts(
        "tier:pagerank_epilogue", [(64, 256, 1024)],
        donation_dropped=1, dropped_bytes=256)
    rec = canonical_record(report)
    entry = rec["kernels"]["tier:pagerank_epilogue"]
    assert entry["donation_dropped"] == 1
    assert entry["dropped_bytes"] == 256


def test_perf_gate_check_memory_pass_and_fail(capsys):
    from tools.perf_gate import check_memory
    env = {"memory": {"max_growth": 0.10,
                      "kernels": {"segment:pagerank": 9_676,
                                  "tier:pagerank_epilogue": 1_024}}}
    clean = {"kernels": {
        "segment:pagerank": {"peak_bytes": 9_676,
                             "donation_dropped": 0},
        "tier:pagerank_epilogue": {"peak_bytes": 1_024,
                                   "donation_dropped": 0}}}
    assert check_memory(clean, env) == 0
    broken = {"kernels": {
        "segment:pagerank": {"peak_bytes": 19_352,
                             "donation_dropped": 0},
        "tier:pagerank_epilogue": {"peak_bytes": 1_024,
                                   "donation_dropped": 1,
                                   "dropped_bytes": 256}}}
    assert check_memory(broken, env) == 1
    cap = capsys.readouterr()
    out = cap.out + cap.err
    assert "segment:pagerank" in out and "+100.0%" in out
    assert "256" in out and "dropped donation" in out
    # an envelope without a record is a FAIL, not a silent pass
    assert check_memory(None, env) == 1
    # no envelope -> the gate has nothing to enforce yet
    assert check_memory(None, {}) == 0


def test_envelope_roundtrip_shapes():
    report = CheckReport()
    report.facts["segment:pagerank"] = _facts(
        "segment:pagerank", [(64, 256, 9_676)])
    env = memory_envelope_from(report)
    assert env["kernels"] == {"segment:pagerank": 9_676}
    assert 0 < env["max_growth"] < 1


# --- runtime surfacing: the modeled-peak gauge + health memory section ------


def test_kernel_server_surfaces_modeled_memory(tmp_path):
    from memgraph_tpu.observability.metrics import global_metrics
    srv = ks.KernelServer(socket_path=str(tmp_path / "mem.sock"),
                          hbm_budget_bytes=1 << 30)
    src = np.array([0, 1, 2, 3], np.int64)
    dst = np.array([1, 2, 3, 0], np.int64)
    gen = srv._resolve_generation(
        {"graph_key": "g-mem", "graph_version": 1, "n_nodes": 4},
        {"src": src, "dst": dst})
    assert gen is not None
    want = ks._generation_modeled_bytes(gen)
    snap = {name: v for name, _k, v in global_metrics.snapshot()}
    assert snap["kernel_server.hbm_modeled_peak_bytes"] == float(want)
    h = srv._health_reply()
    mem = h["memory"]
    assert mem["hbm_budget_bytes"] == 1 << 30
    assert mem["modeled_peak_bytes"] == want
    assert mem["headroom_bytes"] == (1 << 30) - want
    assert mem["resident_generations"] == {"g-mem": want}
    # the modeled peak is priced at the column-wise worst case
    assert want >= ks._graph_footprint_bytes("pagerank", 4, 4)


def test_stat_names_cover_memory_gauges():
    from memgraph_tpu.observability.metrics import STAT_NAMES
    assert "kernel_server.hbm_modeled_peak_bytes" in STAT_NAMES
    assert "tier.modeled_request_bytes" in STAT_NAMES
