"""TLS on every listener: Bolt (bolt+s), replication, Raft mgmt.

Reference analog: communication/context.cpp (Bolt SSL) and the
intra-cluster TLS of memgraph.cpp:302-317.
"""

import socket
import subprocess
import sys
import time

import pytest

# self-signed cert generation needs the optional cryptography package —
# skip (not error) on images that don't ship it
pytest.importorskip("cryptography")

from memgraph_tpu.utils import tls as T


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = T.generate_self_signed(str(d))
    return cert, key


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_bolt_server_tls(certs, tmp_path):
    """Real server process with --bolt-cert-file; client speaks bolt+s."""
    cert, key = certs
    port = _free_port()
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.Popen(
        [sys.executable, "-m", "memgraph_tpu.main",
         "--bolt-port", str(port), "--log-level", "WARNING",
         "--bolt-cert-file", cert, "--bolt-key-file", key],
        cwd="/root/repo", env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port), 0.3)
                s.close()
                break
            except OSError:
                time.sleep(0.2)
        from memgraph_tpu.server.client import BoltClient
        # plaintext must NOT work against a TLS listener
        with pytest.raises(Exception):
            c = BoltClient(port=port, timeout=3)
            c.execute("RETURN 1")
        # encrypted works
        c = BoltClient(port=port, encrypted=True, ca_file=cert)
        _, rows, _ = c.execute("RETURN 40 + 2")
        assert rows == [[42]]
        c.close()
    finally:
        p.terminate()
        p.wait(timeout=10)


def test_replication_over_cluster_tls(certs):
    """MAIN<->replica channel encrypted via set_cluster_tls."""
    cert, key = certs
    T.set_cluster_tls(cert, key, cert)
    try:
        from memgraph_tpu.query.interpreter import (Interpreter,
                                                    InterpreterContext)
        from memgraph_tpu.storage import InMemoryStorage
        main = Interpreter(InterpreterContext(InMemoryStorage()))
        rep_ictx = InterpreterContext(InMemoryStorage())
        rep = Interpreter(rep_ictx)
        port = _free_port()
        rep.execute(f"SET REPLICATION ROLE TO REPLICA WITH PORT {port}")
        main.execute(f"REGISTER REPLICA tls1 SYNC TO '127.0.0.1:{port}'")
        main.execute("CREATE (:Enc {v: 7})")
        _, rows, _ = rep.execute("MATCH (n:Enc) RETURN n.v")
        assert rows == [[7]]
        # a PLAINTEXT peer cannot talk to the TLS replica listener
        raw = socket.create_connection(("127.0.0.1", port), timeout=2)
        raw.settimeout(2)
        from memgraph_tpu.replication import protocol as P
        try:
            P.send_json(raw, P.MSG_HEARTBEAT, {})
            with pytest.raises((ConnectionError, OSError)):
                P.recv_frame(raw)
        finally:
            raw.close()
        rep_ictx.replication.replica_server.stop()
    finally:
        T.clear_cluster_tls()


def test_raft_mgmt_over_cluster_tls(certs):
    """Coordinator Raft RPCs work with cluster TLS installed."""
    cert, key = certs
    T.set_cluster_tls(cert, key, cert)
    try:
        from memgraph_tpu.coordination.raft import RaftNode
        ports = [_free_port() for _ in range(3)]
        peers = {f"c{i}": ("127.0.0.1", ports[i]) for i in range(3)}
        nodes = []
        for i in range(3):
            n = RaftNode(f"c{i}", "127.0.0.1", ports[i],
                         {k: v for k, v in peers.items() if k != f"c{i}"})
            n.start()
            nodes.append(n)
        try:
            deadline = time.time() + 15
            leader = None
            while time.time() < deadline and leader is None:
                leaders = [n for n in nodes if n.is_leader()]
                if leaders:
                    leader = leaders[0]
                time.sleep(0.2)
            assert leader is not None, "no leader elected over TLS"
        finally:
            for n in nodes:
                n.stop()
    finally:
        T.clear_cluster_tls()
