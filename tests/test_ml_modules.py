"""JAX GraphSAGE link-prediction / node-classification modules
(reference: mage/python/link_prediction.py, node_classification.py)."""

import itertools

import pytest

from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture(scope="module")
def interp():
    """Two 6-node communities, dense intra-edges, no inter-edges."""
    i = Interpreter(InterpreterContext(InMemoryStorage()))
    i.execute("UNWIND range(0, 11) AS x CREATE (:U {id: x, label: x / 6})")
    for block in (range(0, 6), range(6, 12)):
        for a, b in itertools.combinations(block, 2):
            if (a + b) % 3 != 0:
                i.execute(f"MATCH (a:U {{id: {a}}}), (b:U {{id: {b}}}) "
                          f"CREATE (a)-[:F]->(b)")
    return i


def rows(result):
    return result[1]


def test_link_prediction_learns_communities(interp):
    interp.execute("CALL link_prediction.set_model_parameters("
                   "{num_epochs: 30}) YIELD status RETURN status")
    out = rows(interp.execute(
        "CALL link_prediction.train() "
        "YIELD training_results, validation_results RETURN *"))
    final = out[0][1][-1] if isinstance(out[0][1], list) else out[0][0][-1]
    assert final["auc"] > 0.6
    intra = rows(interp.execute(
        "MATCH (a:U {id: 0}), (b:U {id: 3}) "
        "CALL link_prediction.predict(a, b) YIELD score RETURN score"
    ))[0][0]
    inter = rows(interp.execute(
        "MATCH (a:U {id: 0}), (b:U {id: 9}) "
        "CALL link_prediction.predict(a, b) YIELD score RETURN score"
    ))[0][0]
    assert 0.0 <= inter <= 1.0 and 0.0 <= intra <= 1.0
    assert intra > inter


def test_link_prediction_recommend(interp):
    out = rows(interp.execute(
        "MATCH (a:U {id: 0}) MATCH (c:U) WHERE c.id IN [3, 9] "
        "WITH a, collect(c) AS cs "
        "CALL link_prediction.recommend(a, cs, 1) "
        "YIELD recommendation RETURN recommendation.id"))
    assert out == [[3]]  # intra-community candidate wins


def test_link_prediction_results_and_reset(interp):
    out = rows(interp.execute(
        "CALL link_prediction.get_training_results() "
        "YIELD training_results RETURN size(training_results)"))
    assert out[0][0] >= 30
    interp.execute("CALL link_prediction.reset_parameters() "
                   "YIELD status RETURN status")
    with pytest.raises(QueryException):
        interp.execute("CALL link_prediction.get_training_results() "
                       "YIELD training_results RETURN 1")
    with pytest.raises(QueryException):
        interp.execute("CALL link_prediction.set_model_parameters("
                       "{bogus_knob: 1}) YIELD status RETURN status")


def test_node_classification_end_to_end(interp):
    interp.execute(
        "CALL node_classification.set_model_parameters("
        "{target_property: 'label', num_epochs: 50}) "
        "YIELD status RETURN status")
    out = rows(interp.execute(
        "CALL node_classification.train() YIELD epoch, loss "
        "RETURN count(epoch), min(loss)"))
    assert out[0][0] == 50
    assert out[0][1] < 0.5  # converged well below chance
    for node_id, expected in ((1, 0), (10, 1)):
        out = rows(interp.execute(
            f"MATCH (v:U {{id: {node_id}}}) "
            f"CALL node_classification.predict(v) "
            f"YIELD predicted_class RETURN predicted_class"))
        assert out == [[expected]]
    out = rows(interp.execute(
        "CALL node_classification.get_training_data() "
        "YIELD epoch RETURN count(epoch)"))
    assert out == [[50]]


def test_node_classification_missing_target():
    i = Interpreter(InterpreterContext(InMemoryStorage()))
    i.execute("CREATE (:V)")
    with pytest.raises(QueryException):
        i.execute("CALL node_classification.train() YIELD epoch RETURN 1")


def test_kernel_shapes_direct():
    """ops/gnn.py API sanity without the module layer."""
    import numpy as np
    from memgraph_tpu.ops.csr import from_coo
    from memgraph_tpu.ops.gnn import (degree_features, sage_forward,
                                      train_link_prediction)
    graph = from_coo(np.array([0, 1, 2]), np.array([1, 2, 3]))
    feats = degree_features(graph, dim=8)
    assert feats.shape == (graph.n_pad, 8)
    params, feats, history = train_link_prediction(graph, epochs=2,
                                                   hidden_dim=8,
                                                   out_dim=4)
    emb = sage_forward(params, feats, graph.csc_src, graph.csc_dst,
                       graph.n_pad)
    assert emb.shape == (graph.n_pad, 4)
    assert len(history) == 2
