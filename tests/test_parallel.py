"""Sharded-kernel tests on the virtual 8-device CPU mesh.

Validates that the multi-chip path (shard_map + psum over an edge-partition
mesh) produces the same results as the single-device kernels — the same
check the driver's dryrun performs.
"""

import numpy as np
import pytest

import jax

from memgraph_tpu.ops import csr
from memgraph_tpu.ops.pagerank import pagerank
from memgraph_tpu.ops.traversal import sssp
from memgraph_tpu.ops.components import weakly_connected_components
from memgraph_tpu.parallel import (make_mesh, shard_graph, pagerank_sharded,
                                   sssp_sharded, wcc_sharded)


@pytest.fixture(scope="module")
def graph():
    rng = np.random.default_rng(42)
    n, e = 200, 1500
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    w = rng.uniform(0.5, 2.0, e).astype(np.float32)
    return csr.from_coo(src, dst, w, n_nodes=n)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_sharded_placement(graph, mesh):
    sg = shard_graph(graph, mesh)
    assert sg.e_pad % 8 == 0
    # each device holds 1/8 of the edges
    shards = sg.src.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape[0] == sg.e_pad // 8 for s in shards)


def test_pagerank_sharded_matches_single(graph, mesh):
    single, _, _ = pagerank(graph, tol=1e-10, max_iterations=200)
    sg = shard_graph(graph, mesh)
    sharded, _, _ = pagerank_sharded(sg, tol=1e-10, max_iterations=200)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(single),
                               atol=1e-5)


def test_sssp_sharded_matches_single(graph, mesh):
    single, _ = sssp(graph, source=0, weighted=True, directed=True)
    sg = shard_graph(graph, mesh)
    sharded, _ = sssp_sharded(sg, source=0)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(single),
                               atol=1e-4)


def test_pagerank_15d_matches_single(graph, mesh):
    """Memory-scalable variant: sharded rank vector + reduce_scatter."""
    from memgraph_tpu.parallel.distributed import (pagerank_sharded_15d,
                                                   shard_graph_by_src)
    single, _, _ = pagerank(graph, tol=1e-10, max_iterations=200)
    sg = shard_graph_by_src(graph, mesh)
    sharded, _, _ = pagerank_sharded_15d(sg, tol=1e-10, max_iterations=200)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(single),
                               atol=1e-5)


def test_pagerank_15d_rank_is_sharded(graph, mesh):
    from memgraph_tpu.parallel.distributed import shard_graph_by_src
    sg = shard_graph_by_src(graph, mesh)
    # each device owns exactly one src block of edges
    import numpy as np
    block = sg.n_pad // 8
    for i, shard in enumerate(sg.src.addressable_shards):
        vals = np.asarray(shard.data)
        real = vals[vals < sg.n_nodes]
        if len(real):
            assert real.min() >= i * block
            assert real.max() < (i + 1) * block


def test_wcc_sharded_matches_single(graph, mesh):
    single, _ = weakly_connected_components(graph)
    sg = shard_graph(graph, mesh)
    sharded, _ = wcc_sharded(sg)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))
