"""Tenant profiles + hot/cold database suspend.

References: /root/reference/src/dbms/tenant_profiles.cpp,
specs/hot-cold-databases.md, MemgraphCypher.g4:995-1001.
"""

import pytest

from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.utils.memory_tracker import MemoryLimitException
from memgraph_tpu.query.interpreter import Interpreter
from memgraph_tpu.storage import StorageConfig


@pytest.fixture
def dbms(tmp_path):
    from memgraph_tpu.dbms.dbms import DbmsHandler
    return DbmsHandler(StorageConfig(durability_dir=str(tmp_path),
                                     wal_enabled=True))


def run(ictx, q, params=None):
    _, rows, _ = Interpreter(ictx).execute(q, params)
    return rows


def test_tenant_profile_ddl_and_show(dbms):
    root = dbms.default()
    run(root, "CREATE TENANT PROFILE small LIMIT memory_limit 10MB")
    run(root, "CREATE DATABASE t1")
    run(root, "SET TENANT PROFILE ON DATABASE t1 TO small")
    rows = run(root, "SHOW TENANT PROFILES")
    assert rows[0][0] == "small" and "10485760" in rows[0][1]
    assert rows[0][2] == ["t1"]
    run(root, "ALTER TENANT PROFILE small SET memory_limit 5MB")
    rows = run(root, "SHOW TENANT PROFILE small")
    assert "5242880" in rows[0][1]
    run(root, "CLEAR TENANT PROFILE ON DATABASE t1")
    assert run(root, "SHOW TENANT PROFILES")[0][2] == []
    run(root, "DROP TENANT PROFILE small")
    with pytest.raises(QueryException):
        run(root, "SHOW TENANT PROFILE small")


def test_profile_memory_limit_enforced(dbms):
    root = dbms.default()
    run(root, "CREATE DATABASE small_db")
    run(root, "CREATE TENANT PROFILE tiny LIMIT memory_limit 300KB")
    run(root, "SET TENANT PROFILE ON DATABASE small_db TO tiny")
    ictx = dbms.get("small_db")
    # a memory-hungry query trips the profile's default cap
    with pytest.raises(MemoryLimitException):
        run(ictx, "UNWIND range(1, 200000) AS i "
                  "WITH collect(i) AS xs RETURN size(xs)")
    # the same query on an unprofiled database is fine
    assert run(root, "UNWIND range(1, 200000) AS i "
                     "WITH collect(i) AS xs RETURN size(xs)") == [[200000]]
    # explicit QUERY MEMORY LIMIT still wins over the profile
    assert run(ictx, "RETURN 1 QUERY MEMORY LIMIT 100 MB") == [[1]]


def test_profiles_survive_restart(tmp_path):
    from memgraph_tpu.dbms.dbms import DbmsHandler
    cfg = StorageConfig(durability_dir=str(tmp_path), wal_enabled=True)
    d1 = DbmsHandler(cfg)
    run(d1.default(), "CREATE TENANT PROFILE p LIMIT memory_limit 1MB")
    d2 = DbmsHandler(StorageConfig(durability_dir=str(tmp_path),
                                   wal_enabled=True))
    rows = run(d2.default(), "SHOW TENANT PROFILES")
    assert rows and rows[0][0] == "p"


def test_suspend_resume_cycle(dbms):
    root = dbms.default()
    run(root, "CREATE DATABASE tenant_a")
    ictx = dbms.get("tenant_a")
    run(ictx, "CREATE (:Keep {v: 41}), (:Keep {v: 1})")
    run(root, "SUSPEND DATABASE tenant_a")
    # cold: not queryable, still listed
    with pytest.raises(QueryException, match="suspended"):
        dbms.get("tenant_a")
    assert "tenant_a" in dbms.names()
    assert ("tenant_a", "suspended") in dbms.database_states()
    # suspend is idempotent; default cannot be suspended
    run(root, "SUSPEND DATABASE tenant_a")
    with pytest.raises(QueryException):
        run(root, "SUSPEND DATABASE memgraph")
    # resume restores the exact data
    run(root, "RESUME DATABASE tenant_a")
    ictx = dbms.get("tenant_a")
    assert run(ictx, "MATCH (k:Keep) RETURN sum(k.v)") == [[42]]


def test_suspended_state_survives_restart(tmp_path):
    from memgraph_tpu.dbms.dbms import DbmsHandler
    cfg = StorageConfig(durability_dir=str(tmp_path), wal_enabled=True)
    d1 = DbmsHandler(cfg)
    run(d1.default(), "CREATE DATABASE cold_t")
    run(d1.get("cold_t"), "CREATE (:X {v: 7})")
    run(d1.default(), "SUSPEND DATABASE cold_t")

    d2 = DbmsHandler(StorageConfig(durability_dir=str(tmp_path),
                                   wal_enabled=True))
    assert ("cold_t", "suspended") in d2.database_states()
    with pytest.raises(QueryException, match="suspended"):
        d2.get("cold_t")
    run(d2.default(), "RESUME DATABASE cold_t")
    assert run(d2.get("cold_t"), "MATCH (x:X) RETURN x.v") == [[7]]


def test_password_policy_flags():
    """--auth-password-strength-regex / --no-auth-password-permit-null."""
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage
    from memgraph_tpu.auth.auth import Auth
    ictx = InterpreterContext(InMemoryStorage(), {
        "auth_password_strength_regex": r"[A-Za-z0-9]{8,}",
        "auth_password_permit_null": False})
    ictx.auth_store = Auth()     # isolated: never touch the global store
    interp = Interpreter(ictx)
    with pytest.raises(QueryException, match="strength"):
        interp.execute("CREATE USER weak IDENTIFIED BY 'short'")
    with pytest.raises(QueryException, match="null"):
        interp.execute("CREATE USER nopw")
    interp.execute("CREATE USER strong IDENTIFIED BY 'longenough1'")
    interp.username = "strong"
    with pytest.raises(QueryException, match="strength"):
        interp.execute("SET PASSWORD TO 'nope'")
    interp.execute("SET PASSWORD TO 'alsolongenough2'")


def test_allow_load_csv_flag(tmp_path):
    from memgraph_tpu.query.interpreter import (Interpreter,
                                                InterpreterContext)
    from memgraph_tpu.storage import InMemoryStorage
    csv = tmp_path / "rows.csv"
    csv.write_text("a,b\n1,2\n")
    blocked = Interpreter(InterpreterContext(
        InMemoryStorage(), {"allow_load_csv": False}))
    with pytest.raises(QueryException, match="disabled"):
        blocked.execute(
            f'LOAD CSV FROM "{csv}" WITH HEADER AS row RETURN row.a')
    allowed = Interpreter(InterpreterContext(InMemoryStorage()))
    _, rows, _ = allowed.execute(
        f'LOAD CSV FROM "{csv}" WITH HEADER AS row RETURN row.a')
    assert rows == [["1"]]
