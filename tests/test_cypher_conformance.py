"""openCypher conformance slice — TCK-flavored semantic edge cases.

Counterpart of the reference's gql_behave suites
(/root/reference/tests/gql_behave/tests/openCypher_M09, memgraph_V1):
behavioral corners of the language that implementations commonly get wrong.
"""

import math

import pytest

from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def db():
    return InterpreterContext(InMemoryStorage())


def run(db, q, params=None):
    _, rows, _ = Interpreter(db).execute(q, params)
    return rows


# --- null semantics ----------------------------------------------------------

def test_aggregates_skip_nulls(db):
    rows = run(db, "UNWIND [1, null, 3] AS x "
                   "RETURN count(x), sum(x), avg(x), collect(x)")
    assert rows == [[2, 4, 2.0, [1, 3]]]


def test_count_star_counts_null_rows(db):
    rows = run(db, "UNWIND [1, null] AS x RETURN count(*)")
    assert rows == [[2]]


def test_avg_of_empty_is_null(db):
    rows = run(db, "UNWIND [1] AS x WITH x WHERE x > 5 "
                   "RETURN avg(x), sum(x), count(x)")
    assert rows == [[None, 0, 0]]


def test_property_of_missing_key_is_null(db):
    run(db, "CREATE (:N {a: 1})")
    rows = run(db, "MATCH (n:N) RETURN n.nonexistent")
    assert rows == [[None]]


def test_where_null_filters_out(db):
    run(db, "CREATE (:W {a: 1}), (:W)")
    rows = run(db, "MATCH (n:W) WHERE n.a > 0 RETURN count(n)")
    assert rows == [[1]]  # null comparison is null → filtered


def test_order_by_nulls_last_ascending(db):
    rows = run(db, "UNWIND [3, null, 1] AS x RETURN x ORDER BY x")
    assert [r[0] for r in rows] == [1, 3, None]


def test_distinct_treats_nulls_equal(db):
    rows = run(db, "UNWIND [null, null, 1] AS x RETURN DISTINCT x")
    values = [r[0] for r in rows]
    assert sorted(values, key=lambda v: (v is None, v or 0)) == [1, None]


# --- arithmetic + types ------------------------------------------------------

def test_integer_division_truncates_toward_zero(db):
    rows = run(db, "RETURN 7 / 2, -7 / 2, 7 % 2, -7 % 2")
    assert rows == [[3, -3, 1, -1]]


def test_division_by_zero_integer_raises(db):
    from memgraph_tpu.exceptions import ArithmeticException
    with pytest.raises(ArithmeticException):
        run(db, "RETURN 1 / 0")


def test_float_division_by_zero_is_inf(db):
    rows = run(db, "RETURN 1.0 / 0.0")
    assert rows[0][0] == math.inf


def test_string_concat_and_list_concat(db):
    rows = run(db, "RETURN 'a' + 'b', [1] + [2], [1] + 2, 1 + [2]")
    assert rows == [["ab", [1, 2], [1, 2], [1, 2]]]


def test_mixed_numeric_comparison(db):
    rows = run(db, "RETURN 1 = 1.0, 1 < 1.5, '1' = 1")
    assert rows == [[True, True, False]]


def test_list_index_out_of_bounds_is_null(db):
    rows = run(db, "RETURN [1, 2][5], [1, 2][-1], [1, 2][-5]")
    assert rows == [[None, 2, None]]


def test_list_slice(db):
    rows = run(db, "WITH [1,2,3,4,5] AS l RETURN l[1..3], l[..2], l[3..]")
    assert rows == [[[2, 3], [1, 2], [4, 5]]]


# --- MERGE semantics ---------------------------------------------------------

def test_merge_binds_per_input_row(db):
    run(db, "UNWIND [1, 1, 2] AS x MERGE (:M {k: x})")
    rows = run(db, "MATCH (n:M) RETURN count(n)")
    assert rows == [[2]]


def test_merge_full_pattern_semantics(db):
    """MERGE matches the WHOLE pattern or creates the WHOLE pattern."""
    run(db, "CREATE (:MA {k: 1}), (:MB {k: 2})")
    # pattern (a)-[r]->(b) doesn't exist → ALL of it is created fresh
    run(db, "MERGE (a:MA {k: 1})-[:R]->(b:MB {k: 2})")
    rows = run(db, "MATCH (n) RETURN count(n)")
    assert rows == [[4]]  # the two originals + a fresh pair
    run(db, "MERGE (a:MA {k: 1})-[:R]->(b:MB {k: 2})")  # now it matches
    rows = run(db, "MATCH ()-[r:R]->() RETURN count(r)")
    assert rows == [[1]]


# --- OPTIONAL MATCH ----------------------------------------------------------

def test_optional_match_aggregation(db):
    run(db, "CREATE (:OA {k: 1})")
    rows = run(db, "MATCH (a:OA) OPTIONAL MATCH (a)-[:NOPE]->(b) "
                   "RETURN count(b)")
    assert rows == [[0]]


def test_optional_match_property_of_null(db):
    run(db, "CREATE (:OB)")
    rows = run(db, "MATCH (a:OB) OPTIONAL MATCH (a)-->(b) "
                   "RETURN b.name, labels(b)")
    assert rows == [[None, None]]


# --- pattern matching corners ------------------------------------------------

def test_self_loop_matched_once_per_direction(db):
    run(db, "CREATE (a:SL)-[:R]->(a)")
    rows = run(db, "MATCH (a:SL)-[r:R]->(a) RETURN count(r)")
    assert rows == [[1]]
    rows = run(db, "MATCH (a:SL)-[r:R]-(b) RETURN count(r)")
    assert rows == [[1]]  # undirected: the self-loop isn't double-counted


def test_bidirectional_counts_both_orientations(db):
    run(db, "CREATE (:BA)-[:R]->(:BB)")
    rows = run(db, "MATCH (x)-[r:R]-(y) RETURN count(*)")
    assert rows == [[2]]  # (a,b) and (b,a)


def test_multiple_match_cartesian(db):
    run(db, "CREATE (:CA), (:CA), (:CB)")
    rows = run(db, "MATCH (a:CA) MATCH (b:CB) RETURN count(*)")
    assert rows == [[2]]
    rows = run(db, "MATCH (a:CA), (b:CA) RETURN count(*)")
    assert rows == [[4]]  # no uniqueness across comma patterns for nodes


def test_var_length_zero_hops(db):
    run(db, "CREATE (:Z {k: 1})-[:R]->(:Z {k: 2})")
    rows = run(db, "MATCH (a:Z {k: 1})-[*0..1]->(b) RETURN b.k ORDER BY b.k")
    assert [r[0] for r in rows] == [1, 2]  # zero hops includes a itself


# --- WITH / projection corners ----------------------------------------------

def test_with_shadows_previous_scope(db):
    rows = run(db, "WITH 1 AS x WITH x + 1 AS x RETURN x")
    assert rows == [[2]]


def test_with_limit_before_more_match(db):
    run(db, "UNWIND range(1, 10) AS i CREATE (:L {v: i})")
    rows = run(db, "MATCH (n:L) WITH n ORDER BY n.v DESC LIMIT 3 "
                   "RETURN collect(n.v)")
    assert rows == [[[10, 9, 8]]]


def test_unwind_empty_list_produces_no_rows(db):
    rows = run(db, "UNWIND [] AS x RETURN x")
    assert rows == []


def test_unwind_null_produces_no_rows(db):
    rows = run(db, "UNWIND null AS x RETURN x")
    assert rows == []


# --- string functions --------------------------------------------------------

def test_case_insensitive_keywords_and_functions(db):
    rows = run(db, "return TOUPPER('ab') as X")
    assert rows == [["AB"]]


def test_temporal_ordering(db):
    rows = run(db, "UNWIND [date('2024-03-01'), date('2024-01-01')] AS d "
                   "RETURN d ORDER BY d")
    assert str(rows[0][0]) == "2024-01-01"


def test_deeply_nested_expression(db):
    rows = run(db, "RETURN size([x IN range(1, 3) | "
                   "[y IN range(1, x) WHERE y % 2 = 1 | y * x]]) AS s")
    assert rows == [[3]]


def test_conversion_families(db):
    rows = run(db, "RETURN toIntegerList(['1', 'x', 2.7, null]), "
                   "toFloatList(['1.5', 'bad']), "
                   "toBooleanList(['true', 'nope', 1]), "
                   "toStringList([1, 2.5, true]), "
                   "toIntegerOrNull('oops'), toFloatOrNull('2.5'), "
                   "toBooleanOrNull([1]), toStringOrNull(7)")
    assert rows == [[[1, None, 2, None], [1.5, None], [True, None, True],
                     ["1", "2.5", "true"], None, 2.5, None, "7"]]


def test_isempty_toset_values(db):
    rows = run(db, "RETURN isEmpty([]), isEmpty('x'), isEmpty({}), "
                   "toSet([1, 1.0, 2, 1]), values({a: 1, b: 2})")
    assert rows == [[True, False, True, [1, 2], [1, 2]]]


def test_username_and_hops_counter(db):
    rows = run(db, "RETURN username()")
    assert rows == [[None]]  # anonymous embedded session
    run(db, "CREATE (:H)-[:E]->(:H)")
    rows = run(db, "MATCH (a)-[e]->(b) USING HOPS LIMIT 100 "
                   "RETURN getHopsCounter() > 0 LIMIT 1")
    assert rows == [[True]]
