"""Per-user profiles (auth/profiles.py — reference:
auth/profiles/user_profiles.cpp, grammar MemgraphCypher.g4:974-991):
DDL surface, session-count enforcement at the Bolt server, and the
transactions_memory default query cap."""

import socket

import pytest

from memgraph_tpu.query import Interpreter
from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def interp():
    return Interpreter(InterpreterContext(InMemoryStorage()))


class TestDDL:
    def test_create_show_update_drop(self, interp):
        interp.execute("CREATE PROFILE basic LIMIT sessions 2, "
                       "transactions_memory 10MB")
        _, rows, _ = interp.execute("SHOW PROFILES")
        assert rows == [["basic", {"sessions": 2,
                                   "transactions_memory": 10 * 1024 * 1024}]]
        interp.execute("UPDATE PROFILE basic LIMIT sessions UNLIMITED")
        _, rows, _ = interp.execute("SHOW PROFILE basic")
        assert rows[0][1]["sessions"] == "UNLIMITED"
        interp.execute("DROP PROFILE basic")
        _, rows, _ = interp.execute("SHOW PROFILES")
        assert rows == []

    def test_assign_show_for_clear(self, interp):
        interp.execute("CREATE PROFILE p1 LIMIT sessions 5")
        interp.execute("SET PROFILE FOR ann TO p1")
        _, rows, _ = interp.execute("SHOW PROFILE FOR ann")
        assert rows[0][0] == "p1"
        _, rows, _ = interp.execute("SHOW USERS FOR PROFILE p1")
        assert rows == [["ann"]]
        interp.execute("CLEAR PROFILE FOR ann")
        _, rows, _ = interp.execute("SHOW PROFILE FOR ann")
        assert rows == []

    def test_unknown_limit_key_rejected(self, interp):
        with pytest.raises(Exception, match="unknown profile limit"):
            interp.execute("CREATE PROFILE bad LIMIT bananas 3")

    def test_drop_unassigns(self, interp):
        interp.execute("CREATE PROFILE p2 LIMIT sessions 1")
        interp.execute("SET PROFILE FOR bob TO p2")
        interp.execute("DROP PROFILE p2")
        _, rows, _ = interp.execute("SHOW PROFILE FOR bob")
        assert rows == []


def test_session_limit_enforced_at_bolt(tmp_path):
    from memgraph_tpu.auth.auth import Auth
    from memgraph_tpu.server.bolt import BoltServer
    from memgraph_tpu.server.client import BoltClient, BoltClientError

    ictx = InterpreterContext(InMemoryStorage())
    auth = Auth(str(tmp_path / "auth.json"))
    auth.create_user("admin", "pw")
    auth.create_user("worker", "wpw")
    Interpreter(ictx).execute("CREATE PROFILE tight LIMIT sessions 1")
    Interpreter(ictx).execute("SET PROFILE FOR worker TO tight")
    with socket.socket() as p:
        p.bind(("127.0.0.1", 0))
        port = p.getsockname()[1]
    server = BoltServer(ictx, "127.0.0.1", port, auth=auth)
    thread, loop = server.run_in_thread()
    try:
        c1 = BoltClient(port=port, username="worker", password="wpw")
        c1.execute("RETURN 1")
        # second concurrent session for the same user: refused
        with pytest.raises(BoltClientError, match="session limit"):
            BoltClient(port=port, username="worker", password="wpw")
        # other users unaffected
        c2 = BoltClient(port=port, username="admin", password="pw")
        c2.execute("RETURN 1")
        c2.close()
        # after the first session closes, the user can log in again
        c1.close()
        import time
        deadline = time.time() + 5
        again = None
        while time.time() < deadline:
            try:
                again = BoltClient(port=port, username="worker",
                                   password="wpw")
                break
            except BoltClientError:
                time.sleep(0.1)   # close still propagating
        assert again is not None
        again.close()
    finally:
        loop.call_soon_threadsafe(loop.stop)


def test_transactions_memory_cap_enforced(interp):
    from memgraph_tpu.exceptions import MemgraphTpuError, QueryException
    interp.execute("CREATE PROFILE small LIMIT transactions_memory 1MB")
    interp.execute("SET PROFILE FOR miser TO small")
    interp.username = "miser"
    with pytest.raises(Exception, match="[Mm]emory"):
        interp.execute(
            "UNWIND range(1, 200000) AS i WITH collect(i) AS xs "
            "RETURN size(xs)")
    # same query passes for a user without the profile
    interp.username = "other"
    _, rows, _ = interp.execute(
        "UNWIND range(1, 200000) AS i WITH collect(i) AS xs "
        "RETURN size(xs)")
    assert rows == [[200000]]
