"""Graph-utility modules (uuid/text/util/label/node/nodes/neighbors/meta/
path/merge/distance_calculator/periodic) — reference mage/cpp parity."""

import pytest

from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def interp():
    i = Interpreter(InterpreterContext(InMemoryStorage()))
    i.execute(
        "CREATE (a:P {name: 'a', lat: 0.0, lng: 0.0})"
        "-[:KNOWS]->(b:P {name: 'b', lat: 1.0, lng: 1.0}),"
        "(b)-[:LIKES]->(c:Q {name: 'c'})")
    return i


def rows(result):
    return result[1]


def test_uuid_and_md5(interp):
    out = rows(interp.execute("CALL uuid.get() YIELD uuid RETURN uuid"))
    assert len(out[0][0]) == 36
    out = rows(interp.execute(
        "CALL util.md5(['a', 1]) YIELD result RETURN result"))
    assert out == [["8a8bb7cd343aa2ad99b7d762030857a2"]]


def test_text_procs(interp):
    assert rows(interp.execute(
        "CALL text.join(['a', 'b'], '-') YIELD string RETURN string")) == \
        [["a-b"]]
    assert rows(interp.execute(
        "CALL text.format('x={}', [3]) YIELD result RETURN result")) == \
        [["x=3"]]
    out = rows(interp.execute(
        "CALL text.regex_groups('ab12cd34', '([a-z]+)([0-9]+)') "
        "YIELD results RETURN results"))
    assert out == [[[["ab12", "ab", "12"], ["cd34", "cd", "34"]]]]
    with pytest.raises(QueryException):
        interp.execute("CALL text.join([1], '-') YIELD string RETURN 1")


def test_label_and_node_procs(interp):
    assert rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL label.exists(n, 'P') "
        "YIELD exists RETURN exists")) == [[True]]
    assert rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL label.exists(n, 'Q') "
        "YIELD exists RETURN exists")) == [[False]]
    assert rows(interp.execute(
        "MATCH (n:P {name:'b'}) CALL node.degree_in(n) "
        "YIELD degree RETURN degree")) == [[1]]
    assert rows(interp.execute(
        "MATCH (n:P {name:'b'}) CALL node.degree_out(n, 'LIKES') "
        "YIELD degree RETURN degree")) == [[1]]
    assert rows(interp.execute(
        "MATCH (n:P {name:'b'}) CALL node.relationship_types(n) "
        "YIELD relationship_types AS t RETURN t")) == [[["KNOWS", "LIKES"]]]
    out = rows(interp.execute(
        "MATCH (n:P {name:'b'}) "
        "CALL node.relationships_exist(n, ['KNOWS>', '<KNOWS', 'NOPE']) "
        "YIELD result RETURN result"))
    assert out == [[{"KNOWS>": False, "<KNOWS": True, "NOPE": False}]]


def test_nodes_link_and_delete(interp):
    interp.execute(
        "MATCH (n) WITH collect(n) AS ns "
        "CALL nodes.link(ns, 'NEXT') YIELD success RETURN success")
    assert rows(interp.execute(
        "MATCH ()-[r:NEXT]->() RETURN count(r)")) == [[2]]
    interp.execute(
        "MATCH (n:Q) WITH collect(n) AS ns "
        "CALL nodes.delete(ns) YIELD success RETURN success")
    assert rows(interp.execute("MATCH (n:Q) RETURN count(n)")) == [[0]]


def test_neighbors(interp):
    assert rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL neighbors.at_hop(n, [], 2) "
        "YIELD nodes RETURN nodes.name")) == [["c"]]
    assert rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL neighbors.at_hop(n, ['KNOWS>'], 1) "
        "YIELD nodes RETURN nodes.name")) == [["b"]]
    out = rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL neighbors.by_hop(n, [], 3) "
        "YIELD nodes RETURN size(nodes)"))
    assert [r[0] for r in out] == [1, 1, 0]
    with pytest.raises(QueryException):
        interp.execute(
            "MATCH (n:P {name:'a'}) CALL neighbors.at_hop(n, [], 0) "
            "YIELD nodes RETURN 1")


def test_meta_stats(interp):
    out = rows(interp.execute(
        "CALL meta.stats_online() YIELD nodeCount, relationshipCount, "
        "labels, relationshipTypes, relationshipTypesCount, stats "
        "RETURN nodeCount, relationshipCount, labels, relationshipTypes, "
        "relationshipTypesCount, stats.labelCount"))
    assert out == [[3, 2, {"P": 2, "Q": 1},
                    {"(:P)-[:KNOWS]->()": 1, "()-[:KNOWS]->(:P)": 1,
                     "(:P)-[:LIKES]->()": 1, "()-[:LIKES]->(:Q)": 1},
                    {"KNOWS": 1, "LIKES": 1}, 2]]


def test_path_expand_and_subgraph(interp):
    out = rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL path.expand(n, [], [], 1, 2) "
        "YIELD result RETURN size(nodes(result)) ORDER BY 1"))
    assert [r[0] for r in out] == [2, 3]
    # label deny filter stops at :Q
    out = rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL path.expand(n, [], ['-Q'], 1, 3) "
        "YIELD result RETURN size(nodes(result))"))
    assert [r[0] for r in out] == [2]
    out = rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL path.subgraph_all(n, {max_level: 1}) "
        "YIELD nodes, rels RETURN size(nodes), size(rels)"))
    assert out == [[2, 1]]


def test_merge_node_and_relationship(interp):
    out = rows(interp.execute(
        "CALL merge.node(['M'], {k: 1}, {c: 1}, {m: 1}) "
        "YIELD node RETURN node.k, node.c, node.m"))
    assert out == [[1, 1, None]]  # created: createProps only
    out = rows(interp.execute(
        "CALL merge.node(['M'], {k: 1}, {c: 2}, {m: 9}) "
        "YIELD node RETURN node.k, node.c, node.m"))
    assert out == [[1, 1, 9]]     # matched: matchProps applied, c untouched
    assert rows(interp.execute("MATCH (n:M) RETURN count(n)")) == [[1]]
    out = rows(interp.execute(
        "MATCH (a:P {name:'a'}), (b:P {name:'b'}) "
        "CALL merge.relationship(a, 'KNOWS', {}, {w: 1}, b, {}) "
        "YIELD rel RETURN rel.w"))
    assert out == [[None]]  # matched the existing KNOWS edge
    assert rows(interp.execute(
        "MATCH (:P {name:'a'})-[r:KNOWS]->() RETURN count(r)")) == [[1]]


def test_distance_calculator(interp):
    out = rows(interp.execute(
        "MATCH (a:P {name:'a'}), (b:P {name:'b'}) "
        "CALL distance_calculator.single(a, b, 'km') "
        "YIELD distance RETURN round(distance)"))
    assert out == [[157.0]]  # ~157 km per diagonal degree at the equator
    out = rows(interp.execute(
        "MATCH (a:P {name:'a'}), (b:P {name:'b'}) "
        "CALL distance_calculator.multiple([a], [b], 'm') "
        "YIELD distances RETURN round(distances[0] / 1000)"))
    assert out == [[157.0]]
    with pytest.raises(QueryException):
        interp.execute(
            "MATCH (a:P {name:'a'}) "
            "CALL distance_calculator.single(a, a, 'furlongs') "
            "YIELD distance RETURN 1")


def test_periodic_iterate_and_delete(interp):
    # canonical reference form: running query sees each column per row
    out = rows(interp.execute(
        "CALL periodic.iterate("
        "'MATCH (n:P) RETURN n.name AS name', "
        "'CREATE (:Copy {name: name})', "
        "{batch_size: 1}) "
        "YIELD success, number_of_executed_batches RETURN *"))
    assert out == [[2, True]] or out == [[True, 2]]
    assert rows(interp.execute("MATCH (c:Copy) RETURN count(c)")) == [[2]]
    out = rows(interp.execute(
        "CALL periodic.delete({labels: ['Copy'], batch_size: 1}) "
        "YIELD number_of_deleted_nodes RETURN number_of_deleted_nodes"))
    assert out == [[2]]
    assert rows(interp.execute("MATCH (c:Copy) RETURN count(c)")) == [[0]]


def test_exists_still_works_as_function(interp):
    # the YIELD-name fix must not break EXISTS( pattern ) expressions
    out = rows(interp.execute(
        "MATCH (n:P {name:'a'}) RETURN exists((n)-[:KNOWS]->())"))
    assert out == [[True]]


def test_periodic_iterate_node_columns(interp):
    # node columns are re-matched by id in the running query
    out = rows(interp.execute(
        "CALL periodic.iterate("
        "'MATCH (n:P) RETURN n', "
        "'SET n.seen = true', "
        "{batch_size: 10}) "
        "YIELD success RETURN success"))
    assert out == [[True]]
    assert rows(interp.execute(
        "MATCH (n:P) WHERE n.seen RETURN count(n)")) == [[2]]


def test_path_expand_zero_hops(interp):
    out = rows(interp.execute(
        "MATCH (n:P {name:'a'}) CALL path.expand(n, [], [], 0, 1) "
        "YIELD result RETURN size(nodes(result)) ORDER BY 1"))
    assert [r[0] for r in out] == [1, 2]  # includes the start-only path


def test_do_when_and_case(interp):
    out = rows(interp.execute(
        "CALL do.when(true, 'RETURN 1 AS a', 'RETURN 2 AS a') "
        "YIELD value RETURN value.a"))
    assert out == [[1]]
    out = rows(interp.execute(
        "CALL do.case([false, 'RETURN 1 AS a', true, 'RETURN 2 AS a'], "
        "'RETURN 3 AS a') YIELD value RETURN value.a"))
    assert out == [[2]]
    out = rows(interp.execute(
        "CALL do.case([false, 'RETURN 1 AS a'], 'RETURN 3 AS a') "
        "YIELD value RETURN value.a"))
    assert out == [[3]]
    with pytest.raises(QueryException):
        interp.execute("CALL do.case([], 'RETURN 1') YIELD value RETURN 1")
    with pytest.raises(QueryException):
        interp.execute("CALL do.case([true], 'RETURN 1') "
                       "YIELD value RETURN 1")


def test_do_rejects_global_operations(interp):
    # whitespace variants must still be caught (parsed, not substring-matched)
    with pytest.raises(QueryException):
        interp.execute(
            "CALL do.when(true, 'CREATE  INDEX ON :L(p)', 'RETURN 1') "
            "YIELD value RETURN 1")
    # string literals mentioning global ops are NOT false positives
    out = rows(interp.execute(
        "CALL do.when(true, "
        "\"RETURN 'storage mode tips' AS a\", 'RETURN 2') "
        "YIELD value RETURN value.a"))
    assert out == [["storage mode tips"]]
