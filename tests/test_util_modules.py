"""map/collections/create/refactor modules + new builtin functions."""

import pytest

from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def db():
    return InterpreterContext(InMemoryStorage())


def run(db, q, params=None):
    _, rows, _ = Interpreter(db).execute(q, params)
    return rows


def test_map_module(db):
    rows = run(db, "CALL map.from_pairs([['a', 1], ['b', 2]]) YIELD map "
                   "RETURN map")
    assert rows == [[{"a": 1, "b": 2}]]
    rows = run(db, "CALL map.merge({a: 1}, {b: 2}) YIELD result RETURN result")
    assert rows == [[{"a": 1, "b": 2}]]
    rows = run(db, "CALL map.flatten({a: {b: 1}}) YIELD result RETURN result")
    assert rows == [[{"a.b": 1}]]


def test_collections_module(db):
    rows = run(db, "CALL collections.sum([1, 2, 3]) YIELD sum RETURN sum")
    assert rows == [[6.0]]
    rows = run(db, "CALL collections.sort([3, 1, 2]) YIELD sorted "
                   "RETURN sorted")
    assert rows == [[[1, 2, 3]]]
    rows = run(db, "CALL collections.partition([1,2,3,4,5], 2) "
                   "YIELD partition RETURN partition")
    assert [r[0] for r in rows] == [[1, 2], [3, 4], [5]]


def test_create_module(db):
    rows = run(db, "CALL create.node(['Person'], {name: 'zed'}) YIELD node "
                   "RETURN labels(node), node.name")
    assert rows == [[["Person"], "zed"]]
    run(db, "MATCH (a:Person) CALL create.node(['Other'], {}) YIELD node "
            "CALL create.relationship(a, 'LIKES', {w: 1}, node) "
            "YIELD relationship RETURN relationship")
    rows = run(db, "MATCH (:Person)-[r:LIKES]->(:Other) RETURN r.w")
    assert rows == [[1]]


def test_refactor_module(db):
    run(db, "CREATE (:Old {a: 1}), (:Old {a: 2})")
    rows = run(db, "CALL refactor.rename_label('Old', 'New') "
                   "YIELD nodes_changed RETURN nodes_changed")
    assert rows == [[2]]
    assert run(db, "MATCH (n:New) RETURN count(n)") == [[2]]
    rows = run(db, "CALL refactor.rename_node_property('a', 'b') "
                   "YIELD nodes_changed RETURN nodes_changed")
    assert rows == [[2]]
    assert run(db, "MATCH (n:New) WHERE n.b IS NOT NULL RETURN count(n)") \
        == [[2]]


def test_refactor_invert(db):
    run(db, "CREATE (:A)-[:R {k: 7}]->(:B)")
    run(db, "MATCH (:A)-[r:R]->(:B) CALL refactor.invert(r) "
            "YIELD relationship RETURN relationship")
    rows = run(db, "MATCH (:B)-[r:R]->(:A) RETURN r.k")
    assert rows == [[7]]


def test_assert_function(db):
    from memgraph_tpu.exceptions import TypeException
    assert run(db, "RETURN assert(1 = 1) AS ok") == [[True]]
    with pytest.raises(TypeException):
        run(db, "RETURN assert(1 = 2, 'boom')")


def test_counter_function(db):
    rows = run(db, "UNWIND range(1, 3) AS i RETURN counter('c1', 10) AS c")
    assert [r[0] for r in rows] == [10, 11, 12]
    rows = run(db, "RETURN counter('c2', 0, 5) AS c")
    assert rows == [[0]]


def test_tocharlist_propertysize(db):
    run(db, "CREATE (:PS {s: 'hello'})")
    rows = run(db, "MATCH (n:PS) RETURN toCharList(n.s), "
                   "propertySize(n, 's') > 0")
    assert rows == [[["h", "e", "l", "l", "o"], True]]


def test_export_import_json(db, tmp_path):
    run(db, "CREATE (a:X {name:'a', tags:[1,2]})-[:R {w: 1.5}]->(b:Y)")
    path = str(tmp_path / "graph.json")
    rows = run(db, f"CALL export_util.json('{path}') "
                   f"YIELD nodes, relationships RETURN nodes, relationships")
    assert rows == [[2, 1]]
    fresh = InterpreterContext(InMemoryStorage())
    rows = run(fresh, f"CALL import_util.json('{path}') "
                      f"YIELD nodes, relationships "
                      f"RETURN nodes, relationships")
    assert rows == [[2, 1]]
    rows = run(fresh, "MATCH (a:X)-[r:R]->(b:Y) RETURN a.name, a.tags, r.w")
    assert rows == [["a", [1, 2], 1.5]]


def test_export_cypherl(db, tmp_path):
    run(db, "CREATE (:C {v: 1})")
    path = str(tmp_path / "dump.cypherl")
    rows = run(db, f"CALL export_util.cypherl('{path}') "
                   f"YIELD statements RETURN statements > 0")
    assert rows == [[True]]
    content = open(path).read()
    assert "CREATE" in content


def test_mock_context_api():
    from memgraph_tpu.procedures.mock import call_procedure, mock_context
    ctx, nodes = mock_context(
        nodes=[{"labels": ["U"], "name": "a"}, {"labels": ["U"],
               "name": "b"}],
        edges=[(0, 1, "KNOWS", {"w": 2.0})])
    assert len(nodes) == 2
    graph = ctx.device_graph()
    assert graph.n_nodes == 2 and graph.n_edges == 1
    rows = call_procedure(
        "degree_centrality.get",
        nodes=[{"labels": ["U"]}, {"labels": ["U"]}],
        edges=[(0, 1, "E")])
    assert len(rows) == 2
