"""Round-5 flag surface: property-store compression, schema-info gate,
aggressive GC, slow-query/plan logging, callable mappings, recovery
failure tolerance, edges metadata, strict flag check, metrics format.
References: /root/reference/src/flags/*.cpp,
storage/v2/property_store.cpp:44 (compression flag).
"""

import json
import logging

import numpy as np
import pytest

from memgraph_tpu.query import Interpreter
from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.storage import InMemoryStorage, StorageConfig
from memgraph_tpu.storage.property_store import (COMPRESSION,
                                                 decode_properties,
                                                 encode_properties)


@pytest.fixture
def compression_on():
    old = dict(COMPRESSION)
    COMPRESSION.update(enabled=True, level=6, min_bytes=64)
    yield
    COMPRESSION.update(old)


class TestPropertyCompression:
    def test_round_trip_and_shrinks(self, compression_on):
        props = {1: "the quick brown fox " * 50, 2: 42,
                 3: [1.5] * 40, 4: "x"}
        blob = encode_properties(props)
        COMPRESSION["enabled"] = False
        raw = encode_properties(props)
        assert len(blob) < len(raw) // 2
        # decoder auto-detects regardless of current config
        assert decode_properties(blob) == props
        assert decode_properties(raw) == props

    def test_small_blobs_stay_raw(self, compression_on):
        blob = encode_properties({1: "tiny"})
        assert blob[:1] != b"\x00" or len(blob) == 1
        assert decode_properties(blob) == {1: "tiny"}

    def test_empty_props_unambiguous(self, compression_on):
        blob = encode_properties({})
        assert blob == b"\x00"
        assert decode_properties(blob) == {}

    def test_corrupt_compressed_blob_raises_domain_error(self):
        from memgraph_tpu.exceptions import StorageError
        with pytest.raises(StorageError):
            decode_properties(b"\x00not-zlib-data")

    def test_snapshot_round_trip_compressed(self, tmp_path,
                                            compression_on):
        from memgraph_tpu.storage.durability.snapshot import (
            create_snapshot, load_snapshot)
        from memgraph_tpu.storage.common import StorageMode
        cfg = StorageConfig(durability_dir=str(tmp_path))
        storage = InMemoryStorage(cfg)
        acc = storage.access()
        prop = storage.property_mapper.name_to_id("bio")
        for i in range(200):
            v = acc.create_vertex()
            v.set_property(prop, f"a long biography string {i} " * 20)
        acc.commit()
        path = create_snapshot(storage)
        data = load_snapshot(path)
        assert len(data["vertices"]) == 200
        # and the payload is actually smaller than uncompressed
        COMPRESSION["enabled"] = False
        path2 = create_snapshot(storage)
        import os
        assert os.path.getsize(path) < os.path.getsize(path2) // 2

    def test_compressed_snapshot_recovers(self, tmp_path, compression_on):
        from memgraph_tpu.storage.durability.snapshot import create_snapshot
        from memgraph_tpu.storage.durability.recovery import recover
        cfg = StorageConfig(durability_dir=str(tmp_path))
        storage = InMemoryStorage(cfg)
        acc = storage.access()
        prop = storage.property_mapper.name_to_id("t")
        v = acc.create_vertex()
        v.set_property(prop, "payload " * 100)
        acc.commit()
        create_snapshot(storage)
        COMPRESSION["enabled"] = False      # reader config differs
        fresh = InMemoryStorage(cfg)
        recover(fresh)
        acc2 = fresh.access()
        vs = list(acc2.vertices())
        assert len(vs) == 1
        assert vs[0].properties()[prop] == "payload " * 100
        acc2.abort()


class TestInterpreterFlags:
    def test_schema_info_gate(self):
        from memgraph_tpu.exceptions import QueryException
        interp = Interpreter(InterpreterContext(
            InMemoryStorage(), {"schema_info_enabled": False}))
        with pytest.raises(QueryException):
            interp.execute("SHOW SCHEMA INFO")
        interp2 = Interpreter(InterpreterContext(InMemoryStorage()))
        cols, rows, _ = interp2.execute("SHOW SCHEMA INFO")
        assert cols == ["schema"]

    def test_log_min_duration(self, caplog):
        interp = Interpreter(InterpreterContext(
            InMemoryStorage(), {"log_min_duration_ms": 0.0001}))
        with caplog.at_level(logging.INFO,
                             logger="memgraph_tpu.query.interpreter"):
            interp.execute("UNWIND range(1, 100) AS i RETURN sum(i)")
        assert any("slow query" in r.message for r in caplog.records)

    def test_slow_log_never_leaks_credentials(self, caplog):
        """AUTH statements are skipped entirely; other queries have their
        string literals redacted (the monitoring websocket re-broadcasts
        every INFO record, so plaintext secrets must never reach it)."""
        from memgraph_tpu.auth.auth import Auth
        ictx = InterpreterContext(
            InMemoryStorage(), {"log_min_duration_ms": 0.0001})
        ictx.auth_store = Auth()   # session-local: don't leak users
        interp = Interpreter(ictx)
        interp.username = "alice"
        with caplog.at_level(logging.INFO,
                             logger="memgraph_tpu.query.interpreter"):
            interp.execute("CREATE USER alice IDENTIFIED BY 's3cret'")
            interp.execute("RETURN 'sensitive-literal' AS x")
        messages = [r.getMessage() for r in caplog.records]
        assert not any("s3cret" in m for m in messages)
        assert not any("sensitive-literal" in m for m in messages)
        assert any("slow query" in m and "'***'" in m for m in messages)

    def test_log_query_plan(self, caplog):
        interp = Interpreter(InterpreterContext(
            InMemoryStorage(), {"log_query_plan": True}))
        with caplog.at_level(logging.INFO,
                             logger="memgraph_tpu.query.interpreter"):
            interp.execute("MATCH (n) RETURN n LIMIT 1")
        assert any("plan for" in r.message for r in caplog.records)

    def test_edges_metadata_in_storage_info(self):
        interp = Interpreter(InterpreterContext(
            InMemoryStorage(), {"storage_enable_edges_metadata": True}))
        interp.execute("CREATE (a)-[:KNOWS]->(b), (a)-[:LIKES]->(b), "
                       "(b)-[:KNOWS]->(a)")
        _, rows, _ = interp.execute("SHOW STORAGE INFO")
        info = {r[0]: r[1] for r in rows}
        assert info.get("edge_count[KNOWS]") == 2
        assert info.get("edge_count[LIKES]") == 1

    def test_callable_mappings(self, tmp_path):
        from memgraph_tpu.query.procedures.registry import global_registry
        mpath = tmp_path / "mappings.json"
        mpath.write_text(json.dumps(
            {"gds.util.nan": "util.validate"}))
        n = global_registry.load_callable_mappings(str(mpath))
        assert n == 1
        try:
            real = global_registry.find("util.validate")
            if real is not None:     # alias resolves to the same proc
                assert global_registry.find("gds.util.nan") is real
        finally:
            global_registry._aliases.clear()


class TestStorageFlags:
    def test_gc_aggressive_truncates_after_commit(self):
        storage = InMemoryStorage(StorageConfig(gc_aggressive=True))
        acc = storage.access()
        v = acc.create_vertex()
        prop = storage.property_mapper.name_to_id("p")
        v.set_property(prop, 1)
        acc.commit()
        acc2 = storage.access()
        v2 = next(iter(acc2.vertices(View := __import__("memgraph_tpu.storage.common", fromlist=["View"]).View.NEW)))
        v2.set_property(prop, 2)
        acc2.commit()
        # no active readers: the eager GC must have dropped the chain
        vertex = next(iter(storage._vertices.values()))
        assert vertex.delta is None

    def test_allow_recovery_failure_boots_on_corruption(self, tmp_path):
        from memgraph_tpu.dbms.dbms import DbmsHandler
        snapdir = tmp_path / "snapshots"
        snapdir.mkdir(parents=True)
        (snapdir / "snapshot_1.mgsnap").write_bytes(b"GARBAGE" * 10)
        cfg = StorageConfig(durability_dir=str(tmp_path),
                            allow_recovery_failure=True)
        dbms = DbmsHandler(cfg, {}, recover_on_startup=True)
        ictx = dbms.default()     # must not raise
        assert ictx.storage is not None


class TestDbArena:
    def test_memory_estimate_in_storage_info(self):
        interp = Interpreter(InterpreterContext(InMemoryStorage()))
        interp.execute("UNWIND range(1, 500) AS i "
                       "CREATE (:N {data: 'x' + toString(i)})")
        _, rows, _ = interp.execute("SHOW STORAGE INFO")
        info = {r[0]: r[1] for r in rows}
        est = info["memory_usage_db_estimate"]
        # 500 vertices with labels+props: plausibly tens of KB, not 0
        assert est > 50_000, est

    def test_tenant_storage_limit_refuses_writes(self, tmp_path):
        from memgraph_tpu.dbms.dbms import DbmsHandler
        from memgraph_tpu.exceptions import StorageError
        dbms = DbmsHandler(StorageConfig(), {})
        ictx = dbms.default()
        interp = Interpreter(ictx)
        interp.execute("UNWIND range(1, 300) AS i CREATE (:N {v: i})")
        dbms.tenant_profiles.create("tiny", {"storage_limit": 1000})
        dbms.tenant_profiles.assign("memgraph", "tiny")
        # limit-change invalidates the 5s estimate cache immediately
        with pytest.raises(Exception, match="memory limit exceeded"):
            interp.execute("CREATE (:N {v: -1})")
        # reads still work, and so do DELETES — an over-limit database
        # must remain recoverable in-band (review finding r5)
        _, rows, _ = interp.execute("MATCH (n:N) RETURN count(n)")
        assert rows == [[300]]
        interp.execute("MATCH (n:N) WITH n LIMIT 250 DETACH DELETE n")
        # property updates on survivors pass too (not a growing commit)
        interp.execute("MATCH (n:N) SET n.touched = true")
        # still over the (absurdly small) limit for growth...
        with pytest.raises(Exception, match="memory limit exceeded"):
            interp.execute("CREATE (:N {v: -2})")
        # ...until the profile is lifted
        dbms.tenant_profiles.clear("memgraph")
        interp.execute("CREATE (:N {v: -1})")


class TestBuildConfig:
    def test_strict_flag_check(self, capsys):
        from memgraph_tpu.main import build_config
        with pytest.raises(SystemExit):
            build_config(["--no-such-flag"])
        args = build_config(["--no-such-flag", "--no-strict-flag-check"])
        assert args.strict_flag_check is False

    def test_flag_count_at_least_80(self):
        import re
        import os
        src = open(os.path.join(os.path.dirname(__file__), "..",
                                "memgraph_tpu", "main.py")).read()
        flags = set(re.findall(r'add_argument\("(--[a-z0-9-]+)"', src))
        assert len(flags) >= 80, f"only {len(flags)} flags wired"

    def test_compression_flags_parse(self):
        from memgraph_tpu.main import build_config
        args = build_config(
            ["--storage-property-store-compression-enabled",
             "--storage-property-store-compression-level", "high"])
        assert args.storage_property_store_compression_enabled
        assert args.storage_property_store_compression_level == "high"
