"""Offset-recovery crash matrix: a FILE stream consumer killed at exact
protocol steps, then recovered + drained — exactly-once must hold at
EVERY kill point because the offset rides the ingest commit (WAL
OP_STREAM_OFFSET), not the consumer-side ack.

Kill points (tests/stream_crash_child.py, faults via MEMGRAPH_TPU_FAULTS):

* ``stream.commit=kill@K`` — after the Kth durable data+offset commit,
  BEFORE the consumer ack (the classic at-least-once dup window: the
  source would redeliver, but the recovered offset dedups it);
* ``wal.write=torn:N+kill@K`` — mid-WAL-record torn write: the whole
  txn (data AND offset, one atom) is dropped on replay and the batch
  redelivers — no half-ingested batch, no phantom offset;
* ``kvstore.put=kill@K`` — after the source ack, before the kvstore
  offset copy persists (the kv copy is a lagging optimization; the WAL
  position must win on restart).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
CHILD = REPO / "tests" / "stream_crash_child.py"

N_LINES = 6


def _run(tmp_path, mode, faults):
    dur = tmp_path / "data"
    dur.mkdir(exist_ok=True)
    inp = tmp_path / "in.jsonl"
    if not inp.exists():
        inp.write_text("".join(json.dumps({"id": i}) + "\n"
                               for i in range(N_LINES)))
    env = os.environ.copy()
    env["MEMGRAPH_TPU_FAULTS"] = faults
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("MG_TRACK_LOCKS", "1")
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, str(CHILD), mode, str(dur), str(inp),
         str(N_LINES)],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=300)


def _crash_then_drain(tmp_path, faults):
    proc = _run(tmp_path, "run", faults)
    assert proc.returncode == 137, (
        f"child should have been fault-killed, got rc={proc.returncode}\n"
        f"{proc.stdout}{proc.stderr}")
    drain = _run(tmp_path, "drain", "")
    assert drain.returncode == 0, drain.stdout + drain.stderr
    return json.loads(drain.stdout.strip().splitlines()[-1])


def _assert_exactly_once(report):
    recovered = report["recovered_ids"]
    # recovery must never surface a duplicate (a redelivered batch whose
    # first ingest already committed) ...
    assert len(recovered) == len(set(recovered)), (
        f"duplicate ids after recovery: {recovered}")
    # ... and the drain must end with every line exactly once
    assert report["final_ids"] == list(range(N_LINES)), report


# the three protocol windows, each at an early and a later commit
STREAM_CRASH_MATRIX = [
    "stream.commit=kill@1",
    "stream.commit=kill@2",
    "wal.write=torn:12+kill@1",
    "wal.write=torn:30+kill@2",
    "kvstore.put=kill@1",
    "kvstore.put=kill@2",
]

# tier-1 smoke: one kill per protocol window
STREAM_CRASH_SMOKE = [
    "stream.commit=kill@1",
    "wal.write=torn:12+kill@2",
    "kvstore.put=kill@1",
]


@pytest.mark.parametrize("faults", STREAM_CRASH_SMOKE)
def test_stream_crash_smoke(tmp_path, faults):
    _assert_exactly_once(_crash_then_drain(tmp_path, faults))


@pytest.mark.slow
@pytest.mark.crash
@pytest.mark.parametrize("faults", STREAM_CRASH_MATRIX)
def test_stream_crash_matrix(tmp_path, faults):
    _assert_exactly_once(_crash_then_drain(tmp_path, faults))


def test_stream_commit_kill_recovers_the_unacked_batch(tmp_path):
    """The sharpest case spelled out: killed BETWEEN the durable commit
    and the consumer ack, the batch's data AND offset must both be
    there after WAL replay — redelivery dedups instead of duplicating."""
    report = _crash_then_drain(tmp_path, "stream.commit=kill@1")
    assert report["recovered_ids"] == [0, 1]      # batch_size=2, batch 1
    assert report["recovered_offset"] is not None
    assert report["recovered_offset"] > 0
    _assert_exactly_once(report)


def test_torn_offset_record_drops_the_whole_txn(tmp_path):
    """A torn WAL write mid-record drops data+offset as one atom: either
    the batch is fully there with its offset, or fully absent."""
    report = _crash_then_drain(tmp_path, "wal.write=torn:12+kill@1")
    assert report["recovered_ids"] == []          # txn 1 torn away
    assert report["recovered_offset"] is None
    _assert_exactly_once(report)


def test_stream_child_completes_without_faults(tmp_path):
    proc = _run(tmp_path, "run", "")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(_run(tmp_path, "drain", "").stdout
                        .strip().splitlines()[-1])
    assert report["recovered_ids"] == list(range(N_LINES))
    _assert_exactly_once(report)
