"""Combinatorial MAGE-parity modules: max_flow, union_find, graph_coloring,
tsp, vrp, set_cover, bipartite_matching, leiden, temporal."""

import math

import pytest

from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.procedures import load_builtin_modules
from memgraph_tpu.procedures.mock import mock_context
from memgraph_tpu.query.procedures.registry import global_registry

load_builtin_modules()


def proc(name):
    p = global_registry.find(name)
    assert p is not None, f"procedure {name} not registered"
    return p.func


def test_max_flow_diamond():
    ctx, vs = mock_context(
        nodes=[{}, {}, {}, {}],
        edges=[(0, 1, "E", {"weight": 3}), (0, 2, "E", {"weight": 2}),
               (1, 3, "E", {"weight": 2}), (2, 3, "E", {"weight": 4}),
               (1, 2, "E", {"weight": 5})])
    rows = list(proc("max_flow.get_flow")(ctx, vs[0], vs[3]))
    # s->1 (3) splits: 2 along 1->t, 1 along 1->2->t; s->2 adds 2 => flow 5
    assert rows == [{"max_flow": 5.0}]


def test_max_flow_paths_are_paths():
    ctx, vs = mock_context(
        nodes=[{}, {}, {}],
        edges=[(0, 1, "E", {"weight": 2}), (1, 2, "E", {"weight": 1})])
    rows = list(proc("max_flow.get_paths")(ctx, vs[0], vs[2]))
    assert len(rows) == 1
    assert rows[0]["flow"] == 1.0
    path = rows[0]["path"]
    assert [v.gid for v in path.vertices()] == [vs[0].gid, vs[1].gid,
                                                vs[2].gid]


def test_max_flow_disconnected_is_zero():
    ctx, vs = mock_context(nodes=[{}, {}], edges=[])
    rows = list(proc("max_flow.get_flow")(ctx, vs[0], vs[1]))
    assert rows == [{"max_flow": 0.0}]


def test_union_find_connected_pairwise_and_cartesian():
    ctx, vs = mock_context(
        nodes=[{}, {}, {}, {}],
        edges=[(0, 1, "E"), (2, 3, "E")])
    rows = list(proc("union_find.connected")(ctx, [vs[0], vs[0]],
                                             [vs[1], vs[2]]))
    assert [r["connected"] for r in rows] == [True, False]
    rows = list(proc("union_find.connected")(ctx, [vs[0]], [vs[1], vs[3]],
                                             "cartesian"))
    assert [r["connected"] for r in rows] == [True, False]


def test_union_find_mode_validation():
    ctx, vs = mock_context(nodes=[{}, {}], edges=[])
    with pytest.raises(QueryException):
        list(proc("union_find.connected")(ctx, [vs[0]], [vs[1]], "bogus"))


def test_graph_coloring_is_proper():
    # 5-cycle needs 3 colors
    ctx, vs = mock_context(
        nodes=[{} for _ in range(5)],
        edges=[(i, (i + 1) % 5, "E") for i in range(5)])
    rows = list(proc("graph_coloring.color_graph")(ctx))
    color = {r["node"].gid: r["color"] for r in rows}
    assert len(color) == 5
    for i in range(5):
        assert color[vs[i].gid] != color[vs[(i + 1) % 5].gid]
    assert len(set(color.values())) == 3


def test_graph_coloring_subgraph():
    ctx, vs = mock_context(nodes=[{}, {}, {}], edges=[(0, 1, "E")])
    edges = list(vs[0].out_edges())
    eas = [e for e in edges]
    rows = list(proc("graph_coloring.color_subgraph")(
        ctx, [vs[0], vs[1]], eas))
    color = {r["node"].gid: r["color"] for r in rows}
    assert set(color) == {vs[0].gid, vs[1].gid}
    assert color[vs[0].gid] != color[vs[1].gid]


SQUARE = [
    {"lat": 0.0, "lng": 0.0}, {"lat": 0.0, "lng": 1.0},
    {"lat": 1.0, "lng": 1.0}, {"lat": 1.0, "lng": 0.0},
]


def tour_length(order):
    def hav(a, b):
        la1, lo1, la2, lo2 = map(math.radians,
                                 (a["lat"], a["lng"], b["lat"], b["lng"]))
        h = (math.sin((la2 - la1) / 2) ** 2
             + math.cos(la1) * math.cos(la2) * math.sin((lo2 - lo1) / 2) ** 2)
        return 2 * 6_371_000 * math.asin(math.sqrt(h))
    return sum(hav(order[i], order[(i + 1) % len(order)])
               for i in range(len(order)))


@pytest.mark.parametrize("method", ["greedy", "2-approx", "1.5-approx"])
def test_tsp_square(method):
    ctx, vs = mock_context(nodes=SQUARE, edges=[])
    rows = list(proc("tsp.solve")(ctx, vs, method))
    srcs, dsts = rows[0]["sources"], rows[0]["destinations"]
    assert len(srcs) == len(dsts) == 4
    # edges chain into a cycle visiting every node once
    assert srcs[1:] == dsts[:-1]
    assert dsts[-1] is srcs[0]
    assert {v.gid for v in srcs} == {v.gid for v in vs}


def test_tsp_greedy_finds_perimeter():
    ctx, vs = mock_context(nodes=SQUARE, edges=[])
    rows = list(proc("tsp.solve")(ctx, vs, "greedy"))
    order = [{"lat": float(v.get_property(
                  ctx.storage.property_mapper.name_to_id("lat"))),
              "lng": float(v.get_property(
                  ctx.storage.property_mapper.name_to_id("lng")))}
             for v in rows[0]["sources"]]
    best = tour_length(SQUARE)  # perimeter order is optimal for a square
    assert tour_length(order) <= best * 1.0001


def test_tsp_empty_and_unknown_method():
    ctx, vs = mock_context(nodes=SQUARE, edges=[])
    assert list(proc("tsp.solve")(ctx, []))[0]["sources"] is None
    # unknown method falls back to greedy (reference behavior); and the
    # reference's underscore spellings are accepted
    for m in ("annealing", "1.5_approx", "2_approx", "GREEDY"):
        rows = list(proc("tsp.solve")(ctx, vs, m))
        assert len(rows[0]["sources"]) == 4


def test_tsp_missing_coordinates():
    ctx, vs = mock_context(nodes=[{"lat": 0.0}], edges=[])
    with pytest.raises(QueryException):
        list(proc("tsp.solve")(ctx, vs))


def test_vrp_routes_cover_all_stops():
    nodes = [{"lat": 0.0, "lng": 0.0}] + \
        [{"lat": float(i), "lng": 0.5 * i} for i in range(1, 6)]
    ctx, vs = mock_context(nodes=nodes, edges=[])
    rows = list(proc("vrp.route")(ctx, vs[0], 2))
    # every stop appears exactly once as a from_vertex (excluding depot legs)
    froms = [r["from_vertex"].gid for r in rows]
    tos = [r["to_vertex"].gid for r in rows]
    depot = vs[0].gid
    stop_gids = {v.gid for v in vs[1:]}
    assert set(froms) - {depot} == stop_gids
    assert set(tos) - {depot} == stop_gids
    assert froms.count(depot) == 2 and tos.count(depot) == 2  # 2 vehicles


def test_set_cover_greedy():
    # elements e1..e4; set A covers e1,e2,e3; B covers e3,e4; C covers e1
    ctx, vs = mock_context(nodes=[{} for _ in range(7)], edges=[])
    e1, e2, e3, e4, A, B, C = vs
    pairs = [(e1, A), (e2, A), (e3, A), (e3, B), (e4, B), (e1, C)]
    for name in ("set_cover.cp_solve", "set_cover.greedy"):
        rows = list(proc(name)(ctx, [p[0] for p in pairs],
                               [p[1] for p in pairs]))
        chosen = {r["containing_set"].gid for r in rows}
        assert chosen == {A.gid, B.gid}


def test_set_cover_length_mismatch():
    ctx, vs = mock_context(nodes=[{}, {}], edges=[])
    with pytest.raises(QueryException):
        list(proc("set_cover.greedy")(ctx, [vs[0]], []))


def test_bipartite_matching_even_cycle():
    # C4 is bipartite with perfect matching 2
    ctx, _ = mock_context(nodes=[{} for _ in range(4)],
                          edges=[(0, 1, "E"), (1, 2, "E"), (2, 3, "E"),
                                 (3, 0, "E")])
    rows = list(proc("bipartite_matching.max")(ctx))
    assert rows == [{"maximum_bipartite_matching": 2}]


def test_bipartite_matching_odd_cycle_is_zero():
    ctx, _ = mock_context(nodes=[{} for _ in range(3)],
                          edges=[(0, 1, "E"), (1, 2, "E"), (2, 0, "E")])
    rows = list(proc("bipartite_matching.max")(ctx))
    assert rows == [{"maximum_bipartite_matching": 0}]


def test_leiden_two_cliques():
    edges = []
    for block in (range(0, 4), range(4, 8)):
        block = list(block)
        for i in block:
            for j in block:
                if i < j:
                    edges.append((i, j, "E"))
    edges.append((0, 4, "E"))  # weak bridge
    ctx, vs = mock_context(nodes=[{} for _ in range(8)], edges=edges)
    rows = list(proc("leiden_community_detection.get")(ctx))
    comm = {r["node"].gid: r["community_id"] for r in rows}
    first = {comm[vs[i].gid] for i in range(4)}
    second = {comm[vs[i].gid] for i in range(4, 8)}
    assert len(first) == 1 and len(second) == 1 and first != second
    assert all(isinstance(r["communities"], list) for r in rows)


def test_temporal_format():
    from memgraph_tpu.utils.temporal import Date, Duration, LocalDateTime
    import datetime as dt
    ctx, _ = mock_context()
    f = proc("temporal.format")
    assert list(f(ctx, Date(dt.date(2024, 3, 1))))[0]["formatted"] == \
        "2024-03-01"
    assert list(f(ctx, Date(dt.date(2024, 3, 1)), "%d/%m/%Y"))[0][
        "formatted"] == "01/03/2024"
    out = list(f(ctx, LocalDateTime(dt.datetime(2024, 3, 1, 12, 30))))[0]
    assert out["formatted"].startswith("2024-03-01T12:30")
    assert list(f(ctx, Duration(90_000_000)))[0]["formatted"]
    # custom format on Duration: strftime via the Unix epoch
    assert list(f(ctx, Duration(90_000_000), "%H:%M:%S"))[0][
        "formatted"] == "00:01:30"
    # non-temporal values fall through to str()
    assert list(f(ctx, 42))[0]["formatted"] == "42"


def test_max_flow_paths_decompose_through_reverse_arcs():
    # s->u1,s->u2, u1->v1,u1->v2, u2->v1, v1->t, v2->t, all capacity 1.
    # Edmonds-Karp's 2nd augmentation rides the reverse arc v1->u1; the
    # yielded forward paths must still sum to the max flow of 2.
    ctx, vs = mock_context(
        nodes=[{} for _ in range(6)],
        edges=[(0, 1, "E", {"weight": 1}), (0, 2, "E", {"weight": 1}),
               (1, 3, "E", {"weight": 1}), (1, 4, "E", {"weight": 1}),
               (2, 3, "E", {"weight": 1}),
               (3, 5, "E", {"weight": 1}), (4, 5, "E", {"weight": 1})])
    flow = list(proc("max_flow.get_flow")(ctx, vs[0], vs[5]))[0]["max_flow"]
    rows = list(proc("max_flow.get_paths")(ctx, vs[0], vs[5]))
    assert flow == 2.0
    assert sum(r["flow"] for r in rows) == flow
    for r in rows:
        verts = r["path"].vertices()
        assert verts[0].gid == vs[0].gid and verts[-1].gid == vs[5].gid


def test_vrp_zero_vehicles_rejected():
    ctx, vs = mock_context(nodes=[{"lat": 0.0, "lng": 0.0},
                                  {"lat": 1.0, "lng": 1.0}], edges=[])
    with pytest.raises(QueryException):
        list(proc("vrp.route")(ctx, vs[0], 0))


def test_graph_coloring_respects_color_budget():
    # 5-cycle: DSATUR wants 3 colors; with no_of_colors=2 every color < 2
    ctx, _ = mock_context(
        nodes=[{} for _ in range(5)],
        edges=[(i, (i + 1) % 5, "E") for i in range(5)])
    rows = list(proc("graph_coloring.color_graph")(ctx, {"no_of_colors": 2}))
    assert rows and all(r["color"] in (0, 1) for r in rows)
    with pytest.raises(QueryException):
        list(proc("graph_coloring.color_graph")(ctx, {"no_of_colors": 0}))


def test_leiden_refinement_sees_in_edges():
    # star pointing INTO the hub: hub adjacency is all in-edges in CSR.
    # Refinement must not strand the hub or leaves in a foreign community.
    edges = [(i, 0, "E") for i in range(1, 6)]
    ctx, vs = mock_context(nodes=[{} for _ in range(6)], edges=edges)
    rows = list(proc("leiden_community_detection.get")(ctx))
    comm = {r["node"].gid: r["community_id"] for r in rows}
    assert len(set(comm.values())) == 1  # one community covers the star
