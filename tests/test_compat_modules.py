"""mgps / graph_analyzer / schema / meta_util compatibility modules
(reference: query_modules/mgps.py, graph_analyzer.py, schema.cpp,
mage/python/meta_util.py)."""

import pytest

from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def interp():
    i = Interpreter(InterpreterContext(InMemoryStorage()))
    i.execute("CREATE (a:P {x: 1})-[:R {w: 2}]->(b:Q), (c:P)")
    return i


def rows(result):
    return result[1]


def test_mgps_components_and_await(interp):
    out = rows(interp.execute(
        "CALL mgps.components() YIELD name, edition, versions RETURN *"))
    assert [r for r in out] == [["community", "Memgraph", ["5.9.0"]],
                                ["community", "Neo4j Kernel", ["5.9.0"]]]
    assert rows(interp.execute(
        "CALL mgps.await_indexes(1) YIELD * RETURN 1")) == []


def test_mgps_validate(interp):
    assert rows(interp.execute(
        "CALL mgps.validate(false, 'bad %s', ['x']) YIELD * RETURN 1")) == []
    with pytest.raises(QueryException, match="bad x"):
        interp.execute(
            "CALL mgps.validate(true, 'bad %s', ['x']) YIELD * RETURN 1")


def test_graph_analyzer(interp):
    out = dict(rows(interp.execute(
        "CALL graph_analyzer.analyze() YIELD name, value RETURN *")))
    assert out["nodes"] == "3"
    assert out["edges"] == "1"
    assert out["number_of_weakly_components"] == "2"
    assert out["bridges"] == "1"
    assert out["self_loops"] == "0"
    assert out["is_dag"] == "True"
    assert out["is_tree"] == "False"  # disconnected
    # reference analysis names resolve
    out = rows(interp.execute(
        "CALL graph_analyzer.analyze(['avg_degree']) "
        "YIELD value RETURN value"))
    assert abs(float(out[0][0]) - 2 / 3) < 1e-9
    with pytest.raises(QueryException):
        interp.execute(
            "CALL graph_analyzer.analyze(['bogus']) YIELD value RETURN 1")
    assert len(rows(interp.execute(
        "CALL graph_analyzer.help() YIELD name RETURN name"))) >= 10


def test_graph_analyzer_subgraph(interp):
    out = rows(interp.execute(
        "MATCH (a:P)-[r:R]->(b:Q) "
        "CALL graph_analyzer.analyze_subgraph([a, b], [r], ['nodes', "
        "'edges', 'is_tree']) YIELD name, value RETURN value"))
    assert [v[0] for v in out] == ["2", "1", "True"]


def test_schema_node_type_properties(interp):
    out = rows(interp.execute(
        "CALL schema.node_type_properties() "
        "YIELD nodeType, nodeLabels, mandatory, propertyName, propertyTypes "
        "RETURN nodeType, nodeLabels, mandatory, propertyName, "
        "propertyTypes ORDER BY nodeType"))
    # one P carries x, the other doesn't -> mandatory False
    assert out == [[":`P`", ["P"], False, "x", ["INTEGER"]],
                   [":`Q`", ["Q"], False, "", []]]


def test_schema_rel_type_properties(interp):
    out = rows(interp.execute(
        "CALL schema.rel_type_properties() "
        "YIELD relType, sourceNodeLabels, targetNodeLabels, mandatory, "
        "propertyName RETURN *"))
    assert out == [[True, "w", ":`R`", ["P"], ["Q"]]]


def test_schema_assert_creates_and_drops(interp):
    out = rows(interp.execute(
        "CALL schema.assert({P: ['x']}, {}, {}, true) "
        "YIELD action, label, key RETURN *"))
    assert out == [["Created", "x", "P"]]
    assert [r[:4] for r in rows(interp.execute("SHOW INDEX INFO"))] == [
        ["label+property", "P", ["x"], 1]]
    # re-assert: existing entries are reported as Kept (reference behavior)
    assert rows(interp.execute(
        "CALL schema.assert({P: ['x']}, {}, {}, true) "
        "YIELD action RETURN action")) == [["Kept"]]
    # dropping via empty assertion
    out = rows(interp.execute(
        "CALL schema.assert({}, {}, {}, true) YIELD action, label "
        "RETURN *"))
    assert out == [["Dropped", "P"]]
    assert rows(interp.execute("SHOW INDEX INFO")) == []


def test_schema_assert_constraints(interp):
    interp.execute("MATCH (q:Q) SET q.name = 'only'")
    # reference shape: unique_constraints is a list of property LISTS
    rows(interp.execute(
        "CALL schema.assert({}, {Q: [['name']]}, {Q: ['name']}, false) "
        "YIELD action, unique RETURN *"))
    out = rows(interp.execute("SHOW CONSTRAINT INFO"))
    kinds = sorted(r[0] for r in out)
    assert kinds == ["exists", "unique"]
    # drop_existing reconciles constraints away too
    out = rows(interp.execute(
        "CALL schema.assert({}, {}, {}, true) YIELD action, unique "
        "RETURN action, unique ORDER BY unique"))
    assert out == [["Dropped", False], ["Dropped", True]]
    assert rows(interp.execute("SHOW CONSTRAINT INFO")) == []
    # an assertion the data violates surfaces the engine's error
    with pytest.raises(Exception):
        interp.execute(
            "CALL schema.assert({}, {}, {P: ['x']}, false) "
            "YIELD action RETURN action")


def test_meta_util_schema(interp):
    out = rows(interp.execute(
        "CALL meta_util.schema(true) YIELD nodes, relationships RETURN *"))
    nodes, relationships = out[0]
    labels = sorted(tuple(n["labels"]) for n in nodes)
    assert labels == [("P",), ("Q",)]
    assert all(n["type"] == "node" for n in nodes)
    rel = relationships[0]
    assert rel["type"] == "relationship"
    assert rel["label"] == "R"
    assert rel["properties"] == {"count": 1, "properties_count": {"w": 1}}
    assert {"id", "start", "end"} <= set(rel)
    # empty database raises, as in the reference
    empty = Interpreter(InterpreterContext(InMemoryStorage()))
    with pytest.raises(QueryException):
        empty.execute("CALL meta_util.schema() YIELD nodes RETURN 1")


def test_convert_functions(interp):
    out = rows(interp.execute(
        "RETURN convert.from_json_map('{\"k\": 1}') AS m, "
        "convert.from_json_list('[1, 2]') AS l"))
    assert out == [[{"k": 1}, [1, 2]]]
    # reference node shape: {id, type, labels, properties}
    import json
    out = rows(interp.execute(
        "MATCH (n:P {x: 1}) RETURN convert.to_json(n), convert.to_map(n)"))
    doc = json.loads(out[0][0])
    assert doc["type"] == "node" and doc["labels"] == ["P"]
    assert doc["properties"] == {"x": 1}
    assert out[0][1] == {"x": 1}
    # relationship shape has full start/end node objects
    out = rows(interp.execute(
        "MATCH ()-[r:R]->() RETURN convert.to_json(r)"))
    rel = json.loads(out[0][0])
    assert rel["type"] == "relationship" and rel["label"] == "R"
    assert rel["start"]["type"] == "node" and rel["end"]["type"] == "node"
    # optional JSON path argument + null semantics
    out = rows(interp.execute(
        "RETURN convert.from_json_map('{\"a\": {\"b\": 1}}', '$.a'), "
        "convert.from_json_map('{\"a\": 1}', '$.zzz'), "
        "convert.from_json_map('null')"))
    assert out == [[{"b": 1}, None, None]]
    # non-map-convertible yields null; bad JSON raises
    assert rows(interp.execute("RETURN convert.to_map(5)")) == [[None]]
    with pytest.raises(Exception):
        interp.execute("RETURN convert.from_json_map('[1]')")
    with pytest.raises(Exception):
        interp.execute("RETURN convert.from_json_list('nope')")


def test_mgps_functions(interp):
    assert rows(interp.execute("RETURN mgps.version()")) == [["5.9.0"]]
    assert rows(interp.execute(
        "RETURN mgps.validate_predicate(false, 'm %s', ['x'])")) == [[True]]
    with pytest.raises(Exception):
        interp.execute("RETURN mgps.validate_predicate(true, 'm %s', ['x'])")
    # bad format strings surface as query errors, not raw TypeErrors
    with pytest.raises(QueryException, match="format"):
        interp.execute(
            "RETURN mgps.validate_predicate(true, 'm %s %s', ['x'])")
    # null predicate propagates null (openCypher ternary)
    assert rows(interp.execute(
        "RETURN mgps.validate_predicate(null, 'm', [])")) == [[None]]


def test_export_graphml_and_csv(tmp_path, interp):
    out = rows(interp.execute(
        f"CALL export_util.graphml('{tmp_path}/g.graphml') "
        f"YIELD status RETURN status"))
    assert "3 nodes" in out[0][0]
    content = (tmp_path / "g.graphml").read_text()
    assert content.startswith('<?xml version="1.0"')
    assert '<data key="labels">:P</data>' in content
    assert '<data key="label">R</data>' in content
    # round-trip sanity: stdlib XML parser accepts it
    import xml.etree.ElementTree as ET
    ET.fromstring(content)
    out = rows(interp.execute(
        "CALL export_util.csv_query('MATCH (n:P) RETURN n.x AS x "
        "ORDER BY x', '', true) YIELD data RETURN data"))
    assert out[0][0].splitlines()[0] == '"x"'
    with pytest.raises(Exception):
        interp.execute(
            "CALL export_util.csv_query('RETURN 1', '', false) "
            "YIELD data RETURN data")


def test_csv_utils(tmp_path, interp):
    f = tmp_path / "t.csv"
    interp.execute(
        f"CALL csv_utils.create_csv_file('{f}', 'a,b\\n') "
        f"YIELD filepath RETURN filepath")
    interp.execute(
        f"CALL csv_utils.create_csv_file('{f}', '1,2\\n', true) "
        f"YIELD filepath RETURN filepath")
    assert f.read_text() == "a,b\n1,2\n"
    interp.execute(
        f"CALL csv_utils.delete_csv_file('{f}') YIELD filepath RETURN 1")
    assert not f.exists()
    with pytest.raises(Exception):
        interp.execute(
            f"CALL csv_utils.delete_csv_file('{f}') YIELD filepath RETURN 1")


def test_export_graphml_stream_and_bool_config(interp):
    out = rows(interp.execute(
        "CALL export_util.graphml('', {stream: true}) "
        "YIELD status RETURN status"))
    import xml.etree.ElementTree as ET
    ET.fromstring(out[0][0])  # stream mode returns the XML document
    # leaveOutProperties is a boolean (reference set_default_config)
    out = rows(interp.execute(
        "CALL export_util.graphml('', {stream: true, "
        "leaveOutProperties: true}) YIELD status RETURN status"))
    assert "<data key=\"d0\">" not in out[0][0]
    with pytest.raises(Exception):
        interp.execute(
            "CALL export_util.graphml('', {stream: true, "
            "leaveOutLabels: ['A']}) YIELD status RETURN 1")


def test_export_graphml_reserved_key_collision(tmp_path, interp):
    # a property literally named 'labels' must not clash with the
    # reserved labels key (sequential data-key ids)
    interp.execute("CREATE (:Tricky {labels: 'x'})")
    out = rows(interp.execute(
        "CALL export_util.graphml('', {stream: true}) "
        "YIELD status RETURN status"))
    import xml.etree.ElementTree as ET
    root = ET.fromstring(out[0][0])
    key_ids = [k.get("id") for k in root.findall(
        "{http://graphml.graphdrawing.org/xmlns}key")]
    assert len(key_ids) == len(set(key_ids))  # no duplicate key ids


def test_csv_query_serializes_nodes_as_json(interp):
    out = rows(interp.execute(
        "CALL export_util.csv_query('MATCH (n:Q) RETURN n', '', true) "
        "YIELD data RETURN data"))
    assert "VertexAccessor object at" not in out[0][0]
    assert '""type"":""node""' in out[0][0].replace("\r", "")
