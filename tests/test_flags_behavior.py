"""Behavior behind the widened flag surface (reference: src/flags/*.cpp).

Every flag tested here is WIRED — the test drives the behavior, not just
argument parsing.
"""

import pytest

from memgraph_tpu.main import build_config, build_database
from memgraph_tpu.query import Interpreter
from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.storage import InMemoryStorage
from memgraph_tpu.storage.storage import StorageConfig


def test_delta_on_identical_property_update_disabled():
    storage = InMemoryStorage(StorageConfig(
        delta_on_identical_property_update=False))
    acc = storage.access()
    pid = storage.property_mapper.name_to_id("x")
    v = acc.create_vertex()
    v.set_property(pid, 7)
    before = len(acc.txn.deltas)
    v.set_property(pid, 7)          # identical rewrite: no delta
    assert len(acc.txn.deltas) == before
    v.set_property(pid, 8)          # real change: delta
    assert len(acc.txn.deltas) == before + 1
    # type-sensitive: 7 -> 7.0 changes the stored type, must delta
    v.set_property(pid, 7.0)
    assert len(acc.txn.deltas) == before + 2
    acc.commit()


def test_delta_on_identical_default_still_writes():
    storage = InMemoryStorage()
    acc = storage.access()
    pid = storage.property_mapper.name_to_id("x")
    v = acc.create_vertex()
    v.set_property(pid, 7)
    before = len(acc.txn.deltas)
    v.set_property(pid, 7)
    assert len(acc.txn.deltas) == before + 1
    acc.commit()


def test_automatic_index_creation():
    storage = InMemoryStorage(StorageConfig(
        automatic_label_index=True, automatic_edge_type_index=True))
    interp = Interpreter(InterpreterContext(storage))
    interp.execute("CREATE (:Auto {x: 1})-[:REL]->(:Auto {x: 2})")
    lid = storage.label_mapper.maybe_name_to_id("Auto")
    tid = storage.edge_type_mapper.maybe_name_to_id("REL")
    assert storage.indices.label.has(lid)
    assert storage.indices.edge_type.has(tid)
    # and they actually serve queries
    _, rows, _ = interp.execute("SHOW INDEX INFO")
    kinds = {r[0] for r in rows}
    assert "label" in kinds and "edge-type" in kinds


def test_no_automatic_index_by_default():
    storage = InMemoryStorage()
    interp = Interpreter(InterpreterContext(storage))
    interp.execute("CREATE (:Auto)")
    lid = storage.label_mapper.maybe_name_to_id("Auto")
    assert not storage.indices.label.has(lid)


def test_init_data_file_runs_after_init_file(tmp_path):
    (tmp_path / "schema.cypherl").write_text(
        "CREATE INDEX ON :P(x);\n")
    (tmp_path / "data.cypherl").write_text(
        "CREATE (:P {x: 1});\nCREATE (:P {x: 2});\n")
    args = build_config([
        "--data-directory", str(tmp_path / "dd"),
        "--init-file", str(tmp_path / "schema.cypherl"),
        "--init-data-file", str(tmp_path / "data.cypherl"),
    ])
    ictx = build_database(args)
    interp = Interpreter(ictx)
    _, rows, _ = interp.execute("MATCH (p:P) RETURN count(p)")
    assert rows[0][0] == 2
    _, rows, _ = interp.execute("SHOW INDEX INFO")
    assert any(r[0] == "label+property" for r in rows)


def test_replication_state_restore(tmp_path):
    from memgraph_tpu.replication.main_role import ReplicationState
    from memgraph_tpu.storage.kvstore import KVStore

    storage = InMemoryStorage()
    ctx = InterpreterContext(storage)
    ctx.kvstore = KVStore(str(tmp_path / "kv"))
    state = ReplicationState(storage, ictx=ctx)
    state.set_role_replica("127.0.0.1", 0)
    port = state.replica_server.port
    assert port > 0
    state.replica_server.stop()

    # a fresh process: restore from the kvstore
    storage2 = InMemoryStorage()
    ctx2 = InterpreterContext(storage2)
    ctx2.kvstore = KVStore(str(tmp_path / "kv"))
    state2 = ReplicationState(storage2, ictx=ctx2)
    assert state2.role == "main"
    state2.restore_state()
    assert state2.role == "replica"
    assert state2.replica_server is not None
    state2.replica_server.stop()


def test_replication_restore_skips_unreachable_replicas(tmp_path):
    import json
    from memgraph_tpu.replication.main_role import ReplicationState
    from memgraph_tpu.storage.kvstore import KVStore

    ctx = InterpreterContext(InMemoryStorage())
    ctx.kvstore = KVStore(str(tmp_path / "kv"))
    ctx.kvstore.put("replication:state", json.dumps(
        {"role": "main", "listen_port": 0,
         "replicas": [{"name": "gone", "address": "127.0.0.1:1",
                       "mode": "ASYNC"}]}))
    state = ReplicationState(ctx.storage, ictx=ctx)
    state.restore_state()        # must not raise
    assert state.role == "main" and not state.replicas


def test_hops_limit_partial_results_flag_default():
    ctx = InterpreterContext(InMemoryStorage(),
                             {"hops_limit_partial_results": False})
    interp = Interpreter(ctx)
    interp.execute("CREATE (:H)-[:E]->(:H)-[:E]->(:H)-[:E]->(:H)")
    from memgraph_tpu.exceptions import QueryException
    with pytest.raises(QueryException):
        interp.execute("MATCH (a)-[e]->(b) USING HOPS LIMIT 1 "
                       "RETURN count(*)")


def test_bolt_server_name_flag_parses():
    args = build_config(["--bolt-server-name-for-init", "Neo4j/5.2.0"])
    assert args.bolt_server_name_for_init == "Neo4j/5.2.0"
