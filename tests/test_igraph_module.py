"""igraphalg bridge + algo module parity tests."""

import math

import pytest

from memgraph_tpu.exceptions import QueryException
from memgraph_tpu.procedures import load_builtin_modules
from memgraph_tpu.procedures.mock import mock_context
from memgraph_tpu.query.procedures.registry import global_registry

load_builtin_modules()


def proc(name):
    p = global_registry.find(name)
    assert p is not None, f"procedure {name} not registered"
    return p.func


def chain_ctx():
    # 0 -> 1 -> 2 -> 3 with a shortcut 0 -> 3 (weight 10)
    return mock_context(
        nodes=[{} for _ in range(4)],
        edges=[(0, 1, "E", {"weight": 1.0}), (1, 2, "E", {"weight": 1.0}),
               (2, 3, "E", {"weight": 1.0}), (0, 3, "E", {"weight": 10.0})])


def test_pagerank_delegates_to_kernel():
    ctx, vs = mock_context(nodes=[{}, {}, {}],
                           edges=[(0, 2, "E"), (1, 2, "E")])
    rows = list(proc("igraphalg.pagerank")(ctx))
    ranks = {r["node"].gid: r["rank"] for r in rows}
    assert ranks[vs[2].gid] > ranks[vs[0].gid]
    assert abs(sum(ranks.values()) - 1.0) < 1e-3
    with pytest.raises(QueryException):
        list(proc("igraphalg.pagerank")(ctx, 0.85, None, True, "bogus"))


def test_maxflow_alias():
    ctx, vs = chain_ctx()
    rows = list(proc("igraphalg.maxflow")(ctx, vs[0], vs[3]))
    assert rows == [{"max_flow": 11.0}]


def test_get_all_simple_paths_and_cutoff():
    ctx, vs = chain_ctx()
    rows = list(proc("igraphalg.get_all_simple_paths")(ctx, vs[0], vs[3]))
    paths = sorted([v.gid for v in r["path"]] for r in rows)
    assert paths == [[vs[0].gid, vs[1].gid, vs[2].gid, vs[3].gid],
                     [vs[0].gid, vs[3].gid]]
    rows = list(proc("igraphalg.get_all_simple_paths")(ctx, vs[0], vs[3], 1))
    assert len(rows) == 1  # only the direct edge fits in cutoff 1


def test_mincut_partitions():
    # bottleneck edge 1->2 (capacity 1) separates {0,1} from {2,3}
    ctx, vs = mock_context(
        nodes=[{} for _ in range(4)],
        edges=[(0, 1, "E", {"weight": 5.0}), (1, 2, "E", {"weight": 1.0}),
               (2, 3, "E", {"weight": 5.0})])
    rows = list(proc("igraphalg.mincut")(ctx, vs[0], vs[3], "weight"))
    part = {r["node"].gid: r["partition_id"] for r in rows}
    assert part[vs[0].gid] == part[vs[1].gid] == 0
    assert part[vs[2].gid] == part[vs[3].gid] == 1


def test_topological_sort_and_cycle():
    ctx, vs = mock_context(nodes=[{} for _ in range(3)],
                           edges=[(0, 1, "E"), (1, 2, "E")])
    rows = list(proc("igraphalg.topological_sort")(ctx))
    order = [v.gid for v in rows[0]["nodes"]]
    assert order.index(vs[0].gid) < order.index(vs[1].gid) < \
        order.index(vs[2].gid)
    ctx2, _ = mock_context(nodes=[{}, {}],
                           edges=[(0, 1, "E"), (1, 0, "E")])
    with pytest.raises(QueryException):
        list(proc("igraphalg.topological_sort")(ctx2))
    with pytest.raises(QueryException):
        list(proc("igraphalg.topological_sort")(ctx, "sideways"))


def test_spanning_tree():
    # triangle with one heavy edge: MST keeps the two light edges
    ctx, vs = mock_context(
        nodes=[{} for _ in range(3)],
        edges=[(0, 1, "E", {"w": 1.0}), (1, 2, "E", {"w": 1.0}),
               (0, 2, "E", {"w": 9.0})])
    rows = list(proc("igraphalg.spanning_tree")(ctx, "w"))
    tree = {frozenset((a.gid, b.gid)) for a, b in rows[0]["tree"]}
    assert tree == {frozenset((vs[0].gid, vs[1].gid)),
                    frozenset((vs[1].gid, vs[2].gid))}


def test_shortest_path_length_weighted_vs_hops():
    ctx, vs = chain_ctx()
    rows = list(proc("igraphalg.shortest_path_length")(
        ctx, vs[0], vs[3], "weight"))
    assert rows[0]["length"] == 3.0  # 1+1+1 beats the 10 shortcut
    rows = list(proc("igraphalg.shortest_path_length")(ctx, vs[0], vs[3]))
    assert rows[0]["length"] == 1.0  # hop count takes the shortcut


def test_all_shortest_path_lengths_symmetric():
    ctx, vs = mock_context(nodes=[{}, {}], edges=[(0, 1, "E")])
    rows = list(proc("igraphalg.all_shortest_path_lengths")(ctx))
    lengths = {(r["src_node"].gid, r["dest_node"].gid): r["length"]
               for r in rows}
    assert lengths[(vs[0].gid, vs[1].gid)] == 1.0
    assert lengths[(vs[1].gid, vs[0].gid)] == 1.0  # undirected default


def test_get_shortest_path_vertices():
    ctx, vs = chain_ctx()
    rows = list(proc("igraphalg.get_shortest_path")(
        ctx, vs[0], vs[3], "weight"))
    assert [v.gid for v in rows[0]["path"]] == [v.gid for v in vs]
    # unreachable -> empty path
    ctx2, vs2 = mock_context(nodes=[{}, {}], edges=[])
    rows = list(proc("igraphalg.get_shortest_path")(ctx2, vs2[0], vs2[1]))
    assert rows[0]["path"] == []


def test_astar_with_haversine_heuristic():
    nodes = [{"lat": 0.0, "lon": 0.0}, {"lat": 0.0, "lon": 1.0},
             {"lat": 0.0, "lon": 2.0}, {"lat": 5.0, "lon": 1.0}]
    ctx, vs = mock_context(
        nodes=nodes,
        edges=[(0, 1, "R", {"distance": 1.0}), (1, 2, "R", {"distance": 1.0}),
               (0, 3, "R", {"distance": 1.0}), (3, 2, "R", {"distance": 5.0})])
    rows = list(proc("algo.astar")(ctx, vs[0], vs[2]))
    assert rows[0]["weight"] == 2.0
    assert [v.gid for v in rows[0]["path"].vertices()] == \
        [vs[0].gid, vs[1].gid, vs[2].gid]
    # unreachable target -> no rows
    ctx2, vs2 = mock_context(nodes=[{}, {}], edges=[])
    assert list(proc("algo.astar")(ctx2, vs2[0], vs2[1])) == []


def test_algo_all_simple_paths_type_filter():
    ctx, vs = mock_context(
        nodes=[{} for _ in range(3)],
        edges=[(0, 1, "A"), (1, 2, "A"), (0, 2, "B")])
    rows = list(proc("algo.all_simple_paths")(ctx, vs[0], vs[2], ["A"], 5))
    assert len(rows) == 1
    assert [v.gid for v in rows[0]["path"].vertices()] == \
        [vs[0].gid, vs[1].gid, vs[2].gid]
    rows = list(proc("algo.all_simple_paths")(ctx, vs[0], vs[2], [], 5))
    assert len(rows) == 2
    with pytest.raises(QueryException):
        list(proc("algo.all_simple_paths")(ctx, vs[0], vs[2], [], -1))


def test_algo_cover():
    ctx, vs = mock_context(
        nodes=[{} for _ in range(3)],
        edges=[(0, 1, "E"), (1, 2, "E")])
    rows = list(proc("algo.cover")(ctx, [vs[0], vs[1]]))
    assert len(rows) == 1  # only 0->1 has both endpoints in the set
    assert rows[0]["rel"].from_vertex().gid == vs[0].gid


def test_mincut_unit_capacities_and_undirected():
    # no weight property at all: igraph unit-capacity convention must
    # still separate source from target
    ctx, vs = mock_context(nodes=[{} for _ in range(3)],
                           edges=[(0, 1, "E"), (1, 2, "E")])
    rows = list(proc("igraphalg.mincut")(ctx, vs[0], vs[2]))
    part = {r["node"].gid: r["partition_id"] for r in rows}
    assert part[vs[0].gid] == 0 and part[vs[2].gid] == 1
    # undirected: A->B, C->B — cut must separate A from C through B
    ctx2, vs2 = mock_context(
        nodes=[{} for _ in range(3)],
        edges=[(0, 1, "E", {"w": 5.0}), (2, 1, "E", {"w": 5.0})])
    rows = list(proc("igraphalg.mincut")(ctx2, vs2[0], vs2[2], "w", False))
    part = {r["node"].gid: r["partition_id"] for r in rows}
    assert part[vs2[0].gid] == 0 and part[vs2[2].gid] == 1


def test_parallel_edges_take_min_weight():
    ctx, vs = mock_context(
        nodes=[{}, {}],
        edges=[(0, 1, "E", {"w": 1.0}), (0, 1, "E", {"w": 9.0})])
    rows = list(proc("igraphalg.get_shortest_path")(ctx, vs[0], vs[1], "w"))
    assert [v.gid for v in rows[0]["path"]] == [vs[0].gid, vs[1].gid]
    rows = list(proc("igraphalg.all_shortest_path_lengths")(ctx, "w"))
    lengths = {(r["src_node"].gid, r["dest_node"].gid): r["length"]
               for r in rows}
    assert lengths[(vs[0].gid, vs[1].gid)] == 1.0


def test_pagerank_undirected():
    ctx, vs = mock_context(nodes=[{}, {}, {}],
                           edges=[(0, 2, "E"), (1, 2, "E")])
    directed = {r["node"].gid: r["rank"]
                for r in proc("igraphalg.pagerank")(ctx)}
    undirected = {r["node"].gid: r["rank"]
                  for r in proc("igraphalg.pagerank")(ctx, 0.85, None,
                                                      False)}
    # undirected walk flows back out of the sink: its rank drops
    assert undirected[vs[2].gid] < directed[vs[2].gid]
    assert abs(sum(undirected.values()) - 1.0) < 1e-3
