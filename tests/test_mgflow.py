"""mgflow: escape-engine units on synthetic trees, both-direction
protocol drift, retry classification, registry extraction from the real
tree, and the CLI gate (exit codes + baseline discipline).

The fixture-file TP/TN tests for the mglint rule surface (MG012/MG013
at exact lines) live in tests/test_mglint.py; this file exercises the
analysis engine itself.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools.mgflow.contracts import check_contracts  # noqa: E402
from tools.mgflow.engine import (EscapeModel, UNKNOWN,  # noqa: E402
                                 get_escape_model)
from tools.mgflow.protocol import check_wires  # noqa: E402
from tools.mgflow.retrycheck import check_retries  # noqa: E402
from tools.mgflow.spec import extract_specs  # noqa: E402
from tools.mglint.core import Project  # noqa: E402


def _proj(tmp_path, **files):
    for name, src in files.items():
        p = tmp_path / name.replace("__", "/")
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project([str(tmp_path)], cwd=str(tmp_path))


def _escapes(tmp_path, src, qual):
    em = EscapeModel(_proj(tmp_path, **{"m.py": src}))
    return set(em.escapes[f"m.py::{qual}"])


# --- escape engine units ----------------------------------------------------


def test_direct_raise_escapes(tmp_path):
    assert _escapes(tmp_path, """
        def f():
            raise ValueError("x")
        """, "f") == {"ValueError"}


def test_interprocedural_propagation(tmp_path):
    assert _escapes(tmp_path, """
        def helper():
            raise KeyError("x")

        def outer():
            return helper()
        """, "outer") == {"KeyError"}


def test_except_narrows_and_subclasses_are_covered(tmp_path):
    src = """
        def helper():
            raise ConnectionResetError("gone")

        def caught():
            try:
                helper()
            except OSError:
                return None

        def uncaught():
            try:
                helper()
            except KeyError:
                return None
        """
    assert _escapes(tmp_path, src, "caught") == set()
    # ConnectionResetError is not a KeyError: it keeps escaping
    assert _escapes(tmp_path, src, "uncaught") == \
        {"ConnectionResetError"}


def test_except_exception_misses_base_only(tmp_path):
    assert _escapes(tmp_path, """
        def f():
            try:
                raise KeyboardInterrupt()
            except Exception:
                pass
        """, "f") == {"KeyboardInterrupt"}


def test_bare_reraise_and_alias_survive(tmp_path):
    src = """
        def reraiser():
            try:
                open("x")
            except OSError:
                raise

        def aliaser():
            last = None
            try:
                open("x")
            except OSError as e:
                last = e
            if last is not None:
                raise last
        """
    assert _escapes(tmp_path, src, "reraiser") == {"OSError"}
    assert _escapes(tmp_path, src, "aliaser") == {"OSError"}


def test_known_raising_stdlib_calls(tmp_path):
    assert _escapes(tmp_path, """
        import json
        import struct

        def f(payload):
            n = struct.unpack("<I", payload[:4])
            return n, json.loads(payload[4:])
        """, "f") == {"struct.error", "ValueError"}


def test_retrypolicy_call_passes_wrapped_escapes_through(tmp_path):
    assert _escapes(tmp_path, """
        def do_io():
            raise ValueError("bad frame")

        def f(policy):
            return policy.call(do_io)
        """, "f") == {"ValueError"}


def test_os_exit_finally_is_a_process_barrier(tmp_path):
    # the fork-child idiom: nothing propagates past os._exit
    assert _escapes(tmp_path, """
        import os

        def child_main():
            raise ValueError("child-side only")

        def spawn():
            pid = os.fork()
            if pid == 0:
                try:
                    child_main()
                finally:
                    os._exit(0)
            return pid
        """, "spawn") == set()


def test_dynamic_raise_is_unknown_not_silent(tmp_path):
    esc = _escapes(tmp_path, """
        def f(make_error):
            raise make_error()
        """, "f")
    assert esc == {UNKNOWN}


def test_dict_of_classes_raise_resolves_members(tmp_path):
    assert _escapes(tmp_path, """
        ERRORS = {"a": KeyError, "b": ValueError}

        def f(kind):
            cls = ERRORS.get(kind, ValueError)
            raise cls(kind)
        """, "f") == {"KeyError", "ValueError"}


def test_covered_by_walks_project_hierarchy(tmp_path):
    em = EscapeModel(_proj(tmp_path, **{"m.py": """
        class Base(Exception):
            pass

        class Leaf(Base):
            pass
        """}))
    assert em.covered_by("Leaf", "Base")
    assert em.covered_by("Leaf", "Exception")
    assert not em.covered_by("Base", "Leaf")


# --- contract check on a synthetic registry ---------------------------------


_CONTRACT_TREE = """
    class ServingRoot:
        def __init__(self, **kw):
            pass

    class Base(Exception):
        pass

    class Leaf(Base):
        pass

    SERVING_ROOTS = (
        ServingRoot(root_id="t.ok", path="m.py", qualname="covered",
                    raises=("Base",)),
        ServingRoot(root_id="t.bad", path="m.py", qualname="leaky",
                    raises=("Base",)),
        ServingRoot(root_id="t.gone", path="m.py", qualname="missing",
                    raises=()),
    )

    def covered(x):
        raise Leaf(x)       # subclass of the contracted Base: fine

    def leaky(x):
        raise KeyError(x)   # outside the contract
    """


def test_contract_subclasses_covered_and_dead_roots_flagged(tmp_path):
    proj = _proj(tmp_path, **{"m.py": _CONTRACT_TREE})
    prints = {f.fingerprint for f in check_contracts(proj)}
    assert prints == {"escape:t.bad:KeyError", "dead-root:t.gone"}


# --- protocol drift (both directions) on a synthetic wire -------------------


_WIRE_TREE = {
    "flow.py": """
        class Wire:
            def __init__(self, **kw):
                pass

        class WireSide:
            def __init__(self, **kw):
                pass

        WIRES = (
            Wire(wire_id="t",
                 server=(WireSide(path="srv.py", scope=("reply",),
                                  extract=(("dict_value", "outcome"),)),),
                 client=(WireSide(path="cli.py", scope=("decode",),
                                  extract=(("compare", "outcome"),)),),
                 declared=("srv.py", "OUTCOMES"),
                 handled_inline=("done",)),
        )
        """,
    "srv.py": """
        OUTCOMES = ("done", "lost", "shed")

        def reply(ok):
            if ok:
                return {"outcome": "done"}
            return {"outcome": "bogus"}     # not declared -> drift
        """,
    "cli.py": """
        def decode(reply):
            outcome = reply["outcome"]
            if outcome == "shed":
                raise RuntimeError("shed")
            if outcome == "ghost":          # no server emits this
                raise RuntimeError("ghost")
            return reply
        """,
}


def test_wire_drift_fires_in_both_directions(tmp_path):
    proj = _proj(tmp_path, **_WIRE_TREE)
    prints = {f.fingerprint for f in check_wires(proj)}
    # server -> client: undeclared emit, and a declared outcome with no
    # decoder; client -> server: a decoder no server feeds
    assert "undeclared-emit:t:bogus" in prints
    assert "undecoded:t:lost" in prints
    assert "dead-decoder:t:ghost" in prints
    # declared+decoded ("shed") and inline ("done") stay silent
    assert not any(p.endswith(":shed") or p.endswith(":done")
                   for p in prints), prints


def test_clean_wire_is_silent(tmp_path):
    tree = dict(_WIRE_TREE)
    tree["srv.py"] = """
        OUTCOMES = ("done", "shed")

        def reply(ok):
            if ok:
                return {"outcome": "done"}
            return {"outcome": "shed"}
        """
    tree["cli.py"] = """
        def decode(reply):
            outcome = reply["outcome"]
            if outcome == "shed":
                raise RuntimeError("shed")
            return reply
        """
    proj = _proj(tmp_path, **tree)
    assert check_wires(proj) == []


# --- retry classification (.call regions) -----------------------------------


def test_call_region_retry_on_checked_against_registry(tmp_path):
    proj = _proj(tmp_path, **{"m.py": """
        IDEMPOTENCY = {
            "send_once": "unsafe",
            "Bounce": "retryable",
        }

        class Bounce(Exception):
            pass

        def send_once(policy, payload):
            return policy.call(_ship, retry_on=(Bounce, OSError))

        def _ship():
            pass
        """})
    prints = {f.fingerprint for f in check_retries(proj)}
    # retrying the registered-retryable Bounce is fine; blind-retrying
    # OSError on an unsafe op is the finding
    assert prints == {"blind-retry:send_once:OSError"}


# --- the real tree ----------------------------------------------------------


@pytest.fixture(scope="module")
def package_project():
    return Project([os.path.join(REPO, "memgraph_tpu")], cwd=REPO)


def test_registry_extraction_from_product(package_project):
    spec = extract_specs(package_project)
    roots = {r.root_id for r in spec.roots}
    assert {"bolt.session", "kernel.dispatch", "mp.worker",
            "shard.worker", "twopc.prepare", "twopc.decide",
            "replication.apply", "raft.rpc", "stream.consumer",
            "http.monitoring"} <= roots
    assert {w.wire_id for w in spec.wires} == \
        {"kernel", "mp_executor", "twopc"}
    idem = {e.name: e.classification for e in spec.idempotency}
    assert idem["ShardedClient.write"] == "unsafe"
    assert idem["KernelOom"] == "unsafe"
    assert idem["StaleShardEpoch"] == "retryable"


def test_product_wires_are_live_in_both_directions(package_project):
    """Every declared wire must extract a NON-EMPTY vocabulary on both
    sides — an empty side means the extraction directives rotted and
    the drift check is vacuously green."""
    from tools.mgflow.protocol import _extract_side
    spec = extract_specs(package_project)
    for wire in spec.wires:
        emitted = {}
        for side in wire.server:
            emitted.update(_extract_side(package_project, side))
        decoded = {}
        for side in wire.client:
            decoded.update(_extract_side(package_project, side))
        assert emitted, f"wire {wire.wire_id}: no emitted outcomes"
        assert decoded, f"wire {wire.wire_id}: no decoded outcomes"


def test_product_roots_resolve_and_contracts_hold(package_project):
    from tools.mglint.core import load_baseline
    spec = extract_specs(package_project)
    findings = check_contracts(package_project, spec)
    baseline = load_baseline(
        os.path.join(REPO, "tools", "mgflow", "baseline.json"))
    unbaselined = [f for f in findings if f.key not in baseline]
    assert not unbaselined, "\n".join(f.render() for f in unbaselined)
    # no dead roots hide behind the baseline either
    assert not any(f.fingerprint.startswith("dead-root:")
                   for f in findings)


def test_flow_stats_shape():
    from memgraph_tpu.flowspec import SERVING_ROOTS, flow_stats
    doc = flow_stats()
    assert doc["contract_roots"] == len(SERVING_ROOTS) >= 10
    assert set(doc["wires"]) == {"kernel", "mp_executor", "twopc"}
    assert doc["roots"]["twopc.prepare"] == ["MemgraphTpuError"]
    assert doc["roots"]["kernel.dispatch"] == []


# --- CLI gate ---------------------------------------------------------------


def _cli(*args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "tools.mgflow", *args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_cli_check_package_is_green():
    proc = _cli("check", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == [] and doc["unused_baseline"] == []
    assert doc["roots"] >= 10 and doc["wires"] == 3


def test_cli_list_prints_contracts():
    proc = _cli("list", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert {r["root_id"] for r in doc["roots"]} >= {"kernel.dispatch"}
    assert doc["idempotency"]["ShardedClient.write"] == "unsafe"


def test_cli_exit_1_on_unbaselined_findings():
    proc = _cli("check", "--no-baseline", "tests/lint_fixtures")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "MG012" in proc.stdout and "MG013" in proc.stdout


def test_cli_unused_baseline_entry_fails_the_gate(tmp_path):
    tree = tmp_path / "t"
    tree.mkdir()
    (tree / "m.py").write_text("def quiet():\n    return 1\n")
    stale = tmp_path / "baseline.json"
    stale.write_text(json.dumps({"entries": [
        {"key": "MG012:gone.py:x:escape:x:ValueError",
         "justification": "this finding was fixed long ago and the "
                          "entry should have been removed with it"}]}))
    proc = _cli("check", "--baseline", str(stale), str(tree))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "unused baseline entry" in proc.stdout


def test_cli_exit_2_on_broken_baseline(tmp_path):
    tree = tmp_path / "t"
    tree.mkdir()
    (tree / "m.py").write_text("def quiet():\n    return 1\n")
    broken = tmp_path / "baseline.json"
    broken.write_text(json.dumps({"entries": [
        {"key": "MG012:x:y:z"}]}))          # no justification
    proc = _cli("check", "--baseline", str(broken), str(tree))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "broken baseline" in proc.stderr


def test_cli_exit_2_on_empty_tree(tmp_path):
    empty = tmp_path / "nothing"
    empty.mkdir()
    proc = _cli("check", str(empty))
    assert proc.returncode == 2


def test_escape_model_is_cached_per_project(package_project):
    em1 = get_escape_model(package_project)
    em2 = get_escape_model(package_project)
    assert em1 is em2
